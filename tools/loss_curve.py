#!/usr/bin/env python
"""Produce a CUB-shaped loss trajectory in the reference's log format.

The reference's committed training evidence is `all-logs/cool-frog-21.txt`
(one `epoch iter loss lr` line per step, written at ref train_dalle.py:378;
654 iters/epoch = ~10.5k caption pairs at batch 16): first loss ~7.36,
epoch-99 mean ~4.28.  CUB images cannot ship in this environment, so this
harness trains the same model geometry (cool-frog-21's: dim 256 / depth 8 /
heads 8 / text 80 / VQGAN-1024 codes -> 256 image tokens / batch 16 /
lr from flag) on a SYNTHETIC caption->codes dataset with learnable
conditional structure: each of `--num_pairs` captions deterministically
selects a code template, observed under token noise — so the loss must fall
from the ~7.4 init toward the template entropy, exercising the identical
train step the real run uses (training.make_dalle_train_step, codes path).

Two additions over the bare harness mirror the real training loop:
* ``--lr_plateau`` steps the same host-side ``ReduceLROnPlateau`` that
  train_dalle.py uses (ref train_dalle.py:286-295, :415-416) on each
  epoch-mean loss, and the logged lr column carries the *actual* lr — so a
  multi-epoch run shows the scheduler firing, like the reference's logs.
* ``--ckpt`` (on by default, derived from --out) saves {params, opt state,
  rng, scheduler} after every chunk and resumes from it on restart — a
  tunnel drop mid-run costs one chunk, not the run.

Usage:
    python tools/loss_curve.py --steps 400 --out all-logs-tpu/synthetic-cub.txt
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def make_synthetic_pairs(rng, num_pairs, text_len, vocab, image_seq,
                         image_vocab, templates=32, noise=0.1):
    """Caption tokens -> noisy code template, with the template derived from
    the caption CONTENT (its first token modulo `templates`) — a
    generalizable conditional rule the transformer can pick up within an
    epoch, so the curve descends through the unconditional floor
    (ln-uniform ~7.19 at this geometry) the way real conditioning does,
    instead of requiring per-pair memorization.  Conditional floor:
    ~(ln V_text + 7*(noise*ln V_img + H(noise)))/8 ~ 2.0."""
    caps = rng.integers(1, vocab, size=(num_pairs, text_len))
    return caps.astype(np.int32), _codes_for(rng, caps[:, 0], image_seq,
                                             image_vocab, templates, noise)


def _codes_for(rng, tmpl_src, image_seq, image_vocab, templates, noise):
    """Template codes selected by the 1-D per-pair template source
    (caption content), observed under noise."""
    tmpl_of_cap = tmpl_src % templates
    templates_codes = rng.integers(0, image_vocab,
                                   size=(templates, image_seq))
    codes = templates_codes[tmpl_of_cap]
    flip = rng.random(codes.shape) < noise
    codes = np.where(flip, rng.integers(0, image_vocab, codes.shape), codes)
    return codes.astype(np.int32)


def make_real_caption_pairs(rng, num_pairs, text_len, image_seq, image_vocab,
                            templates=32, noise=0.1):
    """REAL CUB captions -> synthetic noisy code templates.

    Uses the bundled data artifacts the reference ships
    (`cub_2011_test_captions.pkl`: 30k real bird captions;
    `cub200_bpe_vsize_7800.json`: the CUB BPE vocab — both at the repo
    root, see genrank.py defaults): a deterministic sample of
    ``num_pairs`` captions, tokenized exactly as train_dalle.py would
    (pad 0, truncate at ``text_len``).  The text half of the loss is then
    a REAL language-modeling task with CUB's token statistics; only the
    image codes remain synthetic (no CUB images exist in this
    environment).  The code template hashes the whole caption content, so
    conditioning still has a learnable rule."""
    from dalle_pytorch_tpu.data.bundled import load_captions_pickle
    from dalle_pytorch_tpu.data.tokenizer import HugTokenizer

    df = load_captions_pickle(REPO / "cub_2011_test_captions.pkl")
    tok = HugTokenizer(REPO / "cub200_bpe_vsize_7800.json")
    sel = rng.choice(len(df), size=num_pairs, replace=num_pairs > len(df))
    texts = [str(c) for c in df["caption"].iloc[sel]]
    caps = tok.tokenize(texts, context_length=text_len, truncate_text=True)
    # content hash over the full caption: same caption -> same template
    tmpl_src = (caps.astype(np.int64)
                * (np.arange(caps.shape[1]) + 1)).sum(1) % (2 ** 31)
    return caps, _codes_for(rng, tmpl_src, image_seq, image_vocab,
                            templates, noise)


# default values for sig fields added AFTER a checkpoint was written: a
# stored sig missing such a key is compatible iff the current run uses the
# default (the stored run could only have used it)
_SIG_LATER_DEFAULTS = {"plateau_threshold": 1e-4, "captions": "synthetic",
                       "fresh_noise": False}


def _config_sig(args):
    """Fields that must match for a checkpoint to be resumable."""
    return {k: getattr(args, k) for k in
            ("batch_size", "learning_rate", "num_pairs", "seed", "templates",
             "noise", "lr_plateau", "plateau_factor", "plateau_patience",
             "plateau_threshold", "captions", "fresh_noise")}


def _sig_compatible(stored: dict, current: dict) -> bool:
    return all(
        stored.get(k, _SIG_LATER_DEFAULTS.get(k)) == v
        for k, v in current.items())


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--steps", type=int, default=400)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--learning_rate", type=float, default=3e-4)
    parser.add_argument("--num_pairs", type=int, default=10464,
                        help="654 iters/epoch x batch 16, as cool-frog-21")
    parser.add_argument("--templates", type=int, default=32)
    parser.add_argument("--noise", type=float, default=0.1)
    parser.add_argument("--fresh_noise", action="store_true",
                        help="re-sample the code observation noise on every "
                             "visit (per-step rng) instead of fixing it per "
                             "pair: the noise becomes IRREDUCIBLE, so the "
                             "loss truly stalls at the conditional floor "
                             "and torch-default plateau thresholds (1e-4) "
                             "genuinely fire — the regime of the "
                             "reference's own cool-frog-21 run, whose lr "
                             "column halves 7 times at defaults")
    parser.add_argument("--captions", choices=("synthetic", "real"),
                        default="synthetic",
                        help="'real' trains on the bundled CUB captions "
                             "(cub_2011_test_captions.pkl via the bundled "
                             "BPE): the text loss becomes a real language "
                             "task with CUB token statistics; codes stay "
                             "synthetic (no images in this environment)")
    parser.add_argument("--lr_plateau", action="store_true",
                        help="step ReduceLROnPlateau on each epoch-mean "
                             "loss, as train_dalle.py does (ref :415-416)")
    parser.add_argument("--plateau_factor", type=float, default=0.5)
    parser.add_argument("--plateau_patience", type=int, default=5)
    parser.add_argument("--plateau_threshold", type=float, default=1e-4,
                        help="relative improvement below this counts as a "
                             "bad epoch (torch's default 1e-4 only fires on "
                             "a true stall; raise it to demonstrate firing "
                             "on a converged-but-still-creeping curve)")
    parser.add_argument("--out", type=str,
                        default="all-logs-tpu/synthetic-cub.txt")
    parser.add_argument("--ckpt", type=str, default=None,
                        help="checkpoint path (default: <out>.ckpt); "
                             "'' disables")
    parser.add_argument("--ckpt_every_s", type=float, default=120.0,
                        help="min seconds between checkpoint writes: each "
                             "save fetches the full params+opt state "
                             "(~180 MB at CUB geometry) — through the "
                             "remote-TPU tunnel an every-chunk save could "
                             "rival the training it protects; the final "
                             "chunk always saves")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--chunk", type=int, default=50,
                        help="steps per device dispatch: a lax.scan over "
                             "the chunk's batches turns per-step RPC "
                             "latency (dominant through the remote-TPU "
                             "tunnel) into one dispatch per chunk; losses "
                             "are bit-identical to --chunk 1")
    args = parser.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from flax import serialization

    from dalle_pytorch_tpu import DALLE, DALLEConfig
    from dalle_pytorch_tpu.cli import (apply_platform_env,
                                       enable_compilation_cache)
    from dalle_pytorch_tpu.training import (make_dalle_train_step,
                                            make_optimizer,
                                            set_learning_rate)
    from dalle_pytorch_tpu.utils.schedule import ReduceLROnPlateau

    apply_platform_env()  # honor JAX_PLATFORMS=cpu despite the axon pin
    enable_compilation_cache()  # a tunnel drop mid-run must not re-pay compile

    cfg = DALLEConfig(
        dim=256, num_text_tokens=7800, text_seq_len=80, depth=8, heads=8,
        dim_head=64, attn_types=("full", "axial_row", "axial_col",
                                 "conv_like"),
        num_image_tokens=1024, image_size=256, image_fmap_size=16,
        dtype=jnp.float32)
    model = DALLE(cfg)

    host = np.random.default_rng(args.seed)
    # fresh_noise: build CLEAN codes here and re-noise per step below —
    # same marginal noise rate, but unmemorizable (a new draw every visit)
    ds_noise = 0.0 if args.fresh_noise else args.noise
    if args.captions == "real":
        caps, codes = make_real_caption_pairs(
            host, args.num_pairs, cfg.text_seq_len, cfg.image_seq_len,
            cfg.num_image_tokens, templates=args.templates,
            noise=ds_noise)
    else:
        caps, codes = make_synthetic_pairs(
            host, args.num_pairs, cfg.text_seq_len, cfg.num_text_tokens,
            cfg.image_seq_len, cfg.num_image_tokens,
            templates=args.templates, noise=ds_noise)

    rng = jax.random.PRNGKey(args.seed)
    params = jax.jit(lambda r: model.init(
        r, jnp.asarray(caps[:1]), jnp.asarray(codes[:1]))["params"])(rng)
    tx = make_optimizer(args.learning_rate)
    opt_state = jax.jit(tx.init)(params)
    sched = ReduceLROnPlateau(args.learning_rate, factor=args.plateau_factor,
                              patience=args.plateau_patience,
                              threshold=args.plateau_threshold)

    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    ckpt = Path(args.ckpt) if args.ckpt else (
        None if args.ckpt == "" else out.with_suffix(out.suffix + ".ckpt"))

    # ---- resume ---------------------------------------------------------
    # single-file checkpoint: {params, opt_state, meta-json} in ONE msgpack
    # blob behind ONE os.replace — a crash can only ever leave the previous
    # complete checkpoint, never a params/meta mismatch
    start_step = 0
    epoch_sum, epoch_cnt = 0.0, 0  # running epoch-mean accumulator
    if ckpt is not None and ckpt.exists():
        try:
            state = serialization.from_bytes(
                {"params": params, "opt_state": opt_state, "meta": ""},
                ckpt.read_bytes())
        except (ValueError, KeyError) as e:
            # a checkpoint whose param tree no longer matches this build
            # (e.g. written before a model-layout migration).  Refuse
            # loudly instead of silently restarting: a fresh start would
            # truncate the log this checkpoint was extending.
            raise SystemExit(
                f"checkpoint {ckpt} does not match this build's param "
                f"layout ({e}); delete it to start the run fresh") from None
        meta = json.loads(state["meta"])
        log_lines = (out.read_text().splitlines(keepends=True)
                     if out.exists() else [])
        if not _sig_compatible(meta["sig"], _config_sig(args)):
            print(f"checkpoint {ckpt} config mismatch; starting fresh",
                  flush=True)
        elif len(log_lines) < meta["next_step"]:
            # the log this checkpoint continues is gone/truncated (e.g. a
            # reused --ckpt with a fresh --out): resuming would produce a
            # file silently missing its head
            print(f"log {out} has {len(log_lines)} lines < checkpoint step "
                  f"{meta['next_step']}; starting fresh", flush=True)
        else:
            params, opt_state = state["params"], state["opt_state"]
            rng = jnp.asarray(np.asarray(meta["rng"], dtype=np.uint32))
            sched.load_state_dict(meta["sched"])
            opt_state = set_learning_rate(opt_state, sched.lr)
            start_step = meta["next_step"]
            epoch_sum, epoch_cnt = meta["epoch_sum"], meta["epoch_cnt"]
            # drop any log lines past the checkpoint (died between write
            # and save): keep exactly start_step lines
            out.write_text("".join(log_lines[:start_step]))
            print(f"resumed from {ckpt} at step {start_step} "
                  f"(lr {sched.lr:.2e})", flush=True)

    if start_step == 0 and out.exists():
        out.unlink()

    iters_per_epoch = args.num_pairs // args.batch_size
    chunk = max(1, args.chunk)
    raw_step = make_dalle_train_step(model, tx, jit=False)

    # env-armed on-chip capture (GRAFT_XPROF / GRAFT_XPROF_WINDOW): the
    # babysitter's xprof_capture stage points this at chip-logs/ so a
    # measured trace of the loss-parity workload rides the end-of-round
    # commit beside PERF_LEDGER.json's predicted rows.  The window snaps
    # to chunk boundaries (on_step fires per chunk, not per step) — use
    # --chunk 1..4 when arming so the capture stays a few steps wide.
    from dalle_pytorch_tpu.obs import prof
    xprof = prof.XprofWindow()

    def drain():
        jax.block_until_ready(params)

    import functools

    @functools.partial(jax.jit, static_argnames="n", donate_argnums=(0, 1, 2))
    def run_chunk(params, opt_state, rng, chunk_caps, chunk_codes, n):
        """lax.scan over the chunk's pre-gathered batches [n, B, ...] —
        one device dispatch per chunk, same step math and rng chain as the
        per-step loop, so losses are bit-identical to --chunk 1."""
        def body(carry, batch):
            params, opt_state, rng = carry
            rng, k = jax.random.split(rng)
            b_caps, b_codes = batch
            params, opt_state, loss = raw_step(params, opt_state, None,
                                               b_caps, b_codes, k)
            return (params, opt_state, rng), loss

        (params, opt_state, rng), losses = jax.lax.scan(
            body, (params, opt_state, rng), (chunk_caps, chunk_codes),
            length=n)
        return params, opt_state, rng, losses

    def batch_indices(step):
        epoch, it = divmod(step, iters_per_epoch)
        order = epoch_orders.setdefault(
            epoch,
            np.random.default_rng(args.seed + epoch).permutation(
                args.num_pairs))
        return epoch, it, order[it * args.batch_size:(it + 1) * args.batch_size]

    last_save = [time.time()]

    def save_ckpt(next_step, final=False):
        if ckpt is None:
            return
        if not final and time.time() - last_save[0] < args.ckpt_every_s:
            return
        last_save[0] = time.time()
        meta = {"sig": _config_sig(args), "next_step": next_step,
                "rng": np.asarray(jax.device_get(rng)).tolist(),
                "sched": sched.state_dict(),
                "epoch_sum": epoch_sum, "epoch_cnt": epoch_cnt}
        tmp = ckpt.with_suffix(".tmp")
        tmp.write_bytes(serialization.to_bytes(
            {"params": jax.device_get(params),
             "opt_state": jax.device_get(opt_state),
             "meta": json.dumps(meta)}))
        os.replace(tmp, ckpt)

    epoch_orders = {}
    t0 = time.time()
    done_before = start_step
    with out.open("a") as f:
        start = start_step
        while start < args.steps:
            # never let a chunk cross an epoch boundary: the plateau step
            # (and its lr change) belongs between epochs, as in the loop it
            # mirrors (train_dalle.py:722-725)
            it0 = start % iters_per_epoch
            n = min(chunk, args.steps - start, iters_per_epoch - it0)
            meta, sels = [], []
            for step in range(start, start + n):
                epoch, it, sel = batch_indices(step)
                meta.append((epoch, it))
                sels.append(sel)
            sel = np.stack(sels)                       # [n, B]
            chunk_codes = codes[sel]
            if args.fresh_noise and args.noise > 0:
                # per-step deterministic noise draw (seed, step): resumes
                # replay the identical observation, so the loss stream is
                # still bit-reproducible across crashes
                for j, step in enumerate(range(start, start + n)):
                    nr = np.random.default_rng((args.seed, 7919, step))
                    flip = nr.random(chunk_codes[j].shape) < args.noise
                    chunk_codes[j] = np.where(
                        flip, nr.integers(0, cfg.num_image_tokens,
                                          chunk_codes[j].shape),
                        chunk_codes[j])
            xprof.on_step(start, sync=drain)
            params, opt_state, rng, losses = run_chunk(
                params, opt_state, rng, jnp.asarray(caps[sel]),
                jnp.asarray(chunk_codes), n)
            host_losses = jax.device_get(losses)  # one transfer per chunk
            for (epoch, it), loss_v in zip(meta, host_losses):
                # the reference's exact line format (ref train_dalle.py:378)
                f.write(f"{epoch} {it} {float(loss_v)} {sched.lr}\n")
            f.flush()
            epoch_sum += float(host_losses.sum())
            epoch_cnt += n
            start += n
            if args.lr_plateau and start % iters_per_epoch == 0:
                epoch_mean = epoch_sum / max(epoch_cnt, 1)
                new_lr = sched.step(epoch_mean)
                opt_state = set_learning_rate(opt_state, new_lr)
                print(f"epoch {start // iters_per_epoch - 1} done: "
                      f"mean loss {epoch_mean:.4f} lr {new_lr:.2e}",
                      flush=True)
                epoch_sum, epoch_cnt = 0.0, 0
            save_ckpt(start, final=start >= args.steps)
            rate = (start - done_before) / (time.time() - t0)
            print(f"step {start - 1}: loss {float(host_losses[-1]):.4f} "
                  f"({rate:.2f} steps/s)", flush=True)
    xprof.close(sync=drain)  # exit-path safety net (window past --steps)
    print(f"wrote {args.steps} lines to {out}")


if __name__ == "__main__":
    main()
