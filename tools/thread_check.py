#!/usr/bin/env python
"""graftrace CLI — static lock-discipline sweep over the thread-bearing
modules (the graftspmd of concurrency; analyses live in
dalle_pytorch_tpu/lint/threads.py).

Usage:
    python tools/thread_check.py                  # sweep the serving stack
    python tools/thread_check.py path/to/mod.py   # sweep specific files
    python tools/thread_check.py --json out.json  # machine-readable findings
    python tools/thread_check.py --selftest       # prove T1-T4 catch fixtures

Exit codes: 0 clean, 1 findings, 2 usage/parse error.  Pure AST — no jax,
no imports of the swept modules, milliseconds per run.  Every finding must
be fixed or carry a parenthesized graftrace pragma; there is no baseline
file by design.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dalle_pytorch_tpu.lint import threads  # noqa: E402

# The thread-bearing surface: every module where more than one thread
# touches shared state (PR 6/8/12/16 growth), plus the refcounted prefix
# cache the drivers share.
DEFAULT_TARGETS = (
    "dalle_pytorch_tpu/serve/scheduler.py",
    "dalle_pytorch_tpu/serve/replica.py",
    "dalle_pytorch_tpu/serve/router.py",
    "dalle_pytorch_tpu/serve/autoscale.py",
    "dalle_pytorch_tpu/serve/prefix.py",
    "dalle_pytorch_tpu/utils/ckpt_manager.py",
    "dalle_pytorch_tpu/obs/metrics.py",
    "dalle_pytorch_tpu/obs/telemetry.py",
)


def run_sweep(paths, select=None, json_out=None) -> int:
    findings = []
    for path in paths:
        p = Path(path)
        if not p.is_file():
            print(f"thread_check: no such file: {p}", file=sys.stderr)
            return 2
        try:
            findings.extend(threads.analyze_file(p, select=select))
        except SyntaxError as e:
            print(f"thread_check: parse error in {p}: {e}", file=sys.stderr)
            return 2
    for f in findings:
        print(f.render())
    counts = {}
    for f in findings:
        counts[f.code] = counts.get(f.code, 0) + 1
    if json_out:
        payload = {
            "tool": "thread_check",
            "analyses": list(threads.ANALYSES),
            "paths": [str(p) for p in paths],
            "counts": counts,
            "findings": [
                {"code": f.code, "path": f.path, "line": f.line,
                 "message": f.message}
                for f in findings
            ],
        }
        Path(json_out).write_text(json.dumps(payload, indent=2) + "\n")
    if findings:
        summary = ", ".join(f"{c} {code}" for code, c in sorted(
            counts.items()))
        print(f"\nthread_check: FAIL — {len(findings)} finding(s) "
              f"({summary}); fix or annotate with a justified graftrace "
              f"pragma")
        return 1
    print(f"thread_check: PASS — {len(paths)} module(s) clean "
          f"({', '.join(threads.ANALYSES)})")
    return 0


def selftest() -> int:
    """Prove T1-T4 have teeth against lint/threads_fixtures.py (the CLI
    twin of tests/test_thread_check.py)."""
    fixture = REPO / "dalle_pytorch_tpu/lint/threads_fixtures.py"
    findings = threads.analyze_file(fixture)
    failures = 0

    def expect_catch(label, pred):
        nonlocal failures
        hits = [f for f in findings if pred(f)]
        if hits:
            print(f"PASS {label}: caught ({hits[0].message[:80]}...)")
        else:
            print(f"FAIL {label}: broken fixture NOT caught")
            failures += 1

    expect_catch("T1 unguarded write",
                 lambda f: f.code == "T1"
                 and "BrokenUnguardedCounter" in f.message
                 and "written without a lock" in f.message)
    expect_catch("T1 unguarded read",
                 lambda f: f.code == "T1"
                 and "BrokenUnguardedCounter" in f.message
                 and "read without it" in f.message)
    expect_catch("T2 compile under lock",
                 lambda f: f.code == "T2"
                 and "BrokenCompileUnderLock" in f.message)
    expect_catch("T3 AB/BA cycle",
                 lambda f: f.code == "T3"
                 and "BrokenOrderInversion" in f.message)
    expect_catch("T4 future resolve under lock",
                 lambda f: f.code == "T4"
                 and "BrokenResolveUnderLock" in f.message
                 and "set_result" in f.message)
    expect_catch("T4 caller callback under lock",
                 lambda f: f.code == "T4"
                 and "BrokenResolveUnderLock" in f.message
                 and "on_done" in f.message)

    dirty_twins = [f for f in findings if "Clean" in f.message]
    if dirty_twins:
        print(f"FAIL clean twins flagged: {[f.render() for f in dirty_twins]}")
        failures += 1
    else:
        print("PASS clean twins: no findings")

    print(f"\nselftest: {'FAIL' if failures else 'PASS'}")
    return 1 if failures else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("paths", nargs="*",
                        help="files to sweep (default: the thread-bearing "
                             "serving stack)")
    parser.add_argument("--select", type=str, default=None,
                        help="comma-separated analyses to run "
                             "(default: all of T1,T2,T3,T4)")
    parser.add_argument("--json", type=str, default=None,
                        help="write machine-readable findings to this path")
    parser.add_argument("--selftest", action="store_true",
                        help="prove each analysis catches its deliberately-"
                             "broken fixture, then exit")
    args = parser.parse_args(argv)
    if args.selftest:
        return selftest()
    select = None
    if args.select:
        select = tuple(s.strip() for s in args.select.split(",") if s.strip())
        unknown = set(select) - set(threads.ANALYSES) - {"TP"}
        if unknown:
            print(f"thread_check: unknown analyses {sorted(unknown)} "
                  f"(have {threads.ANALYSES})", file=sys.stderr)
            return 2
    paths = args.paths or [str(REPO / t) for t in DEFAULT_TARGETS]
    return run_sweep(paths, select=select, json_out=args.json)


if __name__ == "__main__":
    raise SystemExit(main())
