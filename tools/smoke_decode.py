#!/usr/bin/env python
"""Smoke-test converted pretrained checkpoints: one decode/embed per model.

The last stage of `tools/fetch_and_convert.sh`: proves each converted
msgpack actually loads into its wrapper graph and produces finite outputs
of the published shapes (ref runtime use: vae.py:98-170 decodes, genrank.py
:118-135 CLIP-scores).  Writes one PNG per VAE so a human can eyeball the
result the day real weights are converted.

Usage:
    python tools/smoke_decode.py --dir pretrained [--models vqgan,openai,clip]
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def smoke_vqgan(path: Path, outdir: Path):
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.pretrained_vae import VQGanVAE1024
    from dalle_pytorch_tpu.utils.images import save_image

    vae = VQGanVAE1024(weights_path=str(path))
    rng = np.random.default_rng(0)
    codes = jnp.asarray(rng.integers(0, vae.num_tokens, (1, 256)), jnp.int32)
    img = np.asarray(vae.decode(codes))
    assert img.shape == (1, 256, 256, 3) and np.isfinite(img).all(), img.shape
    save_image(outdir / "vqgan_smoke.png", img[0])
    # round-trip: encode the decode back to codes of the right range
    back = np.asarray(vae.get_codebook_indices(jnp.asarray(img)))
    assert back.shape == (1, 256) and 0 <= back.min() \
        and back.max() < vae.num_tokens
    print(f"vqgan: decode {img.shape} ok -> {outdir / 'vqgan_smoke.png'}")


def smoke_openai(path: Path, outdir: Path):
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.pretrained_vae import OpenAIDiscreteVAE
    from dalle_pytorch_tpu.utils.images import save_image

    vae = OpenAIDiscreteVAE(weights_path=str(path))
    rng = np.random.default_rng(0)
    codes = jnp.asarray(rng.integers(0, vae.num_tokens, (1, 1024)), jnp.int32)
    img = np.asarray(vae.decode(codes))
    assert img.shape == (1, 256, 256, 3) and np.isfinite(img).all(), img.shape
    save_image(outdir / "openai_smoke.png", img[0])
    back = np.asarray(vae.get_codebook_indices(jnp.asarray(img)))
    assert back.shape == (1, 1024) and 0 <= back.min() \
        and back.max() < vae.num_tokens
    print(f"openai: decode {img.shape} ok -> {outdir / 'openai_smoke.png'}")


def smoke_clip(path: Path, outdir: Path):
    import jax
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.clip_vit import CLIPViT, CLIPViTConfig
    from dalle_pytorch_tpu.utils.checkpoint import load_checkpoint

    ckpt = load_checkpoint(path)
    cfg = CLIPViTConfig.from_dict(dict(ckpt["hparams"]))
    model = CLIPViT(cfg)
    params = jax.tree.map(jnp.asarray, ckpt["weights"])
    rng = np.random.default_rng(0)
    image = jnp.asarray(rng.uniform(0, 1, (2, cfg.image_size, cfg.image_size,
                                           3)), jnp.float32)
    text = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, cfg.context_length)),
                       jnp.int32)
    logits_per_text, logits_per_image = model.apply({"params": params},
                                                    text, image)
    lt = np.asarray(logits_per_text)
    assert lt.shape == (2, 2) and np.isfinite(lt).all()
    print(f"clip: text/image logits {lt.shape} ok (ViT-B/32 geometry "
          f"{cfg.vision_width}x{cfg.vision_layers})")


def main(argv=None):
    # honor JAX_PLATFORMS=cpu over the sitecustomize-pinned tunnel plugin
    # BEFORE the smoke decodes touch a backend (BACKEND001 contract —
    # same order tools/chip_equiv.py uses)
    from dalle_pytorch_tpu.cli import apply_platform_env

    apply_platform_env()
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--dir", required=True,
                        help="directory holding the converted *.msgpack")
    parser.add_argument("--models", default="vqgan,openai,clip")
    args = parser.parse_args(argv)
    d = Path(args.dir)
    outdir = d / "smoke"
    outdir.mkdir(parents=True, exist_ok=True)
    runners = {"vqgan": (d / "vqgan_jax.msgpack", smoke_vqgan),
               "openai": (d / "openai_jax.msgpack", smoke_openai),
               "clip": (d / "clip_jax.msgpack", smoke_clip)}
    for name in args.models.split(","):
        name = name.strip()
        if name not in runners:
            raise SystemExit(f"unknown model '{name}': choose from "
                             f"{', '.join(runners)}")
        path, fn = runners[name]
        if not path.exists():
            raise SystemExit(f"{path} missing — run the convert stage first")
        fn(path, outdir)
    print("smoke ok")


if __name__ == "__main__":
    main()
