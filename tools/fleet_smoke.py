#!/usr/bin/env python
"""Fleet serving chaos smoke: prove zero dropped futures under replica loss.

The CI crash-resume job's fleet row (and the multi-process leg of
tests/test_router.py): build a tiny CPU model, stand up a
``FleetRouter`` over N in-process replicas — each with its OWN
graftscope stream under ``--out`` — inject a mid-decode replica kill
(``replica_down:at_tick``), push a request mix through, and exit 0 only
when:

* every submitted future resolved (result / ShedError / RouterError) —
  the zero-dropped-futures gate;
* the router's audit ledger balances with nothing outstanding;
* every successful result is BIT-IDENTICAL to the single-server
  greedy reference for its prompt;
* the live-buffer census returns to the post-warmup baseline once the
  router drains — the serve leak gate (obs/mem.py): a retire/evict
  path stashing an arena cache reference fails the run, not a pager.

Afterwards the streams replay as one fleet view::

    python tools/fleet_smoke.py --replicas 2 --requests 12 --kill-tick 40 \
        --out fleet-smoke
    python tools/obs_report.py --merge fleet-smoke/router \
        fleet-smoke/replica0 fleet-smoke/replica1
"""
from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dalle_pytorch_tpu.cli import apply_platform_env  # noqa: E402

# CPU smoke by contract: never let a wedged accelerator tunnel hang it
apply_platform_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from dalle_pytorch_tpu import DALLE, DALLEConfig, VAEConfig  # noqa: E402
from dalle_pytorch_tpu.models.dalle import (decode_codes,  # noqa: E402
                                            prefill_codes)
from dalle_pytorch_tpu.obs import mem as obs_mem  # noqa: E402
from dalle_pytorch_tpu.obs import metrics as obs_metrics  # noqa: E402
from dalle_pytorch_tpu.obs import telemetry  # noqa: E402
from dalle_pytorch_tpu.serve import (LATENCY, THROUGHPUT,  # noqa: E402
                                     FleetRouter, Replica, RouterError)
from dalle_pytorch_tpu.utils import faults, locks  # noqa: E402


def build_model():
    """The test_serve-scale toy: big enough to tick, small enough to
    compile in seconds on a CI box."""
    vcfg = VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                     num_layers=2, hidden_dim=8)
    cfg = DALLEConfig.from_vae(
        vcfg, dim=32, num_text_tokens=50, text_seq_len=6, depth=2, heads=2,
        dim_head=8, attn_types=("full", "axial_row"))
    dalle = DALLE(cfg)
    rng = jax.random.PRNGKey(0)
    texts = [np.asarray(jax.random.randint(
        jax.random.PRNGKey(i), (cfg.text_seq_len,), 1, 50), np.int32)
        for i in range(4)]
    codes = jax.random.randint(rng, (1, cfg.image_seq_len), 0, 32)
    params = dalle.init(rng, jnp.asarray(texts[0])[None], codes,
                        return_loss=True)
    return cfg, dalle, params, texts


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--requests", type=int, default=12)
    parser.add_argument("--slots", type=int, default=2,
                        help="slots per replica arena")
    parser.add_argument("--kill-tick", type=int, default=40,
                        help="replica_down:at_tick value (0 = no kill)")
    parser.add_argument("--out", type=Path, default=Path("fleet-smoke"),
                        help="output root: router/ + replicaN/ streams")
    parser.add_argument("--timeout", type=float, default=300.0,
                        help="bound on the whole drive (seconds)")
    parser.add_argument("--metrics_port", type=int, default=None,
                        help="optionally serve /metrics while running")
    parser.add_argument("--no-leak-gate", action="store_true",
                        help="skip the post-drain live-buffer leak check")
    args = parser.parse_args(argv)

    args.out.mkdir(parents=True, exist_ok=True)
    # graftrace witness: honors GRAFT_LOCK_WITNESS=1 (the CI fleet row
    # sets it); armed, every lock acquisition across router + replica
    # drivers feeds the order graph gated below
    if locks.armed():
        locks.reset()
        print("[fleet_smoke] graftrace lock-order witness armed")
    telemetry.init(args.out / "router", run_id="fleet-router")
    reg = obs_metrics.init()
    metrics_server = (obs_metrics.serve(args.metrics_port, reg)
                      if args.metrics_port is not None else None)

    cfg, dalle, params, texts = build_model()

    # single-server greedy references: the bit-match baseline
    prefill = jax.jit(lambda p, t: prefill_codes(dalle, p, t))
    refs = []
    for t in texts:
        fl, caches = prefill(params, jnp.asarray(t)[None])
        refs.append(np.asarray(decode_codes(
            dalle, params, fl, caches, jax.random.PRNGKey(7),
            filter_thres=1.0))[0])
    print(f"[fleet_smoke] references ready ({len(refs)} prompts)")

    faults.install(f"replica_down:at_tick={args.kill_tick}"
                   if args.kill_tick > 0 else "")
    replicas = [
        Replica(f"r{i}", dalle, params, args.slots,
                telemetry_dir=args.out / f"replica{i}", host_index=i + 1,
                warmup_text=texts[0], filter_thres=1.0)
        for i in range(args.replicas)]
    router = FleetRouter(
        replicas, retry_backoff_s=0.05, retry_backoff_cap_s=0.5,
        heartbeat_timeout_s=1.0, monitor_interval_s=0.02,
        probe_every_s=0.2,
        shed_bounds={LATENCY: 10_000, THROUGHPUT: 10_000}).start()
    router.wait_serving(args.replicas, timeout_s=args.timeout)
    # post-warmup census: every replica has prefilled + decoded once, so
    # the jit caches and arenas are resident — anything the chaos run
    # adds on top of THIS is a leak
    mem_tracker = obs_mem.MemTracker(emit=True)
    base = mem_tracker.baseline(phase="post-warmup")
    print(f"[fleet_smoke] leak-gate baseline: {base['live_count']} live "
          f"buffers / {base['live_bytes']} bytes")
    print(f"[fleet_smoke] {args.replicas} replicas serving; submitting "
          f"{args.requests} requests (kill-tick={args.kill_tick})")

    handles = []
    for i in range(args.requests):
        slo = LATENCY if i % 5 == 4 else THROUGHPUT
        handles.append(router.submit(texts[i % len(texts)], slo=slo))
        time.sleep(0.002)  # a trickle, so the kill lands mid-stream

    deadline = time.monotonic() + args.timeout
    dropped = 0
    mismatched = 0
    errors = 0
    for i, h in enumerate(handles):
        try:
            out = h.result(max(0.1, deadline - time.monotonic()))
            if not np.array_equal(out, refs[i % len(refs)]):
                mismatched += 1
        except RouterError:
            errors += 1  # typed resolution: counted, not a drop
        # graftlint: disable=EXC001 (the gate itself: ANY atypical resolution — timeout, untyped error — must count as a dropped future, and the exit code is the loud failure)
        except Exception:
            dropped += 1
    dropped += sum(not h.future.done() for h in handles)

    audit = router.audit()
    states = {n: r["state"] for n, r in router.stats()["replicas"].items()}
    router.close()
    # leak gate runs AFTER the router threads stop but BEFORE the
    # replicas release their arenas: against a baseline that includes
    # the arenas, a stashed per-request cache reference reads as pure
    # growth instead of hiding under the freed-arena bytes
    leak = None
    if not args.no_leak_gate:
        try:
            delta = mem_tracker.check_baseline("fleet-chaos")
            print(f"[fleet_smoke] leak gate: back to baseline "
                  f"(count delta {delta['count_delta']}, bytes delta "
                  f"{delta['bytes_delta']})")
        except obs_mem.LeakError as e:
            leak = str(e)
            print(f"[fleet_smoke] {e}", file=sys.stderr)
    for r in replicas:
        r.close()
    # lock-order witness gate: with GRAFT_LOCK_WITNESS=1 a cycle in the
    # observed acquisition graph fails the run even when this particular
    # interleaving never deadlocked; stats/graph land in metrics + stream
    lock_cycle = None
    if locks.armed():
        locks.publish_metrics()
        locks.emit_telemetry()
        try:
            locks.assert_acyclic()
            rep = locks.order_report()
            print(f"[fleet_smoke] lock witness: {len(rep['edges'])} order "
                  f"edge(s), acyclic")
        except locks.LockOrderError as e:
            lock_cycle = str(e)
            print(f"[fleet_smoke] {e}", file=sys.stderr)
    if metrics_server is not None:
        metrics_server.close()
    telemetry.shutdown()
    faults.reset()

    print(f"[fleet_smoke] audit: {audit}")
    print(f"[fleet_smoke] replica states: {states}")
    ok = (dropped == 0 and mismatched == 0 and audit["balanced"]
          and audit["outstanding"] == 0 and audit["resolved_ok"] > 0
          and (args.kill_tick == 0 or audit["replica_deaths"] >= 1)
          and leak is None and lock_cycle is None)
    if ok:
        print(f"[fleet_smoke] PASS: zero dropped futures "
              f"({audit['resolved_ok']} ok, {errors} typed errors, "
              f"{audit['shed']} shed, {audit['retries']} retries, "
              f"{audit['replica_deaths']} replica deaths), all completed "
              "results bit-match the single-server path")
        return 0
    print(f"[fleet_smoke] FAIL: dropped={dropped} mismatched={mismatched} "
          f"leak={'yes' if leak else 'no'} "
          f"lock_cycle={'yes' if lock_cycle else 'no'} audit={audit}",
          file=sys.stderr)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
