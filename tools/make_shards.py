#!/usr/bin/env python
"""Convert a folder dataset into tar shards + an index manifest.

The streaming input pipeline (``dalle_pytorch_tpu/data/stream.py``,
trainers' ``--data_format shards``) reads tar shards addressed by an
``index.json`` manifest; this tool builds both from the reference's folder
layouts:

* paired mode (default): ``*.txt`` captions matched to images by file stem,
  exactly the ``TextImageDataset`` pairing rule — for ``train_dalle.py``;
* ``--image_only``: every image in sorted-path order, the
  ``ImageFolderDataset`` rule — for ``train_vae.py``.

Samples keep the folder datasets' sort order and the tar metadata is
pinned, so the build is deterministic: the same folder always produces the
same shard bytes, the same per-shard crc32s, and therefore the same
shard-list fingerprint (the resume cursor's identity check).  Shard files
land via temp + atomic rename and the index publishes last — a crash
mid-build can leave temp files, never a readable-but-wrong shard set.

Usage:
    python tools/make_shards.py SRC_FOLDER OUT_DIR [--samples_per_shard N]
        [--image_only] [--verify]
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dalle_pytorch_tpu.data import stream  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("src", type=Path,
                        help="source folder (CUB layout: images + stem-"
                             "paired .txt captions, or images only with "
                             "--image_only)")
    parser.add_argument("out", type=Path,
                        help="output shard directory (shard-*.tar + "
                             "index.json)")
    parser.add_argument("--samples_per_shard", type=int, default=512,
                        help="samples per tar shard (default 512); use "
                             "enough shards that every training host owns "
                             "at least one")
    parser.add_argument("--image_only", action="store_true",
                        help="shard images without captions (train_vae's "
                             "diet; ImageFolderDataset sample order)")
    parser.add_argument("--verify", action="store_true",
                        help="after building, re-read every shard and "
                             "check it against the index's crc32")
    args = parser.parse_args(argv)

    index = stream.build_shards(args.src, args.out,
                                samples_per_shard=args.samples_per_shard,
                                image_only=args.image_only)
    fp = stream.shard_fingerprint(index["shards"])
    print(f"wrote {len(index['shards'])} shard(s), "
          f"{index['num_samples']} samples, "
          f"captions={index['has_captions']}, fingerprint={fp} "
          f"-> {args.out}")
    for s in index["shards"]:
        print(f"  {s['name']}: {s['count']} samples, {s['size']} bytes, "
              f"crc32 {s['crc32']}")
    if args.verify:
        stream.ShardIndex(args.out).verify()
        print("verify: every shard matches its recorded crc32")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
