#!/usr/bin/env python
"""External training-run monitor: scan heartbeat files for dead/stalled hosts.

The in-process side (``dalle_pytorch_tpu.utils.failure``) writes one
``heartbeat-p{process}.json`` per host into ``--heartbeat_dir``; this tool is
the babysitter that watches them from outside — e.g. under cron or a
supervisor loop — and exits non-zero when any host has gone quiet, so a
wrapper script can alert or restart the run.  (SURVEY.md §5.3: the reference
has no failure detection at all.)

Usage:
    python tools/monitor.py HEARTBEAT_DIR [--timeout 300] [--expect N] [--watch S]

Exit codes: 0 all hosts healthy, 1 stalled/missing hosts, 2 no heartbeats.
"""
from __future__ import annotations

import argparse
import re
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dalle_pytorch_tpu.cli import apply_platform_env  # noqa: E402
from dalle_pytorch_tpu.utils.failure import Heartbeat  # noqa: E402

# the monitor itself never needs a device, but an accidental backend
# query downstream must honor JAX_PLATFORMS=cpu instead of hanging on a
# pinned-but-down tunnel (BACKEND001 contract)
apply_platform_env()


def scan(directory: Path, timeout: float, expect: int | None) -> int:
    # filter the glob through the exact name pattern: a leftover temp/copy
    # like heartbeat-p0.json.bak or heartbeat-pX.json must be skipped, not
    # crash the babysitter
    files = sorted(
        (int(m.group(1)), p)
        for p in directory.glob("heartbeat-p*.json")
        if (m := re.fullmatch(r"heartbeat-p(\d+)", p.stem)))
    if not files:
        print(f"no heartbeat files in {directory}", file=sys.stderr)
        return 2

    now = time.time()
    bad = 0
    seen = set()
    for proc, path in files:
        seen.add(proc)
        stalled = Heartbeat.is_stalled(path, timeout, now=now)
        done = False
        try:
            info = Heartbeat.read(path)
            done = bool(info.get("done"))
            age = now - info["time"]
            detail = f"step {info.get('step', '?')} age {age:.0f}s"
        # graftlint: disable=EXC001 (a heartbeat mid-write is expected; any parse error = torn file, reported as status below)
        except Exception:
            detail = "unreadable (torn write?)"
        # a finished run's heartbeat ages forever — that's completion, not
        # death, and must not trigger an auto-restart wrapper
        status = "done" if done else ("STALLED" if stalled else "ok")
        print(f"process {proc}: {status} ({detail})")
        bad += stalled and not done

    if expect is not None:
        missing = set(range(expect)) - seen
        for proc in sorted(missing):
            print(f"process {proc}: MISSING (never wrote a heartbeat)")
        bad += len(missing)
    return 1 if bad else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("heartbeat_dir", type=Path)
    parser.add_argument("--timeout", type=float, default=300,
                        help="seconds without a beat before a host counts as "
                             "stalled (default 300)")
    parser.add_argument("--expect", type=int, default=None,
                        help="expected process count; missing heartbeat files "
                             "below this index are reported as failures")
    parser.add_argument("--watch", type=float, default=0,
                        help="re-scan every S seconds instead of exiting; "
                             "on ctrl-C/SIGINT exits with the last scan's "
                             "code")
    args = parser.parse_args(argv)

    code = 2
    try:
        while True:
            code = scan(args.heartbeat_dir, args.timeout, args.expect)
            if not args.watch:
                return code
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return code


if __name__ == "__main__":
    raise SystemExit(main())
