#!/usr/bin/env python
"""External training-run monitor: scan heartbeat files for dead/stalled hosts.

The in-process side (``dalle_pytorch_tpu.utils.failure``) writes one
``heartbeat-p{process}.json`` per host into ``--heartbeat_dir``; this tool is
the babysitter that watches them from outside — e.g. under cron or a
supervisor loop — and exits non-zero when any host has gone quiet, so a
wrapper script can alert or restart the run.  (SURVEY.md §5.3: the reference
has no failure detection at all.)

With ``--restart-cmd`` the monitor is a full babysitter: a stalled/dead
scan runs the command (typically the trainer relaunched with ``--resume
auto``, which resumes from the newest manifest-valid managed checkpoint,
falling back past torn ones), bounded by ``--max-restarts``.  When
``--ckpt-dir`` is given the restart only fires if that directory holds a
manifest-valid checkpoint, and ``{ckpt}`` in the command expands to its
payload path.  A foreground restart command that exits with the trainer's
``ExitCode.ROLLBACK_BUDGET`` (70) stops the babysitter immediately —
that code means automatic recovery will NOT converge (a human must read
the anomaly bundles), so burning the remaining restart budget on it would
just produce more bundles.  ``ExitCode.WEDGED`` (75, the hung-step
watchdog) is transient by definition and consumes one restart like any
other death.

The trainers ride their health extras (``loss``, ``grad_norm``,
``health_state`` — see utils/guardrails.py) on every heartbeat, and the
scan prints them, flagging non-finite values and non-``ok`` verdicts with
an ``UNHEALTHY`` marker — an operator sees a sick run here without
reading training logs.

Heartbeats carry ``run_id`` + ``telemetry_seq`` (the graftscope stream's
last event number); with ``--telemetry-dir`` a STALLED host's scan line is
followed by its last few telemetry records — what the run was *doing*
when it went quiet, not just that it did.

**Fleet mode** (``--fleet DIR1 DIR2 ...``): tail N hosts' telemetry dirs
instead of heartbeat files.  The scan aligns the streams onto one
timebase (obs/align.py — heartbeats and beacons carry the clock payload,
so a host that died between rotations still aligns), prints each lane's
clock offset + residual bound, its last event age, the ``alert`` events
already in its stream, and re-runs the declarative rules
(obs/alerts.py) offline over the tail so a condition that built up right
before a death still surfaces.  Exit 1 when any lane has active alerts
or a stale stream, 2 when nothing is readable.

With ``--metrics URL ...`` the fleet scan also scrapes each ``/metrics``
endpoint (a serve fleet's ``obs_metrics.serve`` port) and prints one
line per replica: lifecycle state (the one-hot
``graft_replica_state{replica,state}`` gauges the serve tier exports),
queue depth per SLO class, and slot occupancy — the live half of
``obs_report --merge``'s after-the-fact fleet view.  An unreachable
endpoint counts as a failed scan (exit 1); a DEAD replica is
informational (a rolled replica is supposed to be dead).

Usage:
    python tools/monitor.py HEARTBEAT_DIR [--timeout 300] [--expect N] [--watch S]
    python tools/monitor.py hb --watch 60 --ckpt-dir checkpoints \
        --telemetry-dir tel \
        --restart-cmd 'nohup python train_dalle.py --resume auto ... &'
    python tools/monitor.py --fleet telA telB --timeout 120

Exit codes (the ``ExitCode`` taxonomy in utils/failure.py): 0 all hosts
healthy, 1 stalled/missing hosts, 2 no heartbeats, 3 restart budget
exhausted (or nothing valid to restart from, or a terminal rc=70 from the
restarted trainer).
"""
from __future__ import annotations

import argparse
import re
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dalle_pytorch_tpu.cli import apply_platform_env  # noqa: E402
from dalle_pytorch_tpu.utils.failure import ExitCode, Heartbeat  # noqa: E402

# the monitor itself never needs a device, but an accidental backend
# query downstream must honor JAX_PLATFORMS=cpu instead of hanging on a
# pinned-but-down tunnel (BACKEND001 contract)
apply_platform_env()


def _health_flag(info: dict) -> str | None:
    """Operator-visible sickness from the health extras the trainers ride
    on every beat (guardrails.HealthMonitor.beat_extras): a non-``ok``
    verdict, or a non-finite loss/grad_norm (belt-and-braces — a verdict
    should already cover it, but a half-wired trainer must still flag)."""
    import math

    bits = []
    state = info.get("health_state")
    if state and state != "ok":
        bits.append(str(state))
    for key in ("loss", "grad_norm"):
        value = info.get(key)
        if value is not None and not math.isfinite(float(value)):
            bits.append(f"{key}={value}")
    return " ".join(bits) or None


def _telemetry_tail(telemetry_dir: Path, proc: int, run_id: str | None,
                    n: int = 5) -> list[str]:
    """The last ``n`` telemetry records for host ``proc`` (of ``run_id``
    when the heartbeat named one) — the "what was it doing when it
    stalled" answer, printed under a STALLED host's line."""
    from dalle_pytorch_tpu.obs.telemetry import read_events

    try:
        events = read_events(telemetry_dir)
    except OSError:
        return []
    rows = [r for r in events if r.get("host", 0) == proc
            and (run_id is None or r.get("run") == run_id)]
    out = []
    for r in rows[-n:]:
        bits = " ".join(f"{k}={r[k]}" for k in ("step", "ph", "msg")
                        if r.get(k) is not None)
        out.append(f"    seq {r.get('seq')} [{r.get('kind')}."
                   f"{r.get('name')}] {bits}")
    return out


def scan(directory: Path, timeout: float, expect: int | None,
         telemetry_dir: Path | None = None) -> int:
    # filter the glob through the exact name pattern: a leftover temp/copy
    # like heartbeat-p0.json.bak or heartbeat-pX.json must be skipped, not
    # crash the babysitter
    files = sorted(
        (int(m.group(1)), p)
        for p in directory.glob("heartbeat-p*.json")
        if (m := re.fullmatch(r"heartbeat-p(\d+)", p.stem)))
    if not files:
        print(f"no heartbeat files in {directory}", file=sys.stderr)
        return int(ExitCode.MONITOR_NO_HEARTBEATS)

    now = time.time()
    bad = 0
    seen = set()
    for proc, path in files:
        seen.add(proc)
        stalled = Heartbeat.is_stalled(path, timeout, now=now)
        done = False
        sick = None
        run_id = None
        try:
            info = Heartbeat.read(path)
            done = bool(info.get("done"))
            run_id = info.get("run_id")
            age = now - info["time"]
            detail = f"step {info.get('step', '?')} age {age:.0f}s"
            # run_id + telemetry_seq correlate this host with its event
            # stream: "run X stalled at telemetry seq N" is a greppable
            # coordinate, not a guess
            if run_id:
                detail += f" run {run_id}"
            if info.get("telemetry_seq") is not None:
                detail += f" tel_seq {info['telemetry_seq']}"
            # loader_stall_s rides every beat (DevicePrefetcher metering):
            # an input-bound host reads as "stall 2.3" here instead of
            # masquerading as a slow chip
            for key in ("loss", "grad_norm", "loader_stall_s"):
                if info.get(key) is not None:
                    detail += f" {key} {float(info[key]):.5g}"
            # the beat's compact memory snapshot (obs/mem.heartbeat_snapshot
            # via Heartbeat): a host creeping toward OOM shows its RSS/HBM
            # trajectory right here, before the stall — no stream parse
            for key in ("rss_mb", "hbm_used_mb", "hbm_peak_mb"):
                if info.get(key) is not None:
                    detail += f" {key} {float(info[key]):.0f}"
            sick = _health_flag(info)
        # graftlint: disable=EXC001 (a heartbeat mid-write is expected; any parse error = torn file, reported as status below)
        except Exception:
            detail = "unreadable (torn write?)"
        # a finished run's heartbeat ages forever — that's completion, not
        # death, and must not trigger an auto-restart wrapper
        status = "done" if done else ("STALLED" if stalled else "ok")
        flag = f"  << UNHEALTHY: {sick}" if sick and not done else ""
        print(f"process {proc}: {status} ({detail}){flag}")
        if stalled and not done and telemetry_dir is not None:
            tail = _telemetry_tail(telemetry_dir, proc, run_id)
            if tail:
                print(f"  last telemetry of process {proc}:")
                for line in tail:
                    print(line)
        bad += stalled and not done

    if expect is not None:
        missing = set(range(expect)) - seen
        for proc in sorted(missing):
            print(f"process {proc}: MISSING (never wrote a heartbeat)")
        bad += len(missing)
    return int(ExitCode.MONITOR_STALLED) if bad else int(ExitCode.CLEAN)


_METRIC_LINE_RE = re.compile(r"^(\w+)(?:\{(.*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _scrape_replica_metrics(url: str, timeout: float = 3.0
                            ) -> tuple[dict[str, dict], dict[str, dict],
                                       dict[str, float]]:
    """GET an endpoint's /metrics and fold the per-replica serve series
    into ``{replica: {state, queue: {slo: depth}, occupancy,
    bytes_per_token, hbm_headroom}}`` plus the graftrace witness series
    into ``{lock: {acquires, contended, wait_s, held_s, held_max_s}}``
    plus the router's live audit ledger (``graft_router_audit_*``
    gauges) into ``{field: value}``.  Only replica-labeled (serve) /
    lock-labeled (witness) / router-audit series participate (a
    single-server trainer's unlabeled gauges are not a fleet)."""
    import urllib.request

    target = url if "://" in url else f"http://{url}"
    if not target.rstrip("/").endswith("/metrics"):
        target = target.rstrip("/") + "/metrics"
    with urllib.request.urlopen(target, timeout=timeout) as resp:
        text = resp.read().decode("utf-8", "replace")
    out: dict[str, dict] = {}
    locks: dict[str, dict] = {}
    ledger: dict[str, float] = {}
    lock_fields = {
        "graft_lock_acquires_total": "acquires",
        "graft_lock_contended_total": "contended",
        "graft_lock_wait_seconds_total": "wait_s",
        "graft_lock_held_seconds_total": "held_s",
        "graft_lock_held_seconds_max": "held_max_s",
    }
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        m = _METRIC_LINE_RE.match(line)
        if not m:
            continue
        name, labelstr, value = m.groups()
        labels = dict(_LABEL_RE.findall(labelstr or ""))
        try:
            v = float(value)
        except ValueError:
            continue
        lk = labels.get("lock")
        if lk is not None and name in lock_fields:
            locks.setdefault(lk, {})[lock_fields[name]] = v
            continue
        if name.startswith("graft_router_audit_"):
            ledger[name[len("graft_router_audit_"):]
                   .removesuffix("_total")] = v
            continue
        rep = labels.get("replica")
        if rep is None:
            continue
        info = out.setdefault(rep, {"queue": {}})
        if name == "graft_replica_state" and v == 1.0:
            info["state"] = labels.get("state", "?")
        elif name == "graft_serve_queue_depth":
            info["queue"][labels.get("slo", "?")] = v
        elif name == "graft_serve_occupancy":
            info["occupancy"] = v
        elif name == "graft_serve_predicted_bytes_per_token":
            info["bytes_per_token"] = v
        elif name == "graft_hbm_headroom_bytes":
            info["hbm_headroom"] = v
    return out, locks, ledger


def _print_replica_metrics(urls: list[str]) -> int:
    """The per-replica serve-state lines of a fleet scan; returns the
    number of UNREACHABLE endpoints (scrape failures, not dead replicas)."""
    bad = 0
    for url in urls:
        try:
            reps, lock_stats, ledger = _scrape_replica_metrics(url)
        except OSError as e:
            print(f"metrics {url}: unreachable ({e})", file=sys.stderr)
            bad += 1
            continue
        if not reps and not lock_stats and not ledger:
            print(f"metrics {url}: no replica-labeled serve series")
            continue
        for name in sorted(reps):
            info = reps[name]
            state = info.get("state", "?")
            bits = [f"state {state}"]
            if info["queue"]:
                bits.append("queue " + ",".join(
                    f"{slo}={int(d)}"
                    for slo, d in sorted(info["queue"].items())))
            if info.get("occupancy") is not None:
                bits.append(f"occupancy {info['occupancy']:.2f}")
            if info.get("bytes_per_token") is not None:
                # the arena's cost-model HBM stream per decoded token
                # (scheduler.predicted_bytes_per_token): occupancy says how
                # busy a replica is, this says how heavy each token is
                bits.append(
                    f"pred {info['bytes_per_token'] / 2**20:.2f} MiB/tok")
            if info.get("hbm_headroom") is not None:
                # measured HBM headroom (scheduler watermark gauge) beside
                # the predicted byte stream: "how heavy is a token" and
                # "how close is this replica to OOM" read on one line
                bits.append(
                    f"hbm headroom {info['hbm_headroom'] / 2**20:.0f} MiB")
            flag = "  << DOWN" if state == "dead" else ""
            print(f"replica {name} [{url}]: {' '.join(bits)}{flag}")
        if ledger:
            # the router's live audit ledger (graftscale's input signals):
            # submitted == ok + err + shed + outstanding, and "balanced"
            # says the invariant held at scrape time
            fields = ["submitted", "ok", "err", "shed", "outstanding"]
            bits = [f"{f}={int(ledger[f])}" for f in fields if f in ledger]
            bal = ledger.get("balanced")
            if bal is not None:
                bits.append("balanced" if bal >= 1.0 else "UNBALANCED")
            print(f"router ledger [{url}]: {' '.join(bits)}")
        if lock_stats:
            # graftrace witness rollup: the top held-time locks tell you
            # WHERE serialization lives; contended acquires tell you who
            # is paying for it
            top = sorted(lock_stats.items(),
                         key=lambda kv: -kv[1].get("held_s", 0.0))[:5]
            contended = sum(int(st.get("contended", 0))
                            for st in lock_stats.values())
            print(f"locks [{url}]: {len(lock_stats)} witnessed, "
                  f"{contended} contended acquires")
            for lk, st in top:
                print(f"  lock {lk}: {int(st.get('acquires', 0))} acquires "
                      f"({int(st.get('contended', 0))} contended, wait "
                      f"{st.get('wait_s', 0.0):.3f}s), held "
                      f"{st.get('held_s', 0.0):.3f}s total / "
                      f"{st.get('held_max_s', 0.0) * 1e3:.1f}ms max")
    return bad


def fleet_scan(dirs: list[Path], timeout: float, window: float = 300.0,
               metrics_urls: list[str] | None = None) -> int:
    """One fleet-mode scan over N telemetry dirs: align, tail, alert —
    plus the live per-replica serve state when ``metrics_urls`` name
    scrapeable endpoints."""
    import time as _time

    from dalle_pytorch_tpu.obs import merge_streams
    from dalle_pytorch_tpu.obs.alerts import AlertEngine

    events, clocks = merge_streams(dirs)
    if not events:
        print(f"no readable events under {[str(d) for d in dirs]}",
              file=sys.stderr)
        return int(ExitCode.MONITOR_NO_HEARTBEATS)
    now = _time.time()
    by_lane: dict[int, list[dict]] = {}
    for r in events:
        by_lane.setdefault(int(r.get("host", 0)), []).append(r)
    bad = 0
    for clock in clocks:
        lane = by_lane.get(clock.lane, [])
        last = lane[-1] if lane else None
        # ages compare FLEET time to this box's clock: the solved offset
        # has already removed the host's skew, so "age" means what it says
        age = (now - float(last["t"])) if last and last.get("t") else None
        stale = age is not None and age > timeout
        steps = [r for r in lane if r.get("kind") == "step"
                 and "ph" not in r and r.get("step") is not None]
        last_step = max((int(r["step"]) for r in steps), default=None)
        # alerts already in the stream (the in-process engine fired) ...
        recent_alerts = sorted({
            str(r.get("name")) for r in lane if r.get("kind") == "alert"
            and r.get("t") is not None and now - float(r["t"]) <= window})
        # ... plus an offline re-run over the tail, so a condition that
        # built up right before a death still surfaces here
        engine = AlertEngine()
        for r in lane:
            for fired in engine.observe(r):
                recent_alerts = sorted(set(recent_alerts)
                                       | {fired["rule"]})
        bound = clock.bound
        status = "STALE" if stale else "ok"
        print(f"lane {clock.lane} [{clock.run} host {clock.orig_host}]: "
              f"{status} (last event "
              f"{'-' if age is None else f'{age:.0f}s'} ago, step "
              f"{last_step}, clock offset {clock.offset:+.3f}s "
              f"±{'?' if bound is None else f'{bound:.3f}'} "
              f"[{clock.method}])")
        if recent_alerts:
            print(f"  ALERTS: {', '.join(recent_alerts)}")
        bad += stale or bool(recent_alerts)
    if metrics_urls:
        bad += _print_replica_metrics(metrics_urls)
    return int(ExitCode.MONITOR_STALLED) if bad else int(ExitCode.CLEAN)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("heartbeat_dir", type=Path, nargs="?", default=None)
    parser.add_argument("--fleet", nargs="+", type=Path, default=None,
                        metavar="TEL_DIR",
                        help="fleet mode: scan N telemetry dirs (one per "
                             "host) instead of heartbeat files — aligned "
                             "clock offsets, last-event ages, active "
                             "alerts per host")
    parser.add_argument("--metrics", nargs="+", type=str, default=None,
                        metavar="URL",
                        help="fleet mode add-on: scrape each /metrics "
                             "endpoint and print per-replica serve state "
                             "(lifecycle, queue depth per SLO class, "
                             "occupancy); an unreachable endpoint counts "
                             "as a failed scan")
    parser.add_argument("--timeout", type=float, default=300,
                        help="seconds without a beat before a host counts as "
                             "stalled (default 300)")
    parser.add_argument("--expect", type=int, default=None,
                        help="expected process count; missing heartbeat files "
                             "below this index are reported as failures")
    parser.add_argument("--watch", type=float, default=0,
                        help="re-scan every S seconds instead of exiting; "
                             "on ctrl-C/SIGINT exits with the last scan's "
                             "code")
    parser.add_argument("--restart-cmd", type=str, default=None,
                        help="shell command to run when a scan reports "
                             "stalled/dead hosts (exit 1) — typically the "
                             "trainer relaunched with --resume auto; "
                             "'{ckpt}' expands to the newest valid managed "
                             "checkpoint's payload path when --ckpt-dir is "
                             "given")
    parser.add_argument("--max-restarts", type=int, default=3,
                        help="restart budget: stop restarting (exit 3) "
                             "after this many attempts")
    parser.add_argument("--ckpt-dir", type=Path, default=None,
                        help="managed checkpoint run dir; restarts only "
                             "fire when it holds a manifest-valid "
                             "checkpoint (latest_valid fallback semantics)")
    parser.add_argument("--restart-plan", type=str, default=None,
                        help="elastic relaunch: append '--plan SPEC' to "
                             "--restart-cmd so the restarted trainer "
                             "reshards its resume onto a DIFFERENT "
                             "parallelism plan / topology (e.g. the "
                             "smaller pod the scheduler granted after a "
                             "preemption); checkpoint manifests record "
                             "the written-under plan, the restore "
                             "reshards by construction")
    parser.add_argument("--telemetry-dir", type=Path, default=None,
                        help="graftscope events dir (the trainer's "
                             "--telemetry_dir): a STALLED host's last "
                             "events are printed under its scan line, so "
                             "the report says WHAT it was doing, not just "
                             "that it stopped")
    args = parser.parse_args(argv)

    if args.fleet:
        code = int(ExitCode.MONITOR_NO_HEARTBEATS)
        try:
            while True:
                code = fleet_scan(args.fleet, args.timeout,
                                  metrics_urls=args.metrics)
                if not args.watch:
                    return code
                time.sleep(args.watch)
        except KeyboardInterrupt:
            return code
    if args.heartbeat_dir is None:
        parser.error("heartbeat_dir is required (or use --fleet)")

    def try_restart(restarts: int) -> int | None:
        """Run --restart-cmd once; returns an exit code to stop with, or
        None to keep watching."""
        if restarts >= args.max_restarts:
            print(f"restart budget exhausted ({args.max_restarts}); "
                  "giving up", file=sys.stderr)
            return int(ExitCode.RESTART_BUDGET)
        cmd = args.restart_cmd
        if args.ckpt_dir is not None:
            from dalle_pytorch_tpu.utils.ckpt_manager import latest_valid

            info = latest_valid(args.ckpt_dir)
            if info is None:
                print(f"no manifest-valid checkpoint under {args.ckpt_dir}; "
                      "nothing to restart from", file=sys.stderr)
                return int(ExitCode.RESTART_BUDGET)
            cmd = cmd.replace("{ckpt}", str(info.payload))
            written = (info.manifest.get("plan") or {}).get("spec")
            if written and args.restart_plan \
                    and written != args.restart_plan:
                print(f"elastic restart: checkpoint written under plan "
                      f"{written}; relaunching under --plan "
                      f"{args.restart_plan} (restore reshards on load)",
                      file=sys.stderr)
        if args.restart_plan:
            # '{plan}' in the command places the spec explicitly (compound
            # commands, backgrounded trainers); otherwise the flag pair is
            # appended
            if "{plan}" in cmd:
                cmd = cmd.replace("{plan}", args.restart_plan)
            else:
                cmd = f"{cmd} --plan {args.restart_plan}"
        print(f"restart {restarts + 1}/{args.max_restarts}: {cmd}",
              file=sys.stderr)
        rc = subprocess.run(cmd, shell=True).returncode
        if rc == int(ExitCode.ROLLBACK_BUDGET):
            # terminal by contract: the trainer's anomaly-recovery ladder
            # gave up — a relaunch reruns the same divergence, so stop
            # here instead of burning the rest of the budget on it
            print(f"restarted trainer exited {rc} (rollback budget "
                  "exhausted) — terminal, a human must read the anomaly "
                  "bundles; giving up", file=sys.stderr)
            return int(ExitCode.RESTART_BUDGET)
        if rc == int(ExitCode.WEDGED):
            print(f"restarted trainer exited {rc} (hung-step watchdog) — "
                  "transient, will relaunch on the next stalled scan",
                  file=sys.stderr)
        if rc == int(ExitCode.PREEMPT_EXPIRED):
            print(f"restarted trainer exited {rc} (preemption grace window "
                  "expired mid-save) — transient, the last committed "
                  "manifest resumes it on the next stalled scan",
                  file=sys.stderr)
        return None

    code = int(ExitCode.MONITOR_NO_HEARTBEATS)
    restarts = 0
    try:
        while True:
            code = scan(args.heartbeat_dir, args.timeout, args.expect,
                        telemetry_dir=args.telemetry_dir)
            if args.restart_cmd and code == int(ExitCode.MONITOR_STALLED):
                stop = try_restart(restarts)
                if stop is not None:
                    return stop
                restarts += 1
            if not args.watch:
                return code
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return code


if __name__ == "__main__":
    raise SystemExit(main())
