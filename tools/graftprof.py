#!/usr/bin/env python
"""graftprof: per-module roofline attribution + the committed perf ledger.

Walks the jaxpr of every ``training.STEP_FACTORIES`` entry under its
parallelism plans — plus the decode scan and the serving arena tick — at
the production CUB geometry, attributes analytic flops / bytes to the
``graftprof:`` cost scopes threaded through the models
(``dalle_pytorch_tpu/obs/prof.py``), folds in the chip-spec roofline
(v4-8 / v5e-4), and maintains the committed ``PERF_LEDGER.json``:
config fingerprint -> per-scope flops/bytes -> predicted MFU ceiling.

Chip-free by construction (the same 8-device virtual CPU mesh as
``tools/spmd_check.py``, whose harness this reuses): every number here is
computable on a laptop while the TPU tunnel is wedged — exactly when the
perf trajectory question comes up.

Modes:
    --update   recompute all rows, merge (preserving measured history),
               write the ledger
    --check    recompute and diff against the committed ledger — the CI
               drift gate: exit 1 on >2% flops / >5% bytes drift without
               a ledger update
    --report   read-only predicted-vs-measured table from the ledger
               (no jax work; runs on a wedged box)
    --quick    tiny geometry instead of CUB (tests / smoke)
    --targets  substring filter over target names
    --json     machine-readable output next to the human table

Shard-map plans (sp-ring / sp-ulysses / pp) trace one shard's program;
their walker numbers are scaled by the mesh device count to recover the
global figures — an approximation (ring exchanges and the pipeline
bubble are not charged), held stable by construction so the drift gate
stays exact.

Usage:
    python tools/graftprof.py --update
    python tools/graftprof.py --check            # CI
    python tools/graftprof.py --report
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# spmd_check owns the chip-free env preamble (CPU backend + 8 virtual
# devices BEFORE jax initializes) and the plan/geometry harness; load it
# as a module (tools/ is not a package).
_spec = importlib.util.spec_from_file_location(
    "spmd_check", Path(__file__).resolve().parent / "spmd_check.py")
spmd_check = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(spmd_check)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from dalle_pytorch_tpu.lint import spmd  # noqa: E402
from dalle_pytorch_tpu.models.clip import CLIP, CLIPConfig  # noqa: E402
from dalle_pytorch_tpu.models.dalle import DALLE  # noqa: E402
from dalle_pytorch_tpu.models.vae import DiscreteVAE, VAEConfig  # noqa: E402
from dalle_pytorch_tpu.obs import prof  # noqa: E402
from dalle_pytorch_tpu.parallel.mesh import make_mesh  # noqa: E402
from dalle_pytorch_tpu.serve.engine import SlotArena  # noqa: E402
from dalle_pytorch_tpu.training import (make_clip_train_step,  # noqa: E402
                                        make_dalle_pp_train_step,
                                        make_dalle_sp_train_step,
                                        make_dalle_train_step, make_optimizer,
                                        make_vae_train_step)

PLANS = spmd_check.PLANS
CHIP = "v4-8"          # the pod the roofline is rendered against
TRAIN_BATCH = 8        # spmd_check's harness batch (pp microbatch law)
DECODE_BATCH = 8
SERVE_SLOTS = 8
_sds = spmd_check._sds


def _cfg_payload(cfg, **extra) -> dict:
    """Fingerprint payload of one geometry: the dataclass fields (dtype
    et al. stringified by row_fingerprint's canonical JSON) + the sweep
    knobs.  A measured run hashes the SAME payload to land beside its
    prediction — the one shared implementation lives in obs.prof."""
    return prof.fingerprint_payload(cfg, **extra)


def _compiled_stats(lowered, arg_labels=None, donate=(0, 1)) -> dict:
    """XLA's own numbers for a lowered program at OPT0 (the spmd_check S4
    convention: buffer assignment matches the full pipeline, compile is
    cheap).  ``donated_bytes`` substitutes the donation-audit fraction
    for the alias stat opt0 zeroes (the _s4_detail substitution) — the
    field the dropped-donation twin trips."""
    with spmd.fresh_stats_compile():
        compiled = lowered.compile(spmd_check.OPT0)
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    est = spmd.hbm_estimate(compiled)
    out = {
        "flops": int(ca.get("flops", 0.0)),
        "bytes_accessed": int(ca.get("bytes accessed", 0.0)),
        "argument_bytes": est.argument_bytes,
        "output_bytes": est.output_bytes,
        "temp_bytes": est.temp_bytes,
    }
    if arg_labels is not None:
        audit = spmd.audit_donation(lowered, arg_labels, donate)
        out["donated_bytes"] = int(audit.donated_fraction
                                   * est.argument_bytes)
    return out


def _traffic(compiled_stats) -> int:
    """Per-device HBM stream of one step for the roofline byte-time:
    arguments + outputs + temps of the compiled program (opt0-stable)."""
    return (compiled_stats["argument_bytes"] + compiled_stats["output_bytes"]
            + compiled_stats["temp_bytes"])


# --- per-target builders ---------------------------------------------------


def _dalle_plan_row(plan: str, make_cfg) -> dict:
    """One DALLE train-step row: jaxpr attribution (scaled to global
    figures under shard_map plans) + opt0 compiled stats."""
    spec = PLANS[plan]
    cfg = make_cfg(**spec["plan"])
    dalle = DALLE(cfg)
    tx = make_optimizer(1e-3)
    mesh = make_mesh(**spec["mesh"])
    devices = 1
    for n in spec["mesh"].values():
        devices *= int(n)
    text = _sds((TRAIN_BATCH, cfg.text_seq_len), jnp.int32)
    codes = _sds((TRAIN_BATCH, cfg.image_seq_len), jnp.int32)
    rng = _sds((2,), jnp.uint32)
    fs = _sds((), jnp.float32)
    params = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                            codes)["params"]
    if plan == "pp":
        step, pp_params = make_dalle_pp_train_step(
            dalle, tx, spmd_check._zeros_like_tree(params), mesh,
            num_microbatches=2, health=True)
        opt = jax.eval_shape(tx.init, pp_params)
        args = (pp_params, opt, None, text, codes, rng, fs)
        per_shard = True
    elif cfg.ring_axis is not None:
        step = make_dalle_sp_train_step(dalle, tx, mesh, health=True)
        opt = jax.eval_shape(tx.init, params)
        args = (params, opt, None, text, codes, rng, fs)
        per_shard = True
    else:
        step = make_dalle_train_step(dalle, tx, health=True)
        opt = jax.eval_shape(tx.init, params)
        args = (params, opt, None, text, codes, rng, fs)
        per_shard = False
    attr = prof.attribute(jax.make_jaxpr(step)(*args),
                          scale=devices if per_shard else 1)
    factory = ("dalle_pp" if plan == "pp"
               else "dalle_sp" if cfg.ring_axis is not None else "dalle")
    target = f"{factory}/{plan}"
    prof.check_coverage(attr, label=target)
    compiled = _compiled_stats(spmd_check.dalle_step_lowered(
        plan, make_cfg=make_cfg, batch=TRAIN_BATCH),
        arg_labels=spmd_check.DALLE_ARG_LABELS)
    roof = prof.roofline(attr, CHIP, traffic_bytes=_traffic(compiled))
    config = _cfg_payload(cfg, target=target, plan=plan, batch=TRAIN_BATCH)
    return prof.predicted_row(target=target, plan=plan, chip=CHIP,
                              config=config, attr=attr, roof=roof,
                              compiled=compiled)


def _scale_row(plan: str) -> dict:
    """A scale rung's row (presets.SCALE_PRESETS geometry under its
    registry plan): walker-only — no opt0 compile (dim-512 compiles for
    ~8 minutes, dim-1024 longer; the full S4 proof is ``spmd_check
    --presets``' nightly concern, cached in S4_PROOFS.json), the same
    carve-out as the decode row.  The memory twin in ``tools/graftmem.py``
    gives each rung its binding headroom verdict."""
    from dalle_pytorch_tpu.presets import preset_config

    cfg = preset_config(plan)
    dalle = DALLE(cfg)
    tx = make_optimizer(1e-3)
    text = _sds((TRAIN_BATCH, cfg.text_seq_len), jnp.int32)
    codes = _sds((TRAIN_BATCH, cfg.image_seq_len), jnp.int32)
    rng = _sds((2,), jnp.uint32)
    fs = _sds((), jnp.float32)
    params = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                            codes)["params"]
    opt = jax.eval_shape(tx.init, params)
    step = make_dalle_train_step(dalle, tx, health=True)
    attr = prof.attribute(jax.make_jaxpr(step)(params, opt, None, text,
                                               codes, rng, fs))
    target = f"dalle/{plan}"
    prof.check_coverage(attr, label=target)
    roof = prof.roofline(attr, CHIP)
    config = _cfg_payload(cfg, target=target, plan=plan, batch=TRAIN_BATCH)
    return prof.predicted_row(target=target, plan=plan, chip=CHIP,
                              config=config, attr=attr, roof=roof)


def _vae_cfg(quick: bool) -> VAEConfig:
    if quick:
        return VAEConfig(image_size=16, num_tokens=16, codebook_dim=16,
                         num_layers=1, hidden_dim=16)
    # bench.py::vae128_config — the reference stage-1 geometry
    return VAEConfig(image_size=128, num_tokens=8192, codebook_dim=512,
                     num_layers=2, num_resnet_blocks=2, hidden_dim=256)


def _vae_row(quick: bool) -> dict:
    cfg = _vae_cfg(quick)
    vae = DiscreteVAE(cfg)
    tx = make_optimizer(1e-3)
    images = _sds((TRAIN_BATCH, cfg.image_size, cfg.image_size, 3),
                  jnp.float32)
    rng = _sds((2,), jnp.uint32)
    temp = _sds((), jnp.float32)
    fs = _sds((), jnp.float32)
    params = jax.eval_shape(
        lambda im: vae.init(jax.random.PRNGKey(0), im,
                            rng=jax.random.PRNGKey(1)), images)["params"]
    opt = jax.eval_shape(tx.init, params)
    step = make_vae_train_step(vae, tx, health=True)
    args = (params, opt, images, rng, temp, fs)
    attr = prof.attribute(jax.make_jaxpr(step)(*args))
    prof.check_coverage(attr, label="vae")
    compiled = _compiled_stats(step.lower(*args),
                               arg_labels=spmd_check.VAE_ARG_LABELS)
    roof = prof.roofline(attr, CHIP, traffic_bytes=_traffic(compiled),
                         devices=1)
    config = _cfg_payload(cfg, target="vae", plan="single",
                          batch=TRAIN_BATCH)
    return prof.predicted_row(target="vae", plan="single", chip=CHIP,
                              config=config, attr=attr, roof=roof,
                              compiled=compiled)


def _clip_cfg(quick: bool) -> CLIPConfig:
    if quick:
        return CLIPConfig(dim_text=16, dim_image=16, dim_latent=16,
                          num_text_tokens=64, text_enc_depth=1,
                          text_seq_len=8, text_heads=2,
                          num_visual_tokens=64, visual_enc_depth=1,
                          visual_heads=2, visual_image_size=16,
                          visual_patch_size=8)
    # the CUB-shaped ViT-B/32 ranker geometry (bench.py genrank stand-in)
    return CLIPConfig(dim_text=256, dim_image=256, dim_latent=256,
                      num_text_tokens=7800, text_enc_depth=4,
                      text_seq_len=80, text_heads=8, num_visual_tokens=512,
                      visual_enc_depth=6, visual_heads=8,
                      visual_image_size=224, visual_patch_size=32)


def _clip_row(quick: bool) -> dict:
    cfg = _clip_cfg(quick)
    clip = CLIP(cfg)
    tx = make_optimizer(1e-3)
    text = _sds((TRAIN_BATCH, cfg.text_seq_len), jnp.int32)
    images = _sds((TRAIN_BATCH, cfg.visual_image_size,
                   cfg.visual_image_size, 3), jnp.float32)
    mask = _sds((TRAIN_BATCH, cfg.text_seq_len), jnp.bool_)
    fs = _sds((), jnp.float32)
    params = jax.eval_shape(
        lambda t, im, m: clip.init(jax.random.PRNGKey(0), t, im,
                                   text_mask=m), text, images,
        mask)["params"]
    opt = jax.eval_shape(tx.init, params)
    step = make_clip_train_step(clip, tx, health=True)
    args = (params, opt, text, images, mask, fs)
    # the CLIP towers carry no graftprof scopes of their own yet — the
    # whole model is one "clip" cost center (embed/logits taxonomy is a
    # DALLE/VAE concern); default_scope keeps the coverage gate honest
    attr = prof.attribute(jax.make_jaxpr(step)(*args),
                          default_scope="clip")
    prof.check_coverage(attr, label="clip")
    compiled = _compiled_stats(step.lower(*args),
                               arg_labels=spmd_check.CLIP_ARG_LABELS)
    roof = prof.roofline(attr, CHIP, traffic_bytes=_traffic(compiled),
                         devices=1)
    config = _cfg_payload(cfg, target="clip", plan="single",
                          batch=TRAIN_BATCH)
    return prof.predicted_row(target="clip", plan="single", chip=CHIP,
                              config=config, attr=attr, roof=roof,
                              compiled=compiled)


def _decode_row(make_cfg) -> dict:
    """The sampling scan (prefill state -> full image code sequence) —
    spmd_check's decode harness, attributed per scope.  No compile (the
    1000-step scan at CUB is jaxpr-walkable in seconds but minutes to
    compile); the roofline reads the walker bytes."""
    jaxpr = spmd_check.decode_jaxpr(make_cfg=make_cfg, batch=DECODE_BATCH)
    attr = prof.attribute(jaxpr)
    prof.check_coverage(attr, label="decode")
    roof = prof.roofline(attr, CHIP, devices=1)
    cfg = make_cfg()
    config = _cfg_payload(cfg, target="decode", plan="single",
                          batch=DECODE_BATCH)
    return prof.predicted_row(target="decode", plan="single", chip=CHIP,
                              config=config, attr=attr, roof=roof)


def _serve_tick_row(make_cfg) -> dict:
    """One continuous-batching arena tick (serve/engine.py), all slots
    advancing.  The row carries ``serve.predicted_bytes_per_token`` —
    the number GenerationServer.stats() / the /metrics serve instruments
    export."""
    cfg = make_cfg()
    dalle = DALLE(cfg)
    text = jnp.zeros((1, cfg.text_seq_len), jnp.int32)
    codes = jnp.zeros((1, cfg.image_seq_len), jnp.int32)
    variables = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                               codes)
    # a real SlotArena on zeroed params — the tick jaxpr IS the serving
    # program (same closure GenerationServer jits), every slot advancing
    arena = SlotArena(
        dalle, jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            variables),
        num_slots=SERVE_SLOTS)
    active = jnp.ones((SERVE_SLOTS,), bool)
    write_pos = jnp.int32(0)
    jaxpr = jax.make_jaxpr(arena._tick)(
        arena.variables, arena.state, active, write_pos, arena._qweights)
    attr = prof.attribute(jaxpr)
    prof.check_coverage(attr, label="serve-tick")
    roof = prof.roofline(attr, CHIP, devices=1)
    config = _cfg_payload(cfg, target="serve-tick", plan="single",
                          batch=SERVE_SLOTS, num_slots=SERVE_SLOTS)
    row = prof.predicted_row(target="serve-tick", plan="single", chip=CHIP,
                             config=config, attr=attr, roof=roof)
    row["serve"] = {"num_slots": SERVE_SLOTS,
                    "predicted_bytes_per_token":
                        prof.predicted_serve_bytes_per_token(cfg,
                                                             SERVE_SLOTS)}
    return row


def _decode_spec_row(make_cfg) -> dict:
    """The SELF-SPECULATIVE sampling loop (models/dalle.py::
    _decode_codes_spec — shallow drafts + one K-wide verify per
    iteration), attributed per scope; the loop body is a while_loop so
    the walker's figures are per-iteration-shaped rather than
    whole-scan — held stable by construction, which is all the drift
    gate needs.  The row carries the cost-model speedup
    (``prof.predicted_spec_speedup``): bytes/token divides by the
    accepted span length at the price of the draft-fraction overhead."""
    cfg = make_cfg(spec_decode=True, spec_k=4, spec_draft_depth=1)
    dalle = DALLE(cfg)
    from dalle_pytorch_tpu.models.dalle import decode_codes

    text = _sds((DECODE_BATCH, cfg.text_seq_len), jnp.int32)
    codes = _sds((DECODE_BATCH, cfg.image_seq_len), jnp.int32)
    variables = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                               codes)
    logits, kvs = jax.eval_shape(
        lambda v, t: dalle.apply(v, t, method=DALLE.prefill), variables,
        text)
    rng = _sds((2,), jnp.uint32)
    jaxpr = jax.make_jaxpr(
        lambda v, fl, c, r: decode_codes(dalle, v, fl, c, r))(
            variables, logits, kvs, rng)
    attr = prof.attribute(jaxpr)
    prof.check_coverage(attr, label="decode-spec")
    roof = prof.roofline(attr, CHIP, devices=1)
    config = _cfg_payload(cfg, target="decode-spec", plan="single",
                          batch=DECODE_BATCH)
    row = prof.predicted_row(target="decode-spec", plan="single", chip=CHIP,
                             config=config, attr=attr, roof=roof)
    row["spec"] = prof.predicted_spec_speedup(cfg)
    return row


def _serve_spec_row(make_cfg) -> dict:
    """One SPECULATIVE arena tick (serve/engine.py tick_spec: K-1 shallow
    drafts + the K-wide verify), all slots advancing.  Beside the scope
    attribution the row carries the serving cost model: the greedy
    bytes/token divided by the expected accepted-K, against the
    draft-stream overhead (``prof.predicted_spec_speedup``)."""
    cfg = make_cfg(spec_decode=True, spec_k=4, spec_draft_depth=1)
    dalle = DALLE(cfg)
    text = jnp.zeros((1, cfg.text_seq_len), jnp.int32)
    codes = jnp.zeros((1, cfg.image_seq_len), jnp.int32)
    variables = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                               codes)
    arena = SlotArena(
        dalle, jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            variables),
        num_slots=SERVE_SLOTS)
    active = jnp.ones((SERVE_SLOTS,), bool)
    jaxpr = jax.make_jaxpr(arena._tick_spec)(
        arena.variables, arena.state, active, arena._qweights)
    attr = prof.attribute(jaxpr)
    prof.check_coverage(attr, label="serve-spec")
    roof = prof.roofline(attr, CHIP, devices=1)
    config = _cfg_payload(cfg, target="serve-spec", plan="single",
                          batch=SERVE_SLOTS, num_slots=SERVE_SLOTS)
    row = prof.predicted_row(target="serve-spec", plan="single", chip=CHIP,
                             config=config, attr=attr, roof=roof)
    model = prof.predicted_spec_speedup(cfg)
    bpt = prof.predicted_serve_bytes_per_token(cfg, SERVE_SLOTS)
    row["spec"] = dict(
        model,
        greedy_bytes_per_token=bpt,
        predicted_bytes_per_token=int(
            bpt * model["stream_overhead"] / model["assumed_accepted_k"]),
        num_slots=SERVE_SLOTS)
    return row


# --- sweep -----------------------------------------------------------------


def sweep(quick: bool = False, targets_filter=None) -> dict:
    """Recompute every predicted row.  Returns {fingerprint: row}."""
    make_cfg = spmd_check.tiny_config if quick else spmd_check.cub_config
    builders = []
    for plan in PLANS:
        builders.append((f"dalle/{plan}",
                         lambda p=plan: _dalle_plan_row(p, make_cfg)))
    if not quick:
        # the scale rungs ride the full sweep only (their point is the
        # real dim-512/dim-1024 geometry; quick twins would fingerprint
        # apart)
        builders.append(("dalle/cub-512", lambda: _scale_row("cub-512")))
        builders.append(("dalle/cub-1024", lambda: _scale_row("cub-1024")))
    builders.append(("vae", lambda: _vae_row(quick)))
    builders.append(("clip", lambda: _clip_row(quick)))
    builders.append(("decode", lambda: _decode_row(make_cfg)))
    builders.append(("serve-tick", lambda: _serve_tick_row(make_cfg)))
    # graftspec (ISSUE 16): labels deliberately avoid the "serve-tick"
    # substring so --targets serve-tick keeps selecting exactly one row
    builders.append(("decode-spec", lambda: _decode_spec_row(make_cfg)))
    builders.append(("serve-spec", lambda: _serve_spec_row(make_cfg)))

    rows = {}
    for label, build in builders:
        if targets_filter and not any(t in label for t in targets_filter):
            continue
        row = build()
        rows[row["fingerprint"]] = row
        roof = row["roofline"]
        print(f"  {row['target']:>18} [{row['plan']}] "
              f"fp={row['fingerprint']} "
              f"pred_mfu={roof['predicted_mfu']:.3f} "
              f"bound={roof['bound']} "
              f"residual f={row['residual']['flops']:.1%} "
              f"b={row['residual']['bytes']:.1%}")
    return rows


# --- report ----------------------------------------------------------------


def render_report(ledger: dict) -> str:
    """Predicted-vs-measured in one table (read-only: no jax work)."""
    head = (f"{'target':>18} {'plan':>10} {'fp':>12} {'pred mfu':>8} "
            f"{'bound':>5} {'measured':>24} {'gap':>6}")
    lines = ["graftprof ledger report", head, "-" * len(head)]
    for fp, row in sorted(ledger.get("rows", {}).items(),
                          key=lambda kv: (kv[1].get("target", ""),
                                          kv[1].get("plan", ""))):
        roof = row.get("roofline", {})
        pred = roof.get("predicted_mfu")
        meas = row.get("measured") or []
        last = meas[-1] if meas else {}
        meas_txt = ("-" if not last else " ".join(
            f"{k}={last[k]:.4g}" if isinstance(last[k], float)
            else f"{k}={last[k]}"
            for k in sorted(last) if k not in ("t",)))
        gap = "-"
        if pred and isinstance(last.get("mfu"), (int, float)) and pred > 0:
            gap = f"{last['mfu'] / pred:.0%}"
        pred_txt = f"{pred:.3f}" if isinstance(pred, (int, float)) else "-"
        lines.append(
            f"{row.get('target', '?'):>18} {row.get('plan', '?'):>10} "
            f"{fp:>12} {pred_txt:>8} "
            f"{roof.get('bound', '-'):>5} {meas_txt[:24]:>24} {gap:>6}")
    lines.append("")
    lines.append("gap = measured MFU / predicted ceiling; measured rows "
                 "append via bench.record_history / tools/perf_ab.py")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    mode = parser.add_mutually_exclusive_group(required=True)
    mode.add_argument("--update", action="store_true",
                      help="recompute rows and write the ledger")
    mode.add_argument("--check", action="store_true",
                      help="recompute and diff vs the committed ledger "
                           "(CI drift gate; exit 1 on drift)")
    mode.add_argument("--report", action="store_true",
                      help="print predicted-vs-measured from the ledger")
    parser.add_argument("--quick", action="store_true",
                        help="tiny geometry (tests); rows fingerprint "
                             "differently from the CUB sweep")
    parser.add_argument("--targets", nargs="+", default=None,
                        help="substring filter over target names")
    parser.add_argument("--ledger", type=Path, default=None,
                        help="ledger path (default: committed "
                             "PERF_LEDGER.json, GRAFT_PERF_LEDGER env "
                             "overrides)")
    parser.add_argument("--json", type=Path, default=None,
                        help="also write the mode's result as JSON")
    args = parser.parse_args(argv)
    path = args.ledger or prof.ledger_path()

    if args.report:
        ledger = prof.load_ledger(path)
        out = render_report(ledger)
        print(out)
        if args.json:
            args.json.write_text(json.dumps(ledger, indent=1) + "\n")
        return 0

    print(f"graftprof sweep ({'tiny' if args.quick else 'CUB'} geometry, "
          f"chip {CHIP}):")
    rows = sweep(quick=args.quick, targets_filter=args.targets)

    if args.update:
        ledger = prof.load_ledger(path)
        if not args.targets:
            # full sweep: retired fingerprints leave the ledger (unless
            # they hold measured history worth keeping: stub rows stay)
            keep = {fp: r for fp, r in ledger["rows"].items()
                    if fp in rows or "total" not in r}
            ledger["rows"] = keep
        for row in rows.values():
            prof.upsert_predicted(ledger, row)
        out_path = prof.save_ledger(ledger, path)
        print(f"wrote {len(rows)} predicted row(s) -> {out_path}")
        if args.json:
            args.json.write_text(json.dumps(ledger, indent=1) + "\n")
        return 0

    # --check: the drift gate
    ledger = prof.load_ledger(path)
    if args.targets:
        scoped = {fp for fp, r in ledger["rows"].items()
                  if any(t in str(r.get("target")) for t in args.targets)}
        committed = {"rows": {fp: r for fp, r in ledger["rows"].items()
                              if fp in scoped}}
    else:
        committed = ledger
    problems = prof.diff_ledger(committed, rows)
    doc = {"tool": "graftprof", "mode": "check", "chip": CHIP,
           "quick": args.quick, "problems": problems,
           "rows_checked": len(rows)}
    if args.json:
        args.json.write_text(json.dumps(doc, indent=1) + "\n")
    if problems:
        print(f"\ngraftprof drift gate: {len(problems)} problem(s)")
        for p in problems:
            print(f"  DRIFT {p}")
        return 1
    print(f"\ngraftprof drift gate: green ({len(rows)} row(s) match the "
          "committed ledger)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
