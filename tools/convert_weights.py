#!/usr/bin/env python
"""Convert released torch checkpoints -> this framework's msgpack params.

Covers the pretrained models the reference downloads at runtime
(`/root/reference/dalle_pytorch/vae.py:29-33`):

* **Taming VQGAN f=16 / 1024 codes** (`vqgan.1024.model.ckpt`) -> params for
  ``models.pretrained_vae.VQGanVAE1024`` (graphs mirror taming's topology,
  so the mapping is 1:1 by name).
* **OpenAI dVAE** (`encoder.pkl`/`decoder.pkl` from the DALL-E package) ->
  params for ``models.pretrained_vae.OpenAIDiscreteVAE``.

This environment has no network egress, so the real checkpoints cannot be
fetched here — the name maps and tensor transforms are validated by unit
tests that build torch twins of the graphs with the published naming
(tests/test_weight_conversion.py) and compare forward passes numerically.

Usage:
  python tools/convert_weights.py vqgan --ckpt vqgan.1024.model.ckpt --out vqgan_jax.msgpack
  python tools/convert_weights.py openai --encoder encoder.pkl --decoder decoder.pkl --out openai_jax.msgpack
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dalle_pytorch_tpu.models.pretrained_vae import convert_conv_weight  # noqa: E402


def _set(tree: dict, path: str, value: np.ndarray):
    node = tree
    parts = path.split("/")
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def _conv(sd, key):
    return convert_conv_weight(np.asarray(sd[key]))


def _vec(sd, key):
    return np.asarray(sd[key])


# ---------------------------------------------------------------------------
# Taming VQGAN (keys as in taming-transformers VQModel state_dict)
# ---------------------------------------------------------------------------


def _vq_resblock(params, sd, flax_prefix, torch_prefix, has_shortcut):
    _set(params, f"{flax_prefix}/norm1/scale", _vec(sd, f"{torch_prefix}.norm1.weight"))
    _set(params, f"{flax_prefix}/norm1/bias", _vec(sd, f"{torch_prefix}.norm1.bias"))
    _set(params, f"{flax_prefix}/conv1/kernel", _conv(sd, f"{torch_prefix}.conv1.weight"))
    _set(params, f"{flax_prefix}/conv1/bias", _vec(sd, f"{torch_prefix}.conv1.bias"))
    _set(params, f"{flax_prefix}/norm2/scale", _vec(sd, f"{torch_prefix}.norm2.weight"))
    _set(params, f"{flax_prefix}/norm2/bias", _vec(sd, f"{torch_prefix}.norm2.bias"))
    _set(params, f"{flax_prefix}/conv2/kernel", _conv(sd, f"{torch_prefix}.conv2.weight"))
    _set(params, f"{flax_prefix}/conv2/bias", _vec(sd, f"{torch_prefix}.conv2.bias"))
    if has_shortcut:
        _set(params, f"{flax_prefix}/nin_shortcut/kernel",
             _conv(sd, f"{torch_prefix}.nin_shortcut.weight"))
        _set(params, f"{flax_prefix}/nin_shortcut/bias",
             _vec(sd, f"{torch_prefix}.nin_shortcut.bias"))


def _vq_attnblock(params, sd, flax_prefix, torch_prefix):
    _set(params, f"{flax_prefix}/norm/scale", _vec(sd, f"{torch_prefix}.norm.weight"))
    _set(params, f"{flax_prefix}/norm/bias", _vec(sd, f"{torch_prefix}.norm.bias"))
    for name in ("q", "k", "v", "proj_out"):
        _set(params, f"{flax_prefix}/{name}/kernel",
             _conv(sd, f"{torch_prefix}.{name}.weight"))
        _set(params, f"{flax_prefix}/{name}/bias",
             _vec(sd, f"{torch_prefix}.{name}.bias"))


def convert_vqgan_state_dict(sd: dict, ch: int = 128,
                             ch_mult=(1, 1, 2, 2, 4),
                             num_res_blocks: int = 2,
                             resolution: int = 256,
                             attn_resolutions=(16,)) -> dict:
    """taming VQModel state_dict -> VQGanVAE1024 params dict
    ({encoder, decoder, codebook, quant_proj, post_quant_proj}).

    ``attn_resolutions`` follows the released `vqgan_imagenet_f16_1024`
    ddconfig: levels running at those resolutions interleave AttnBlocks
    after every res block (`encoder.down.4.attn.{0,1}`,
    `decoder.up.4.attn.{0,1,2}` in the published checkpoint)."""
    from dalle_pytorch_tpu.models.pretrained_vae import vqgan_attn_levels

    attn_levels = vqgan_attn_levels(resolution, tuple(ch_mult),
                                    tuple(attn_resolutions))
    enc: dict = {}
    _set(enc, "conv_in/kernel", _conv(sd, "encoder.conv_in.weight"))
    _set(enc, "conv_in/bias", _vec(sd, "encoder.conv_in.bias"))
    c_in = ch
    for i, mult in enumerate(ch_mult):
        c_out = ch * mult
        for b in range(num_res_blocks):
            _vq_resblock(enc, sd, f"down_{i}_block_{b}",
                         f"encoder.down.{i}.block.{b}",
                         has_shortcut=(c_in != c_out))
            c_in = c_out
            if i in attn_levels:
                _vq_attnblock(enc, sd, f"down_{i}_attn_{b}",
                              f"encoder.down.{i}.attn.{b}")
        if i < len(ch_mult) - 1:
            _set(enc, f"down_{i}_downsample/kernel",
                 _conv(sd, f"encoder.down.{i}.downsample.conv.weight"))
            _set(enc, f"down_{i}_downsample/bias",
                 _vec(sd, f"encoder.down.{i}.downsample.conv.bias"))
    _vq_resblock(enc, sd, "mid_block_1", "encoder.mid.block_1", False)
    _vq_attnblock(enc, sd, "mid_attn_1", "encoder.mid.attn_1")
    _vq_resblock(enc, sd, "mid_block_2", "encoder.mid.block_2", False)
    _set(enc, "norm_out/scale", _vec(sd, "encoder.norm_out.weight"))
    _set(enc, "norm_out/bias", _vec(sd, "encoder.norm_out.bias"))
    _set(enc, "conv_out/kernel", _conv(sd, "encoder.conv_out.weight"))
    _set(enc, "conv_out/bias", _vec(sd, "encoder.conv_out.bias"))

    dec: dict = {}
    _set(dec, "conv_in/kernel", _conv(sd, "decoder.conv_in.weight"))
    _set(dec, "conv_in/bias", _vec(sd, "decoder.conv_in.bias"))
    _vq_resblock(dec, sd, "mid_block_1", "decoder.mid.block_1", False)
    _vq_attnblock(dec, sd, "mid_attn_1", "decoder.mid.attn_1")
    _vq_resblock(dec, sd, "mid_block_2", "decoder.mid.block_2", False)
    # taming's decoder.up is indexed by resolution level (0 = lowest mult);
    # our decoder names up_{i} along its forward order (0 = highest mult)
    n = len(ch_mult)
    c_in = ch * ch_mult[-1]
    for i, mult in enumerate(reversed(ch_mult)):
        lvl = n - 1 - i
        c_out = ch * mult
        for b in range(num_res_blocks + 1):
            _vq_resblock(dec, sd, f"up_{i}_block_{b}",
                         f"decoder.up.{lvl}.block.{b}",
                         has_shortcut=(c_in != c_out))
            c_in = c_out
            if lvl in attn_levels:
                _vq_attnblock(dec, sd, f"up_{i}_attn_{b}",
                              f"decoder.up.{lvl}.attn.{b}")
        if i < n - 1:
            _set(dec, f"up_{i}_upsample/kernel",
                 _conv(sd, f"decoder.up.{lvl}.upsample.conv.weight"))
            _set(dec, f"up_{i}_upsample/bias",
                 _vec(sd, f"decoder.up.{lvl}.upsample.conv.bias"))
    _set(dec, "norm_out/scale", _vec(sd, "decoder.norm_out.weight"))
    _set(dec, "norm_out/bias", _vec(sd, "decoder.norm_out.bias"))
    _set(dec, "conv_out/kernel", _conv(sd, "decoder.conv_out.weight"))
    _set(dec, "conv_out/bias", _vec(sd, "decoder.conv_out.bias"))

    def conv1x1_to_matrix(key):
        w = np.asarray(sd[key])        # [out, in, 1, 1]
        return np.squeeze(w, (2, 3)).T  # -> [in, out] matmul kernel

    return {
        "encoder": enc,
        "decoder": dec,
        "codebook": np.asarray(sd["quantize.embedding.weight"]),
        "quant_proj": {"kernel": conv1x1_to_matrix("quant_conv.weight"),
                       "bias": _vec(sd, "quant_conv.bias")},
        "post_quant_proj": {"kernel": conv1x1_to_matrix("post_quant_conv.weight"),
                            "bias": _vec(sd, "post_quant_conv.bias")},
    }


# ---------------------------------------------------------------------------
# OpenAI dVAE (keys as in the DALL-E package's Encoder/Decoder: custom
# Conv2d storing `w` [out, in, kh, kw] and `b`)
# ---------------------------------------------------------------------------


def _oai_block(params, sd, flax_prefix, torch_prefix, has_id_path):
    for i in range(1, 5):
        _set(params, f"{flax_prefix}/conv_{i}/kernel",
             _conv(sd, f"{torch_prefix}.res_path.conv_{i}.w"))
        _set(params, f"{flax_prefix}/conv_{i}/bias",
             _vec(sd, f"{torch_prefix}.res_path.conv_{i}.b").reshape(-1))
    if has_id_path:
        _set(params, f"{flax_prefix}/id_path/kernel",
             _conv(sd, f"{torch_prefix}.id_path.w"))
        _set(params, f"{flax_prefix}/id_path/bias",
             _vec(sd, f"{torch_prefix}.id_path.b").reshape(-1))


def convert_openai_state_dicts(enc_sd: dict, dec_sd: dict | None,
                               hidden: int = 256,
                               blocks_per_group: int = 2) -> dict:
    """DALL-E package encoder/decoder state_dicts -> OpenAIDiscreteVAE
    params ({encoder, decoder}); `dec_sd=None` converts the encoder only."""
    enc: dict = {}
    _set(enc, "stem/kernel", _conv(enc_sd, "blocks.input.w"))
    _set(enc, "stem/bias", _vec(enc_sd, "blocks.input.b").reshape(-1))
    prev = hidden
    for g, mult in enumerate((1, 2, 4, 8)):
        n_out = hidden * mult
        for b in range(blocks_per_group):
            _oai_block(enc, enc_sd, f"group_{g}_block_{b}",
                       f"blocks.group_{g + 1}.block_{b + 1}",
                       has_id_path=(prev != n_out))
            prev = n_out
    _set(enc, "head/kernel", _conv(enc_sd, "blocks.output.conv.w"))
    _set(enc, "head/bias", _vec(enc_sd, "blocks.output.conv.b").reshape(-1))
    if dec_sd is None:
        return {"encoder": enc}

    dec: dict = {}
    _set(dec, "stem/kernel", _conv(dec_sd, "blocks.input.w"))
    _set(dec, "stem/bias", _vec(dec_sd, "blocks.input.b").reshape(-1))
    prev = hidden // 2  # n_init
    for g, mult in enumerate((8, 4, 2, 1)):
        n_out = hidden * mult
        for b in range(blocks_per_group):
            _oai_block(dec, dec_sd, f"group_{g}_block_{b}",
                       f"blocks.group_{g + 1}.block_{b + 1}",
                       has_id_path=(prev != n_out))
            prev = n_out
    _set(dec, "head/kernel", _conv(dec_sd, "blocks.output.conv.w"))
    _set(dec, "head/bias", _vec(dec_sd, "blocks.output.conv.b").reshape(-1))
    return {"encoder": enc, "decoder": dec}


# ---------------------------------------------------------------------------
# OpenAI CLIP ViT (keys as in the released clip package state_dict)
# ---------------------------------------------------------------------------


def _clip_block(params, sd, flax_prefix, torch_prefix):
    _set(params, f"{flax_prefix}/ln_1/scale", _vec(sd, f"{torch_prefix}.ln_1.weight"))
    _set(params, f"{flax_prefix}/ln_1/bias", _vec(sd, f"{torch_prefix}.ln_1.bias"))
    _set(params, f"{flax_prefix}/ln_2/scale", _vec(sd, f"{torch_prefix}.ln_2.weight"))
    _set(params, f"{flax_prefix}/ln_2/bias", _vec(sd, f"{torch_prefix}.ln_2.bias"))
    # torch MultiheadAttention packs qkv as in_proj_weight [3w, w]
    _set(params, f"{flax_prefix}/in_proj/kernel",
         np.asarray(sd[f"{torch_prefix}.attn.in_proj_weight"]).T)
    _set(params, f"{flax_prefix}/in_proj/bias",
         _vec(sd, f"{torch_prefix}.attn.in_proj_bias"))
    _set(params, f"{flax_prefix}/out_proj/kernel",
         np.asarray(sd[f"{torch_prefix}.attn.out_proj.weight"]).T)
    _set(params, f"{flax_prefix}/out_proj/bias",
         _vec(sd, f"{torch_prefix}.attn.out_proj.bias"))
    _set(params, f"{flax_prefix}/c_fc/kernel",
         np.asarray(sd[f"{torch_prefix}.mlp.c_fc.weight"]).T)
    _set(params, f"{flax_prefix}/c_fc/bias", _vec(sd, f"{torch_prefix}.mlp.c_fc.bias"))
    _set(params, f"{flax_prefix}/c_proj/kernel",
         np.asarray(sd[f"{torch_prefix}.mlp.c_proj.weight"]).T)
    _set(params, f"{flax_prefix}/c_proj/bias",
         _vec(sd, f"{torch_prefix}.mlp.c_proj.bias"))


def infer_clip_config(sd: dict) -> dict:
    """Geometry of a released CLIP ViT state_dict (for CLIPViTConfig)."""
    conv1 = np.asarray(sd["visual.conv1.weight"])  # [w, 3, p, p]
    vision_width, _, patch, _ = conv1.shape
    grid_plus1 = np.asarray(sd["visual.positional_embedding"]).shape[0]
    grid = int(np.sqrt(grid_plus1 - 1))
    vision_layers = 1 + max(
        int(k.split(".")[3]) for k in sd if k.startswith("visual.transformer.resblocks."))
    text_layers = 1 + max(
        int(k.split(".")[2]) for k in sd
        if k.startswith("transformer.resblocks."))
    vocab, text_width = np.asarray(sd["token_embedding.weight"]).shape
    embed_dim = np.asarray(sd["text_projection"]).shape[1]
    return dict(
        image_size=grid * patch, patch_size=patch,
        vision_width=vision_width, vision_layers=vision_layers,
        vision_heads=vision_width // 64, embed_dim=embed_dim,
        text_width=text_width, text_layers=text_layers,
        text_heads=text_width // 64,
        context_length=np.asarray(sd["positional_embedding"]).shape[0],
        vocab_size=vocab)


def convert_clip_state_dict(sd: dict, vision_layers: int = 12,
                            text_layers: int = 12) -> dict:
    """Released OpenAI CLIP (ViT) state_dict -> models.clip_vit.CLIPViT
    params."""
    p: dict = {}
    _set(p, "conv1/kernel", _conv(sd, "visual.conv1.weight"))
    _set(p, "class_embedding", _vec(sd, "visual.class_embedding"))
    _set(p, "vision_pos", _vec(sd, "visual.positional_embedding"))
    _set(p, "ln_pre/scale", _vec(sd, "visual.ln_pre.weight"))
    _set(p, "ln_pre/bias", _vec(sd, "visual.ln_pre.bias"))
    for i in range(vision_layers):
        _clip_block(p, sd, f"vision_block_{i}",
                    f"visual.transformer.resblocks.{i}")
    _set(p, "ln_post/scale", _vec(sd, "visual.ln_post.weight"))
    _set(p, "ln_post/bias", _vec(sd, "visual.ln_post.bias"))
    _set(p, "vision_proj", _vec(sd, "visual.proj"))

    _set(p, "token_embedding/embedding", _vec(sd, "token_embedding.weight"))
    _set(p, "text_pos", _vec(sd, "positional_embedding"))
    for i in range(text_layers):
        _clip_block(p, sd, f"text_block_{i}", f"transformer.resblocks.{i}")
    _set(p, "ln_final/scale", _vec(sd, "ln_final.weight"))
    _set(p, "ln_final/bias", _vec(sd, "ln_final.bias"))
    _set(p, "text_projection", _vec(sd, "text_projection"))
    _set(p, "logit_scale", np.asarray(sd["logit_scale"]))
    return p


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _torch_load(path):
    import torch

    try:
        obj = torch.load(path, map_location="cpu", weights_only=False)
    except RuntimeError as plain_err:
        # the released CLIP ViT-B-32.pt is a TorchScript archive, which
        # plain torch.load rejects (ref genrank.py:22 loads it via
        # clip.load); jit.load gives the same state_dict.  Chain the
        # original error if jit.load ALSO fails — a truncated download
        # raises here too, and the plain-load message is the diagnosis.
        try:
            obj = torch.jit.load(path, map_location="cpu")
        except Exception:
            raise plain_err from None
    if hasattr(obj, "state_dict"):
        obj = obj.state_dict()
    if isinstance(obj, dict) and "state_dict" in obj:
        obj = obj["state_dict"]
    return {k: v.numpy() if hasattr(v, "numpy") else np.asarray(v)
            for k, v in obj.items()}


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_vq = sub.add_parser("vqgan")
    p_vq.add_argument("--ckpt", required=True)
    p_vq.add_argument("--out", required=True)

    p_oa = sub.add_parser("openai")
    p_oa.add_argument("--encoder", required=True)
    p_oa.add_argument("--decoder", required=True)
    p_oa.add_argument("--out", required=True)

    p_cl = sub.add_parser("clip")
    p_cl.add_argument("--ckpt", required=True,
                      help="torch-saved CLIP ViT model or state_dict")
    p_cl.add_argument("--out", required=True)

    args = parser.parse_args(argv)
    from dalle_pytorch_tpu.utils.checkpoint import save_checkpoint

    if args.cmd == "vqgan":
        params = convert_vqgan_state_dict(_torch_load(args.ckpt))
    elif args.cmd == "clip":
        sd = _torch_load(args.ckpt)
        cfg = infer_clip_config(sd)
        params = {
            "hparams": cfg,
            "weights": convert_clip_state_dict(
                sd, vision_layers=cfg["vision_layers"],
                text_layers=cfg["text_layers"]),
        }
    else:
        params = convert_openai_state_dicts(_torch_load(args.encoder),
                                            _torch_load(args.decoder))
    save_checkpoint(args.out, params)
    n = sum(np.asarray(v).size for v in _leaves(params))
    print(f"wrote {args.out}: {n / 1e6:.1f}M params")


def _leaves(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _leaves(v)
    else:
        yield tree


if __name__ == "__main__":
    main()
