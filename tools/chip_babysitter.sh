#!/bin/bash
# Chip-work babysitter: drain the on-chip measurement queue through a flaky
# TPU tunnel (see PERF.md "Pending on-chip A/Bs" and
# all-logs-tpu/README.md for why this exists: the tunnel alternates short
# up-windows with hours-long outages, and a wedged tunnel hangs inside
# device calls with no exception — only subprocess timeouts bound it).
#
# Run DETACHED and re-armable at any time (stages are idempotent via
# marker files, loss_curve resumes from its checkpoint, and the persistent
# XLA compile cache makes retries cheap):
#
#   nohup setsid tools/chip_babysitter.sh >> /tmp/chipwork.log 2>&1 &
#
# Stage logs land in /tmp/chip_<stage>.log with /tmp/chip_<stage>.ok
# markers; a harvest loop (below, started alongside) copies finished logs
# into all-logs-tpu/chip-logs/ so an end-of-round commit captures them
# even when the window arrives after the working session ended.  After a
# window: fold the A/B logs via tools/collect_ab.py into PERF.md and flip
# measured winners into bench.py::cub200_config.
cd "$(dirname "$0")/.."

probe() {
  timeout 75 python -c "import jax, jax.numpy as jnp; v=float((jnp.ones((128,128))@jnp.ones((128,128))).sum()); assert v==128.0**3" \
    >/dev/null 2>&1
}

wait_tunnel() {
  until probe; do echo "$(date +%T) tunnel down, sleeping 120s"; sleep 120; done
  echo "$(date +%T) tunnel up"
}

run_stage() { # run_stage <name> <timeout_s> <cmd...>
  local name=$1 tmo=$2; shift 2
  [ -f "/tmp/chip_${name}.ok" ] && { echo "$name already done"; return 0; }
  local tries=0
  while [ $tries -lt 4 ]; do
    wait_tunnel
    echo "$(date +%T) starting $name (try $((tries+1))/4)"
    if timeout "$tmo" "$@" > "/tmp/chip_${name}.log" 2>&1; then
      echo "$(date +%T) $name DONE"; touch "/tmp/chip_${name}.ok"
      return 0
    fi
    echo "$(date +%T) $name failed rc=$?"
    tries=$((tries+1))
    sleep 30
  done
  echo "$(date +%T) $name GAVE UP"
  return 1
}

# harvest loop: finished stage logs -> committable repo path
(
  mkdir -p all-logs-tpu/chip-logs
  while true; do
    for ok in /tmp/chip_*.ok; do
      [ -e "$ok" ] || continue
      name=$(basename "$ok" .ok)
      log="/tmp/${name}.log"
      dst="all-logs-tpu/chip-logs/${name#chip_}.log"
      if [ -f "$log" ] && [ ! -f "$dst" ]; then
        cp "$log" "$dst"
        echo "$(date +%T) harvested $name"
      fi
    done
    sleep 180
  done
) &

run_stage ab_core   1500 python tools/perf_ab.py baseline bf16-logits+onehot --reps 3
run_stage ab_knobs  1500 python tools/perf_ab.py baseline full-head onehot-embed --reps 2
run_stage ab_batch  1500 python tools/perf_ab.py baseline batch64 batch128 --reps 2
run_stage ab_cand   1500 python tools/perf_ab.py baseline candidate --reps 3
run_stage bench     2400 env BENCH_VAE=1 python bench.py
run_stage bench64   1800 env BENCH_BATCH=64 python bench.py
run_stage ab_pallas 1500 python tools/perf_ab.py baseline pallas --reps 3
run_stage loss_tpu  2400 python tools/loss_curve.py --steps 1632 --num_pairs 1632 \
  --batch_size 16 --lr_plateau --plateau_patience 3 \
  --out all-logs-tpu/synthetic-cub-tpu.txt
run_stage ab_ptiles 1500 python tools/perf_ab.py pallas pallas-b64 pallas-b256 --reps 2
run_stage ab_fmap   1800 python tools/perf_ab.py fmap64 fmap64-pallas --reps 2
run_stage gen_ab    1800 python tools/perf_ab.py gen gen-dense gen64 vae --reps 2
echo "$(date +%T) all chip work finished"
