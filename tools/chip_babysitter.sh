#!/bin/bash
# Chip-work babysitter: drain the on-chip measurement queue through a flaky
# TPU tunnel (see PERF.md "Pending on-chip A/Bs" and
# all-logs-tpu/README.md for why this exists: the tunnel alternates short
# up-windows with hours-long outages, and a wedged tunnel hangs inside
# device calls with no exception — only subprocess timeouts bound it).
#
# Run DETACHED and re-armable at any time (stages are idempotent via
# marker files, loss_curve resumes from its checkpoint, and the persistent
# XLA compile cache makes retries cheap):
#
#   nohup setsid tools/chip_babysitter.sh >> /tmp/chipwork.log 2>&1 &
#
# Stage logs land in ${CHIP_TMP}/chip_<stage>.log with ${CHIP_TMP}/chip_<stage>.ok
# markers; a harvest loop (started alongside, lifecycle-bounded: it exits
# once every stage is harvested and is killed at script exit either way)
# copies finished logs into all-logs-tpu/chip-logs/ so an end-of-round
# commit captures them even when the window arrives after the working
# session ended.  After a window: fold the A/B logs via
# tools/collect_ab.py into PERF.md and flip measured winners into
# bench.py::cub200_config.
#
# Stages are ordered by evidence value per tunnel-minute: a short window
# should produce the candidate-stack decision, the headline bench record,
# and the sliced-KV generation A/B before anything else runs.
cd "$(dirname "$0")/.."

# Queue version: markers are per-version (chip_<stage>.v${QV}.ok) so a
# re-armed queue whose stage COMMANDS changed can never be skipped by a
# stale marker from an older queue definition — bump QV whenever any
# stage's command line changes.
QV=13

STAGES="spmd_1024 gen_bf16_ab gen_int8_ab gen_spec_ab serve_prefix_ab gen_fused_ab ab_cand bench xprof_capture gen_ab gen64_ab bench64 ab_core ab_pallas loss_tpu ab_ptiles ab_batch ab_knobs ab_fmap bench_serve"

# Overridable knobs so tests/test_babysitter.py can drive the REAL script
# (fake python on PATH, private marker dir, second-scale sleeps) without
# touching the production /tmp markers an armed queue is using.
CHIP_TMP=${CHIP_TMP:-/tmp}
PROBE_SLEEP=${PROBE_SLEEP:-120}
RETRY_SLEEP=${RETRY_SLEEP:-30}
HARVEST_SLEEP=${HARVEST_SLEEP:-180}

probe() {
  timeout 75 python -c "import jax, jax.numpy as jnp; v=float((jnp.ones((128,128))@jnp.ones((128,128))).sum()); assert v==128.0**3" \
    >/dev/null 2>&1
}

wait_tunnel() {
  until probe; do echo "$(date +%T) tunnel down, sleeping ${PROBE_SLEEP}s"; sleep "$PROBE_SLEEP"; done
  echo "$(date +%T) tunnel up"
}

run_stage() { # run_stage <name> <timeout_s> <cmd...>
  local name=$1 tmo=$2; shift 2
  [ -f "${CHIP_TMP}/chip_${name}.v${QV}.ok" ] && { echo "$name already done"; return 0; }
  local tries=0 rc
  while [ $tries -lt 4 ]; do
    wait_tunnel
    echo "$(date +%T) starting $name (try $((tries+1))/4)"
    # plain statement + immediate capture: $? read after an un-taken `if`
    # branch is 0, which would report every failure as rc=0 and destroy
    # the rc=124 (stage timeout = wedged tunnel) vs crash triage signal
    timeout "$tmo" "$@" > "${CHIP_TMP}/chip_${name}.log" 2>&1
    rc=$?
    if [ "$rc" -eq 0 ]; then
      echo "$(date +%T) $name DONE"; touch "${CHIP_TMP}/chip_${name}.v${QV}.ok"
      return 0
    fi
    echo "$(date +%T) $name failed rc=$rc"
    tries=$((tries+1))
    # no sleep after the FINAL failure: the next stage should get the
    # remaining tunnel window immediately
    [ $tries -lt 4 ] && sleep "$RETRY_SLEEP"
  done
  echo "$(date +%T) $name GAVE UP"
  return 1
}

harvest_once() { # finished stage logs -> committable repo path
  mkdir -p all-logs-tpu/chip-logs
  local name ok log dst all_done=1
  for name in $STAGES; do
    ok="${CHIP_TMP}/chip_${name}.v${QV}.ok"; log="${CHIP_TMP}/chip_${name}.log"
    dst="all-logs-tpu/chip-logs/${name}.log"
    if [ -e "$ok" ]; then
      # copy when missing OR when the stage re-ran under a newer queue
      # version (-nt): a stale harvested file from an older queue must
      # never shadow the re-run's results
      if [ -f "$log" ] && { [ ! -f "$dst" ] || [ "$log" -nt "$dst" ]; }; then
        cp "$log" "$dst"
        echo "$(date +%T) harvested $name"
      fi
    else
      all_done=0
    fi
  done
  return $all_done  # rc 1 = everything harvested
}

# background harvest loop, lifecycle-bounded (ADVICE r3: the r3 loop was
# unkillable and leaked one copy per re-arm): exits on its own once all
# stages are harvested, and the EXIT trap kills it when the queue script
# ends for any other reason (a GAVE-UP stage never gets an .ok marker).
(
  while true; do
    harvest_once || exit 0
    sleep "$HARVEST_SLEEP"
  done
) &
HARVEST_PID=$!
trap 'harvest_once; kill "$HARVEST_PID" 2>/dev/null' EXIT

# -- chip-free pre-flight gate ---------------------------------------------
# contract_check statically asserts the invariants the A/Bs below measure
# (bf16 cache dtype, f32 accumulation, shardings resolve) via eval_shape on
# CPU — zero FLOPs, no tunnel, seconds.  A dead invariant must never reach
# the chip queue: every stage after it would measure a broken program, so
# refuse to arm instead.  No marker file — the gate is cheap and re-runs on
# every (re-)arm so a regression between arms is still caught.
echo "$(date +%T) pre-flight: chip-free contract check"
if ! env JAX_PLATFORMS=cpu timeout 600 python tools/contract_check.py \
    > "${CHIP_TMP}/chip_contract_check.log" 2>&1; then
  echo "$(date +%T) contract check FAILED — refusing to arm the chip queue (see ${CHIP_TMP}/chip_contract_check.log)"
  exit 1
fi
echo "$(date +%T) contract check PASS"

# -- second chip-free gate: graftspmd (jaxpr-level SPMD analyses) ----------
# spmd_check traces every train-step factory under every parallelism plan
# on a virtual CPU mesh and enforces S1 collective order (SPMD deadlock),
# S2 donation aliasing (silent HBM doubling), S3 single-trace (recompile
# storm) and S4 static HBM budget at CUB geometry — the three most
# expensive TPU failure modes, all decidable before paying for the pod.
echo "$(date +%T) pre-flight: graftspmd jaxpr analysis (S1-S4)"
if ! env JAX_PLATFORMS=cpu timeout 600 python tools/spmd_check.py \
    --chip "${BABYSIT_CHIP:-v4-8}" \
    --json "${CHIP_TMP}/chip_spmd_check.json" \
    > "${CHIP_TMP}/chip_spmd_check.log" 2>&1; then
  echo "$(date +%T) spmd check FAILED — refusing to arm the chip queue (see ${CHIP_TMP}/chip_spmd_check.log)"
  exit 1
fi
echo "$(date +%T) spmd check PASS"

# -- optional training auto-restart supervisor -----------------------------
# Arm with BABYSIT_TRAIN_CMD="python train_dalle.py --image_text_folder ..."
# (do NOT include --resume/--heartbeat_dir — the supervisor adds them).
# The run is launched with `--resume auto`, so every (re)launch resumes
# from the newest manifest-valid managed checkpoint, falling back past a
# torn final write; stalled-or-dead per tools/monitor.py heartbeat scan ->
# kill + relaunch, bounded by BABYSIT_MAX_RESTARTS.  Inactive when the env
# var is unset, so the measurement queue below is unaffected.
#
# Exit-code taxonomy (dalle_pytorch_tpu/utils/failure.py ExitCode — the
# frozen supervisor contract): 0 = clean OR a graceful preemption stop
# (distinguished by the heartbeat done-marker, never by exit code);
# 75 (WEDGED) = the hung-step watchdog fired on a device call that never
# returned — transient by definition, relaunch with --resume auto;
# 70 (ROLLBACK_BUDGET) = the anomaly-recovery ladder exhausted
# --max_rollbacks — TERMINAL, a relaunch replays the same divergence, so
# never restart it: a human must read the anomaly bundles.
# BABYSIT_STEP_DEADLINE > 0 arms the trainer's in-process hung-step
# watchdog (--step_deadline) so a wedge inside a device call turns into
# the rc=75 relaunch instead of waiting out the heartbeat stall scan.
# BABYSIT_RELAUNCH_PLAN (elastic resume): when set, every RELAUNCH (never
# the first launch) appends "--plan $BABYSIT_RELAUNCH_PLAN" — the shape of
# a preempted pod coming back on whatever topology the scheduler granted:
# checkpoint manifests record the written-under plan and the restore
# reshards onto the new one (rc=74 PREEMPT_EXPIRED, like rc=75, is a
# transient death that resumes from the last committed manifest).
# probe_healthz PORT: the /healthz liveness probe, retried with the SAME
# policy constants as the graftwire transport (serve/wire.py:
# RETRY_ATTEMPTS=3, BACKOFF_BASE_S=0.05 doubling) — one blip on a busy
# box is not a wedge, three in a row across ~0.35s of backoff is a
# signal worth logging.  Returns 0 on any success, 1 after the budget.
probe_healthz() {
  port=$1
  backoff=0.05
  attempt=1
  while [ "$attempt" -le 3 ]; do
    if curl -sf -m 5 "http://127.0.0.1:${port}/healthz" >/dev/null 2>&1; then
      return 0
    fi
    if [ "$attempt" -lt 3 ]; then
      sleep "$backoff"
      backoff=$(awk "BEGIN{print ${backoff}*2}")
    fi
    attempt=$((attempt + 1))
  done
  return 1
}

if [ -n "${BABYSIT_TRAIN_CMD:-}" ]; then
  BABYSIT_HB_DIR=${BABYSIT_HB_DIR:-${CHIP_TMP}/train_hb}
  BABYSIT_MAX_RESTARTS=${BABYSIT_MAX_RESTARTS:-3}
  BABYSIT_STALL_TIMEOUT=${BABYSIT_STALL_TIMEOUT:-600}
  BABYSIT_POLL=${BABYSIT_POLL:-60}
  BABYSIT_STEP_DEADLINE=${BABYSIT_STEP_DEADLINE:-0}
  BABYSIT_RELAUNCH_PLAN=${BABYSIT_RELAUNCH_PLAN:-}
  # BABYSIT_METRICS_PORT > 0 wires --metrics_port into the supervised run
  # (in-process /metrics + /healthz, obs/metrics.py) and the poll loop
  # curls /healthz as a liveness probe ALONGSIDE the heartbeat scan — an
  # endpoint that stops answering while the process is alive is an early
  # wedge signal, logged here; the heartbeat scan stays the restart
  # authority (the probe alone never kills)
  BABYSIT_METRICS_PORT=${BABYSIT_METRICS_PORT:-0}
  # graftscope stream: the supervised run appends its events here, and on
  # every death/stall the victim's last events land in train_run.log via
  # obs_report --tail — a babysitter restart carries the previous run's
  # final moments into its own report instead of discarding them
  BABYSIT_TEL_DIR=${BABYSIT_TEL_DIR:-${CHIP_TMP}/train_tel}
  (
    restarts=0
    while :; do
      # elastic relaunch: restarts (not the first launch) may come back on
      # a different parallelism plan — the manifest-recorded written-under
      # plan makes the restore reshard onto it
      plan_args=""
      if [ "$restarts" -gt 0 ] && [ -n "$BABYSIT_RELAUNCH_PLAN" ]; then
        plan_args="--plan ${BABYSIT_RELAUNCH_PLAN}"
        echo "$(date +%T) train supervisor: relaunching under --plan ${BABYSIT_RELAUNCH_PLAN} (elastic resume)"
      fi
      echo "$(date +%T) train supervisor: launch (restarts so far: $restarts/${BABYSIT_MAX_RESTARTS})"
      metrics_args=""
      if [ "${BABYSIT_METRICS_PORT}" -gt 0 ]; then
        metrics_args="--metrics_port ${BABYSIT_METRICS_PORT}"
      fi
      ${BABYSIT_TRAIN_CMD} --resume auto --heartbeat_dir "${BABYSIT_HB_DIR}" \
        --step_deadline "${BABYSIT_STEP_DEADLINE}" \
        --telemetry_dir "${BABYSIT_TEL_DIR}" ${plan_args} ${metrics_args} \
        >> "${CHIP_TMP}/train_run.log" 2>&1 &
      train_pid=$!
      while kill -0 "$train_pid" 2>/dev/null; do
        sleep "$BABYSIT_POLL"
        if [ "${BABYSIT_METRICS_PORT}" -gt 0 ]; then
          if ! probe_healthz "${BABYSIT_METRICS_PORT}"; then
            echo "$(date +%T) train supervisor: /healthz probe FAILED 3x with backoff (pid alive; heartbeat scan decides the restart)"
          fi
        fi
        python tools/monitor.py "${BABYSIT_HB_DIR}" \
          --timeout "${BABYSIT_STALL_TIMEOUT}" \
          --telemetry-dir "${BABYSIT_TEL_DIR}" >/dev/null 2>&1
        if [ $? -eq 1 ]; then  # stalled (a done/healthy run exits 0)
          echo "$(date +%T) train supervisor: stalled heartbeats — killing $train_pid"
          echo "$(date +%T) train supervisor: victim's last telemetry:"
          python tools/obs_report.py "${BABYSIT_TEL_DIR}" --tail 8 2>/dev/null || true
          kill "$train_pid" 2>/dev/null; sleep 5
          kill -9 "$train_pid" 2>/dev/null
          break
        fi
      done
      wait "$train_pid"; rc=$?
      if [ "$rc" -ne 0 ]; then
        echo "$(date +%T) train supervisor: rc=$rc — victim's last telemetry:"
        python tools/obs_report.py "${BABYSIT_TEL_DIR}" --tail 8 2>/dev/null || true
      fi
      # a done-marked heartbeat means the run FINISHED — never relaunch it
      if grep -q '"done": true' "${BABYSIT_HB_DIR}"/heartbeat-p*.json 2>/dev/null; then
        echo "$(date +%T) train supervisor: run completed"; break
      fi
      if [ "$rc" -eq 0 ]; then
        echo "$(date +%T) train supervisor: run exited cleanly"; break
      fi
      if [ "$rc" -eq 70 ]; then  # ExitCode.ROLLBACK_BUDGET: terminal
        echo "$(date +%T) train supervisor: rc=70 rollback budget exhausted — NOT restarting (automatic recovery will not converge; read the anomaly bundles)"
        break
      fi
      restarts=$((restarts+1))
      if [ "$restarts" -gt "$BABYSIT_MAX_RESTARTS" ]; then
        echo "$(date +%T) train supervisor: restart budget exhausted"; break
      fi
      if [ "$rc" -eq 75 ]; then  # ExitCode.WEDGED: transient, resume
        echo "$(date +%T) train supervisor: rc=75 hung-step watchdog — relaunching with --resume auto"
      elif [ "$rc" -eq 74 ]; then  # ExitCode.PREEMPT_EXPIRED: transient
        echo "$(date +%T) train supervisor: rc=74 preemption grace expired mid-save — relaunching from the last committed manifest"
      else
        echo "$(date +%T) train supervisor: rc=$rc — restarting from the last good checkpoint"
      fi
    done
  ) &
  TRAIN_SUP_PID=$!
  trap 'harvest_once; kill "$HARVEST_PID" "$TRAIN_SUP_PID" 2>/dev/null' EXIT
fi

# -- the queue, highest evidence value first -------------------------------
# compiled-S4 proof at the cub-1024 rung (ISSUE 20): AOT-lower the full
# registry train step at dim-1024 on the virtual CPU mesh and gate the
# compiler's own per-device HBM estimate through the rung's declared
# verdict (spmd_check.S4_PRESET_EXPECT: cub-1024 is "over" — opt0 buffer
# assignment is reuse-free across remat blocks, so the stage is a drift
# sentinel on the committed estimate, not a fit proof; P3 + the walker
# own the fit verdict).  The proof is cached in S4_PROOFS.json keyed by a
# config+plan fingerprint, so an unchanged rung re-gates in seconds; a
# geometry/plan drift pays the long recompile HERE (chip-free, retryable)
# instead of on the pod.  First in the queue because a red scale proof
# should surface before any chip budget is spent.  Timeout sized for the
# COLD dim-1024 opt0 compile (tens of minutes on a weak core), not the
# cached re-gate.
run_stage spmd_1024 3600 env JAX_PLATFORMS=cpu python tools/spmd_check.py \
  --preset cub-1024 --chip v5e-4
# bf16 KV cache at eval dtype (f32 activations) vs the f32-cache control:
# the decode loop is measured HBM-bound on cache reads (gen_ab 2.16x), so
# this is the round's headline decode A/B.  Two cold decode-scan compiles
# per stage is the ceiling (bench.py bounds one at 900s)
run_stage gen_bf16_ab 2400 python tools/perf_ab.py gen_bf16 gen_f32cache --reps 2
# int8 quantized serving (ISSUE 7) vs the bf16 cache it halves again:
# int8 KV cache + int8 decode weights at eval dtype — the wall-clock side
# of the ≤0.55x compiler gate (tests/test_perf_model.py) and the C2/C3
# no-dequant contracts, queued directly behind its bf16 control
run_stage gen_int8_ab 2400 python tools/perf_ab.py gen_int8 gen_bf16 --reps 2
# graftspec self-speculative decode (ISSUE 16): shallow-exit drafts + one
# K-wide verify per iteration vs the greedy sampler — the wall-clock side
# of graftprof's predicted-speedup row (accepted-K / stream-overhead);
# bit-equality is the tier-1 gate, this stage is the speed claim
run_stage gen_spec_ab 2400 python tools/perf_ab.py gen_spec gen --reps 2
# cross-request radix prefix cache on the 64-slot arena (ISSUE 16): the
# open-loop trace shares one prompt, so this measures the all-hit
# admission path (one prefill per drive) vs serve64's per-request prefill
run_stage serve_prefix_ab 2400 python tools/perf_ab.py serve_prefix serve64 --reps 2
# fused generate→VAE-decode→CLIP-rerank pipeline wall-clock (genrank
# rank_codes: shared prefill + zero disk round-trips), images-ranked/sec
run_stage gen_fused_ab 1800 python tools/perf_ab.py gen_fused_rank --reps 2
# candidate stack: the one A/B that decides the production config flip
run_stage ab_cand   1500 python tools/perf_ab.py baseline candidate --reps 3
# headline bench record (writes all-logs-tpu/bench-history.jsonl): one gen
# batch only — two cold decode-scan compiles can outlive the stage timeout
run_stage bench     2400 env BENCH_VAE=1 BENCH_GEN_BATCHES=8 python bench.py
# measured on-chip trace for the perf ledger (ISSUE 14): a short
# loss-parity run with the env-armed GRAFT_XPROF window over steps
# [32,36) — 32 warm steps, then prof.capture opens a managed
# jax.profiler trace (OBS003) for two 2-step chunks.  The trace dir is
# written STRAIGHT into chip-logs/ (not CHIP_TMP: the harvest loop only
# copies stage logs, and a multi-file xprof dump shouldn't round-trip
# through /tmp), so the end-of-round commit carries the measured trace
# beside PERF_LEDGER.json's predicted rows — graftprof --report joins
# the two, the trace explains any gap.
run_stage xprof_capture 1500 env GRAFT_XPROF=all-logs-tpu/chip-logs/xprof \
  GRAFT_XPROF_WINDOW=32:36 python tools/loss_curve.py --captions synthetic \
  --steps 48 --num_pairs 2048 --batch_size 16 --chunk 2 \
  --out "${CHIP_TMP}/xprof_loss.txt"
# sliced-KV decode A/B (north-star #2): gen vs its dense-cache control.
# batch 64 is a SEPARATE stage — each variant here is a cold decode-scan
# compile (bench.py bounds ONE at 900s), so two per stage is the ceiling
run_stage gen_ab    2400 python tools/perf_ab.py gen gen-dense --reps 2
run_stage gen64_ab  1800 python tools/perf_ab.py gen64 --reps 2
# candidate headline at batch 64 (no gen stages — gen_ab covers them)
run_stage bench64   1500 env BENCH_BATCH=64 BENCH_GEN_BATCHES= python bench.py
# lever attribution: bf16 head + onehot embed, separately and together
run_stage ab_core   1500 python tools/perf_ab.py baseline bf16-logits+onehot --reps 3
run_stage ab_knobs  1500 python tools/perf_ab.py baseline full-head onehot-embed --reps 2
# flagship Pallas kernel: prove or re-target (VERDICT r3 weak #2)
run_stage ab_pallas 1500 python tools/perf_ab.py baseline pallas --reps 3
# loss parity at the reference geometry: 654 iters/epoch x 16 epochs on
# the real chip, REAL bundled CUB captions for the text half (resumable:
# a dropped window costs one 50-step chunk)
run_stage loss_tpu  2400 python tools/loss_curve.py --captions real \
  --steps 10464 --num_pairs 10464 \
  --batch_size 16 --lr_plateau \
  --out all-logs-tpu/cub-captions-tpu.txt
# tile ladder is 128 (plain pallas) / 256 / 512: sub-128 tiles cannot
# lower on TPU and perf_ab rejects them at the API edge
run_stage ab_ptiles 1500 python tools/perf_ab.py pallas pallas-b256 pallas-b512 --reps 2
run_stage ab_batch  1500 python tools/perf_ab.py baseline batch64 batch128 --reps 2
run_stage ab_fmap   1800 python tools/perf_ab.py fmap64 fmap64-pallas --reps 2
# continuous-batching serve vs gen64's static-batch headline: aggregate
# tok/s across interleaved open-loop requests at 64 slots + p50/p99 per
# request (ISSUE 6; behind the queued A/Bs — it shares their chip budget
# but decides no pending config flip).  The serve-tick no-retrace
# property is pre-gated chip-free by spmd_check's serve harness above.
run_stage bench_serve 2400 python tools/perf_ab.py serve64 gen64 --reps 2
echo "$(date +%T) all chip work finished"
