#!/usr/bin/env python
"""Print the compiler-model perf table (PERF.md "Compiler-model gates").

Compiles (never executes) the production train step, the candidate stack,
the full-head control, and the sliced/dense decode steps, and prints XLA's
own cost model for each — the chip-independent perf numbers that
tests/test_perf_model.py gates.  Run on any backend; CPU is the CI
calibration target:

    JAX_PLATFORMS=cpu python tools/perf_model.py [--fast]

``--fast`` skips the three CUB-sized train-step compiles (minutes on a
small host) and prints only the decode rows.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))

GiB = 2 ** 30


def fmt(costs: dict) -> str:
    parts = [f"flops={costs['flops']:.4g}",
             f"bytes={costs['bytes_accessed']:.4g}"]
    if "temp_bytes" in costs:
        parts.append(f"temp={costs['temp_bytes'] / GiB:.2f}GiB")
    return " ".join(parts)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--fast", action="store_true",
                        help="decode rows only (skip CUB train compiles)")
    args = parser.parse_args(argv)

    from dalle_pytorch_tpu.cli import (apply_platform_env,
                                       enable_compilation_cache)

    apply_platform_env()
    enable_compilation_cache()  # re-runs and the test suite share compiles

    # the same builders the gate tests use — this tool can never drift
    # from what tests/test_perf_model.py asserts
    from test_perf_model import cub_train_costs, layer_decode_costs

    if not args.fast:
        from dalle_pytorch_tpu.utils.profiling import dalle_train_flops

        prod, cfg = cub_train_costs(16)
        print(f"production train step (CUB, b16): {fmt(prod)} "
              f"analytic/xla={dalle_train_flops(cfg, 16) / prod['flops']:.4f}")
        cand, cfg64 = cub_train_costs(64, logits_bf16=True, onehot_embed=True)
        print(f"candidate stack (b64+bf16+onehot): {fmt(cand)} "
              f"flops x{cand['flops'] / prod['flops']:.2f} vs b16")
        full, _ = cub_train_costs(16, head_phase_sliced=False)
        print(f"full-head control (b16): {fmt(full)} "
              f"sliced/full flops={prod['flops'] / full['flops']:.3f}")

    for variant in ("axial_row", "conv_like"):
        d1 = layer_decode_costs(variant, True, 1105)["bytes_accessed"]
        d2 = layer_decode_costs(variant, True, 2210)["bytes_accessed"]
        f1 = layer_decode_costs(variant, False, 1105)["bytes_accessed"]
        f2 = layer_decode_costs(variant, False, 2210)["bytes_accessed"]
        ds, dd = (d2 - d1) / 1105, (f2 - f1) / 1105
        print(f"decode layer {variant}: d(bytes)/d(key) sliced={ds:.0f} "
              f"dense={dd:.0f} (streaming eliminated at n=1105: "
              f"{(dd - ds) * 1105 / 2**20:.1f} MiB/step)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
