#!/bin/bash
# Round-5 follow-up chip queue: the work discovered by the 2026-08-02
# session (chip_babysitter.sh drained its whole queue in one 45-min
# window; these stages are the follow-ups its results created).  Same
# probe/retry/harvest design as chip_babysitter.sh — see its header for
# the rationale — but a separate marker namespace (r5b) so the drained
# main queue is never re-run.
#
#   nohup setsid tools/chip_round5b.sh >> /tmp/chipwork5b.log 2>&1 &
#
# Stage order = decision value:
#   equiv      on-chip dense-vs-pallas equivalence at n=1104/b512 (gates
#              any default flip; VERDICT r4 next-#5's missing half)
#   ab_flip    baseline vs pallas-b512 interleaved (the tile ladder showed
#              232.8 vs ~217 img/s ACROSS windows; this is the same-window
#              confirmation for flipping the production default)
#   bench_pallas  headline bench at the pallas-b512 config -> a
#              bench-history row under the measured-best config
#   ab_batch2  b64 + remat'd b128 (plain b128 OOMs: 30.3G of 15.75G HBM)
#   ab_fmap_tiles  tile ladder at the 4096-token geometry pallas already
#              wins by 2x
cd "$(dirname "$0")/.."

QV=r5b1

STAGES="equiv ab_flip bench_pallas ab_batch2 ab_fmap_tiles"

CHIP_TMP=${CHIP_TMP:-/tmp}
PROBE_SLEEP=${PROBE_SLEEP:-120}
RETRY_SLEEP=${RETRY_SLEEP:-30}
HARVEST_SLEEP=${HARVEST_SLEEP:-180}

probe() {
  timeout 75 python -c "import jax, jax.numpy as jnp; v=float((jnp.ones((128,128))@jnp.ones((128,128))).sum()); assert v==128.0**3" \
    >/dev/null 2>&1
}

wait_tunnel() {
  until probe; do echo "$(date +%T) tunnel down, sleeping ${PROBE_SLEEP}s"; sleep "$PROBE_SLEEP"; done
  echo "$(date +%T) tunnel up"
}

run_stage() { # run_stage <name> <timeout_s> <cmd...>
  local name=$1 tmo=$2; shift 2
  [ -f "${CHIP_TMP}/chip_${name}.${QV}.ok" ] && { echo "$name already done"; return 0; }
  local tries=0 rc
  while [ $tries -lt 4 ]; do
    wait_tunnel
    echo "$(date +%T) starting $name (try $((tries+1))/4)"
    timeout "$tmo" "$@" > "${CHIP_TMP}/chip_${name}.log" 2>&1
    rc=$?
    if [ "$rc" -eq 0 ]; then
      echo "$(date +%T) $name DONE"; touch "${CHIP_TMP}/chip_${name}.${QV}.ok"
      return 0
    fi
    echo "$(date +%T) $name failed rc=$rc"
    tries=$((tries+1))
    [ $tries -lt 4 ] && sleep "$RETRY_SLEEP"
  done
  echo "$(date +%T) $name GAVE UP"
  return 1
}

harvest_once() {
  mkdir -p all-logs-tpu/chip-logs
  local name ok log dst all_done=1
  for name in $STAGES; do
    ok="${CHIP_TMP}/chip_${name}.${QV}.ok"; log="${CHIP_TMP}/chip_${name}.log"
    dst="all-logs-tpu/chip-logs/${name}.log"
    if [ -e "$ok" ]; then
      if [ -f "$log" ] && { [ ! -f "$dst" ] || [ "$log" -nt "$dst" ]; }; then
        cp "$log" "$dst"
        echo "$(date +%T) harvested $name"
      fi
    else
      all_done=0
    fi
  done
  return $all_done
}

(
  while true; do
    harvest_once || exit 0
    sleep "$HARVEST_SLEEP"
  done
) &
HARVEST_PID=$!
trap 'harvest_once; kill "$HARVEST_PID" 2>/dev/null' EXIT

run_stage equiv         1500 python tools/chip_equiv.py 512
run_stage ab_flip       1500 python tools/perf_ab.py baseline pallas-b512 --reps 3
run_stage bench_pallas  1500 env BENCH_PALLAS=1 BENCH_PALLAS_BLOCK=512 BENCH_GEN_BATCHES= python bench.py
run_stage ab_batch2     1800 python tools/perf_ab.py baseline batch64 batch128-remat --reps 2
run_stage ab_fmap_tiles 1800 python tools/perf_ab.py fmap64-pallas fmap64-pallas-b256 --reps 2
echo "$(date +%T) round-5b chip work finished"
