#!/usr/bin/env python
"""graftlint CLI — TPU/JAX static analysis over this repo's bug history.

Runs the rule catalog in ``dalle_pytorch_tpu.lint`` (ENV001 env-truthiness,
SEED001 hash()-seeds, BACKEND001 import-time backend queries, DOT001
missing accumulation contracts, TRACE001 host syncs in traced code, EXC001
swallowed XLA errors) over the given files/directories.  Pure AST — no
backend init, no device calls, milliseconds per file once imported — so it
gates in CI and at the head of the chip babysitter queue without costing
tunnel time.

Usage:
    python tools/graftlint.py dalle_pytorch_tpu tools bench.py \
        train_dalle.py genrank.py
    python tools/graftlint.py --select ENV001 --fix dalle_pytorch_tpu
    python tools/graftlint.py --write-baseline ...   # grandfather findings
    python tools/graftlint.py --format json --output lint.json ...  # CI
    python tools/graftlint.py --prune-baseline ...   # drop stale entries

Suppress a finding inline WITH a justification (enforced — a bare pragma
is itself an error):
    x = risky()  # graftlint: disable=RULE (why the rule does not apply)

Exit codes: 0 clean, 1 findings, 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dalle_pytorch_tpu.lint import (RULES, filter_baseline,  # noqa: E402
                                    findings_to_json, findings_to_sarif,
                                    fix_env001, iter_python_files,
                                    lint_paths, load_baseline, prune_baseline,
                                    stale_baseline_entries, write_baseline)

DEFAULT_BASELINE = REPO / ".graftlint-baseline.json"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint")
    parser.add_argument("--select", type=str, default=None,
                        help="comma-separated rule subset (default: all)")
    parser.add_argument("--fix", action="store_true",
                        help="apply mechanical ENV001 rewrites "
                             "(os.environ.get truth-tests -> env_flag)")
    parser.add_argument("--baseline", type=Path, default=None,
                        help="baseline file of grandfathered findings "
                             f"(default: {DEFAULT_BASELINE.name} at the "
                             "repo root, auto-loaded when present)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write the current findings to the baseline "
                             "file and exit 0")
    parser.add_argument("--prune-baseline", action="store_true",
                        help="drop baseline fingerprints matching no "
                             "current finding, then exit 0")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text",
                        help="findings output format (default: text; json "
                             "follows lint.FINDINGS_JSON_SCHEMA, sarif is "
                             "SARIF 2.1.0 for code-scanning UIs)")
    parser.add_argument("--output", type=Path, default=None,
                        help="write --format json/sarif document here "
                             "instead of stdout (text stays on stdout)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for name, fn in RULES.items():
            doc = (fn.__doc__ or "").strip().split("\n")[0]
            print(f"{name}: {doc}")
        return 0
    if not args.paths:
        parser.error("no paths given")

    select = None
    if args.select:
        select = [r.strip().upper() for r in args.select.split(",")]
        unknown = [r for r in select if r not in RULES]
        if unknown:
            parser.error(f"unknown rule(s) {unknown}; known: {list(RULES)}")

    if args.fix:
        fixed_files = 0
        for f in iter_python_files(args.paths):
            src = f.read_text()
            new, n = fix_env001(src, path=str(f))
            if n:
                f.write_text(new)
                fixed_files += 1
                print(f"fixed {n} ENV001 site(s) in {f}")
        print(f"--fix: rewrote {fixed_files} file(s)")

    findings = lint_paths(args.paths, select=select)

    baseline_path = args.baseline or DEFAULT_BASELINE
    if args.write_baseline:
        write_baseline(findings, baseline_path)
        print(f"baseline: {len(findings)} finding(s) -> {baseline_path}")
        return 0
    if args.prune_baseline:
        stale = prune_baseline(findings, baseline_path)
        print(f"--prune-baseline: dropped {len(stale)} stale "
              f"fingerprint(s) from {baseline_path}")
        for fp in stale:
            print(f"  {fp}")
        return 0
    baseline = load_baseline(baseline_path)
    stale = stale_baseline_entries(findings, baseline)
    findings = filter_baseline(findings, baseline)

    n_files = len(iter_python_files(args.paths))
    if args.format != "text":
        doc = (findings_to_json(findings, files_scanned=n_files)
               if args.format == "json" else findings_to_sarif(findings))
        text = json.dumps(doc, indent=2) + "\n"
        if args.output:
            args.output.write_text(text)
            print(f"{args.format} findings -> {args.output}")
        else:
            sys.stdout.write(text)
    else:
        for f in findings:
            print(f.format())
    # stale entries warn (stderr — machine formats keep a clean stdout)
    # but don't fail the run: they mask nothing yet, they only risk
    # shadowing a future same-line regression
    for fp in stale:
        print(f"warning: stale baseline entry {fp} matches no current "
              "finding (prune with --prune-baseline)", file=sys.stderr)
    if findings:
        if args.format == "text":
            counts: dict = {}
            for f in findings:
                counts[f.rule] = counts.get(f.rule, 0) + 1
            summary = ", ".join(
                f"{r}: {n}" for r, n in sorted(counts.items()))
            print(f"\n{len(findings)} finding(s) ({summary})")
        return 1
    if args.format == "text":
        print(f"graftlint: clean ({n_files} files)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
