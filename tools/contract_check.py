#!/usr/bin/env python
"""Chip-free tracing contract checker: statically assert the invariants the
generation/training stack otherwise holds only by convention.

Five review rounds' worth of contracts live in comments ("the cache is
bf16 when the flag is on", "attention accumulates in f32", "pjit shardings
resolve on every mesh") — this tool turns them into assertions that run in
seconds on CPU with **zero FLOPs**: everything goes through
``jax.eval_shape`` / ``jax.make_jaxpr`` / AOT lowering on a virtual
8-device host mesh, so a dead invariant is caught before it ever reaches
the chip queue (tools/chip_babysitter.sh runs this ahead of the A/B
stages).

Checked contracts (see ISSUE 2 / PERF.md "bf16 sliced-KV cache" and
ISSUE 7 "int8 quantized serving"):

* C1 cache dtype — ``DALLE.prefill`` returns bf16 caches iff
  ``kv_cache_bf16`` (or the model itself runs bf16), and ``(int8 values,
  f32 per-head scale)`` pairs iff ``kv_cache_int8``; head logits stay
  f32.
* C2 f32 accumulation — in the decode jaxpr every dot with a bf16 OR
  int8 operand carries ``preferred_element_type=f32`` (the MXU's
  low-precision-in/f32-acc mode); applies to f32-activation models,
  where such an operand can only be the stored cache or a quantized
  weight.
* C3 no full-cache / full-weight dequant materialization — the decode
  jaxpr (and, under the int8 flags, the serve-tick jaxpr) contains no
  bf16/int8 -> f32 convert of a full-cache-sized array and no int8 ->
  f32/bf16 convert of a full-weight-sized array (the XLA hoist that
  defeated the bf16 cache until PR 1 pinned cache-dtype multiplicands —
  the int8 recipe has the same failure mode one byte lower).
* C4 shardings resolve — for all five parallel strategies (dp, fsdp, tp,
  sp-ring, sp-ulysses) the strategy's step traces and its shardings
  lower/partition on a virtual mesh.
* C5 config variants instantiate — the pallas tile ladder (128/256/512)
  and all three KV-cache storage layouts prefill to the expected shapes
  at the production CUB geometry.

Usage:
    JAX_PLATFORMS=cpu python tools/contract_check.py [--quick]

Exit 0 iff every contract holds.
"""
from __future__ import annotations

import argparse
import dataclasses
import sys
from pathlib import Path
from typing import Optional

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import os

# Chip-free by construction: an 8-device virtual CPU mesh, forced BEFORE
# jax initializes a backend — with the axon tunnel plugin pinned and the
# tunnel down, any device query would otherwise hang (BACKEND001).
os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=8"

import jax

from dalle_pytorch_tpu.cli import apply_platform_env

apply_platform_env()

import jax.numpy as jnp
import numpy as np

from dalle_pytorch_tpu import DALLE, DALLEConfig
from dalle_pytorch_tpu.models.dalle import decode_codes
from dalle_pytorch_tpu.parallel.mesh import Partitioner, make_mesh
from dalle_pytorch_tpu.training import (make_dalle_sp_train_step,
                                        make_optimizer)


class ContractViolation(AssertionError):
    """A statically-checkable invariant the codebase relies on is broken."""


# --- geometries ----------------------------------------------------------


def tiny_config(**overrides) -> DALLEConfig:
    """Small geometry for the strategy checks: seq 24 (divisible by sp=2),
    heads 4 (divisible by the ulysses sp axis)."""
    base = dict(dim=32, depth=2, heads=4, dim_head=8, num_text_tokens=50,
                text_seq_len=8, num_image_tokens=32, image_size=64,
                image_fmap_size=4)
    base.update(overrides)
    return DALLEConfig(**base)


def cub_config(**overrides) -> DALLEConfig:
    """The production CUB-200 geometry (bench.py::cub200_config shapes)."""
    base = dict(dim=256, depth=8, heads=8, dim_head=64,
                num_text_tokens=7800, text_seq_len=80,
                num_image_tokens=1024, image_size=256, image_fmap_size=32)
    base.update(overrides)
    return DALLEConfig(**base)


# --- shape/jaxpr plumbing ------------------------------------------------


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _init_shapes(dalle: DALLE, batch: int = 2):
    cfg = dalle.cfg
    text = _sds((batch, cfg.text_seq_len), jnp.int32)
    # init with image codes present so the full param tree exists (text-only
    # forwards never create image_emb)
    codes = _sds((batch, cfg.image_seq_len), jnp.int32)
    variables = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                               codes)
    return variables, text


def _prefill_shapes(dalle: DALLE, batch: int = 2):
    variables, text = _init_shapes(dalle, batch)
    logits, kvs = jax.eval_shape(
        lambda v, t: dalle.apply(v, t, method=DALLE.prefill), variables, text)
    return variables, text, logits, kvs


def _iter_eqns(jaxpr):
    """All equations of a jaxpr, recursing into nested jaxprs (pjit bodies,
    scan/while/cond branches)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            vals = val if isinstance(val, (tuple, list)) else (val,)
            for v in vals:
                inner = getattr(v, "jaxpr", None)
                if inner is not None:
                    yield from _iter_eqns(inner)
                elif hasattr(v, "eqns"):
                    yield from _iter_eqns(v)


def _decode_jaxpr(cfg: DALLEConfig, dalle=None, batch: int = 2):
    """Jaxpr of the full sampling scan (prefill state -> all image codes) —
    the program whose HBM traffic the bf16-cache contract governs."""
    dalle = dalle or DALLE(cfg)
    variables, _, logits, kvs = _prefill_shapes(dalle, batch)
    rng = _sds((2,), jnp.uint32)  # raw PRNGKey layout

    def run(v, first_logits, caches, rng):
        return decode_codes(dalle, v, first_logits, caches, rng)

    return jax.make_jaxpr(run)(variables, logits, kvs, rng), kvs


# --- C1: cache/Logits dtype ---------------------------------------------


def check_cache_dtype(cfg: DALLEConfig, dalle=None) -> None:
    """prefill caches are bf16 iff kv_cache_bf16 (or a bf16 model), and
    (int8 values, f32 per-head scale) pairs iff kv_cache_int8; the
    logits head output stays f32 regardless."""
    dalle = dalle or DALLE(cfg)
    _, _, logits, kvs = _prefill_shapes(dalle)
    expected = jnp.bfloat16 if (cfg.kv_cache_bf16
                                or cfg.dtype == jnp.bfloat16) else jnp.float32
    for i, (k, v) in enumerate(kvs):
        for name, leaf in (("k", k), ("v", v)):
            if cfg.kv_cache_int8:
                if not (isinstance(leaf, tuple) and len(leaf) == 2):
                    raise ContractViolation(
                        f"layer {i} cache {name} is not an (int8, scale) "
                        f"pair under kv_cache_int8: {type(leaf).__name__}")
                values, scale = leaf
                if values.dtype != jnp.int8:
                    raise ContractViolation(
                        f"layer {i} cache {name} values dtype "
                        f"{values.dtype} != int8 (kv_cache_int8=True)")
                b, h = values.shape[0], values.shape[1]
                if scale.dtype != jnp.float32 or scale.shape != (b, h, 1, 1):
                    raise ContractViolation(
                        f"layer {i} cache {name} scale {scale.dtype}"
                        f"{scale.shape} != f32 per-head plane "
                        f"{(b, h, 1, 1)} — the ops/quant.py scale-layout "
                        "contract")
                leaf = values
            elif leaf.dtype != expected:
                raise ContractViolation(
                    f"layer {i} cache {name} dtype {leaf.dtype} != "
                    f"{jnp.dtype(expected).name} (kv_cache_bf16="
                    f"{cfg.kv_cache_bf16}, dtype={jnp.dtype(cfg.dtype).name})")
            if name == "k" and leaf.shape[2] != cfg.seq_len:
                raise ContractViolation(
                    f"layer {i} cache holds {leaf.shape[2]} positions, "
                    f"expected seq_len={cfg.seq_len}")
    if logits.dtype != jnp.float32:
        raise ContractViolation(
            f"prefill logits dtype {logits.dtype} != float32 — the head "
            "must accumulate and emit f32")
    if logits.shape[-1] != cfg.num_image_tokens:
        raise ContractViolation(
            f"prefill logits vocab {logits.shape[-1]} != image vocab "
            f"{cfg.num_image_tokens}")


# --- C2 + C3: decode jaxpr contracts ------------------------------------


def check_decode_dots_accumulate_f32(cfg: DALLEConfig, dalle=None) -> None:
    """Every dot in the decode program with a bf16 or int8 operand must
    state f32 accumulation.  Only meaningful for f32-activation models
    (checkpoint eval dtype): there, such an operand can only be the
    stored cache or a session-quantized weight."""
    if cfg.dtype != jnp.float32:
        raise ValueError("C2 applies to f32-activation configs only")
    jaxpr, _ = _decode_jaxpr(cfg, dalle)
    low = (jnp.bfloat16, jnp.int8)
    for eqn in _iter_eqns(jaxpr.jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        hits = [v.aval.dtype for v in eqn.invars if v.aval.dtype in low]
        if not hits:
            continue
        pref = eqn.params.get("preferred_element_type")
        if pref is None or jnp.dtype(pref) != jnp.dtype(jnp.float32):
            name = "bf16" if hits[0] == jnp.bfloat16 else "int8"
            raise ContractViolation(
                f"decode dot_general with {name} operand accumulates in "
                f"{pref or 'operand dtype'} (line {eqn.source_info.traceback}"
                f") — must be preferred_element_type=f32")


def _cache_elems(kvs) -> int:
    """Smallest per-layer cache element count; int8 entries are (values,
    scale) pairs."""
    sizes = []
    for k, _ in kvs:
        values = k[0] if isinstance(k, tuple) else k
        sizes.append(int(np.prod(values.shape)))
    return min(sizes)


def _min_weight_elems(cfg: DALLEConfig, variables) -> int:
    """Smallest quantized decode-weight kernel (element count) — the
    threshold above which an int8->float convert means a dequantized
    weight copy, not a per-step activation."""
    from dalle_pytorch_tpu.models.dalle import quantize_decode_weights

    qw = jax.eval_shape(lambda v: quantize_decode_weights(v, cfg),
                        variables)
    sizes = [int(np.prod(leaf.shape))
             for leaf in jax.tree.leaves(qw)
             if leaf.dtype == jnp.int8]
    return min(sizes)


def _scan_dequant_converts(jaxpr, cache_elems: int,
                           weight_elems: Optional[int], label: str) -> None:
    """The shared C3 walk: no low-precision -> f32 convert at or above
    full-cache size, and (when weights are quantized) no int8 -> float
    convert at or above full-weight size."""
    low = (jnp.bfloat16, jnp.int8)
    for eqn in _iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        (invar,), (outvar,) = eqn.invars, eqn.outvars
        if getattr(invar, "aval", None) is None:
            continue
        src, dst = invar.aval.dtype, outvar.aval.dtype
        size = int(np.prod(outvar.aval.shape))
        # the weight rule first: an int8 convert that clears the (smaller)
        # weight threshold is a dequantized kernel, the sharper diagnosis
        if weight_elems is not None and src == jnp.int8 \
                and dst in (jnp.float32, jnp.bfloat16) \
                and size >= weight_elems:
            raise ContractViolation(
                f"{label} program materializes a dequantized weight copy: "
                f"convert_element_type int8->{dst} of shape "
                f"{outvar.aval.shape} (>= weight size {weight_elems})")
        if src in low and dst == jnp.float32 and size >= cache_elems:
            raise ContractViolation(
                f"{label} program materializes a full-cache f32 copy: "
                f"convert_element_type {src}->f32 of shape "
                f"{outvar.aval.shape} (>= cache size {cache_elems})")


def check_no_f32_cache_materialization(cfg: DALLEConfig, dalle=None) -> None:
    """The decode program never converts a full-cache-sized bf16/int8
    array to f32 — the hoist that would silently double decode HBM
    traffic and defeat kv_cache_bf16/kv_cache_int8 (PR 1's measured
    failure mode) — nor, under weights_int8, a full-weight-sized int8
    array to any float."""
    dalle = dalle or DALLE(cfg)
    jaxpr, kvs = _decode_jaxpr(cfg, dalle)
    weight_elems = None
    if cfg.weights_int8:
        variables, _ = _init_shapes(dalle)
        weight_elems = _min_weight_elems(cfg, variables)
    _scan_dequant_converts(jaxpr.jaxpr, _cache_elems(kvs), weight_elems,
                           "decode")


def check_serve_tick_no_dequant(cfg: DALLEConfig, num_slots: int = 2) -> None:
    """C3 over the SERVE-TICK jaxpr: the phase-aligned batched decode
    step the arena runs every tick (per-slot index vector, shared write
    column, session-quantized weight arguments) must be as free of
    dequant hoists as the static decode scan — a full-precision copy
    here would re-pay the cache/weight bytes on every tick for every
    slot."""
    dalle = DALLE(cfg)
    variables, _ = _init_shapes(dalle, batch=1)
    S = num_slots
    cache_shape = (S, cfg.heads, cfg.seq_len, cfg.dim_head)
    if cfg.kv_cache_int8:
        entry = (_sds(cache_shape, jnp.int8),
                 _sds((S, cfg.heads, 1, 1), jnp.float32))
    else:
        entry = _sds(cache_shape,
                     jnp.bfloat16 if (cfg.kv_cache_bf16
                                      or cfg.dtype == jnp.bfloat16)
                     else cfg.dtype)
    caches = [(entry, entry) for _ in range(cfg.depth)]
    code = _sds((S,), jnp.int32)
    index = _sds((S,), jnp.int32)
    write_pos = _sds((), jnp.int32)
    weight_elems = None
    qw = None
    if cfg.weights_int8:
        from dalle_pytorch_tpu.models.dalle import quantize_decode_weights

        qw = jax.eval_shape(lambda v: quantize_decode_weights(v, cfg),
                            variables)
        weight_elems = _min_weight_elems(cfg, variables)

    def tick(v, code, caches, index, write_pos, qw):
        return dalle.apply(v, code, caches, index, None, write_pos, qw,
                           method=DALLE.decode_step)

    jaxpr = jax.make_jaxpr(tick)(variables, code, caches, index, write_pos,
                                 qw)
    _scan_dequant_converts(jaxpr.jaxpr, _cache_elems(caches), weight_elems,
                           "serve-tick")


def check_spec_verify_no_dequant(cfg: DALLEConfig, num_slots: int = 2) -> None:
    """C3 over the SPECULATIVE span jaxpr (``DALLE.decode_span`` at
    K=spec_k, the verify pass of graftspec's tick_spec): the batched
    K-wide verify is the full weight+cache stream one spec tick pays —
    a dequant hoist here would scale with K and erase the entire
    speculation win."""
    assert cfg.spec_decode, "spec_decode must be on for the spec C3 check"
    dalle = DALLE(cfg)
    variables, _ = _init_shapes(dalle, batch=1)
    S, K = num_slots, cfg.spec_k
    cache_shape = (S, cfg.heads, cfg.seq_len, cfg.dim_head)
    if cfg.kv_cache_int8:
        entry = (_sds(cache_shape, jnp.int8),
                 _sds((S, cfg.heads, 1, 1), jnp.float32))
    else:
        entry = _sds(cache_shape,
                     jnp.bfloat16 if (cfg.kv_cache_bf16
                                      or cfg.dtype == jnp.bfloat16)
                     else cfg.dtype)
    caches = [(entry, entry) for _ in range(cfg.depth)]
    codes = _sds((S, K), jnp.int32)
    qpos = _sds((S, K), jnp.int32)
    rot = _sds((S,), jnp.int32)
    valid = _sds((S, K), jnp.bool_)
    weight_elems = None
    qw = None
    if cfg.weights_int8:
        from dalle_pytorch_tpu.models.dalle import quantize_decode_weights

        qw = jax.eval_shape(lambda v: quantize_decode_weights(v, cfg),
                            variables)
        weight_elems = _min_weight_elems(cfg, variables)

    def span(v, codes, caches, qpos, rot, valid, qw):
        return dalle.apply(v, codes, caches, qpos, rot, valid, None, qw,
                           method=DALLE.decode_span)

    jaxpr = jax.make_jaxpr(span)(variables, codes, caches, qpos, rot,
                                 valid, qw)
    _scan_dequant_converts(jaxpr.jaxpr, _cache_elems(caches), weight_elems,
                           "spec-verify")


# --- C4: parallel strategies --------------------------------------------

# The framework's five parallel strategies (README "Scaling guide"):
# pure data parallel, ZeRO-style fsdp, tensor parallel, and the two
# sequence-parallel attention implementations.  pp/ep own separate
# trainers and are exercised by their own tier-1 tests.
STRATEGIES = {
    "dp": dict(mesh=dict(), plan=dict()),
    "fsdp": dict(mesh=dict(fsdp=4), plan=dict()),
    "tp": dict(mesh=dict(tp=2), plan=dict()),
    "sp_ring": dict(mesh=dict(sp=2),
                    plan=dict(ring_axis="sp", sp_impl="ring", sp_size=2)),
    "sp_ulysses": dict(mesh=dict(sp=2),
                       plan=dict(ring_axis="sp", sp_impl="ulysses",
                                 sp_size=2)),
}


def check_strategy(name: str, make_cfg=tiny_config, batch: int = 8) -> None:
    """Trace strategy ``name``'s training step on a virtual mesh and prove
    its shardings resolve — shard_map specs divide, partition rules map
    every param, and the dense strategies lower AOT under pjit."""
    spec = STRATEGIES[name]
    cfg = make_cfg(**spec["plan"])
    dalle = DALLE(cfg)
    mesh = make_mesh(**spec["mesh"])
    variables, text = _init_shapes(dalle, batch)
    codes = _sds((batch, cfg.image_seq_len), jnp.int32)
    try:
        if cfg.ring_axis is not None:
            tx = make_optimizer(1e-3)
            step = make_dalle_sp_train_step(dalle, tx, mesh, donate=False)
            opt = jax.eval_shape(tx.init, variables["params"])
            jax.eval_shape(step, variables["params"], opt, None, text, codes,
                           _sds((2,), jnp.uint32))
        else:
            pt = Partitioner(mesh=mesh)
            shardings = pt.param_shardings(variables["params"])

            def loss_fn(p, text, codes):
                return dalle.apply({"params": p}, text, codes,
                                   return_loss=True)

            jax.jit(loss_fn,
                    in_shardings=(shardings, pt.data_sharding,
                                  pt.data_sharding)).lower(
                        variables["params"], text, codes).compile()
    except ContractViolation:
        raise
    except Exception as e:
        raise ContractViolation(
            f"strategy {name!r} failed to trace/partition on mesh "
            f"{dict(mesh.shape)}: {type(e).__name__}: {e}") from e


# --- C6: scale presets (the cheap per-push half) -------------------------


def check_preset(name: str, batch: int = 8) -> None:
    """The scale rung (presets.SCALE_PRESETS) instantiates, its param
    count sits in the declared band, and the rung plan's shardings
    resolve under AOT lowering — no compile (the full opt0 S4 HBM proof
    is ``spmd_check --presets``' nightly concern; this is the chip-free
    gate every push pays, ~15s at dim-512)."""
    from dalle_pytorch_tpu.parallel.plan import PLAN_REGISTRY
    from dalle_pytorch_tpu.presets import SCALE_PRESETS, check_param_band

    try:
        check_param_band(name)
        plan = PLAN_REGISTRY[name]
        cfg = SCALE_PRESETS[name](**plan.config_overrides())
        dalle = DALLE(cfg)
        pt = plan.partitioner()
        variables, text = _init_shapes(dalle, batch)
        codes = _sds((batch, cfg.image_seq_len), jnp.int32)
        shardings = pt.param_shardings(variables["params"])

        def loss_fn(p, text, codes):
            return dalle.apply({"params": p}, text, codes,
                               return_loss=True)

        jax.jit(loss_fn,
                in_shardings=(shardings, pt.data_sharding,
                              pt.data_sharding)).lower(
                    variables["params"], text, codes)
    except ContractViolation:
        raise
    except ValueError as e:
        raise ContractViolation(str(e)) from e
    except Exception as e:
        raise ContractViolation(
            f"preset {name!r} failed to instantiate/lower: "
            f"{type(e).__name__}: {e}") from e


# --- C5: config variants ------------------------------------------------

PALLAS_TILES = (128, 256, 512)


def check_pallas_variant(block: int, make_cfg=cub_config) -> None:
    """The pallas tile config instantiates and prefills to the contract
    shapes (abstract eval only — Mosaic never lowers here)."""
    cfg = make_cfg(use_pallas=True, pallas_block_q=block,
                   pallas_block_k=block)
    check_cache_dtype(cfg)


# --- driver --------------------------------------------------------------


def run_all(quick: bool = False) -> int:
    make_cfg = tiny_config if quick else cub_config
    failures = 0

    def run(label, fn, *args, **kwargs):
        nonlocal failures
        try:
            fn(*args, **kwargs)
        except ContractViolation as e:
            failures += 1
            print(f"FAIL {label}: {e}")
        else:
            print(f"PASS {label}")

    for kv_bf16 in (True, False):
        cfg = make_cfg(kv_cache_bf16=kv_bf16)
        tag = f"kv_cache_bf16={kv_bf16}"
        run(f"C1 cache dtype [{tag}]", check_cache_dtype, cfg)
        run(f"C2 f32 accumulation [{tag}]",
            check_decode_dots_accumulate_f32, cfg)
        run(f"C3 no f32 cache materialization [{tag}]",
            check_no_f32_cache_materialization, cfg)
    run("C1 cache dtype [dtype=bf16]", check_cache_dtype,
        make_cfg(dtype=jnp.bfloat16, kv_cache_bf16=False))
    # int8 quantized serving (ISSUE 7): cache-only, then cache + weights;
    # C3 additionally walks the serve-tick jaxpr — both decode programs
    # must stay free of dequant hoists
    cfg_i8 = make_cfg(kv_cache_int8=True)
    run("C1 cache dtype [kv_cache_int8]", check_cache_dtype, cfg_i8)
    run("C2 f32 accumulation [kv_cache_int8]",
        check_decode_dots_accumulate_f32, cfg_i8)
    run("C3 no dequant materialization [kv_cache_int8]",
        check_no_f32_cache_materialization, cfg_i8)
    cfg_i8w = make_cfg(kv_cache_int8=True, weights_int8=True)
    run("C2 f32 accumulation [int8 cache+weights]",
        check_decode_dots_accumulate_f32, cfg_i8w)
    run("C3 no dequant materialization [int8 cache+weights]",
        check_no_f32_cache_materialization, cfg_i8w)
    run("C3 serve-tick no dequant [int8 cache+weights]",
        check_serve_tick_no_dequant, cfg_i8w)
    run("C3 serve-tick no dequant [bf16 cache]",
        check_serve_tick_no_dequant, make_cfg())
    # graftspec (ISSUE 16): the K-wide verify span is the spec tick's
    # whole byte stream — walk it under both cache layouts
    run("C3 spec-verify no dequant [int8 cache+weights]",
        check_spec_verify_no_dequant,
        make_cfg(spec_decode=True, kv_cache_int8=True, weights_int8=True))
    run("C3 spec-verify no dequant [bf16 cache]",
        check_spec_verify_no_dequant, make_cfg(spec_decode=True))
    for name in STRATEGIES:
        run(f"C4 shardings resolve [{name}]", check_strategy, name)
    for block in PALLAS_TILES if not quick else PALLAS_TILES[:1]:
        run(f"C5 pallas tiles [block={block}]", check_pallas_variant, block,
            make_cfg)
    if not quick:
        from dalle_pytorch_tpu.presets import SCALE_PRESETS
        for name in sorted(SCALE_PRESETS):
            run(f"C6 scale preset [{name}]", check_preset, name)

    print(f"\ncontract_check: {'FAIL' if failures else 'PASS'} "
          f"({failures} violation(s))")
    return 1 if failures else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--quick", action="store_true",
                        help="tiny geometry only (tests/dev smoke)")
    args = parser.parse_args(argv)
    return run_all(quick=args.quick)


if __name__ == "__main__":
    raise SystemExit(main())
