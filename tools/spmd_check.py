#!/usr/bin/env python
"""graftspmd CLI — jaxpr-level SPMD analysis of every train-step factory.

graftlint reads source and contract_check reads shapes; this tool reads
the *traced programs*.  It builds every train-step factory in
``training.py`` (``STEP_FACTORIES``) plus the decode path in
``models/dalle.py`` under each parallelism plan on a virtual 8-device CPU
mesh and enforces four analyses (``dalle_pytorch_tpu/lint/spmd.py``):

* **S1 collective order** — the per-shard collective sequence is
  identical and unconditionally executed: any psum/ppermute/all_gather/
  all_to_all under data-dependent control flow (a ``while``, or ``cond``
  branches with differing collective signatures) is an SPMD deadlock.
* **S2 donation audit** — params and opt_state leaves of every donating
  jit actually alias outputs (``args_info`` + the optimized HLO's
  ``input_output_alias`` config — jax drops donation silently when a
  donated input matches no output), and large (>1 MiB) undonated array
  args are reported.
* **S3 retrace sentinel** — N simulated steps per factory trace exactly
  once; a weak-hash or unhashable static arg is a per-epoch recompile
  storm.
* **S4 static HBM budget** — per-device live bytes (args + outputs −
  donated aliases + peak XLA temporaries, ``memory_analysis()``) of each
  plan's step at the production CUB geometry must fit the target chip
  (``--chip v4-8|v5e-4|cpu-virtual``).

Zero chip time by the same construction as contract_check: AOT trace/
lower/compile on CPU; only S3 executes, at toy geometry.  S2's alias
check compiles at TINY geometry and full optimization (donation
honoring is structural — and XLA's opt-level-0 path skips the alias
passes entirely, reporting alias=0 for honored donations); S4 compiles
the production geometry at backend optimization level 0 (argument/
output/temp buffer assignment is identical, ~10x faster codegen on one
core) and subtracts the S2-verified donated fraction in place of the
opt0-zeroed alias stat.  ``tools/chip_babysitter.sh`` runs this as its
second pre-flight gate, CI's lint job uploads the ``--json`` findings.

Usage:
    JAX_PLATFORMS=cpu python tools/spmd_check.py [--chip v4-8] [--quick]
    python tools/spmd_check.py --selftest   # prove S1-S4 catch fixtures

Exit 0 iff every analysis passes on every plan.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import os

# Chip-free by construction: an 8-device virtual CPU mesh, forced BEFORE
# jax initializes a backend (BACKEND001 — a pinned-but-down tunnel hangs
# inside the first device query otherwise).
os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=8"

import jax

from dalle_pytorch_tpu.cli import apply_platform_env, enable_compilation_cache

apply_platform_env()
enable_compilation_cache()

import jax.numpy as jnp
import numpy as np

from dalle_pytorch_tpu import DALLE
from dalle_pytorch_tpu.lint import spmd
from dalle_pytorch_tpu.models.clip import CLIP, CLIPConfig
from dalle_pytorch_tpu.models.dalle import decode_codes
from dalle_pytorch_tpu.models.vae import DiscreteVAE, VAEConfig
from dalle_pytorch_tpu.parallel.mesh import Partitioner, make_mesh
from dalle_pytorch_tpu.training import (STEP_FACTORIES,
                                        make_clip_train_step,
                                        make_dalle_pp_train_step,
                                        make_dalle_sp_train_step,
                                        make_dalle_train_step, make_optimizer,
                                        make_vae_train_step)

# Backend optimization level 0 skips the LLVM codegen passes whose output
# S4 never reads — argument/output/temp buffer assignment is identical
# (measured on the CUB dp step) but the ALIAS stat is not: opt0 also
# skips XLA's input/output alias passes, so S2 never compiles with this
# and S4 substitutes the S2-verified donated fraction for the alias term.
OPT0 = {"xla_backend_optimization_level": 0}

# The factories this harness knows how to build and feed.  A new entry in
# training.STEP_FACTORIES without a harness here fails check_factory_
# coverage (and the tests/test_spmd_check.py meta-test).
HARNESSED_FACTORIES = frozenset(("vae", "dalle", "dalle_sp", "dalle_pp",
                                 "clip"))

# The parallelism plans of the DALLE model (contract_check C4's matrix
# plus pp) — GENERATED from the declarative plan registry
# (parallel/plan.py), not maintained beside it: the mesh kwargs, the
# DALLEConfig overrides, and the sharding expectations below all derive
# from the same ParallelPlan objects the trainers run, so this harness
# cannot drift from the production contract (ISSUE 10's single source of
# truth).  A new registry plan lands here automatically.
from dalle_pytorch_tpu.parallel.plan import PLAN_REGISTRY
from dalle_pytorch_tpu.presets import (SCALE_PRESETS, cub512_config,
                                       cub_config, tiny_config)

# Scale-preset rungs (cub-512: an ~8-minute opt0 compile at dim-512) are
# excluded from the per-push matrix; ``--presets`` runs their full S4.
PLANS = {name: dict(mesh=p.mesh_kwargs(), plan=p.config_overrides())
         for name, p in PLAN_REGISTRY.items() if name not in SCALE_PRESETS}

DALLE_ARG_LABELS = ("params", "opt_state", "vae_params", "text", "codes",
                    "rng", "fault_scale")
VAE_ARG_LABELS = ("params", "opt_state", "images", "rng", "temp",
                  "fault_scale")
CLIP_ARG_LABELS = ("params", "opt_state", "text", "images", "text_mask",
                   "fault_scale")


# --- geometries: tiny_config / cub_config / cub512_config re-exported
# above from dalle_pytorch_tpu.presets (contract_check's twins; ONE
# source for every scale rung) -------------------------------------------


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _zeros_like_tree(sds_tree):
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), sds_tree)


# --- per-factory setups ---------------------------------------------------


def dalle_step_lowered(plan: str, make_cfg=cub_config, batch: int = 8):
    """AOT-lower (and return labels for) the DALLE train step under one
    parallelism plan — health-enabled, donating, input shardings as the
    trainers place them (batch over the data axes, params as the
    Partitioner rules shard them, replicated under shard_map plans)."""
    spec = PLANS.get(plan) or dict(
        mesh=PLAN_REGISTRY[plan].mesh_kwargs(),
        plan=PLAN_REGISTRY[plan].config_overrides())
    cfg = make_cfg(**spec["plan"])
    dalle = DALLE(cfg)
    tx = make_optimizer(1e-3)
    mesh = make_mesh(**spec["mesh"])
    text = _sds((batch, cfg.text_seq_len), jnp.int32)
    codes = _sds((batch, cfg.image_seq_len), jnp.int32)
    rng = _sds((2,), jnp.uint32)
    fs = _sds((), jnp.float32)
    variables = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                               codes)
    params = variables["params"]

    if plan == "pp":
        # the pp factory restructures CONCRETE params (stage stacking)
        step, pp_params = make_dalle_pp_train_step(
            dalle, tx, _zeros_like_tree(params), mesh, num_microbatches=2,
            health=True)
        opt = jax.eval_shape(tx.init, pp_params)
        lowered = step.lower(pp_params, opt, None, text, codes, rng, fs)
    elif cfg.ring_axis is not None:
        step = make_dalle_sp_train_step(dalle, tx, mesh, health=True)
        opt = jax.eval_shape(tx.init, params)
        lowered = step.lower(params, opt, None, text, codes, rng, fs)
    else:
        # the Partitioner derives from the plan object itself — the same
        # construction path the trainers take, so the shardings this
        # analysis gates ARE the shardings production runs
        pt = PLAN_REGISTRY[plan].partitioner(mesh=mesh)
        sharded = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            params, pt.param_shardings(params))
        opt = jax.eval_shape(tx.init, params)
        opt_sharded = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            opt, pt.param_shardings(opt))
        data = lambda s: jax.ShapeDtypeStruct(  # noqa: E731
            s.shape, s.dtype, sharding=pt.data_sharding)
        step = make_dalle_train_step(dalle, tx, health=True, partitioner=pt)
        lowered = step.lower(sharded, opt_sharded, None, data(text),
                             data(codes), rng, fs)
    return lowered


def tiny_dalle_concrete(plan: str, batch: int = 8):
    # batch 8: under pp the per-microbatch rows (batch/2) must divide the
    # dp axis (4 ways on the 8-device (dp, pp) mesh)
    """Concrete tiny step + fresh-args generator for S1 (jaxpr) and S3
    (trace counting).  donate=False: S3 reuses the same concrete
    params/opt across simulated steps."""
    spec = PLANS[plan]
    cfg = tiny_config(**spec["plan"])
    dalle = DALLE(cfg)
    tx = make_optimizer(1e-3)
    mesh = make_mesh(**spec["mesh"])
    text = jnp.zeros((batch, cfg.text_seq_len), jnp.int32)
    codes = jnp.zeros((batch, cfg.image_seq_len), jnp.int32)
    variables = dalle.init(jax.random.PRNGKey(0), text, codes)
    params = variables["params"]
    if plan == "pp":
        step, params = make_dalle_pp_train_step(
            dalle, tx, params, mesh, num_microbatches=2, donate=False,
            health=True)
    elif cfg.ring_axis is not None:
        step = make_dalle_sp_train_step(dalle, tx, mesh, donate=False,
                                        health=True)
    else:
        step = make_dalle_train_step(dalle, tx, donate=False, health=True)
    opt = tx.init(params)

    def make_args(i):
        r = np.random.RandomState(i)
        return (params, opt, None,
                jnp.asarray(r.randint(1, 40, size=(batch, cfg.text_seq_len)),
                            jnp.int32),
                jnp.asarray(r.randint(0, cfg.num_image_tokens,
                                      size=(batch, cfg.image_seq_len)),
                            jnp.int32),
                jnp.asarray([i, i + 1], jnp.uint32), jnp.float32(1.0))

    # S2 for the dalle factories runs at production geometry via
    # dalle_step_lowered — no donating twin needed here
    return step, make_args, None


def tiny_vae_concrete(batch: int = 4):
    cfg = VAEConfig(image_size=16, num_tokens=16, codebook_dim=16,
                    num_layers=1, hidden_dim=16)
    vae = DiscreteVAE(cfg)
    tx = make_optimizer(1e-3)
    images = jnp.zeros((batch, 16, 16, 3), jnp.float32)
    params = vae.init(jax.random.PRNGKey(0), images,
                      rng=jax.random.PRNGKey(1))["params"]
    # donate=False for S3 (the same concrete params feed N simulated
    # steps); the donating twin the trainers actually run feeds S2
    step = make_vae_train_step(vae, tx, donate=False, health=True)
    donating = make_vae_train_step(vae, tx, health=True)
    opt = tx.init(params)

    def make_args(i):
        r = np.random.RandomState(i)
        return (params, opt,
                jnp.asarray(r.rand(batch, 16, 16, 3), jnp.float32),
                jnp.asarray([i, i + 1], jnp.uint32),
                jnp.float32(0.9 / (i + 1)), jnp.float32(1.0))

    return step, make_args, donating


def tiny_clip_concrete(batch: int = 4):
    cfg = CLIPConfig(dim_text=16, dim_image=16, dim_latent=16,
                     num_text_tokens=64, text_enc_depth=1, text_seq_len=8,
                     text_heads=2, num_visual_tokens=64, visual_enc_depth=1,
                     visual_heads=2, visual_image_size=16,
                     visual_patch_size=8)
    clip = CLIP(cfg)
    tx = make_optimizer(1e-3)
    text = jnp.zeros((batch, cfg.text_seq_len), jnp.int32)
    images = jnp.zeros((batch, 16, 16, 3), jnp.float32)
    mask = jnp.ones((batch, cfg.text_seq_len), bool)
    params = clip.init(jax.random.PRNGKey(0), text, images,
                       text_mask=mask)["params"]
    step = make_clip_train_step(clip, tx, donate=False, health=True)
    donating = make_clip_train_step(clip, tx, health=True)
    opt = tx.init(params)

    def make_args(i):
        r = np.random.RandomState(i)
        return (params, opt,
                jnp.asarray(r.randint(1, 63, size=(batch, cfg.text_seq_len)),
                            jnp.int32),
                jnp.asarray(r.rand(batch, 16, 16, 3), jnp.float32), mask,
                jnp.float32(1.0))

    return step, make_args, donating


TINY_FACTORY_SETUPS = {
    "vae": tiny_vae_concrete,
    "clip": tiny_clip_concrete,
    "dalle": lambda: tiny_dalle_concrete("dp"),
    "dalle_sp": lambda: tiny_dalle_concrete("sp-ring"),
    "dalle_pp": lambda: tiny_dalle_concrete("pp"),
}

FACTORY_ARG_LABELS = {
    "vae": VAE_ARG_LABELS,
    "clip": CLIP_ARG_LABELS,
    "dalle": DALLE_ARG_LABELS,
    "dalle_sp": DALLE_ARG_LABELS,
    "dalle_pp": DALLE_ARG_LABELS,
}


def decode_jaxpr(make_cfg=tiny_config, batch: int = 2):
    """Jaxpr of the sampling scan (prefill state -> image codes) — the
    decode path S1 walks.  Collective-free today; the analysis pins that
    a future sharded sampler cannot regress it silently."""
    cfg = make_cfg()
    dalle = DALLE(cfg)
    text = _sds((batch, cfg.text_seq_len), jnp.int32)
    codes = _sds((batch, cfg.image_seq_len), jnp.int32)
    variables = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                               codes)
    logits, kvs = jax.eval_shape(
        lambda v, t: dalle.apply(v, t, method=DALLE.prefill), variables,
        text)
    rng = _sds((2,), jnp.uint32)

    def run(v, first_logits, caches, rng):
        return decode_codes(dalle, v, first_logits, caches, rng)

    return jax.make_jaxpr(run)(variables, logits, kvs, rng)


def serve_retrace_check(num_slots: int = 3, **cfg_overrides):
    """S3 for the continuous-batching serve tick (ISSUE 6): drive a real
    GenerationServer over the tiny model through admit/retire churn —
    occupancy rising 1 -> num_slots mid-flight, requests retiring at
    staggered ticks, a freed slot re-admitted, the arena clock wrapping
    seq_len — and require every jitted entry point (prefill / admit /
    tick) to have compiled EXACTLY once.  A per-occupancy or per-slot
    shape anywhere in the arena turns every arrival into a recompile on
    the pod (the storm `lint/spmd_fixtures.py::
    make_shape_changing_serve_tick` exhibits, proven caught in the
    selftest).  ``cfg_overrides`` select plan variants — the int8 arena
    (kv_cache_int8 + weights_int8, ISSUE 7) re-runs the same churn over
    the quantized cache/scale planes and the session-quantized weight
    arguments."""
    import numpy as np

    from dalle_pytorch_tpu.serve import GenerationServer

    cfg = tiny_config(**cfg_overrides)
    dalle = DALLE(cfg)
    text = jnp.zeros((1, cfg.text_seq_len), jnp.int32)
    codes = jnp.zeros((1, cfg.image_seq_len), jnp.int32)
    variables = dalle.init(jax.random.PRNGKey(0), text, codes)
    server = GenerationServer(dalle, variables, num_slots=num_slots,
                              filter_thres=0.9)

    def prompt(i):
        r = np.random.RandomState(i)
        return r.randint(1, 40, size=(cfg.text_seq_len,)).astype(np.int32)

    server.submit(prompt(0))
    for _ in range(3):                      # occupancy 1
        server.step()
    for i in range(1, num_slots):
        server.submit(prompt(i))            # fill mid-flight
    for _ in range(3):                      # occupancy num_slots
        server.step()
    server.submit(prompt(num_slots))        # queued; admits on first retire
    server.run_until_idle(max_ticks=40 * cfg.image_seq_len)
    # spec-decode ticks commit multiple tokens, so a fixed request count
    # may finish before the clock wraps — keep the churn going until it
    # does (greedy runs have already wrapped; the loop is a no-op there)
    extra = num_slots + 1
    while server._clock <= cfg.seq_len:
        server.submit(prompt(extra))
        extra += 1
        server.run_until_idle(max_ticks=40 * cfg.image_seq_len)
    assert server._clock > cfg.seq_len, "churn must wrap the arena clock"
    counts = server.trace_counts()
    bad = {k: v for k, v in counts.items() if v != 1}
    if bad:
        raise spmd.SPMDViolation(
            f"S3 retrace [serve-tick]: admit/retire churn across "
            f"occupancies 1..{num_slots} recompiled {bad} — a serve-path "
            "shape depends on occupancy/slot/clock; every arrival would "
            "recompile on the pod")
    return (f"{len(server.completed)} requests across occupancies "
            f"1..{num_slots}, clock wrapped at {server._clock} ticks: "
            "prefill/admit/tick each compiled once")


def pp_scan_schedule_check(microbatch_counts=(2, 4),
                           microbatch_rows: int = 8) -> str:
    """S1 for the pipeline plan's microbatch scan (PR 5 carried
    follow-up): per-body uniformity proves each scan iteration issues one
    lockstep collective sequence, but the pipeline's deadlock surface is
    the TOTAL schedule — iteration count x per-iteration sequence — across
    the GPipe scan.  Extract the schedule (``spmd.scan_collective_
    schedule``: static because scan's trip count is static and any
    collective under data-dependent control flow inside the body is
    refused) and prove it is exactly ``(m + pp - 1) x seq`` with the SAME
    per-iteration sequence at different microbatch counts — i.e. the knob
    that shapes the schedule scales only the iteration count, never the
    sequence the stages must agree on."""
    spec = PLANS["pp"]
    pp_ways = spec["mesh"]["pp"]
    mesh = make_mesh(**spec["mesh"])
    cfg = tiny_config(**spec["plan"])
    dalle = DALLE(cfg)
    tx = make_optimizer(1e-3)
    init_text = jnp.zeros((2, cfg.text_seq_len), jnp.int32)
    init_codes = jnp.zeros((2, cfg.image_seq_len), jnp.int32)
    params = dalle.init(jax.random.PRNGKey(0), init_text,
                        init_codes)["params"]
    rng = jnp.zeros((2,), jnp.uint32)
    fs = jnp.float32(1.0)

    schedules = {}
    for m in microbatch_counts:
        # batch scales with m so the MICROBATCH geometry (what one scan
        # iteration actually moves) is held constant — the comparison below
        # is then exact down to operand shapes, not just primitive order
        batch = microbatch_rows * m
        text = jnp.zeros((batch, cfg.text_seq_len), jnp.int32)
        codes = jnp.zeros((batch, cfg.image_seq_len), jnp.int32)
        step, pp_params = make_dalle_pp_train_step(
            dalle, tx, params, mesh, num_microbatches=m, donate=False,
            health=True)
        opt = jax.eval_shape(tx.init, pp_params)
        jaxpr = jax.make_jaxpr(step)(pp_params, opt, None, text, codes,
                                     rng, fs)
        scans = spmd.scan_collective_schedule(jaxpr, label=f"dalle_pp/m{m}")
        if not scans:
            raise spmd.SPMDViolation(
                f"S1 scan schedule [dalle_pp/m{m}]: no collective-bearing "
                "scan found — the GPipe microbatch scan lost its stage "
                "handoffs (or the analysis no longer sees them)")
        # the microbatch scan is the one whose trip count is m + pp - 1
        # (forward) — the backward scan mirrors it with the transposed
        # collectives, so every entry must obey the same law
        expect_len = m + pp_ways - 1
        bad = [s for s in scans if s.length != expect_len]
        if bad:
            raise spmd.SPMDViolation(
                f"S1 scan schedule [dalle_pp/m{m}]: collective-bearing "
                f"scan(s) with trip count != microbatches + stages - 1 "
                f"({expect_len}): "
                + "; ".join(s.format() for s in bad))
        schedules[m] = scans

    counts = {m: len(s) for m, s in schedules.items()}
    if len(set(counts.values())) != 1:
        raise spmd.SPMDViolation(
            f"S1 scan schedule [dalle_pp]: different numbers of "
            f"collective-bearing scans across microbatch counts ({counts})")
    m0 = microbatch_counts[0]
    for m in microbatch_counts[1:]:
        for a, b in zip(schedules[m0], schedules[m]):
            if a.per_iteration != b.per_iteration:
                raise spmd.SPMDViolation(
                    "S1 scan schedule [dalle_pp]: the per-iteration "
                    f"collective sequence CHANGES with the microbatch "
                    f"count (m={m0}: {a.format()} vs m={m}: {b.format()}) "
                    "— the schedule is not iteration-count x sequence, so "
                    "stages disagreeing on the count deadlock")
    detail = "; ".join(
        f"m={m}: " + " + ".join(s.format() for s in schedules[m])
        for m in microbatch_counts)
    return f"schedule is (m + pp - 1) x fixed sequence — {detail}"


def s4_drift_check(plan: str = "dp", make_cfg=cub_config,
                   temp_tol: float = 0.15) -> str:
    """S4 opt-0 drift gate (PR 5 carried follow-up): S4 budgets every plan
    from a backend-opt-level-0 compile on the assumption that XLA's
    argument/output/temp buffer assignment is identical to the full
    pipeline's.  That held when measured, but nothing pins it across XLA
    upgrades — so compile ONE plan BOTH ways and diff: argument and
    output bytes must match exactly, temp bytes within ``temp_tol``.
    Scheduled CI runs this (tests.yml full job); a failure means the
    opt-0 shortcut now under- or over-budgets and S4 must recalibrate."""
    lowered = dalle_step_lowered(plan, make_cfg=make_cfg)
    with spmd.fresh_stats_compile():
        full = spmd.hbm_estimate(lowered.compile())
        opt0 = spmd.hbm_estimate(lowered.compile(OPT0))
    problems = []
    for field in ("argument_bytes", "output_bytes"):
        a, b = getattr(full, field), getattr(opt0, field)
        if a != b:
            problems.append(f"{field}: full-opt {a} != opt0 {b}")
    drift = abs(opt0.temp_bytes - full.temp_bytes) / max(full.temp_bytes, 1)
    if drift > temp_tol:
        problems.append(
            f"temp_bytes: full-opt {full.temp_bytes} vs opt0 "
            f"{opt0.temp_bytes} ({drift:.1%} > {temp_tol:.0%})")
    if problems:
        raise spmd.SPMDViolation(
            f"S4 opt0-drift [dalle/{plan}]: " + "; ".join(problems) +
            " — XLA's opt-0 buffer assignment no longer matches the full "
            "pipeline; the S4 budget shortcut is invalid")
    return (f"opt0 == full-opt: args {full.argument_bytes}, out "
            f"{full.output_bytes}, temp drift {drift:.1%}")


def proofs_path() -> Path:
    """The committed S4 proof cache: GRAFT_S4_PROOFS env override (tests,
    scratch runs) > repo-root S4_PROOFS.json."""
    env = os.environ.get("GRAFT_S4_PROOFS")
    return Path(env) if env else REPO / "S4_PROOFS.json"


def _preset_proof_fingerprint(name: str, cfg) -> str:
    """Key of one rung's compiled proof: geometry + registry plan +
    harness point + the jax that compiled it.  Any edit that could change
    buffer assignment re-keys the proof, so a stale cache can never gate."""
    from dalle_pytorch_tpu.obs import prof

    return prof.row_fingerprint(prof.fingerprint_payload(
        cfg, target=f"s4-proof/{name}", plan=PLAN_REGISTRY[name].spec(),
        batch=8, devices=len(jax.devices()), opt0=True,
        jax=jax.__version__))


#: Declared opt0 verdict per rung against the gate chip — the PERF_LEDGER
#: ``fits: false`` pattern applied to the compiled proof.  "fits": the
#: estimate must pass check_hbm_budget (the normal gate).  "over": the
#: rung is KNOWN not to prove fit at opt0 — XLA's opt0 buffer assignment
#: does not reuse buffers across the per-block remat regions, so the
#: cub-1024 temp stat is the *sum* of all 76 blocks' internals (~132 GiB
#: at batch 8) while the liveness-aware jaxpr walker peaks at ~10.7
#: GiB/device.  For an "over" rung the compiled proof is still committed
#: and still gates — as a drift sentinel: the compile must succeed AND
#: the estimate must still exceed the budget.  If a geometry/remat/XLA
#: change makes it FIT, that is news the gate surfaces; flip the entry
#: deliberately.  The fit verdict itself at an "over" rung is owned by
#: the analytic P3 state check (lint/plans.py) and the walker timeline
#: (tools/graftmem.py), both committed to PERF_LEDGER.json.
S4_PRESET_EXPECT = {"cub-512": "fits", "cub-1024": "over"}


def _gate_preset_estimate(name: str, est, chip: str) -> str:
    """Gate one rung's compiled estimate against its DECLARED verdict
    (:data:`S4_PRESET_EXPECT`).  Returns the PASS-line detail; raises
    SPMDViolation on any mismatch in either direction."""
    expect = S4_PRESET_EXPECT.get(name, "fits")
    try:
        spmd.check_hbm_budget(est, chip, label=f"preset/{name}@{chip}")
        verdict = "fits"
    except spmd.SPMDViolation as over:
        if expect == "fits":
            raise
        verdict = "over"
    if verdict == "over":
        return ("over budget as declared (opt0 assignment is reuse-free "
                "across remat blocks; P3 + the walker own the fit "
                "verdict at this rung)")
    if expect == "over":
        raise spmd.SPMDViolation(
            f"S4 hbm [preset/{name}@{chip}]: the estimate now FITS the "
            "budget but S4_PRESET_EXPECT declares the rung over — the "
            "opt0 verdict changed under you (geometry/remat/jax edit); "
            "flip the expectation to 'fits' deliberately and commit")
    return "fits budget"


def run_presets(chip: str = "v5e-4", only=None, refresh: bool = False) -> int:
    """The scale-preset S4 proof (``--presets``): for every
    presets.SCALE_PRESETS rung, lower the real train step at the rung's
    geometry under the rung's registry plan and gate the opt0 HBM
    estimate (with the S2-verified donation credit substituted, the
    _s4_detail convention) against ``chip`` — through the rung's
    declared verdict (:data:`S4_PRESET_EXPECT`): a "fits" rung must
    pass the budget, an "over" rung must still measure over (the
    drift-sentinel form; see the table's docstring).  Minutes per rung at
    dim-512, tens of minutes at dim-1024 — so the compiled estimate is
    persisted to S4_PROOFS.json keyed by a config fingerprint: when the
    stored key matches, the rung re-gates the cached estimate against
    the requested chip WITHOUT recompiling (the budget check is
    arithmetic; the 8-minute compile only re-runs when geometry, plan,
    harness point, or jax version actually changed — or under
    ``--refresh-proofs``).  ``only`` filters to one rung (the
    babysitter's spmd_1024 stage).  Nightly CI carries the gate;
    contract_check covers the cheap per-push half (param band +
    shardings lower)."""
    from dalle_pytorch_tpu.presets import check_param_band

    ppath = proofs_path()
    proofs = json.loads(ppath.read_text()) if ppath.exists() else {}
    failures = 0
    dirty = False
    rungs = {k: v for k, v in sorted(SCALE_PRESETS.items())
             if only is None or k == only}
    if only is not None and not rungs:
        print(f"spmd_check --presets: unknown rung {only!r}; known: "
              f"{sorted(SCALE_PRESETS)}", file=sys.stderr)
        return 2
    for name, make_cfg in rungs.items():
        t0 = time.time()
        try:
            band = check_param_band(name)
            fp = _preset_proof_fingerprint(name, make_cfg())
            proof = proofs.get(name)
            if proof and proof.get("fingerprint") == fp and not refresh:
                est = spmd.HBMEstimate(**proof["estimate"])
                detail = _gate_preset_estimate(name, est, chip)
                print(f"PASS S4-preset [{name}@{chip}] "
                      f"({time.time() - t0:.0f}s, cached proof {fp}, "
                      f"compiled in {proof.get('compile_s', '?')}s): "
                      f"{band}; {est.format()}; {detail}")
                continue
            lowered = dalle_step_lowered(name, make_cfg=make_cfg)
            with spmd.fresh_stats_compile():
                compiled = lowered.compile(OPT0)
            est = _s4_estimate(compiled, lowered)
            compile_s = int(time.time() - t0)
            # persist BEFORE gating: the proof records what the compile
            # measured; whether it fits a given chip is re-decided per run
            proofs[name] = {
                "fingerprint": fp,
                "plan": PLAN_REGISTRY[name].spec(),
                "estimate": dataclasses.asdict(est),
                "compile_s": compile_s,
                "jax": jax.__version__,
            }
            dirty = True
            detail = _gate_preset_estimate(name, est, chip)
            print(f"PASS S4-preset [{name}@{chip}] "
                  f"({time.time() - t0:.0f}s): {band}; {est.format()}; "
                  f"{detail}")
        except (spmd.SPMDViolation, ValueError) as e:
            failures += 1
            print(f"FAIL S4-preset [{name}@{chip}] "
                  f"({time.time() - t0:.0f}s): {e}")
    if dirty:
        tmp = ppath.with_name(ppath.name + ".tmp")
        tmp.write_text(json.dumps(proofs, indent=1, sort_keys=True) + "\n")
        os.replace(tmp, ppath)
        print(f"spmd_check --presets: proofs -> {ppath}")
    print(f"\nspmd_check --presets: {'FAIL' if failures else 'PASS'} "
          f"({len(rungs)} rung(s), chip={chip})")
    return 1 if failures else 0


def check_factory_coverage() -> None:
    """The registry/harness sync gate: every training.STEP_FACTORIES entry
    has a harness here, and vice versa."""
    missing = set(STEP_FACTORIES) - set(HARNESSED_FACTORIES)
    stale = set(HARNESSED_FACTORIES) - set(STEP_FACTORIES)
    if missing or stale:
        raise spmd.SPMDViolation(
            f"factory coverage drift: unanalyzed factories {sorted(missing)}"
            f", harnesses without a factory {sorted(stale)} — update "
            "tools/spmd_check.py HARNESSED_FACTORIES alongside "
            "training.STEP_FACTORIES")


# --- driver ---------------------------------------------------------------


def run_all(chip: str = "v4-8", quick: bool = False,
            json_out=None) -> int:
    t_start = time.time()
    results = []
    failures = 0

    def run(analysis: str, target: str, fn):
        nonlocal failures
        t0 = time.time()
        try:
            detail = fn() or ""
            status = "PASS"
        except spmd.SPMDViolation as e:
            detail, status = str(e), "FAIL"
            failures += 1
        # graftlint: disable=EXC001 (recorded as an ERROR result that fails the run — nothing is swallowed)
        except Exception as e:  # harness breakage is a failure, not a pass
            detail, status = f"{type(e).__name__}: {e}", "ERROR"
            failures += 1
        results.append(dict(analysis=analysis, target=target, status=status,
                            detail=str(detail)))
        print(f"{status} {analysis} [{target}] "
              f"({time.time() - t0:.1f}s){': ' + str(detail) if status != 'PASS' else ''}")

    run("coverage", "step-factories", check_factory_coverage)

    # S1 + S3 per factory at tiny geometry (jaxpr structure and trace
    # caching are geometry-independent; S3 is the one analysis that
    # executes, so it must stay toy-sized)
    donating_twins = {}
    for name, setup in TINY_FACTORY_SETUPS.items():
        try:
            step, make_args, donating = setup()
        # graftlint: disable=EXC001 (rethrown into run(), which records a counted ERROR — nothing is swallowed)
        except Exception as e:
            run("setup", name, lambda e=e: (_ for _ in ()).throw(e))
            continue
        donating_twins[name] = (donating, make_args)
        args0 = make_args(0)
        run("S1-collectives", name, lambda s=step, a=args0, n=name: "; ".join(
            x.format() for x in spmd.check_collective_order(
                jax.make_jaxpr(s)(*a), label=n)) or "no collectives")
        run("S3-retrace", name,
            lambda s=step, m=make_args, n=name:
                spmd.check_single_trace(s, m, steps=3, label=n))
    run("S1-collectives", "decode",
        lambda: "; ".join(x.format() for x in spmd.check_collective_order(
            decode_jaxpr(), label="decode")) or "no collectives")
    # the pipeline plan's microbatch scan: iteration-count x per-iteration
    # collective schedule, invariant across microbatch counts (the carried
    # PR 5 follow-up — per-body uniformity alone cannot see a
    # schedule-count mismatch between stages)
    run("S1-scan-schedule", "dalle_pp", pp_scan_schedule_check)
    # the continuous-batching serve tick: admit/retire churn across
    # occupancies must reuse ONE executable per entry point (ISSUE 6
    # acceptance gate, chip-free twin of tests/test_serve.py); the int8
    # arena variant (ISSUE 7) proves the quantized cache/scale planes and
    # session-quantized weight arguments keep the same property
    run("S3-retrace", "serve-tick", serve_retrace_check)
    run("S3-retrace", "serve-tick-int8",
        lambda: serve_retrace_check(kv_cache_int8=True, weights_int8=True))
    # graftspec (ISSUE 16): the speculative tick replaces the greedy tick
    # (trace_counts reports `tick_spec`) — same churn, same one-executable
    # requirement; per-slot accepted lengths are traced values, so
    # variable progress must not retrace either
    run("S3-retrace", "serve-tick-spec",
        lambda: serve_retrace_check(spec_decode=True, spec_k=4,
                                    spec_draft_depth=1))

    # S2 per plan at tiny geometry, FULL-opt compile (donation honoring
    # is structural — layout/sharding mismatches reproduce at any size —
    # and only the full pipeline runs XLA's alias passes; opt0 reports
    # alias=0 even for honored donations).  S4 per plan at the
    # production geometry, opt0 (sizes only); --quick drops S4 to tiny
    # geometry too, for the test suite.
    make_cfg = tiny_config if quick else cub_config

    def s2_plan(plan):
        low_tiny = dalle_step_lowered(plan, make_cfg=tiny_config)
        with spmd.fresh_stats_compile():
            c_tiny = low_tiny.compile()
        return _s2_detail(spmd.check_donation(
            low_tiny, DALLE_ARG_LABELS, (0, 1), compiled=c_tiny,
            label=f"dalle/{plan}"))

    def s4_plan(plan):
        lowered = dalle_step_lowered(plan, make_cfg=make_cfg)
        with spmd.fresh_stats_compile():
            compiled = lowered.compile(OPT0)
        return _s4_detail(compiled, lowered, chip, f"dalle/{plan}")

    for plan in PLANS:
        run("S2-donation", f"dalle/{plan}", lambda p=plan: s2_plan(p))
        run("S4-hbm", f"dalle/{plan}@{chip}", lambda p=plan: s4_plan(p))

    # S2 for the single-chip factories (tiny compile: donation is
    # size-independent, the alias check still needs an executable)
    for name in ("vae", "clip"):
        if name not in donating_twins:
            continue  # setup already reported the failure
        donating, make_args = donating_twins[name]
        lowered = donating.lower(*make_args(0))
        with spmd.fresh_stats_compile():
            compiled = lowered.compile()
        run("S2-donation", name,
            lambda lo=lowered, c=compiled, n=name: _s2_detail(
                spmd.check_donation(lo, FACTORY_ARG_LABELS[n], (0, 1),
                                    compiled=c, label=n)))

    elapsed = time.time() - t_start
    print(f"\nspmd_check: {'FAIL' if failures else 'PASS'} "
          f"({failures} violation(s), {elapsed:.0f}s, chip={chip})")
    if json_out:
        Path(json_out).write_text(json.dumps(
            dict(tool="spmd_check", chip=chip, quick=quick,
                 failures=failures, results=results), indent=2) + "\n")
        print(f"findings -> {json_out}")
    return 1 if failures else 0


def _s2_detail(audit: spmd.DonationAudit) -> str:
    mib = 1024 ** 2
    big = "; ".join(f"{lbl}/{p} {b / mib:.1f} MiB undonated"
                    for lbl, p, b in audit.undonated_big[:4])
    return (f"donated {audit.donated_bytes / mib:.1f} MiB across "
            f"{audit.donated_leaves} leaves, {audit.aliased_params} aliased"
            + (f"; large undonated args: {big}" if big else ""))


def _s4_estimate(compiled, lowered) -> spmd.HBMEstimate:
    est = spmd.hbm_estimate(compiled)
    # opt0 zeroes the compiled alias stat; S2 verified the donation
    # aliases for this plan, so subtract the requested-donated share of
    # the per-device argument bytes in its place (donated and undonated
    # args shard across the same mesh, so the global fraction holds
    # per-device)
    audit = spmd.audit_donation(lowered, DALLE_ARG_LABELS, (0, 1))
    assumed = int(audit.donated_fraction * est.argument_bytes)
    return dataclasses.replace(est, alias_bytes=max(est.alias_bytes, assumed))


def _s4_detail(compiled, lowered, chip: str, label: str) -> str:
    est = _s4_estimate(compiled, lowered)
    spmd.check_hbm_budget(est, chip, label=label)
    return est.format()


# --- selftest: the analyses catch their broken fixtures -------------------


def selftest() -> int:
    """Prove S1-S4 have teeth against lint/spmd_fixtures.py (the CLI twin
    of tests/test_spmd_check.py)."""
    from dalle_pytorch_tpu.lint import spmd_fixtures as fx

    failures = 0

    def expect_catch(label, fn):
        nonlocal failures
        try:
            fn()
        except spmd.SPMDViolation as e:
            print(f"PASS {label}: caught ({str(e)[:90]}...)")
        else:
            print(f"FAIL {label}: broken fixture NOT caught")
            failures += 1

    mesh = make_mesh()
    x = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
    expect_catch("S1 conditional ppermute", lambda: spmd.check_collective_order(
        jax.make_jaxpr(fx.make_conditional_collective_step(mesh))(x)))
    spmd.check_collective_order(
        jax.make_jaxpr(fx.make_branch_matched_collective_step(mesh))(x))
    print("PASS S1 branch-matched twin: clean")

    expect_catch(
        "S1 unbalanced microbatch scan",
        lambda: spmd.scan_collective_schedule(
            jax.make_jaxpr(fx.make_unbalanced_microbatch_scan(mesh))(x)))
    scheds = spmd.scan_collective_schedule(
        jax.make_jaxpr(fx.make_pipelined_collective_scan(mesh, length=4))(x))
    assert len(scheds) == 1 and scheds[0].length == 4 \
        and len(scheds[0].per_iteration) == 1, scheds
    print(f"PASS S1 pipelined-scan twin: clean ({scheds[0].format()})")

    tx = make_optimizer(1e-3)
    params = fx.fixture_params()
    opt = tx.init(params)
    low = fx.make_undonated_train_step(tx).lower(
        params, opt, jnp.ones((8, 64), jnp.float32))
    expect_catch("S2 dropped donation", lambda: spmd.check_donation(
        low, ("params", "opt_state", "batch"), (0, 1)))

    expect_catch("S3 weak-hash static arg", lambda: spmd.check_single_trace(
        *fx.make_retracing_step()))
    expect_catch("S3 unhashable static arg", lambda: spmd.check_single_trace(
        *fx.make_unhashable_static_step()))
    spmd.check_single_trace(*fx.make_stable_step())
    print("PASS S3 stable twin: clean")
    expect_catch(
        "S3 occupancy-shaped serve tick",
        lambda: spmd.check_single_trace(
            *fx.make_shape_changing_serve_tick(), steps=4,
            label="serve-fixture"))

    est = spmd.hbm_estimate(fx.oversized_step_compiled())
    toy = dict(spmd.CHIP_HBM_BYTES, toy=1 << 20)
    expect_catch("S4 oversized plan", lambda: _gate_with(toy, est))

    print(f"\nselftest: {'FAIL' if failures else 'PASS'}")
    return 1 if failures else 0


def _gate_with(table, est):
    orig = dict(spmd.CHIP_HBM_BYTES)
    spmd.CHIP_HBM_BYTES.clear()
    spmd.CHIP_HBM_BYTES.update(table)
    try:
        spmd.check_hbm_budget(est, "toy")
    finally:
        spmd.CHIP_HBM_BYTES.clear()
        spmd.CHIP_HBM_BYTES.update(orig)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--chip", default="v4-8",
                        choices=sorted(spmd.CHIP_HBM_BYTES),
                        help="HBM capacity table for the S4 budget gate")
    parser.add_argument("--quick", action="store_true",
                        help="tiny geometry for S2/S4 too (tests/dev)")
    parser.add_argument("--json", type=str, default=None,
                        help="write machine-readable results to this path")
    parser.add_argument("--selftest", action="store_true",
                        help="prove each analysis catches its deliberately-"
                             "broken fixture, then exit")
    parser.add_argument("--s4-drift", action="store_true",
                        help="compile ONE plan at opt-0 AND full "
                             "optimization and diff arg/out/temp sizes — "
                             "the scheduled-CI gate that keeps the S4 "
                             "opt-0 shortcut honest across XLA upgrades "
                             "(--quick drops to tiny geometry)")
    parser.add_argument("--presets", action="store_true",
                        help="run the scale-preset S4 HBM proof "
                             "(presets.SCALE_PRESETS, e.g. cub-512) at "
                             "the rung's real geometry — minutes per "
                             "rung on a cold S4_PROOFS.json cache, "
                             "seconds on a hit; the nightly-CI gate")
    parser.add_argument("--preset", type=str, default=None,
                        help="with --presets: run only this rung (the "
                             "babysitter's per-stage gate)")
    parser.add_argument("--refresh-proofs", action="store_true",
                        help="with --presets: recompile even on a "
                             "fingerprint hit and rewrite S4_PROOFS.json")
    args = parser.parse_args(argv)
    if args.selftest:
        return selftest()
    if args.presets or args.preset:
        return run_presets(chip=args.chip, only=args.preset,
                           refresh=args.refresh_proofs)
    if args.s4_drift:
        try:
            detail = s4_drift_check(
                make_cfg=tiny_config if args.quick else cub_config)
        except spmd.SPMDViolation as e:
            print(f"FAIL S4-drift: {e}")
            return 1
        print(f"PASS S4-drift [dalle/dp]: {detail}")
        return 0
    return run_all(chip=args.chip, quick=args.quick, json_out=args.json)


if __name__ == "__main__":
    raise SystemExit(main())
