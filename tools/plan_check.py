#!/usr/bin/env python
"""graftplan CLI — static ParallelPlan contract sweep (the graftrace of
sharding; analyses live in dalle_pytorch_tpu/lint/plans.py).

Usage:
    python tools/plan_check.py                     # sweep cub/cub-512/cub-1024
    python tools/plan_check.py --presets tiny,cub  # sweep specific presets
    python tools/plan_check.py --select P1,P2      # subset of analyses
    python tools/plan_check.py --json out.json     # machine-readable findings
    python tools/plan_check.py --selftest          # prove P1-P4 catch fixtures

Exit codes: 0 clean, 1 findings, 2 usage error.  Chip-free: eval_shape +
make_jaxpr on the CPU backend — nothing executes on devices, nothing
compiles (the expensive half of the proof is spmd_check --presets).  A
finding must be fixed or carry a justified plans.WAIVERS entry; a waiver
matching nothing is itself an error (the PRAGMA002 discipline).
"""
from __future__ import annotations

import os
import sys

# Chip-free by construction: force the CPU backend with enough host
# devices for the fixture meshes BEFORE anything imports jax.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import argparse  # noqa: E402
import json  # noqa: E402
from pathlib import Path  # noqa: E402

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dalle_pytorch_tpu.lint import plans  # noqa: E402


def run_sweep(presets, select, json_out=None, batch=8) -> int:
    findings = plans.analyze(presets, select=select, batch=batch)
    kept, waived, unused = plans.apply_waivers(findings)
    for f, reason in waived:
        print(f"waived  {f.render()}  [{reason}]")
    for f in kept:
        print(f.render())
    for msg in unused:
        print(f"plan_check: {msg}", file=sys.stderr)
    counts = {}
    for f in kept:
        counts[f.code] = counts.get(f.code, 0) + 1
    if json_out:
        payload = {
            "tool": "plan_check",
            "analyses": list(select),
            "presets": list(presets),
            "topologies": [t.name for t in plans.TOPOLOGIES],
            "batch": batch,
            "counts": counts,
            "waived": [{"code": f.code, "cell": f.cell,
                        "message": f.message, "reason": r}
                       for f, r in waived],
            "findings": [{"code": f.code, "cell": f.cell,
                          "message": f.message} for f in kept],
        }
        Path(json_out).write_text(json.dumps(payload, indent=2) + "\n")
    if kept or unused:
        summary = ", ".join(f"{c} {code}" for code, c in sorted(
            counts.items()))
        print(f"\nplan_check: FAIL — {len(kept)} finding(s) ({summary})"
              f"{' + stale waivers' if unused else ''}; fix the contract "
              "or add a justified plans.WAIVERS entry")
        return 1
    print(f"plan_check: PASS — {len(presets)} preset(s) x "
          f"{len(plans.TOPOLOGIES)} topologies clean "
          f"({', '.join(select)}; {len(waived)} waived)")
    return 0


def selftest() -> int:
    """Prove P1-P4 have teeth against lint/plans_fixtures.py (the CLI
    twin of tests/test_plan_check.py): each broken fixture is caught,
    each clean twin passes."""
    from dalle_pytorch_tpu.lint import plans_fixtures as fx
    from dalle_pytorch_tpu.parallel.plan import ParallelPlan

    failures = 0

    def expect(label, ok):
        nonlocal failures
        print(f"{'PASS' if ok else 'FAIL'} {label}")
        failures += 0 if ok else 1

    # P1 orphan leaf
    broken = plans.check_rule_coverage(fx.ORPHAN_SHAPES, preset="fixture")
    clean = plans.check_rule_coverage(fx.COVERED_SHAPES, preset="fixture")
    expect("P1 orphan leaf caught",
           any("resampler/latents" in f.message for f in broken))
    expect("P1 covered twin clean", not clean)

    # P1 ambiguous double-match
    broken = plans.check_rule_coverage(fx.AMBIGUOUS_SHAPES,
                                       fx.ambiguous_rules(),
                                       preset="fixture")
    clean = plans.check_rule_coverage(fx.AMBIGUOUS_SHAPES,
                                      fx.benign_overlap_rules(),
                                      preset="fixture")
    expect("P1 ambiguous rules caught",
           any("conflicting" in f.message for f in broken))
    expect("P1 terminal-overlap twin clean", not clean)

    # P2 indivisible axis
    plan_tp4 = ParallelPlan.parse("tp4")
    topo = plans.topology("v4-16")
    broken = plans.check_divisibility(fx.INDIVISIBLE_SHAPES, plan_tp4, topo,
                                      preset="fixture")
    clean = plans.check_divisibility(fx.DIVISIBLE_SHAPES, plan_tp4, topo,
                                     preset="fixture")
    expect("P2 indivisible heads caught",
           any("not divisible by tp=4" in f.message for f in broken))
    expect("P2 divisible twin clean", not clean)

    # P3 overweight state
    cost = fx.overweight_cost(plans)
    broken = plans.check_hbm_fit(cost, ParallelPlan.parse("dp"),
                                 plans.topology("v5e-4"))
    clean = plans.check_hbm_fit(cost, ParallelPlan.parse("fsdp4"),
                                plans.topology("v5e-4"))
    expect("P3 overweight dp state caught",
           any("exceeds" in f.message for f in broken))
    expect("P3 fsdp4 twin fits", not clean)

    # P4 dcn-crossing collective
    plan_dcn = ParallelPlan.parse("dcn2.fsdp2")
    topo2 = plans.topology("2x-v5e-8")
    broken = plans.check_collective_placement(
        plan_dcn, topo2, preset="fixture", jaxpr=fx.dcn_crossing_jaxpr())
    clean = plans.check_collective_placement(
        plan_dcn, topo2, preset="fixture", jaxpr=fx.dcn_clean_jaxpr())
    expect("P4 dcn-crossing all_gather caught",
           any("all_gather" in f.message for f in broken))
    expect("P4 psum grad all-reduce twin clean", not clean)

    # P4 structural: fsdp ways spilling over the slice boundary
    spill = plans.check_collective_placement(
        ParallelPlan.parse("dcn2.fsdp4.tp2"),
        plans.Topology("2x-v5e-4", "v5e-4", 8, slices=2), preset="fixture")
    expect("P4 slice-spill structural caught",
           any("exceed" in f.message for f in spill))

    print(f"\nselftest: {'FAIL' if failures else 'PASS'}")
    return 1 if failures else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--presets", type=str, default=None,
                        help="comma-separated presets to sweep (default: "
                             + ",".join(plans.SWEEP_PRESETS) + ")")
    parser.add_argument("--select", type=str, default=None,
                        help="comma-separated analyses "
                             "(default: all of P1,P2,P3,P4)")
    parser.add_argument("--batch", type=int, default=8,
                        help="global batch for the divisibility gate")
    parser.add_argument("--json", type=str, default=None,
                        help="write machine-readable findings to this path")
    parser.add_argument("--selftest", action="store_true",
                        help="prove each analysis catches its deliberately-"
                             "broken fixture, then exit")
    args = parser.parse_args(argv)
    if args.selftest:
        return selftest()
    select = tuple(plans.ANALYSES)
    if args.select:
        select = tuple(s.strip() for s in args.select.split(",") if s.strip())
        unknown = set(select) - set(plans.ANALYSES)
        if unknown:
            print(f"plan_check: unknown analyses {sorted(unknown)} "
                  f"(have {plans.ANALYSES})", file=sys.stderr)
            return 2
    presets = tuple(plans.SWEEP_PRESETS)
    if args.presets:
        presets = tuple(s.strip() for s in args.presets.split(",")
                        if s.strip())
        from dalle_pytorch_tpu.presets import CONFIG_PRESETS
        unknown = set(presets) - set(CONFIG_PRESETS)
        if unknown:
            print(f"plan_check: unknown presets {sorted(unknown)} "
                  f"(have {sorted(CONFIG_PRESETS)})", file=sys.stderr)
            return 2
    return run_sweep(presets, select, json_out=args.json, batch=args.batch)


if __name__ == "__main__":
    raise SystemExit(main())
