#!/usr/bin/env python
"""Interleaved A/B perf experiments on the CUB-200 DALLE train step.

The bench chip is shared and its throughput drifts minutes apart, so single
draws are meaningless; this tool compiles every requested variant once,
then measures them round-robin for `--reps` rounds and reports per-variant
medians — ambient drift hits all variants roughly equally within a round.

Usage:
    python tools/perf_ab.py baseline pallas --reps 3 --steps 30
    python tools/perf_ab.py --list

Variants are train-step configs (see VARIANTS); `gen` measures the KV-cache
sampler instead (`gen64` at batch 64 — the BASELINE target scenario samples
64 images; `gen`'s batch 8 matches bench.py's informational stage).  The
measured loops are bench.py's own (`make_train_measure` /
`make_gen_measure`), so this tool can never drift from the driver-facing
benchmark.
"""
from __future__ import annotations

import argparse
import dataclasses
import os
import statistics
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

VARIANTS = {
    "baseline": {},
    "pallas": dict(use_pallas=True),
    # sub-128 tiles cannot lower on TPU (lane width 128 — measured failure
    # 2026-08-02, chip-logs/ab_ptiles attempt; flash_pattern_attention now
    # rejects them at the API edge), so the tile ladder is 128 (default) /
    # 256 / 512
    "pallas-b256": dict(use_pallas=True, pallas_block_q=256,
                        pallas_block_k=256),
    "pallas-b512": dict(use_pallas=True, pallas_block_q=512,
                        pallas_block_k=512),
    "fp32": dict(dtype=jnp.float32),
    "full-attn": dict(attn_types=("full",)),
    "reversible": dict(reversible=True),
    "remat": dict(use_remat=True),
    "bf16-logits": dict(logits_bf16=True),
    "onehot-embed": dict(onehot_embed=True),
    "bf16-logits+onehot": dict(logits_bf16=True, onehot_embed=True),
    # measures the phase-sliced-head default against the old full-head +
    # output-slice path (same loss; ~9% fewer analytic step FLOPs)
    "full-head": dict(head_phase_sliced=False),
    # batch-scaling A/B (PERF.md "Raising MFU" lever 1): `batch` binds to
    # make_train_measure's batch param, not DALLEConfig; img/s stay
    # comparable across batch sizes (items_per_step scales with the batch).
    # Named batchN, not bN — the pallas-b64 suffix means block size.
    "batch64": dict(batch=64),
    # plain batch128 OOMs on v5e (measured 2026-08-02: 30.3G of 15.75G
    # HBM) — remat is the framework's own answer to that wall, so the
    # b128 rung is measured with it on
    "batch128": dict(batch=128),
    "batch128-remat": dict(batch=128, use_remat=True),
    # the projected production config: every lever PERF.md's analysis says
    # should stack (batch-scale the compute-starved chip + bf16 head +
    # one-hot embed backward) — A/B'd as ONE variant so interactions show
    "candidate": dict(batch=64, logits_bf16=True, onehot_embed=True),
    # 512px-class geometry (fmap 64 -> 4096 image tokens): where O(n·√n)
    # block-skipping should beat dense masks that blow HBM — the Pallas
    # kernel's re-target case (VERDICT r2 weak #2 / next #5).  batch drops
    # to 4 so the dense control fits HBM at n≈4177.
    "fmap64": dict(batch=4, image_fmap_size=64),
    "fmap64-pallas": dict(batch=4, image_fmap_size=64, use_pallas=True),
    "fmap64-pallas-b256": dict(batch=4, image_fmap_size=64, use_pallas=True,
                               pallas_block_q=256, pallas_block_k=256),
}

# pseudo-variants measuring other bench loops (not train-step configs).
# gen-dense: the sampler with the sliced-KV decode disabled (dense cache
# reads every step) — the A/B control for ops/attention.py's
# decode_key_positions gather.
# gen_bf16 / gen_f32cache: the sampler at f32 activations (the checkpoint-
# loaded eval path's dtype) with the bf16 KV cache ON vs OFF — the wall-
# clock side of the kv_cache_bf16 byte-cut (the compiler gate is
# tests/test_perf_model.py::test_bf16_cache_cuts_decode_cache_bytes).
# gen_fused_rank: the fused generate→VAE-decode→CLIP-rerank pipeline
# (genrank.rank_codes, shared prefill, zero disk round-trips), in
# images-ranked/sec.
# serve64 / serve16: the continuous-batching generation service
# (serve.GenerationServer: slot KV arena, per-tick admission, open-loop
# arrival trace at 1.25x oversubscription) — aggregate tok/s across
# INTERLEAVED requests; serve64 is the direct A/B against gen64's
# static-batch 35.2k tok/s headline.
# gen_int8: the ISSUE 7 quantized-serving recipe on the static sampler at
# eval dtype (f32 activations): int8 KV cache (per-head scales) + int8
# decode weights (per-output-channel scales, one-shot per session) — the
# wall-clock side of the ≤0.55x-cache-bytes compiler gate; its direct
# control is gen_bf16 (same dtype, bf16 cache, f32 weights).
# serve_int8: the same recipe on the 64-slot serve arena (per-SLOT scale
# planes, int8 weight args on every tick) vs serve64's bf16 arena.
# gen_spec: graftspec's self-speculative sampler (shallow-exit drafts from
# the first spec_draft_depth blocks + one K-wide full-model verify per
# iteration) vs the greedy scan — A/B control is `gen` (same batch 8).
# serve_spec: the same lever on the 64-slot arena (tick_spec: variable
# tokens-per-tick commits) vs serve64's greedy ticks.
# serve_prefix: the cross-request radix prefix cache on the 64-slot arena —
# the open-loop trace shares ONE prompt across every arrival, so this
# measures the all-hit admission path (one prefill serves the whole
# drive); control is serve64 (same arena, cache off).
EXTRAS = ("gen", "gen64", "vae", "gen-dense", "gen_bf16", "gen_f32cache",
          "gen_fused_rank", "serve64", "serve16", "gen_int8", "serve_int8",
          "gen_spec", "serve_spec", "serve_prefix")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("variants", nargs="*", default=[],
                        help=f"from: {', '.join(VARIANTS)}, or "
                             f"{'/'.join(EXTRAS)}")
    parser.add_argument("--reps", type=int, default=3,
                        help="interleaved measurement rounds (default 3)")
    parser.add_argument("--steps", type=int, default=30,
                        help="train steps per measurement (default 30)")
    parser.add_argument("--list", action="store_true")
    args = parser.parse_args(argv)
    if args.list or not args.variants:
        print("variants:", ", ".join(list(VARIANTS) + list(EXTRAS)))
        return 0
    if args.reps < 1:
        parser.error("--reps must be >= 1")
    unknown = [v for v in args.variants
               if v not in EXTRAS and v not in VARIANTS]
    if unknown:
        parser.error(f"unknown variant(s) {unknown}; choose from "
                     f"{list(VARIANTS) + list(EXTRAS)}")
    dupes = sorted({v for v in args.variants if args.variants.count(v) > 1})
    if dupes:
        # the measurement dict is keyed by name — a repeated variant would be
        # silently measured once, which reads like two independent draws
        parser.error(f"duplicate variant(s) {dupes}: each name gets one "
                     "measurement slot; use --reps for repeated measurement")

    import bench
    from dalle_pytorch_tpu.cli import (apply_platform_env,
                                      enable_compilation_cache)
    from dalle_pytorch_tpu.obs import prof

    apply_platform_env()  # JAX_PLATFORMS=cpu wins over the tunnel pin
    enable_compilation_cache()  # variant recompiles across runs hit the cache

    measures = {}
    # name -> bench.ledger_keys(...): the PERF_LEDGER.json join key built
    # from the cfg the measured loop actually traced, so each variant's
    # median lands beside graftprof's predicted row (or as a measured-only
    # stub at geometries the sweep doesn't cover)
    ledger_info = {}
    for name in args.variants:
        print(f"compiling {name}...", file=sys.stderr, flush=True)

        def gen_measure(b, **ov):
            compile_fn, cfg = bench.make_gen_measure_deferred(batch=b, **ov)
            ledger_info[name] = bench.ledger_keys(
                cfg, target="decode", plan="single", batch=b)
            return compile_fn()

        if name in ("gen", "gen64"):
            measures[name] = gen_measure(64 if name == "gen64" else 8)
        elif name == "gen-dense":
            # the dense-cache control: the same sampler with
            # DALLEConfig.sliced_kv_decode=False, so the choice is part of
            # the traced config — a retrace can never silently measure the
            # sliced path under the gen-dense label
            measures[name] = gen_measure(8, sliced_kv_decode=False)
        elif name in ("gen_bf16", "gen_f32cache"):
            # f32 activations (the eval path's dtype: checkpoints carry no
            # dtype, so loaded models run f32) with the bf16 KV cache on
            # vs off — like gen-dense, the choice rides the traced config
            measures[name] = gen_measure(
                8, dtype=jnp.float32, kv_cache_bf16=(name == "gen_bf16"))
        elif name == "gen_int8":
            # int8 quantized serving (ISSUE 7) at the eval path's f32
            # activations: int8 cache + int8 decode weights, both riding
            # the traced config — A/B control is gen_bf16
            measures[name] = gen_measure(
                8, dtype=jnp.float32, kv_cache_int8=True, weights_int8=True)
        elif name == "gen_spec":
            # graftspec's self-speculative sampler: drafts from the first
            # spec_draft_depth blocks, one K-wide verify per iteration —
            # the choice rides the traced config, control is `gen`
            compile_fn, cfg = bench.make_gen_measure_deferred(
                batch=8, spec_decode=True)
            ledger_info[name] = bench.ledger_keys(
                cfg, target="decode-spec", plan="single", batch=8)
            measures[name] = compile_fn()
        elif name == "gen_fused_rank":
            measures[name] = bench.make_fused_rank_measure(batch=8)
        elif name in ("serve64", "serve16", "serve_int8", "serve_spec",
                      "serve_prefix"):
            # serve_int8: the quantized 64-slot arena (per-slot scale
            # planes, int8 weight args per tick) vs serve64's bf16 arena.
            # serve_spec: tick_spec's variable tokens-per-tick commits vs
            # serve64's greedy ticks.  serve_prefix: the radix prefix
            # cache's all-hit admission path (one shared prompt) — a
            # SERVER knob, not a config field, so it rides the ledger
            # fingerprint as an extra key instead of the traced config.
            slots = 16 if name == "serve16" else 64
            ov = (dict(kv_cache_int8=True, weights_int8=True)
                  if name == "serve_int8"
                  else dict(spec_decode=True) if name == "serve_spec"
                  else {})
            prefix = name == "serve_prefix"
            target = "serve-spec" if name == "serve_spec" else "serve-tick"
            ledger_info[name] = bench.ledger_keys(
                dataclasses.replace(bench.cub200_config(), **ov),
                target=target, plan="single", batch=slots,
                num_slots=slots, **({"prefix_cache": True} if prefix else {}))
            measures[name] = bench.make_serve_measure(
                num_slots=slots, prefix_cache=prefix, **ov)
        elif name == "vae":
            measures[name] = bench.make_vae_measure()
            ledger_info[name] = bench.ledger_keys(
                bench.vae128_config(), target="vae", plan="single", batch=8)
        else:
            measure, cfg, batch = bench.make_train_measure(
                args.steps, **VARIANTS[name])
            measures[name] = measure
            ledger_info[name] = bench.ledger_keys(
                cfg, target="dalle/dp", plan="dp", batch=batch)

    def unit(name):
        if name == "gen_fused_rank":  # rank_codes reports whole images
            return "img/s"
        if name.startswith(("gen", "serve")):
            return "tok/s"
        return "img/s"

    results = {name: [] for name in measures}
    for rep in range(args.reps):
        for name, measure in measures.items():  # interleaved round-robin
            v, _ = measure()
            results[name].append(v)
            print(f"rep{rep} {name:12s} {v:9.2f} {unit(name)}", flush=True)

    print("\nmedians:")
    for name, vals in results.items():
        print(f"  {name:12s} {statistics.median(vals):9.2f} {unit(name)}  "
              f"(spread {min(vals):.2f}-{max(vals):.2f})")

    # medians join PERF_LEDGER.json under the prediction's fingerprint
    # (real chip only, like bench.record_history's history line;
    # GRAFT_PERF_LEDGER arms a scratch ledger so CPU smoke can exercise
    # the join).  `graftprof --report` renders predicted-vs-measured.
    if ledger_info and (jax.devices()[0].platform != "cpu"
                        # graftlint: disable=ENV001 (path-valued var: set at all arms a scratch ledger)
                        or os.environ.get("GRAFT_PERF_LEDGER")):
        appended = 0
        for name, vals in results.items():
            info = ledger_info.get(name)
            if info is None:  # e.g. gen_fused_rank spans three models
                continue
            prof.append_measured(
                {"metric": f"perf_ab:{name}",
                 "value": round(statistics.median(vals), 2),
                 "unit": unit(name), "reps": args.reps},
                fingerprint=info["ledger_fingerprint"],
                target=info["ledger_target"])
            appended += 1
        if appended:
            print(f"ledger: {appended} measured row(s) -> "
                  f"{prof.ledger_path()}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
