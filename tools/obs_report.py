#!/usr/bin/env python
"""graftscope run-report CLI: replay a telemetry stream into answers.

Reads one or more ``events.jsonl`` files (or stream directories — rotated
parts and per-host ``events-p{i}.jsonl`` files are merged) written by the
trainers / serve scheduler via ``dalle_pytorch_tpu.obs`` and renders:

* ``--format text`` (default) — the one-screen run report: step-time/MFU/
  stall trajectory + reservoir percentiles, health verdict timeline,
  checkpoint cadence/fallbacks/torn saves, serve p50/p99 per SLO class
  with attainment, injected faults, quarantines, torn spans.
* ``--format json``  — the same report as a machine-readable document
  (CI uploads this next to the crash-resume artifacts).
* ``--format trace`` — a Perfetto/Chrome trace (load in ui.perfetto.dev):
  spans from every thread of every host on one zoomable timeline.
* ``--tail N``       — just the last N records per host (the babysitter
  and monitor use this to carry a dead run's final moments into their own
  logs).
* ``--bench-jsonl``  — extract the ``bench`` events back into
  bench-history.jsonl lines (bench.py's ``record_history`` emits the
  exact history payload as the event), so the committed perf history is
  derivable from a run's telemetry stream alone.
* ``--merge DIR1 DIR2 …`` — the FLEET view: treat each path as one
  host's stream, solve the cross-host clock model from its beacons /
  matched step anchors (``obs/align.py``), rewrite every timestamp onto
  one fleet timebase, and render the merged result — text/json get the
  fleet report (per-lane offsets + residual bounds, global step
  timeline, straggler ranking, merged serve SLO attainment), trace gets
  one Perfetto document with one pid lane per host.

Stdlib + the jax-free ``obs`` package only: this tool must run on a box
whose accelerator tunnel is wedged — that is precisely when it is needed.

Usage:
    python tools/obs_report.py RUN_DIR [...]
    python tools/obs_report.py tel/ --format trace --output run.trace.json
    python tools/obs_report.py tel/ --tail 8

Exit codes: 0 report rendered, 2 no readable events.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dalle_pytorch_tpu.obs import (build_fleet_report,  # noqa: E402
                                   build_report, merge_streams, read_events,
                                   render_text, to_chrome_trace)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("paths", nargs="*", type=Path,
                        help="events.jsonl files or telemetry directories")
    parser.add_argument("--merge", nargs="+", type=Path, default=None,
                        metavar="DIR",
                        help="fleet mode: one telemetry dir per host — "
                             "align the streams onto one timebase "
                             "(obs/align.py clock solver) and render the "
                             "merged fleet report/trace")
    parser.add_argument("--format", choices=("text", "json", "trace"),
                        default="text")
    parser.add_argument("--output", type=Path, default=None,
                        help="write here instead of stdout")
    parser.add_argument("--tail", type=int, default=0,
                        help="print only the last N records per host "
                             "(one line each) instead of the report")
    parser.add_argument("--bench-jsonl", action="store_true",
                        help="emit the stream's `bench` events as "
                             "bench-history.jsonl lines (payload only, "
                             "envelope stripped) — the history file is "
                             "derivable from telemetry")
    args = parser.parse_args(argv)
    if not args.paths and not args.merge:
        parser.error("give stream paths, or --merge DIR1 DIR2 ...")

    clocks = None
    if args.merge:
        events, clocks = merge_streams(args.merge + args.paths)
    else:
        events = read_events(args.paths)
    if not events:
        srcs = [str(p) for p in (args.merge or []) + args.paths]
        print(f"no readable events under {srcs}", file=sys.stderr)
        return 2

    if args.bench_jsonl:
        from dalle_pytorch_tpu.obs.telemetry import ENVELOPE_KEYS

        lines = [json.dumps({k: v for k, v in r.items()
                             if k not in ENVELOPE_KEYS})
                 for r in events if r.get("kind") == "bench"]
        out = "\n".join(lines) + ("\n" if lines else "")
    elif args.tail > 0:
        hosts = sorted({(r.get("run"), r.get("host", 0)) for r in events})
        lines = []
        for run, host in hosts:
            tail = [r for r in events
                    if r.get("run") == run and r.get("host", 0) == host]
            for r in tail[-args.tail:]:
                extras = " ".join(
                    f"{k}={r[k]}" for k in ("step", "ph", "dur_s", "msg")
                    if r.get(k) is not None)
                lines.append(f"host {host} seq {r.get('seq')} "
                             f"[{r.get('kind')}.{r.get('name')}] {extras}")
        out = "\n".join(lines) + "\n"
    elif args.format == "trace":
        out = json.dumps(to_chrome_trace(events), indent=1)
    elif args.format == "json":
        rep = (build_fleet_report(events, clocks) if clocks is not None
               else build_report(events))
        out = json.dumps(rep, indent=1, default=str)
    else:
        rep = (build_fleet_report(events, clocks) if clocks is not None
               else build_report(events))
        out = render_text(rep)

    if args.output is not None:
        args.output.parent.mkdir(parents=True, exist_ok=True)
        args.output.write_text(out)
        print(f"wrote {args.output}")
    else:
        sys.stdout.write(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
