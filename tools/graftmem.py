#!/usr/bin/env python
"""graftmem: per-scope, per-phase HBM attribution + the committed memory
ledger — the memory-side twin of ``tools/graftprof.py``.

For every ``training.STEP_FACTORIES`` entry under its parallelism plans —
plus the decode scan, the serving arena tick, and the cub-512 scale rung
— this tool builds the memory timeline one run actually traverses (init
-> step peak -> ckpt snapshot -> serve steady-state) from two sources:
XLA's own opt0 buffer assignment (argument/output/temp bytes, the
``lint/spmd.py`` S4 convention, with the S2-verified donation credit)
for the phase totals, and ``obs/mem.py``'s peak-live jaxpr walk for the
attribution (which resident planes — params / opt state / weights /
arena incl. int8 value+scale layout — and which ``prof.scope``
activations were live at the peak).  Each timeline is folded against
``prof.CHIP_SPECS`` HBM into a per-chip headroom verdict and committed
as a ``memory`` sub-row of ``PERF_LEDGER.json`` under the SAME
``prof.fingerprint_payload`` fingerprint graftprof owns — predictions
and memory live on one row, measured watermarks
(``mem.append_measured_memory``) land beside them.

Chip-free by the same construction as graftprof (whose harness this
reuses wholesale): the 8-device virtual CPU mesh, AOT trace/lower/
compile-at-opt0, nothing executes.

Modes:
    --update   recompute memory rows, merge (preserving measured
               history AND every graftprof field), write the ledger
    --check    recompute and diff — the CI drift gate: exit 1 when any
               phase's peak bytes drift >5% without a ledger update,
               naming the guilty scope
    --report   read-only predicted-vs-measured memory table (no jax)
    --quick    tiny geometry (tests / smoke)
    --targets  substring filter over target names
    --json     machine-readable output beside the human table

Usage:
    python tools/graftmem.py --update
    python tools/graftmem.py --check            # CI
    python tools/graftmem.py --report
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# graftprof owns the sweep harness (and transitively the spmd_check env
# preamble: CPU backend + 8 virtual devices BEFORE jax initializes).
_spec = importlib.util.spec_from_file_location(
    "graftprof", Path(__file__).resolve().parent / "graftprof.py")
graftprof = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(graftprof)
spmd_check = graftprof.spmd_check

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from dalle_pytorch_tpu.models.clip import CLIP  # noqa: E402
from dalle_pytorch_tpu.models.dalle import DALLE, decode_codes  # noqa: E402
from dalle_pytorch_tpu.models.vae import DiscreteVAE  # noqa: E402
from dalle_pytorch_tpu.obs import mem, prof  # noqa: E402
from dalle_pytorch_tpu.parallel.mesh import make_mesh  # noqa: E402
from dalle_pytorch_tpu.serve.engine import SlotArena  # noqa: E402
from dalle_pytorch_tpu.training import (make_clip_train_step,  # noqa: E402
                                        make_dalle_pp_train_step,
                                        make_dalle_sp_train_step,
                                        make_dalle_train_step, make_optimizer,
                                        make_vae_train_step)

PLANS = graftprof.PLANS
CHIP = graftprof.CHIP
TRAIN_BATCH = graftprof.TRAIN_BATCH
DECODE_BATCH = graftprof.DECODE_BATCH
SERVE_SLOTS = graftprof.SERVE_SLOTS
_sds = spmd_check._sds


def _wrap(fp: str, target: str, plan: str, memrow: dict) -> dict:
    return {"fingerprint": fp, "target": target, "plan": plan,
            "memory": memrow}


# --- per-target builders ---------------------------------------------------


def _dalle_mem_row(plan: str, make_cfg) -> dict:
    """One DALLE train-step memory row: phase totals from the opt0
    compile (per-device, donation credit applied), attribution from the
    peak-live walk (one shard's program under shard_map plans — the
    planes/scopes split, not the phase totals, which XLA owns)."""
    spec = PLANS[plan]
    cfg = make_cfg(**spec["plan"])
    dalle = DALLE(cfg)
    tx = make_optimizer(1e-3)
    mesh = make_mesh(**spec["mesh"])
    devices = 1
    for n in spec["mesh"].values():
        devices *= int(n)
    text = _sds((TRAIN_BATCH, cfg.text_seq_len), jnp.int32)
    codes = _sds((TRAIN_BATCH, cfg.image_seq_len), jnp.int32)
    rng = _sds((2,), jnp.uint32)
    fs = _sds((), jnp.float32)
    params = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                            codes)["params"]
    if plan == "pp":
        step, pp_params = make_dalle_pp_train_step(
            dalle, tx, spmd_check._zeros_like_tree(params), mesh,
            num_microbatches=2, health=True)
        params = pp_params
    elif cfg.ring_axis is not None:
        step = make_dalle_sp_train_step(dalle, tx, mesh, health=True)
    else:
        step = make_dalle_train_step(dalle, tx, health=True)
    opt = jax.eval_shape(tx.init, params)
    args = (params, opt, None, text, codes, rng, fs)
    walk = mem.peak_live(
        jax.make_jaxpr(step)(*args),
        planes=mem.arg_planes(("params", params), ("opt-state", opt),
                              ("args", (None, text, codes, rng, fs))))
    compiled = graftprof._compiled_stats(
        spmd_check.dalle_step_lowered(plan, make_cfg=make_cfg,
                                      batch=TRAIN_BATCH),
        arg_labels=spmd_check.DALLE_ARG_LABELS)
    phases = mem.train_phases(compiled)
    factory = ("dalle_pp" if plan == "pp"
               else "dalle_sp" if cfg.ring_axis is not None else "dalle")
    target = f"{factory}/{plan}"
    config = graftprof._cfg_payload(cfg, target=target, plan=plan,
                                    batch=TRAIN_BATCH)
    memrow = mem.memory_row(phases=phases, planes=walk["planes"],
                            scopes=walk["scopes"],
                            walker_peak_bytes=walk["peak_bytes"],
                            devices=devices)
    return _wrap(prof.row_fingerprint(config), target, plan, memrow)


def _scale_mem_row(plan: str) -> dict:
    """A scale rung's memory row — the ones where headroom genuinely
    binds.  Walker-only (dim-512 compiles for ~8 minutes, dim-1024
    longer; the compiled S4 proof is ``spmd_check --presets``' nightly
    concern, cached in S4_PROOFS.json): resident state divided by the
    plan's state-sharding ways (fsdp x tp — both axes cut params and
    moments; lint/plans.py's per-leaf walk is the exact version, this
    uniform factor is the committed-row convention), activations from
    the global peak-live walk divided across the mesh — the analytic
    stand-in the decode row precedent allows, held stable for the drift
    gate."""
    from dalle_pytorch_tpu.parallel.plan import PLAN_REGISTRY
    from dalle_pytorch_tpu.presets import preset_config

    cfg = preset_config(plan)
    dalle = DALLE(cfg)
    tx = make_optimizer(1e-3)
    mesh_kwargs = PLAN_REGISTRY[plan].mesh_kwargs()
    devices = 1
    for n in mesh_kwargs.values():
        devices *= int(n)
    text = _sds((TRAIN_BATCH, cfg.text_seq_len), jnp.int32)
    codes = _sds((TRAIN_BATCH, cfg.image_seq_len), jnp.int32)
    rng = _sds((2,), jnp.uint32)
    fs = _sds((), jnp.float32)
    params = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                            codes)["params"]
    opt = jax.eval_shape(tx.init, params)
    step = make_dalle_train_step(dalle, tx, health=True)
    args = (params, opt, None, text, codes, rng, fs)
    walk = mem.peak_live(
        jax.make_jaxpr(step)(*args),
        planes=mem.arg_planes(("params", params), ("opt-state", opt),
                              ("args", (None, text, codes, rng, fs))))
    phases = mem.analytic_train_phases(
        params_bytes=mem.tree_bytes(params),
        opt_bytes=mem.tree_bytes(opt),
        walker_peak_bytes=walk["peak_bytes"],
        resident_bytes=walk["resident_bytes"],
        devices=devices,
        shard_factor=PLAN_REGISTRY[plan].fsdp * PLAN_REGISTRY[plan].tp)
    target = f"dalle/{plan}"
    config = graftprof._cfg_payload(cfg, target=target, plan=plan,
                                    batch=TRAIN_BATCH)
    memrow = mem.memory_row(phases=phases, planes=walk["planes"],
                            scopes=walk["scopes"],
                            walker_peak_bytes=walk["peak_bytes"],
                            devices=devices,
                            note="analytic (walker-only; S4 compile "
                                 "under spmd_check --presets)")
    return _wrap(prof.row_fingerprint(config), target, plan, memrow)


def _vae_mem_row(quick: bool) -> dict:
    cfg = graftprof._vae_cfg(quick)
    vae = DiscreteVAE(cfg)
    tx = make_optimizer(1e-3)
    images = _sds((TRAIN_BATCH, cfg.image_size, cfg.image_size, 3),
                  jnp.float32)
    rng = _sds((2,), jnp.uint32)
    temp = _sds((), jnp.float32)
    fs = _sds((), jnp.float32)
    params = jax.eval_shape(
        lambda im: vae.init(jax.random.PRNGKey(0), im,
                            rng=jax.random.PRNGKey(1)), images)["params"]
    opt = jax.eval_shape(tx.init, params)
    step = make_vae_train_step(vae, tx, health=True)
    args = (params, opt, images, rng, temp, fs)
    walk = mem.peak_live(
        jax.make_jaxpr(step)(*args),
        planes=mem.arg_planes(("params", params), ("opt-state", opt),
                              ("args", (images, rng, temp, fs))))
    compiled = graftprof._compiled_stats(
        step.lower(*args), arg_labels=spmd_check.VAE_ARG_LABELS)
    config = graftprof._cfg_payload(cfg, target="vae", plan="single",
                                    batch=TRAIN_BATCH)
    memrow = mem.memory_row(phases=mem.train_phases(compiled),
                            planes=walk["planes"], scopes=walk["scopes"],
                            walker_peak_bytes=walk["peak_bytes"])
    return _wrap(prof.row_fingerprint(config), "vae", "single", memrow)


def _clip_mem_row(quick: bool) -> dict:
    cfg = graftprof._clip_cfg(quick)
    clip = CLIP(cfg)
    tx = make_optimizer(1e-3)
    text = _sds((TRAIN_BATCH, cfg.text_seq_len), jnp.int32)
    images = _sds((TRAIN_BATCH, cfg.visual_image_size,
                   cfg.visual_image_size, 3), jnp.float32)
    mask = _sds((TRAIN_BATCH, cfg.text_seq_len), jnp.bool_)
    fs = _sds((), jnp.float32)
    params = jax.eval_shape(
        lambda t, im, m: clip.init(jax.random.PRNGKey(0), t, im,
                                   text_mask=m), text, images,
        mask)["params"]
    opt = jax.eval_shape(tx.init, params)
    step = make_clip_train_step(clip, tx, health=True)
    args = (params, opt, text, images, mask, fs)
    walk = mem.peak_live(
        jax.make_jaxpr(step)(*args), default_scope="clip",
        planes=mem.arg_planes(("params", params), ("opt-state", opt),
                              ("args", (text, images, mask, fs))))
    compiled = graftprof._compiled_stats(
        step.lower(*args), arg_labels=spmd_check.CLIP_ARG_LABELS)
    config = graftprof._cfg_payload(cfg, target="clip", plan="single",
                                    batch=TRAIN_BATCH)
    memrow = mem.memory_row(phases=mem.train_phases(compiled),
                            planes=walk["planes"], scopes=walk["scopes"],
                            walker_peak_bytes=walk["peak_bytes"])
    return _wrap(prof.row_fingerprint(config), "clip", "single", memrow)


def _decode_mem_row(make_cfg) -> dict:
    """The sampling scan: weights + KV caches resident, per-step
    transients from the scan body's internal peak (no trip-count
    multiplication — the scan reuses its buffers).  No compile, the
    graftprof decode-row carve-out."""
    cfg = make_cfg()
    dalle = DALLE(cfg)
    text = _sds((DECODE_BATCH, cfg.text_seq_len), jnp.int32)
    codes = _sds((DECODE_BATCH, cfg.image_seq_len), jnp.int32)
    variables = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                               codes)
    logits, kvs = jax.eval_shape(
        lambda v, t: dalle.apply(v, t, method=DALLE.prefill), variables,
        text)
    rng = _sds((2,), jnp.uint32)

    def run(v, first_logits, caches, r):
        return decode_codes(dalle, v, first_logits, caches, r)

    walk = mem.peak_live(
        jax.make_jaxpr(run)(variables, logits, kvs, rng),
        planes=mem.arg_planes(("weights", variables), ("args", logits),
                              ("arena", kvs), ("args", (rng,))))
    phases = mem.decode_phases(
        params_bytes=mem.tree_bytes(variables),
        walker_peak_bytes=walk["peak_bytes"])
    config = graftprof._cfg_payload(cfg, target="decode", plan="single",
                                    batch=DECODE_BATCH)
    memrow = mem.memory_row(phases=phases, planes=walk["planes"],
                            scopes=walk["scopes"],
                            walker_peak_bytes=walk["peak_bytes"],
                            note="walker-only (no compile)")
    return _wrap(prof.row_fingerprint(config), "decode", "single", memrow)


def _serve_mem_row(make_cfg) -> dict:
    """One arena tick, every slot advancing: steady-state = weights +
    the whole arena (int8 cache payloads AND their f32 scale planes are
    both arena state — the avals say so) + tick transients, resident for
    as long as the server is up."""
    cfg = make_cfg()
    dalle = DALLE(cfg)
    text = jnp.zeros((1, cfg.text_seq_len), jnp.int32)
    codes = jnp.zeros((1, cfg.image_seq_len), jnp.int32)
    variables = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                               codes)
    arena = SlotArena(
        dalle, jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            variables),
        num_slots=SERVE_SLOTS)
    active = jnp.ones((SERVE_SLOTS,), bool)
    write_pos = jnp.int32(0)
    walk = mem.peak_live(
        jax.make_jaxpr(arena._tick)(arena.variables, arena.state, active,
                                    write_pos, arena._qweights),
        planes=mem.arg_planes(("weights", arena.variables),
                              ("arena", arena.state),
                              ("args", (active, write_pos)),
                              ("weights", arena._qweights)))
    phases = mem.serve_phases(walker_peak_bytes=walk["peak_bytes"])
    config = graftprof._cfg_payload(cfg, target="serve-tick", plan="single",
                                    batch=SERVE_SLOTS,
                                    num_slots=SERVE_SLOTS)
    memrow = mem.memory_row(phases=phases, planes=walk["planes"],
                            scopes=walk["scopes"],
                            walker_peak_bytes=walk["peak_bytes"])
    return _wrap(prof.row_fingerprint(config), "serve-tick", "single",
                 memrow)


def _serve_spec_mem_row(make_cfg) -> dict:
    """graftspec: the SPECULATIVE arena tick's memory row — the K-1
    shallow draft passes' transients plus the K-wide verify, walked over
    the same weights + arena residency as the greedy tick.  Fingerprints
    identically to graftprof's serve-spec row, so prediction and memory
    merge onto one ledger row.  The label is "serve-spec" — deliberately
    NOT a "serve-tick" superstring, so the quick gate's ``--targets
    serve-tick`` filter still selects exactly one row."""
    cfg = make_cfg(spec_decode=True, spec_k=4, spec_draft_depth=1)
    dalle = DALLE(cfg)
    text = jnp.zeros((1, cfg.text_seq_len), jnp.int32)
    codes = jnp.zeros((1, cfg.image_seq_len), jnp.int32)
    variables = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                               codes)
    arena = SlotArena(
        dalle, jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            variables),
        num_slots=SERVE_SLOTS)
    active = jnp.ones((SERVE_SLOTS,), bool)
    walk = mem.peak_live(
        jax.make_jaxpr(arena._tick_spec)(arena.variables, arena.state,
                                         active, arena._qweights),
        planes=mem.arg_planes(("weights", arena.variables),
                              ("arena", arena.state),
                              ("args", (active,)),
                              ("weights", arena._qweights)))
    phases = mem.serve_phases(walker_peak_bytes=walk["peak_bytes"])
    config = graftprof._cfg_payload(cfg, target="serve-spec",
                                    plan="single", batch=SERVE_SLOTS,
                                    num_slots=SERVE_SLOTS)
    memrow = mem.memory_row(phases=phases, planes=walk["planes"],
                            scopes=walk["scopes"],
                            walker_peak_bytes=walk["peak_bytes"])
    return _wrap(prof.row_fingerprint(config), "serve-spec", "single",
                 memrow)


PREFIX_CAPACITY = 32  # the RadixPrefixCache default in serve/scheduler.py


def _serve_prefix_mem_row(make_cfg) -> dict:
    """The radix prefix cache's worst-case residency: ``capacity``
    retained batch-1 prefill payloads (first_logits + per-layer k/v —
    int8 values AND their f32 scale planes when quantized) held beside
    the serving arena.  Analytic by construction: the cache is host-side
    bookkeeping over device payloads, there is no program to walk — the
    payload is sized via eval_shape on the same ``DALLE.prefill`` the
    scheduler admits from, so a cache-layout change moves this row."""
    cfg = make_cfg()
    dalle = DALLE(cfg)
    text = _sds((1, cfg.text_seq_len), jnp.int32)
    codes = _sds((1, cfg.image_seq_len), jnp.int32)
    variables = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                               codes)
    first_logits, caches = jax.eval_shape(
        lambda v, t: dalle.apply(v, t, method=DALLE.prefill), variables,
        text)
    logits_b = mem.tree_bytes(first_logits)
    cache_b = mem.tree_bytes(caches)
    total = PREFIX_CAPACITY * (logits_b + cache_b)
    phases = {"prefix_full": int(total)}
    config = graftprof._cfg_payload(cfg, target="serve-prefix",
                                    plan="single", batch=1,
                                    capacity=PREFIX_CAPACITY)
    memrow = mem.memory_row(
        phases=phases,
        planes={"prefix-payloads": int(total)},
        scopes={"attn-cache": int(PREFIX_CAPACITY * cache_b),
                "logits-head": int(PREFIX_CAPACITY * logits_b)},
        walker_peak_bytes=int(total),
        note=f"analytic: capacity {PREFIX_CAPACITY} x batch-1 prefill "
             f"payload ({logits_b + cache_b} B)")
    return _wrap(prof.row_fingerprint(config), "serve-prefix", "single",
                 memrow)


# --- sweep -----------------------------------------------------------------


def sweep(quick: bool = False, targets_filter=None) -> dict:
    """Recompute every memory row.  Returns {fingerprint: wrapped row}."""
    make_cfg = spmd_check.tiny_config if quick else spmd_check.cub_config
    builders = []
    for plan in PLANS:
        builders.append((f"dalle/{plan}",
                         lambda p=plan: _dalle_mem_row(p, make_cfg)))
    if not quick:
        builders.append(("dalle/cub-512",
                         lambda: _scale_mem_row("cub-512")))
        builders.append(("dalle/cub-1024",
                         lambda: _scale_mem_row("cub-1024")))
    builders.append(("vae", lambda: _vae_mem_row(quick)))
    builders.append(("clip", lambda: _clip_mem_row(quick)))
    builders.append(("decode", lambda: _decode_mem_row(make_cfg)))
    builders.append(("serve-tick", lambda: _serve_mem_row(make_cfg)))
    builders.append(("serve-spec", lambda: _serve_spec_mem_row(make_cfg)))
    builders.append(("serve-prefix",
                     lambda: _serve_prefix_mem_row(make_cfg)))

    rows = {}
    for label, build in builders:
        if targets_filter and not any(t in label for t in targets_filter):
            continue
        row = build()
        rows[row["fingerprint"]] = row
        m = row["memory"]
        verdict = m["headroom"][CHIP]
        print(f"  {row['target']:>18} [{row['plan']}] "
              f"fp={row['fingerprint']} "
              f"peak={verdict['peak_bytes'] / 2**20:.0f} MiB "
              f"@{verdict['peak_phase']} "
              f"headroom={verdict['headroom_frac']:.0%} "
              f"fits[{CHIP}]={'yes' if verdict['fits'] else 'NO'}")
    return rows


# --- report ----------------------------------------------------------------


def render_report(ledger: dict) -> str:
    """Predicted-vs-measured memory in one table (read-only)."""
    head = (f"{'target':>18} {'plan':>10} {'fp':>12} {'peak':>10} "
            f"{'phase':>12} {'headroom':>9} {'fits':>5} {'measured':>22}")
    lines = ["graftmem ledger report", head, "-" * len(head)]
    for fp, row in sorted(ledger.get("rows", {}).items(),
                          key=lambda kv: (kv[1].get("target", ""),
                                          kv[1].get("plan", ""))):
        m = row.get("memory")
        if not m:
            continue
        verdict = m.get("headroom", {}).get(CHIP, {})
        meas = m.get("measured") or []
        last = meas[-1] if meas else {}
        meas_txt = ("-" if not last else " ".join(
            f"{k}={last[k]:.4g}" if isinstance(last[k], float)
            else f"{k}={last[k]}"
            for k in sorted(last) if k not in ("t",)))
        peak = verdict.get("peak_bytes")
        peak_txt = (f"{peak / 2**20:.0f} MiB"
                    if isinstance(peak, (int, float)) else "-")
        hr = verdict.get("headroom_frac")
        hr_txt = f"{hr:.0%}" if isinstance(hr, (int, float)) else "-"
        fits = verdict.get("fits")
        lines.append(
            f"{row.get('target', '?'):>18} {row.get('plan', '?'):>10} "
            f"{fp:>12} {peak_txt:>10} "
            f"{verdict.get('peak_phase', '-'):>12} {hr_txt:>9} "
            f"{'yes' if fits else 'NO' if fits is not None else '-':>5} "
            f"{meas_txt[:22]:>22}")
    lines.append("")
    lines.append(f"peak/headroom rendered against {CHIP}; measured rows "
                 "append via mem.append_measured_memory (MemTracker "
                 "watermarks on a real chip)")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    mode = parser.add_mutually_exclusive_group(required=True)
    mode.add_argument("--update", action="store_true",
                      help="recompute memory rows and write the ledger")
    mode.add_argument("--check", action="store_true",
                      help="recompute and diff vs the committed ledger "
                           "(CI drift gate; exit 1 on >5% phase drift)")
    mode.add_argument("--report", action="store_true",
                      help="print predicted-vs-measured memory from the "
                           "ledger")
    parser.add_argument("--quick", action="store_true",
                        help="tiny geometry (tests); rows fingerprint "
                             "differently from the CUB sweep")
    parser.add_argument("--targets", nargs="+", default=None,
                        help="substring filter over target names")
    parser.add_argument("--ledger", type=Path, default=None,
                        help="ledger path (default: committed "
                             "PERF_LEDGER.json, GRAFT_PERF_LEDGER env "
                             "overrides)")
    parser.add_argument("--json", type=Path, default=None,
                        help="also write the mode's result as JSON")
    args = parser.parse_args(argv)
    path = args.ledger or prof.ledger_path()

    if args.report:
        ledger = prof.load_ledger(path)
        out = render_report(ledger)
        print(out)
        if args.json:
            args.json.write_text(json.dumps(ledger, indent=1) + "\n")
        return 0

    print(f"graftmem sweep ({'tiny' if args.quick else 'CUB'} geometry, "
          f"verdicts vs {CHIP}):")
    rows = sweep(quick=args.quick, targets_filter=args.targets)

    if args.update:
        ledger = prof.load_ledger(path)
        if not args.targets:
            # full sweep: retired memory sub-rows leave the ledger (the
            # graftprof fields and measured-only stub rows stay)
            for fp, r in ledger["rows"].items():
                if fp not in rows and "phases" in r.get("memory", {}):
                    meas = r["memory"].get("measured")
                    r["memory"] = {"measured": meas} if meas else {}
                    if not r["memory"]:
                        del r["memory"]
        for row in rows.values():
            mem.upsert_memory(ledger, row["fingerprint"], row["memory"],
                              target=row["target"], plan=row["plan"])
        out_path = prof.save_ledger(ledger, path)
        print(f"wrote {len(rows)} memory row(s) -> {out_path}")
        if args.json:
            args.json.write_text(json.dumps(ledger, indent=1) + "\n")
        return 0

    # --check: the drift gate
    ledger = prof.load_ledger(path)
    if args.targets:
        scoped = {fp for fp, r in ledger["rows"].items()
                  if any(t in str(r.get("target")) for t in args.targets)}
        committed = {"rows": {fp: r for fp, r in ledger["rows"].items()
                              if fp in scoped}}
    else:
        committed = ledger
    problems = mem.diff_memory(committed,
                               {fp: r["memory"] for fp, r in rows.items()})
    doc = {"tool": "graftmem", "mode": "check", "chip": CHIP,
           "quick": args.quick, "problems": problems,
           "rows_checked": len(rows)}
    if args.json:
        args.json.write_text(json.dumps(doc, indent=1) + "\n")
    if problems:
        print(f"\ngraftmem drift gate: {len(problems)} problem(s)")
        for p in problems:
            print(f"  DRIFT {p}")
        return 1
    print(f"\ngraftmem drift gate: green ({len(rows)} memory row(s) match "
          "the committed ledger)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
