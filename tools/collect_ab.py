#!/usr/bin/env python
"""Collect perf_ab run logs into a markdown table for PERF.md.

The chip-work babysitter leaves one perf_ab stdout log per stage; this
tool parses each log's ``medians:`` block and emits one markdown table so
A/B results land in PERF.md in a uniform format:

    python tools/collect_ab.py /tmp/chip_ab_core.log /tmp/chip_ab_pallas.log

Logs that contain no medians block (failed/truncated stage) are reported
on stderr and skipped — partial evidence is still collected.
"""
from __future__ import annotations

import re
import sys
from pathlib import Path

# perf_ab median lines: `  name   123.45 img/s  (spread 120.00-130.00)`
MEDIAN_RE = re.compile(
    r"^\s{2}(?P<name>\S+)\s+(?P<median>\d+(?:\.\d+)?)\s(?P<unit>\S+)\s+"
    r"\(spread (?P<lo>\d+(?:\.\d+)?)-(?P<hi>\d+(?:\.\d+)?)\)\s*$")


def parse_log(text: str) -> list[dict]:
    """Return the medians rows of one perf_ab log (empty if none)."""
    rows = []
    in_medians = False
    for line in text.splitlines():
        if line.strip() == "medians:":
            in_medians = True
            rows = []  # keep only the LAST medians block of the log
            continue
        if in_medians:
            m = MEDIAN_RE.match(line)
            if m:
                rows.append(m.groupdict())
            elif line.strip():
                in_medians = False
    return rows


def to_markdown(results: dict[str, list[dict]]) -> str:
    lines = ["| run | variant | median | spread |", "|---|---|---|---|"]
    for run, rows in results.items():
        for r in rows:
            lines.append(
                f"| {run} | {r['name']} | {r['median']} {r['unit']} "
                f"| {r['lo']}-{r['hi']} |")
    return "\n".join(lines)


def main(argv=None) -> int:
    paths = [Path(p) for p in (argv if argv is not None else sys.argv[1:])]
    if not paths:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    results: dict[str, list[dict]] = {}
    for p in paths:
        if not p.exists():
            print(f"skip {p}: no such file", file=sys.stderr)
            continue
        rows = parse_log(p.read_text(errors="replace"))
        if not rows:
            print(f"skip {p.name}: no medians block (stage failed or "
                  "still running?)", file=sys.stderr)
            continue
        run = p.stem.removeprefix("chip_")
        while run in results:  # same-named logs from different runs: keep both
            run += "'"
        results[run] = rows
    if not results:
        print("no parsable results in any input", file=sys.stderr)
        return 1
    print(to_markdown(results))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
