#!/bin/sh
# One-command pretrained-weights path: download the released checkpoints the
# reference uses at runtime, convert them to this framework's msgpack params,
# and smoke-decode one output per model.
#
#   tools/fetch_and_convert.sh [--dry-run] [DIR]
#
# DIR (default ./pretrained) receives raw/ (downloads), the converted
# *.msgpack, and smoke/ (one decoded PNG per VAE).  Idempotent: existing
# files are kept, so a flaky download resumes where it left off.
#
# --dry-run replaces the downloads with synthesized full-size checkpoints in
# the released formats (tools/synth_released.py) — the whole convert+smoke
# pipeline runs for real, so this is executable (and CI-tested) today in the
# egress-less environment, and the real path is one flag away the moment
# egress exists.
#
# Sources (ref /root/reference/dalle_pytorch/vae.py:29-33, genrank.py:20-22):
#   OpenAI dVAE     https://cdn.openai.com/dall-e/{encoder,decoder}.pkl
#   Taming VQGAN    https://heibox.uni-heidelberg.de/f/140747ba53464f49b476/?dl=1
#   CLIP ViT-B/32   https://openaipublic.azureedge.net/clip/models/...ViT-B-32.pt
set -eu

DRY=0
DIR=""
for arg in "$@"; do
  case "$arg" in
    --dry-run) DRY=1 ;;
    --*) echo "unknown flag: $arg (usage: $0 [--dry-run] [DIR])" >&2
         exit 2 ;;
    *) [ -n "$DIR" ] && { echo "extra argument: $arg" >&2; exit 2; }
       DIR=$arg ;;
  esac
done
DIR=${DIR:-pretrained}
RAW="$DIR/raw"
mkdir -p "$RAW"
HERE=$(dirname "$0")

fetch() { # fetch <url> <dest>
  [ -f "$2" ] && { echo "have $2"; return 0; }
  echo "fetching $1 -> $2"
  if command -v curl >/dev/null 2>&1; then
    curl -L --fail --retry 3 -o "$2.part" "$1"
  else
    wget -O "$2.part" "$1"
  fi
  mv "$2.part" "$2"
}

if [ "$DRY" = 1 ]; then
  # .synth_done marks a COMPLETE synth: torch.save is not atomic, so file
  # existence alone could wedge the skip check on an interrupted run
  if [ -f "$RAW/.synth_done" ]; then
    echo "have synthesized checkpoints"
  else
    rm -f "$RAW/.synth_done"
    python "$HERE/synth_released.py" --out "$RAW"
    touch "$RAW/.synth_done"
  fi
else
  fetch "https://cdn.openai.com/dall-e/encoder.pkl" "$RAW/encoder.pkl"
  fetch "https://cdn.openai.com/dall-e/decoder.pkl" "$RAW/decoder.pkl"
  fetch "https://heibox.uni-heidelberg.de/f/140747ba53464f49b476/?dl=1" \
        "$RAW/vqgan.1024.model.ckpt"
  fetch "https://openaipublic.azureedge.net/clip/models/40d365715913c9da98579312b702a82c18be219cc2a73407c4526f58eba950af/ViT-B-32.pt" \
        "$RAW/ViT-B-32.pt"
fi

[ -f "$DIR/openai_jax.msgpack" ] || python "$HERE/convert_weights.py" openai \
  --encoder "$RAW/encoder.pkl" --decoder "$RAW/decoder.pkl" \
  --out "$DIR/openai_jax.msgpack"
[ -f "$DIR/vqgan_jax.msgpack" ] || python "$HERE/convert_weights.py" vqgan \
  --ckpt "$RAW/vqgan.1024.model.ckpt" --out "$DIR/vqgan_jax.msgpack"
[ -f "$DIR/clip_jax.msgpack" ] || python "$HERE/convert_weights.py" clip \
  --ckpt "$RAW/ViT-B-32.pt" --out "$DIR/clip_jax.msgpack"

python "$HERE/smoke_decode.py" --dir "$DIR"

echo "done: $DIR/{openai,vqgan,clip}_jax.msgpack ready"
echo "use: generate.py/genrank.py pick them up via --taming / --clip_path"
