#!/usr/bin/env python
"""Generate images from a trained DALL-E checkpoint — TPU-native CLI.

Capability parity with `/root/reference/generate.py`:
* same flag surface (``--dalle_path`` required, ``--text``, ``--num_images``,
  ``--batch_size``, ``--top_k``, ``--outputs_dir``, ``--bpe_path``,
  ``--chinese``, ``--taming``; ref :25-52);
* checkpoint reconstitution with the same VAE priority custom > OpenAI >
  VQGAN (ref :72-87);
* prompt mode: ``--text`` split on ``|``, each prompt repeated
  ``num_images`` times, generated in ``batch_size`` chunks, saved to
  ``outputs/<model+prompt>/{i}.jpg`` (ref :93-117);
* eval mode (no ``--text``): tokenize every caption of a pickled pandas
  DataFrame (columns ``caption``/``fname``) and generate in big batches of
  30, saving ``{bb}-{i}.jpg`` (ref :118-156).

TPU-native: generation is the jitted prefill + lax.scan KV-cache sampler
(`dalle_pytorch_tpu.models.dalle.generate_codes`) — output-equivalent to the
reference's full-forward-per-token loop but O(n) per token, compiled once
per batch shape.  Prompt mode prefills each prompt ONCE and tiles the
resulting KV caches across the candidate batch (`cli.iter_generated_chunks`
shared-prefill path), so every `batch_size` chunk pays only the decode
scan; the caches are stored bf16 by default (`DALLEConfig.kv_cache_bf16` —
checkpoint-loaded models run f32 activations, and the decode loop is
HBM-bound on cache bytes).
"""
from __future__ import annotations

import argparse
from pathlib import Path

import jax
import numpy as np

from dalle_pytorch_tpu.cli import (enable_compilation_cache,
                                   generate_chunked, load_dalle_checkpoint,
                                   make_decode_fn, select_tokenizer)
from dalle_pytorch_tpu.utils.images import save_image


def exists(val):
    return val is not None


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument('--dalle_path', type=str, required=True,
                        help='path to your trained DALL-E')
    parser.add_argument('--text', type=str, required=False,
                        help='your text prompt (multiple prompts separated '
                             'with |); omit for pickled-captions eval mode')
    parser.add_argument('--num_images', type=int, default=128, required=False,
                        help='number of images per prompt')
    parser.add_argument('--batch_size', type=int, default=4, required=False,
                        help='generation batch size')
    parser.add_argument('--top_k', type=float, default=0.9, required=False,
                        help='top-k filter threshold (0 - 1)')
    parser.add_argument('--top_p', type=float, default=None, required=False,
                        help='nucleus sampling: keep the smallest token set '
                             'with this much probability mass (applied after '
                             'top-k; the reference has no such knob)')
    parser.add_argument('--outputs_dir', type=str, default='./outputs',
                        required=False, help='output directory')
    parser.add_argument('--captions_pickle', type=str,
                        default='./cub_2011_test_captions.pkl',
                        help='pickled pandas DataFrame for eval mode')
    parser.add_argument('--bpe_path', type=str,
                        help='path to your BPE json/txt file')
    parser.add_argument('--chinese', dest='chinese', action='store_true')
    parser.add_argument('--taming', dest='taming', action='store_true')
    return parser.parse_args(argv)


def main(argv=None):
    enable_compilation_cache()
    args = parse_args(argv)
    tokenizer = select_tokenizer(args.bpe_path, chinese=args.chinese)
    dalle, cfg, params, vae, vae_params = load_dalle_checkpoint(
        args.dalle_path, taming=args.taming)
    decode = make_decode_fn(vae, vae_params)
    rng = jax.random.PRNGKey(0)

    if exists(args.text):
        for text in args.text.split('|'):
            text = text.strip()
            tokens = tokenizer.tokenize([text], cfg.text_seq_len,
                                        truncate_text=True)
            tokens = np.repeat(tokens, args.num_images, axis=0)
            images, rng = generate_chunked(
                dalle, params, decode, tokens, batch_size=args.batch_size,
                top_k=args.top_k, top_p=args.top_p, rng=rng,
                desc=f'generating images for - {text}')

            outputs_dir = Path(args.outputs_dir) / (
                args.dalle_path.replace('.', '').replace('/', '')
                + '-' + text.replace(' ', '_'))
            outputs_dir.mkdir(parents=True, exist_ok=True)
            for i, image in enumerate(images):
                save_image(outputs_dir / f'{i}.jpg', image)
            print(f'created {args.num_images} images at "{outputs_dir}"')
    else:
        # eval mode over a pickled caption DataFrame (ref :118-156)
        try:
            import pandas as pd
        except ImportError as e:
            raise SystemExit(
                "eval mode needs pandas: pip install 'dalle-pytorch-tpu[eval]'"
            ) from e

        # sha256-gated for the bundled artifact; user files load as-is
        from dalle_pytorch_tpu.data.bundled import load_captions_pickle
        cap_df = load_captions_pickle(args.captions_pickle)
        all_tokens = tokenizer.tokenize(
            [str(row['caption']) for _, row in cap_df.iterrows()],
            cfg.text_seq_len, truncate_text=True)

        outputs_dir = Path(args.outputs_dir)
        outputs_dir.mkdir(parents=True, exist_ok=True)
        big_batch = 30
        for bb in range((len(all_tokens) + big_batch - 1) // big_batch):
            chunk = all_tokens[bb * big_batch: (bb + 1) * big_batch]
            images, rng = generate_chunked(
                dalle, params, decode, chunk, batch_size=args.batch_size,
                top_k=args.top_k, top_p=args.top_p, rng=rng,
                desc=f'generating images for - {bb}')
            for i, image in enumerate(images):
                save_image(outputs_dir / f'{bb}-{i}.jpg', image)
            print(f'created batch {bb} images at "{outputs_dir}"')


if __name__ == '__main__':
    main()
