#!/usr/bin/env python
"""Train DALL-E (stage 2) on paired text+image data — TPU-native CLI.

Capability parity with the reference trainer (`/root/reference/train_dalle.py`):
same flag surface (``--vae_path | --dalle_path`` mutually exclusive,
``--image_text_folder``, ``--truncate_captions``,
``--random_resize_crop_lower_ratio``, ``--chinese``, ``--taming``,
``--bpe_path``, ``--fp16``, ``--learning_rate`` + distributed flags; ref
:29-61), same CUB-200 hyperparameters (ref :74-97), same checkpoint payload
``{'hparams', 'vae_params', 'weights'}`` with the reference's cadence
(``dalle.pt`` every 100 iters, ``./sweep1/{run}-{epoch}.pt`` every 19th
epoch, ``dalle-final.pt`` at the end; ref :174-184, :405, :425-426, :431),
same plain-text log (one ``epoch iter loss lr`` line per step into
``{run}.txt``; ref :351-353, :378), ReduceLROnPlateau on the epoch loss
(ref :286-295, :415-416) and a sample generation every 100 iters
(ref :396-412).

TPU-native redesign: the frozen VAE tokenizes images *inside* the jitted
train step (stop-gradient), GSPMD data parallelism replaces
DeepSpeed/Horovod, ``--fp16`` selects bf16 compute (the TPU-native mixed
precision — no loss scaling needed), and resume checkpoints additionally
carry optimizer + scheduler state (fixing the gap noted in SURVEY.md §5.3).
"""
from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from dalle_pytorch_tpu import DALLE, DALLEConfig, DiscreteVAE, VAEConfig
from dalle_pytorch_tpu.cli import host_fetch, select_tokenizer, enable_compilation_cache
from dalle_pytorch_tpu.data.dataset import DataLoader, TextImageDataset
from dalle_pytorch_tpu.models.dalle import generate_codes
from dalle_pytorch_tpu.obs import mem as obs_mem
from dalle_pytorch_tpu.obs import prof
from dalle_pytorch_tpu.obs import telemetry as obs
from dalle_pytorch_tpu.parallel import backend as distributed_utils
from dalle_pytorch_tpu.training import (make_dalle_train_step, make_optimizer,
                                        set_learning_rate)
from dalle_pytorch_tpu.utils import faults, guardrails
from dalle_pytorch_tpu.utils.checkpoint import load_checkpoint, save_checkpoint
from dalle_pytorch_tpu.utils.ckpt_manager import (CheckpointManager,
                                                  config_fingerprint)
from dalle_pytorch_tpu.utils.failure import GracefulShutdown, Heartbeat
from dalle_pytorch_tpu.utils.images import save_image
from dalle_pytorch_tpu.utils.logging import TrainLogger
from dalle_pytorch_tpu.utils.schedule import ReduceLROnPlateau


def exists(val):
    return val is not None


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    group = parser.add_mutually_exclusive_group(required=False)
    group.add_argument('--vae_path', type=str,
                       help='path to your trained discrete VAE')
    group.add_argument('--dalle_path', type=str,
                       help='path to your partially trained DALL-E')
    parser.add_argument('--image_text_folder', type=str, required=True,
                        help='path to your folder of images and text for '
                             'learning the DALL-E (with --data_format '
                             'shards: the shard directory holding '
                             'index.json + shard-*.tar, see '
                             'tools/make_shards.py)')
    parser.add_argument('--data_format', choices=('folder', 'shards'),
                        default='folder',
                        help="input pipeline: 'folder' lists loose files "
                             "(the reference layout); 'shards' streams tar "
                             "shards with per-host shard assignment and a "
                             "fingerprinted resume cursor — same batches, "
                             "bitwise, under the same seed")
    parser.add_argument('--truncate_captions', action='store_true',
                        help='Captions passed in which exceed the max token '
                             'length will be truncated if this is set.')
    parser.add_argument('--random_resize_crop_lower_ratio', dest='resize_ratio',
                        type=float, default=0.6,
                        help='Random resized crop lower ratio')
    parser.add_argument('--chinese', dest='chinese', action='store_true')
    parser.add_argument('--taming', dest='taming', action='store_true')
    parser.add_argument('--bpe_path', type=str,
                        help='path to your BPE file: a huggingface tokenizer '
                             'json or a CLIP merges txt')
    parser.add_argument('--fp16', action='store_true',
                        help='mixed precision (bf16 on TPU — no loss scaling '
                             'needed, unlike the reference\'s fp16)')
    parser.add_argument('--learning_rate', default=3e-4)
    parser.add_argument('--epochs', type=int, default=5,
                        help='training epochs (the reference hard-codes '
                             'EPOCHS=5 but its committed logs ran 100)')
    parser.add_argument('--profile_dir', type=str, default=None,
                        help='write a jax.profiler trace of steps 10-20 of '
                             'the first epoch to this dir (XProf/TensorBoard)')
    parser.add_argument('--xprof_dir', type=str, default=None,
                        help='managed on-chip trace window (obs/prof.py '
                             'capture: the trace rides a prof.xprof '
                             'telemetry span); GRAFT_XPROF env arms it '
                             'without a flag, GRAFT_XPROF_WINDOW=a:b moves '
                             'the step window. Alias of --profile_dir')
    parser.add_argument('--heartbeat_dir', type=str, default=None,
                        help='write per-process heartbeat-p{i}.json progress '
                             'files here for external stall/death monitors')
    parser.add_argument('--telemetry_dir', type=str, default=None,
                        help='graftscope run telemetry: append a schema-'
                             'versioned events.jsonl (per-step records, '
                             'ckpt/health/fault/serve events, spans) here '
                             'for tools/obs_report.py; GRAFT_TELEMETRY=0 '
                             'hard-disables even when set')
    parser.add_argument('--metrics_port', type=int, default=0,
                        help='serve /metrics (Prometheus text) + /healthz '
                             'from an in-process daemon thread on this '
                             'port (+ process index, so multi-host runs '
                             'on one box do not collide); series are fed '
                             'by the telemetry emit path. 0 disables')
    parser.add_argument('--alerts', action=argparse.BooleanOptionalAction,
                        default=True,
                        help='attach the declarative alert engine (obs/'
                             'alerts.py DEFAULT_RULES: stall fraction, '
                             'MFU drop vs run median, quarantine rate, '
                             'heartbeat gap) to the telemetry stream — '
                             'fired alerts are emitted as `alert` events '
                             'causally after their cause and printed to '
                             'stderr. No-op without --telemetry_dir')
    parser.add_argument('--stall_timeout', type=float, default=0,
                        help='warn on stderr when no step completes for this '
                             'many seconds (0 disables the in-process '
                             'watchdog); requires --heartbeat_dir')
    parser.add_argument('--health', choices=('off', 'warn', 'skip',
                                             'rollback'), default='skip',
                        help='training-health guardrails: every step '
                             'computes an on-device health vector (loss, '
                             'grad norm, finite flag). warn: observe only; '
                             'skip (default): additionally mask the update '
                             'when grads are non-finite so params/optimizer '
                             'are never poisoned; rollback: additionally '
                             'roll back to the newest valid managed '
                             'checkpoint on loss spikes / divergence, '
                             'skipping the offending data window with an '
                             'LR backoff, bounded by --max_rollbacks')
    parser.add_argument('--step_deadline', type=float, default=0,
                        help='hung-step watchdog: if a training step takes '
                             'longer than this many seconds (compile-bearing '
                             'first step exempt), dump all thread stacks and '
                             'exit with the documented wedge code (75) so a '
                             'supervisor relaunches with --resume auto. '
                             '0 disables')
    parser.add_argument('--max_rollbacks', type=int, default=3,
                        help='anomaly-recovery budget for --health '
                             'rollback; exhausting it aborts with exit '
                             'code 70 (rollback-budget-exhausted)')
    parser.add_argument('--spike_zscore', type=float, default=8.0,
                        help='robust z-score (|loss-median| / 1.4826*MAD '
                             'over a rolling window) above which a finite '
                             'loss counts as a spike')
    parser.add_argument('--sharded_checkpoints', action='store_true',
                        help='save Orbax sharded checkpoint dirs '
                             '({name}.orbax) with per-host shard IO instead '
                             'of gathering to process 0 (for multi-host '
                             'scale); load sites accept both formats')
    parser.add_argument('--resume', type=str, default=None,
                        help="'auto': resume from the newest manifest-valid "
                             'checkpoint in --ckpt_dir, skipping torn or '
                             'corrupt ones; any other value is an explicit '
                             'checkpoint path (same as --dalle_path). '
                             'Resumes are exact mid-epoch: data order, RNG '
                             'stream, optimizer, and scheduler continue '
                             'bitwise from the interrupted step')
    parser.add_argument('--ckpt_dir', type=str, default='./checkpoints',
                        help='managed checkpoint run dir: one '
                             'ckpt-{step:08d}/ per save, each with an '
                             'integrity manifest (per-file crc32) published '
                             'by atomic rename only after the data lands')
    parser.add_argument('--keep_checkpoints', type=int, default=3,
                        help='retention: keep the newest N managed '
                             'checkpoints (0 keeps all)')
    parser.add_argument('--keep_every', type=int, default=0,
                        help='retention: additionally keep every managed '
                             'checkpoint whose step is a multiple of M')
    parser.add_argument('--ckpt_every', type=int, default=100,
                        help='managed-checkpoint cadence in steps (0 '
                             'disables the CheckpointManager entirely)')
    parser.add_argument('--ckpt_async', action=argparse.BooleanOptionalAction,
                        default=True,
                        help='write managed checkpoints from a background '
                             'thread (device arrays still snapshot to host '
                             'synchronously; the atomic manifest publish '
                             'stays the sole commit point, so the '
                             'crash-consistency invariants are unchanged). '
                             '--no-ckpt_async restores blocking saves; '
                             'Orbax sharded saves are always blocking '
                             '(collective)')
    parser.add_argument('--mesh_sp', type=int, default=1,
                        help='sequence-parallel ways: shard the sequence '
                             'over an sp mesh axis with exact ring/Ulysses '
                             'attention (long-context training; seq_len must '
                             'divide by this)')
    parser.add_argument('--sp_impl', choices=('ring', 'ulysses'),
                        default='ring',
                        help='sequence-parallel scheme: ring (k/v rotation) '
                             'or ulysses (head<->sequence all-to-all; needs '
                             'heads %% mesh_sp == 0)')
    parser.add_argument('--pipeline_stages', type=int, default=1,
                        help='pipeline-parallel stages (GPipe schedule): '
                             'depth must divide by this and each stage must '
                             'hold whole attn-type cycles. Checkpoints are '
                             'saved weights-only in this mode (optimizer '
                             'moments are stage-stacked)')
    parser.add_argument('--pipeline_microbatches', type=int, default=4,
                        help='GPipe microbatches per step (batch_size must '
                             'divide by this)')
    parser.add_argument('--ff_experts', type=int, default=0,
                        help='>1: replace feed-forwards with top-k routed '
                             'MoE layers of this many experts (a model '
                             'hyperparameter — stored in checkpoints)')
    parser.add_argument('--ff_expert_top_k', type=int, default=2,
                        help='experts routed per token when --ff_experts > 1')
    parser.add_argument('--ff_expert_dispatch', choices=('dense', 'capacity'),
                        default='dense',
                        help="MoE dispatch: 'dense' (every expert sees every "
                             "token, exact) or 'capacity' (GShard-style "
                             "fixed slots; FLOPs scale with top_k x "
                             "capacity factor instead of expert count)")
    parser.add_argument('--ff_expert_capacity_factor', type=float,
                        default=1.25,
                        help="slot headroom for 'capacity' dispatch")
    parser = distributed_utils.wrap_arg_parser(parser)
    args = parser.parse_args(argv)
    # resolve the declarative ParallelPlan (--plan wins over the individual
    # mesh flags and writes the resolved axis sizes back onto args) BEFORE
    # the flag validation below, so a plan-driven sp/pp run validates the
    # same way a flag-driven one does
    from dalle_pytorch_tpu.parallel.plan import resolve_plan_args
    try:
        args.run_plan = resolve_plan_args(args)
    except ValueError as e:
        parser.error(str(e))
    if args.stall_timeout and not args.heartbeat_dir:
        parser.error('--stall_timeout requires --heartbeat_dir')
    if args.resume and args.dalle_path:
        parser.error('--resume and --dalle_path are mutually exclusive '
                     '(--resume auto resolves the checkpoint itself)')
    if args.mesh_sp > 1 and args.pipeline_stages > 1:
        parser.error('--mesh_sp and --pipeline_stages are mutually exclusive')
    if (args.mesh_sp > 1 or args.pipeline_stages > 1) and (
            args.mesh_fsdp > 1 or args.mesh_tp > 1 or args.mesh_dcn_dp > 1):
        parser.error('--mesh_sp/--pipeline_stages own the non-dp mesh axis; '
                     'combine with --mesh_fsdp/--mesh_tp/--mesh_dcn_dp is '
                     'not supported')
    if args.ff_experts > 1 and args.mesh_sp > 1:
        parser.error('--ff_experts with --mesh_sp is not supported')
    if args.ff_experts > 1 and args.pipeline_stages > 1:
        parser.error('--ff_experts with --pipeline_stages is not supported')
    return args


def build_vae(args, distr_backend, resume_vae_params=None):
    """VAE reconstitution priority (ref train_dalle.py:116-165):
    resume hparams > custom --vae_path > pretrained (OpenAI dVAE / taming
    VQGAN via --taming).  Returns (vae, vae_hparams_or_None, weights_or_None);
    `vae` is either a DiscreteVAE flax module or a duck-typed pretrained
    wrapper exposing image_size/num_layers/num_tokens +
    get_codebook_indices/decode (ref dalle_pytorch.py:308-313)."""
    if resume_vae_params is not None:
        cfg = VAEConfig.from_dict(resume_vae_params)
        return DiscreteVAE(cfg), cfg, cfg.to_dict(), None

    if exists(args.vae_path):
        if distr_backend.is_root_worker():
            print(f'using pretrained VAE {args.vae_path} for encoding images')
        ckpt = load_checkpoint(args.vae_path)
        cfg = VAEConfig.from_dict(dict(ckpt['hparams']))
        return DiscreteVAE(cfg), cfg, cfg.to_dict(), ckpt['weights']

    # pretrained path: requires converted weights on disk (no egress here)
    from dalle_pytorch_tpu.models.pretrained_vae import (OpenAIDiscreteVAE,
                                                         VQGanVAE1024)
    if distr_backend.is_root_worker():
        print('using pretrained VAE for encoding images')
    wrapper = VQGanVAE1024() if args.taming else OpenAIDiscreteVAE()
    # the reference stores vae_params=None for pretrained VAEs and rebuilds
    # them from the --taming flag on load (ref train_dalle.py:167-172)
    return wrapper, wrapper, None, wrapper.params


def main(argv=None):
    """CLI entry: the real run (`_main`) inside the rollback-and-skip
    escalation loop — a `RollbackAndSkip` escape from the anomaly policy
    relaunches with `--resume auto`, the offending data window skipped and
    the LR backed off, bounded by --max_rollbacks (then exit code 70)."""
    argv = list(sys.argv[1:] if argv is None else argv)
    return guardrails.run_with_rollback(_main, argv)


def _main(argv, lr_scale=1.0, skip_past=None):
    enable_compilation_cache()
    args = parse_args(argv)

    # constants (ref train_dalle.py:74-97); sweep/test overrides via
    # $DALLE_TPU_HPARAMS (JSON), replacing the reference's edit-the-file
    # sweep workflow (SURVEY.md §5.6)
    C = dict(
        BATCH_SIZE=16,
        GRAD_CLIP_NORM=0,
        MODEL_DIM=256,
        TEXT_SEQ_LEN=80,
        DEPTH=8,
        HEADS=8,
        DIM_HEAD=64,
        REVERSIBLE=False,
        LOSS_IMG_WEIGHT=7,
        ATTN_TYPES=('full', 'axial_row', 'axial_col', 'conv_like'),
        LR_DECAY_FACTOR=0.5,
        LR_DECAY_PATIENCE=5,
        LR_DECAY_COOLDOWN=0,
        LR_DECAY_MIN=1e-7,
    )
    import json as _json
    import os as _os
    # graftlint: disable=ENV001 (JSON-valued: presence of any override dict is the signal)
    if _os.environ.get('DALLE_TPU_HPARAMS'):
        C.update(_json.loads(_os.environ['DALLE_TPU_HPARAMS']))

    EPOCHS = args.epochs
    BATCH_SIZE = C['BATCH_SIZE']
    LEARNING_RATE = float(args.learning_rate)
    GRAD_CLIP_NORM = C['GRAD_CLIP_NORM']

    MODEL_DIM = C['MODEL_DIM']
    TEXT_SEQ_LEN = C['TEXT_SEQ_LEN']
    DEPTH = C['DEPTH']
    HEADS = C['HEADS']
    DIM_HEAD = C['DIM_HEAD']
    REVERSIBLE = C['REVERSIBLE']
    LOSS_IMG_WEIGHT = C['LOSS_IMG_WEIGHT']
    ATTN_TYPES = tuple(C['ATTN_TYPES'])

    LR_DECAY_FACTOR = C['LR_DECAY_FACTOR']
    LR_DECAY_PATIENCE = C['LR_DECAY_PATIENCE']
    LR_DECAY_COOLDOWN = C['LR_DECAY_COOLDOWN']
    LR_DECAY_MIN = C['LR_DECAY_MIN']

    distr_backend = distributed_utils.set_backend_from_args(args)
    distr_backend.initialize()
    distr_backend.check_batch_size(BATCH_SIZE)

    # chaos rehearsal hooks (GRAFT_FAULTS) — re-parsed per run so in-process
    # reruns (tests) see the current environment, not a cached spec
    faults.install_from_env()

    # crash-consistent managed checkpoints: one manifest-validated dir per
    # save under --ckpt_dir, with retention + auto-resume fallback.  Every
    # manifest records the writing plan + topology (elastic resume
    # provenance): a relaunch under a different --plan or device count
    # reshards the restore and says so below.
    from dalle_pytorch_tpu.parallel.plan import (current_topology,
                                                 describe_transition)
    manager = (CheckpointManager(args.ckpt_dir,
                                 keep_last=args.keep_checkpoints,
                                 keep_every=args.keep_every,
                                 sharded=args.sharded_checkpoints,
                                 async_save=args.ckpt_async,
                                 plan=args.run_plan.to_manifest(),
                                 topology=current_topology())
               if args.ckpt_every > 0 else None)
    if args.resume == 'auto':
        info = manager.latest_valid() if manager is not None else None
        if info is not None:
            args.dalle_path = str(info.payload)
            if distr_backend.is_root_worker():
                print(f'auto-resume: step {info.step} from {info.payload}')
                transition = describe_transition(
                    info.manifest.get('plan'), args.run_plan,
                    info.manifest.get('topology'))
                if transition:
                    print(f'[resume] {transition}')
        elif distr_backend.is_root_worker():
            print(f'auto-resume: no valid checkpoint under {args.ckpt_dir}; '
                  'starting fresh')
    elif args.resume:
        args.dalle_path = args.resume

    # execution-plan config overrides (NOT stored in checkpoints): the model
    # function is identical to dense, only the collectives differ
    sp_plan = {}
    if args.mesh_sp > 1:
        sp_plan = dict(ring_axis='sp', sp_impl=args.sp_impl,
                       sp_size=args.mesh_sp)
    # MoE dispatch is also per-run execution strategy over the same params:
    # CLI-selectable on fresh runs AND resumes (not stored in checkpoints)
    sp_plan.update(ff_expert_dispatch=args.ff_expert_dispatch,
                   ff_expert_capacity_factor=args.ff_expert_capacity_factor)
    # (tp meshes keep the phase-sliced head: PhaseLogits stores one kernel
    # per vocab phase, each tp-sharded on its own vocab dim, so the phase
    # boundary is a param boundary — no interior-slice resharding)
    pp_mode = args.pipeline_stages > 1

    # training-health guardrails (utils/guardrails.py): health vector on
    # device, update masked on non-finite grads, host-side anomaly policy
    health_on = args.health != 'off'
    health_guard = args.health in ('skip', 'rollback')

    tokenizer = select_tokenizer(args.bpe_path, chinese=args.chinese)
    dtype = jnp.bfloat16 if args.fp16 else jnp.float32

    # model reconstitution: resume or fresh (ref :116-165)
    resume_ckpt = None
    resume_sharded = None  # Orbax dir: arrays restore direct-to-device later
    start_epoch = 0
    start_step = 0
    resume_rng = None
    resume_loader = None
    resume_epoch_losses: list = []
    if exists(args.dalle_path):
        from dalle_pytorch_tpu.utils.checkpoint import (is_sharded_checkpoint,
                                                        load_sharded_small)

        dalle_path = Path(args.dalle_path)
        assert dalle_path.exists(), 'DALL-E model file does not exist'
        if is_sharded_checkpoint(dalle_path):
            # two-phase elastic resume: configs/scalars now; arrays restore
            # straight onto this run's shardings after the mesh exists — no
            # host materialization, works across topology changes
            resume_sharded = dalle_path
            resume_ckpt = load_sharded_small(dalle_path)
        else:
            resume_ckpt = load_checkpoint(dalle_path)
            # normalize to host numpy so the standard shard_params /
            # opt-template flow below re-places everything
            resume_ckpt = jax.tree.map(
                lambda v: np.asarray(v) if hasattr(v, 'devices') else v,
                resume_ckpt)
        resume_vae = resume_ckpt.get('vae_params')
        vae, vae_geom, vae_hparams, vae_weights = build_vae(
            args, distr_backend,
            resume_vae_params=dict(resume_vae) if resume_vae else None)
        if (vae_weights is None and resume_sharded is None
                and resume_ckpt.get('vae_weights') is not None):
            vae_weights = resume_ckpt['vae_weights']
        dalle_cfg = DALLEConfig.from_dict(dict(resume_ckpt['hparams']),
                                          dtype=dtype, **sp_plan)
        # the checkpoint's geometry wins over the script constants — a resume
        # of a non-default run must rebuild the exact model (ref :116-133)
        TEXT_SEQ_LEN = dalle_cfg.text_seq_len
        start_epoch = int(resume_ckpt.get('epoch', 0))
        # exact-resume extras (all plain scalars, so both the msgpack and
        # the two-phase sharded restore deliver them here)
        start_step = int(resume_ckpt.get('global_step', 0))
        resume_rng = resume_ckpt.get('rng')
        resume_loader = resume_ckpt.get('loader')
        resume_epoch_losses = [float(v) for v in
                               (resume_ckpt.get('epoch_losses') or [])]
    else:
        vae, vae_geom, vae_hparams, vae_weights = build_vae(args, distr_backend)
        dalle_cfg = DALLEConfig.from_vae(
            vae_geom,
            dim=MODEL_DIM,
            num_text_tokens=tokenizer.vocab_size,
            text_seq_len=TEXT_SEQ_LEN,
            depth=DEPTH,
            heads=HEADS,
            dim_head=DIM_HEAD,
            reversible=REVERSIBLE,
            loss_img_weight=LOSS_IMG_WEIGHT,
            attn_types=ATTN_TYPES,
            ff_experts=args.ff_experts,
            ff_expert_top_k=args.ff_expert_top_k,
            dtype=dtype,
            **sp_plan,
        )
    dalle = DALLE(dalle_cfg)
    if manager is not None:
        # saves record the config identity; latest_valid refuses checkpoints
        # of a *different* model on later resumes
        manager.fingerprint = config_fingerprint(dalle_cfg.to_dict())
    # dense twin: identical param tree, no sp collectives — used for init
    # (which runs the forward outside any shard_map) and for sampling
    import dataclasses as _dc
    dalle_dense = (DALLE(_dc.replace(dalle_cfg, ring_axis=None, sp_size=1))
                   if sp_plan else dalle)

    if args.data_format == 'shards':
        # streaming ingestion: tar shards + index manifest, per-host shard
        # assignment, the same iteration contract (data/stream.py)
        from dalle_pytorch_tpu.data.stream import (ShardStreamDataset,
                                                   StreamingDataLoader)

        ds = ShardStreamDataset(
            args.image_text_folder, tokenizer, text_len=TEXT_SEQ_LEN,
            image_size=vae_geom.image_size, resize_ratio=args.resize_ratio,
            truncate_captions=args.truncate_captions,
        )
        dl = StreamingDataLoader(
            ds, BATCH_SIZE, shuffle=True, drop_last=True,
            shard_num_hosts=jax.process_count(),
            shard_index=jax.process_index(),
        )
    else:
        ds = TextImageDataset(
            args.image_text_folder, tokenizer, text_len=TEXT_SEQ_LEN,
            image_size=vae_geom.image_size, resize_ratio=args.resize_ratio,
            truncate_captions=args.truncate_captions,
        )
        dl = DataLoader(
            ds, BATCH_SIZE, shuffle=True, drop_last=True,
            shard_num_hosts=jax.process_count(),
            shard_index=jax.process_index(),
        )
    assert len(ds) > 0, 'dataset is empty'
    if distr_backend.is_root_worker():
        print(f'{len(ds)} image-text pairs found for training')
    # exact mid-epoch resume: replay the interrupted epoch's permutation and
    # skip the batches already consumed.  A loader snapshot from an earlier
    # epoch (final/sweep checkpoints, written after the epoch-end step) just
    # aligns the permutation stream and starts the epoch fresh.  The loaders
    # coerce their own scalar types (the streaming cursor also carries the
    # shard-list fingerprint, a string, which it validates itself).
    resume_cursor = 0
    if resume_loader is not None and \
            int(dict(resume_loader).get('epoch', -1)) == start_epoch:
        dl.load_state_dict(dict(resume_loader))
        resume_cursor = min(int(dict(resume_loader).get('cursor', 0)),
                            len(dl))
    else:
        dl.epoch = start_epoch
        resume_epoch_losses = []

    rng = jax.random.PRNGKey(42)
    rng, init_rng = jax.random.split(rng)
    dummy_text = jnp.zeros((1, TEXT_SEQ_LEN), jnp.int32)
    dummy_codes = jnp.zeros((1, dalle_cfg.image_seq_len), jnp.int32)
    # ONE construction path for every plan (dp/fsdp/tp/dcn AND sp/pp): the
    # resolved ParallelPlan builds the mesh and the Partitioner, and init /
    # restore / the step-output pin all derive from that partitioner
    part = distr_backend.distribute(plan=args.run_plan)
    if resume_sharded is not None:
        # no device allocation at all: phase 2 below restores straight onto
        # ShapeDtypeStruct templates, so an elastic resume never holds a
        # discarded random init alongside the restored arrays (that 2x peak
        # would bite exactly when resuming onto less hardware)
        param_shapes = jax.eval_shape(
            lambda r: dalle_dense.init(r, dummy_text, dummy_codes)['params'],
            init_rng)
        params = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            param_shapes, part.param_shardings(param_shapes))
    else:
        params = jax.jit(
            lambda r: dalle_dense.init(r, dummy_text, dummy_codes)['params']
        )(init_rng)
        if resume_ckpt is not None:
            from dalle_pytorch_tpu.utils.checkpoint import (
                migrate_head_kernels, migrate_qkv_kernels)

            params = jax.tree.map(
                jnp.asarray,
                migrate_head_kernels(
                    migrate_qkv_kernels(resume_ckpt['weights'],
                                        dim_head=dalle_cfg.dim_head),
                    dalle_cfg.total_text_tokens))
        params = part.shard_params(params)
    is_custom_vae = isinstance(vae, DiscreteVAE)
    if vae_weights is not None:
        vae_params = part.replicate(jax.tree.map(jnp.asarray, vae_weights))
    elif is_custom_vae and resume_sharded is not None:
        # shapes only — the real weights restore in phase 2 below; eval_shape
        # avoids a compile + device compute and, unlike the random-init
        # branch, consumes no rng split (keeping the post-resume RNG stream
        # identical between sharded and msgpack checkpoints of the same run)
        dummy_img = jnp.zeros((1, vae_geom.image_size, vae_geom.image_size, 3))
        vae_shapes = jax.eval_shape(
            lambda r: vae.init({'params': r, 'gumbel': r}, dummy_img)['params'],
            jax.random.PRNGKey(0))
        vae_params = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                           sharding=part.repl_sharding),
            vae_shapes)
    elif is_custom_vae:
        # fresh random VAE only makes sense in smoke tests; a real run always
        # has weights, matching the reference's hard requirement of a VAE.
        rng, vae_rng = jax.random.split(rng)
        dummy_img = jnp.zeros((1, vae_geom.image_size, vae_geom.image_size, 3))
        vae_params = part.replicate(jax.jit(
            lambda r: vae.init({'params': r, 'gumbel': r}, dummy_img)['params']
        )(vae_rng))
    else:
        vae._require_params()  # pretrained wrapper without converted weights
        vae_params = None

    tx = make_optimizer(LEARNING_RATE, grad_clip_norm=GRAD_CLIP_NORM)

    train_step_pp = None
    if pp_mode:
        assert resume_sharded is None, (
            '--pipeline_stages resumes from msgpack checkpoints only (the '
            'sharded two-phase restore targets the dense layout)')
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dalle_pytorch_tpu.training import make_dalle_pp_train_step

        # restructure params {'outer', 'stages'} and place each stage's
        # slice on its pipeline device (leading-axis 'pp' sharding)
        train_step_pp, params = make_dalle_pp_train_step(
            dalle, tx, params, part.mesh,
            num_microbatches=args.pipeline_microbatches,
            health=health_on, guard=health_guard)
        _stage_shard = NamedSharding(part.mesh, P('pp'))  # graftlint: disable=PLAN001 (pp stacks stage params on a leading stage dim sharded by POSITION over 'pp' — a structural axis the path-regex rule table cannot name)

        def _pp_shard(path, leaf):
            in_stages = any(getattr(k, 'key', None) == 'stages' for k in path)
            return (_stage_shard if in_stages and getattr(leaf, 'ndim', 0) > 0
                    else part.repl_sharding)

        params = jax.device_put(
            params, jax.tree_util.tree_map_with_path(_pp_shard, params))

    if resume_sharded is not None:
        # abstract init: params are ShapeDtypeStructs here, and the real
        # moments arrive from the checkpoint in phase 2 — allocating zeros
        # first would only raise the restore's peak memory
        opt_state = jax.eval_shape(tx.init, params)
    elif pp_mode:
        # Adam moments follow the stage-stacked layout
        opt_sds = jax.eval_shape(tx.init, params)
        opt_state = jax.jit(tx.init, out_shardings=jax.tree_util.
                            tree_map_with_path(_pp_shard, opt_sds))(params)
    else:
        opt_state = part.init_opt_state(tx, params)
    if resume_sharded is not None:
        # phase 2 of the elastic resume: swap each array placeholder for a
        # ShapeDtypeStruct carrying THIS run's sharding (params/opt/vae
        # templates above), then restore — every host reads only its shards,
        # directly onto the current mesh, whatever topology wrote the ckpt
        from dalle_pytorch_tpu.utils.checkpoint import (
            load_checkpoint_sharded, migrate_head_kernels)

        target = dict(resume_ckpt)
        target['weights'] = params  # already ShapeDtypeStructs w/ shardings
        # checkpoints written before the per-phase head split store a joint
        # to_logits_dense/{kernel,bias}: restore that pair replicated, then
        # split it onto this run's per-phase shardings after the restore
        legacy_head = 'kernel' in resume_ckpt.get('weights', {}).get(
            'to_logits_dense', {})
        if legacy_head:
            new_head_tmpl = params['to_logits_dense']  # keep: shardings
            target['weights'] = dict(params)
            # int() casts: restored hparams carry 0-d numpy scalars, which
            # sharding.shard_shape cannot hash inside a shape tuple
            target['weights']['to_logits_dense'] = {
                'kernel': jax.ShapeDtypeStruct(
                    (int(dalle_cfg.dim), int(dalle_cfg.total_tokens)),
                    jnp.float32, sharding=part.repl_sharding),
                'bias': jax.ShapeDtypeStruct(
                    (int(dalle_cfg.total_tokens),), jnp.float32,
                    sharding=part.repl_sharding)}
        restore_opt = 'opt_state' in resume_ckpt and not legacy_head
        if 'opt_state' in resume_ckpt and legacy_head:
            # the legacy moment lists no longer align leaf-for-leaf with the
            # split-head template (2 head leaves became 4): leave their
            # `...` placeholders in the target so orbax skips reading them,
            # and restart the optimizer rather than zip-truncate silently
            if distr_backend.is_root_worker():
                print('legacy joint-head checkpoint: weights migrated to the '
                      'per-phase head; optimizer state restarts fresh')
        elif restore_opt:
            target['opt_state'] = [
                sds if saved is ... else saved
                for sds, saved in zip(part.opt_state_templates(opt_state),
                                      resume_ckpt['opt_state'])]
        # ckpt VAE weights are used only when nothing else supplied them
        # (--vae_path wins, matching the msgpack path's precedence); when
        # skipped, their placeholders in `target` make the restore skip
        # reading them entirely
        vae_from_ckpt = ('vae_weights' in resume_ckpt and is_custom_vae
                         and any(isinstance(l, jax.ShapeDtypeStruct)
                                 for l in jax.tree.leaves(vae_params)))
        if vae_from_ckpt:
            target['vae_weights'] = vae_params  # ShapeDtypeStruct templates
        restored = load_checkpoint_sharded(resume_sharded, target=target)
        params = restored['weights']
        if legacy_head:
            head = migrate_head_kernels(
                {'to_logits_dense': {
                    k: np.asarray(v)
                    for k, v in params['to_logits_dense'].items()}},
                dalle_cfg.total_text_tokens)['to_logits_dense']
            params = dict(params)
            params['to_logits_dense'] = {
                k: jax.device_put(jnp.asarray(head[k]), tmpl.sharding)
                for k, tmpl in new_head_tmpl.items()}
        if restore_opt and 'opt_state' in restored:
            # big arrays restored onto their templates' shardings pass
            # through untouched; 0-d leaves (optax count) restored by value
            # get cast back to the template dtype
            fitted = [
                v if (hasattr(v, 'sharding') and getattr(v, 'ndim', 0) > 0)
                else (jax.device_put(jnp.asarray(v, tmpl.dtype),
                                     part.repl_sharding)
                      if hasattr(tmpl, 'dtype') else v)
                for tmpl, v in zip(jax.tree.leaves(opt_state),
                                   restored['opt_state'])]
            opt_state = jax.tree.unflatten(jax.tree.structure(opt_state),
                                           fitted)
        else:
            # weights-only checkpoint: fall back to fresh optimizer state
            opt_state = part.init_opt_state(tx, params)
        if vae_from_ckpt:
            vae_params = restored['vae_weights']
        elif is_custom_vae:
            assert not any(isinstance(l, jax.ShapeDtypeStruct)
                           for l in jax.tree.leaves(vae_params)), (
                f'{resume_sharded} carries no vae_weights but the run needs '
                'a custom VAE — pass --vae_path for its weights')
    elif resume_ckpt is not None and 'opt_state' in resume_ckpt and pp_mode:
        if distr_backend.is_root_worker():
            print('--pipeline_stages: checkpointed optimizer state targets '
                  'the dense layout; continuing with fresh optimizer state')
    elif resume_ckpt is not None and 'opt_state' in resume_ckpt:
        from dalle_pytorch_tpu.utils.checkpoint import migrate_head_kernels

        # legacy joint-head Adam moments split the same way the params do
        # (leaf COUNT changes, so this must happen before the unflatten)
        migrate_head_kernels(resume_ckpt['opt_state'],
                             dalle_cfg.total_text_tokens)

        def _fit_leaf(tmpl, v):
            if not hasattr(tmpl, 'dtype'):
                return v
            v = jnp.asarray(v)
            if v.shape != tmpl.shape and v.size == tmpl.size:
                # legacy flat fused-QKV adam moments -> DenseGeneral layout
                # (same migration migrate_qkv_kernels applies to the params)
                v = v.reshape(tmpl.shape)
            return v.astype(tmpl.dtype)

        opt_state = jax.tree.map(
            _fit_leaf,
            opt_state, jax.tree.unflatten(jax.tree.structure(opt_state),
                                          jax.tree.leaves(resume_ckpt['opt_state'])))

    if args.mesh_sp > 1 or pp_mode:
        # sp/pp steps consume codes: the VAE encodes outside their
        # shard_map'd loss (the codes feed is replicated/dp-sharded data)
        if args.mesh_sp > 1:
            from dalle_pytorch_tpu.training import make_dalle_sp_train_step

            _codes_step = make_dalle_sp_train_step(
                dalle, tx, part.mesh, health=health_on, guard=health_guard)
        else:
            _codes_step = train_step_pp
        if is_custom_vae:
            encode_fn = jax.jit(lambda vp, imgs: vae.apply(
                {'params': vp}, imgs,
                method=DiscreteVAE.get_codebook_indices))

            def train_step(params, opt_state, vae_params, text, images, rng,
                           *fs):
                # codes are concrete int32 outputs of a separate jit — no
                # gradient path into the frozen VAE exists to stop
                codes = encode_fn(vae_params, images)
                return _codes_step(params, opt_state, None, text, codes,
                                   rng, *fs)
        else:
            encode_fn = jax.jit(vae.get_codebook_indices)

            def train_step(params, opt_state, _vae_params, text, images, rng,
                           *fs):
                return _codes_step(params, opt_state, None, text,
                                   encode_fn(images), rng, *fs)
    elif is_custom_vae:
        # frozen DiscreteVAE tokenizes images inside the jitted step
        train_step = make_dalle_train_step(dalle, tx, vae=vae,
                                           health=health_on,
                                           guard=health_guard,
                                           partitioner=part)
    else:
        # pretrained wrapper: encode outside (its params are jit-captured
        # constants), feed codes into a codes-only step
        _codes_step = make_dalle_train_step(dalle, tx, vae=None,
                                            health=health_on,
                                            guard=health_guard,
                                            partitioner=part)
        encode_fn = jax.jit(vae.get_codebook_indices)

        def train_step(params, opt_state, _vae_params, text, images, rng,
                       *fs):
            codes = encode_fn(images)
            return _codes_step(params, opt_state, None, text, codes, rng, *fs)

    if resume_rng is not None:
        # the checkpointed RNG stream continues bitwise: every subsequent
        # step/generation split replays exactly as the uninterrupted run's
        rng = jnp.asarray(np.asarray([int(v) for v in resume_rng],
                                     dtype=np.uint32))

    # device-prefetch double buffer (both data formats): batch k+1 is
    # pulled, cast, and device-placed while step k runs, and the wrapper
    # meters what the step loop actually waited on the input pipeline
    # (loader_stall_s — ridden on heartbeats and the perf extras below).
    # Checkpoints MUST record batches.state_dict(), not dl.state_dict():
    # the loader's own cursor runs ahead by the prefetch depth, and a
    # resume from it would skip a never-trained batch.
    from dalle_pytorch_tpu.data.stream import DevicePrefetcher

    def _place_batch(batch):
        text, images = batch
        return part.shard_batch((text.astype(np.int32), images))

    batches = DevicePrefetcher(dl, place=_place_batch, depth=1)

    sched = ReduceLROnPlateau(
        LEARNING_RATE, factor=LR_DECAY_FACTOR, patience=LR_DECAY_PATIENCE,
        cooldown=LR_DECAY_COOLDOWN, min_lr=LR_DECAY_MIN)
    if resume_ckpt is not None and 'scheduler' in resume_ckpt:
        sched.load_state_dict({k: float(v) if isinstance(v, (int, float)) else v
                               for k, v in dict(resume_ckpt['scheduler']).items()})
    if lr_scale != 1.0:
        # rollback LR backoff: the restored checkpoint predates the
        # rollback, so the accumulated scale (0.5 per rollback) applies to
        # whatever lr the scheduler had at that point
        sched.lr = max(sched.lr * lr_scale, sched.min_lr)
        opt_state = set_learning_rate(opt_state, sched.lr)
        if distr_backend.is_root_worker():
            print(f'[guardrails] rollback lr backoff: lr={sched.lr:.3e}')

    logger = TrainLogger(
        project='dalle_tpu_train_transformer',
        config=dict(dalle_cfg.to_dict(), epochs=EPOCHS, batch_size=BATCH_SIZE,
                    learning_rate=LEARNING_RATE),
    )

    # graftscope run telemetry (obs/): one events.jsonl per run — every
    # layer below (ckpt manager, guardrails, faults, loader, serve) emits
    # into the installed singleton; disabled (a None get()) when no dir.
    # --metrics_port starts the /metrics + /healthz endpoint (fed by the
    # emit path) and --alerts attaches the declarative rule engine, so
    # fired alerts land in the SAME stream, causally after their cause.
    metrics_server = None
    if args.metrics_port:
        from dalle_pytorch_tpu.obs import metrics as obs_metrics
        metrics_server = obs_metrics.serve(
            args.metrics_port + jax.process_index())
    if args.telemetry_dir:
        tel = obs.init(args.telemetry_dir, run_id=logger.run_name,
                       host=jax.process_index())
        if metrics_server is not None:
            tel.attach_metrics(metrics_server.registry)
        if args.alerts:
            from dalle_pytorch_tpu.obs.alerts import AlertEngine
            tel.attach_alerts(AlertEngine())
        obs.emit('run', 'run_start', step=start_step, epoch=start_epoch,
                 config_fingerprint=config_fingerprint(dalle_cfg.to_dict()),
                 resumed_from=(str(args.dalle_path)
                               if exists(args.dalle_path) else None),
                 trainer='train_dalle')
        # predicted-vs-measured: announce the perf ledger's roofline
        # ceiling for this config (exact fingerprint first, plan-level
        # fallback).  obs_report joins it with StepTimer's measured MFU;
        # the mfu_vs_predicted alert rule reads it as its reference.
        import dataclasses as _dc
        _plan_name = args.run_plan.name
        _prof_target = ('dalle_pp' if pp_mode else
                        'dalle_sp' if sp_plan else 'dalle') + '/' + _plan_name
        _fp = prof.row_fingerprint({
            **{k: str(v) for k, v in
               sorted(_dc.asdict(dalle_cfg).items())},
            'target': _prof_target, 'plan': _plan_name,
            'batch': BATCH_SIZE * jax.process_count()})
        _pred = prof.predicted_for(fingerprint=_fp, target=_prof_target,
                                   plan=_plan_name)
        if _pred is not None:
            obs.emit('prof', 'predicted', target=_prof_target, **_pred)
        # the memory half of the same join (graftmem): the ledger's
        # predicted HBM timeline for this config, emitted once so
        # obs_report can set it beside the measured watermarks below
        _mempred = obs_mem.predicted_memory_for(
            fingerprint=_fp, target=_prof_target, plan=_plan_name)
        if _mempred is not None:
            obs.emit('mem', 'predicted', target=_prof_target, **_mempred)

    @jax.jit
    def decode_images(vae_params, codes):
        if is_custom_vae:
            return vae.apply({'params': vae_params}, codes,
                             method=DiscreteVAE.decode)
        return vae.decode(codes)

    def dense_params_view():
        """The standard DALLE param tree, whatever layout training uses —
        checkpoints and the sampler always see the dense structure."""
        if pp_mode:
            from dalle_pytorch_tpu.training import pp_params_to_dense

            return pp_params_to_dense(dalle, params, part.mesh)
        return params

    # the partial epoch's losses ride in checkpoints so the plateau
    # scheduler's epoch mean is bitwise identical after a mid-epoch resume;
    # one shared list object (cleared in place per epoch) so every save
    # closure sees the live values
    epoch_losses: list = list(resume_epoch_losses)

    def resume_extras():
        """Exact-resume state riding in every checkpoint payload: the RNG
        stream, the loader position (epoch/cursor/seed), the step counter,
        and the in-flight epoch's losses — all plain scalars, so both
        checkpoint formats restore them without device state."""
        extras = {
            'rng': [int(v) for v in np.asarray(jax.device_get(rng))],
            # the prefetcher's view: the cursor of the batch the step loop
            # actually holds, not the loader's read-ahead position
            'loader': batches.state_dict(),
            'global_step': int(global_step),
        }
        if epoch_losses:
            extras['epoch_losses'] = [float(v) for v in epoch_losses]
        return extras

    def build_payload(epoch, fetch):
        """The reference's checkpoint dict (+ resume-exactness extras).
        ``fetch=True`` gathers device arrays to host numpy for the msgpack
        writers — a collective every process must join; ``fetch=False``
        keeps device arrays for Orbax's shard-parallel IO."""
        weights = dense_params_view()
        opt_leaves = (None if pp_mode  # pp moments are stage-stacked
                      else jax.tree.leaves(opt_state))
        vae_weights = (vae_params
                       if is_custom_vae and vae_params is not None else None)
        if fetch:
            weights = host_fetch(weights)
            opt_leaves = (host_fetch(opt_leaves)
                          if opt_leaves is not None else None)
            vae_weights = (host_fetch(vae_weights)
                           if vae_weights is not None else None)
        payload = {
            'hparams': dalle_cfg.to_dict(),
            'vae_params': vae_hparams,  # None for pretrained VAEs (ref :167-172)
            'weights': weights,
            'scheduler': sched.state_dict(),
            'epoch': epoch,
        }
        if opt_leaves is not None:
            payload['opt_state'] = opt_leaves
        if vae_weights is not None:
            payload['vae_weights'] = vae_weights
        payload.update(resume_extras())
        return payload

    def save_model(path, epoch):
        if args.sharded_checkpoints:
            # Orbax writes each host's shards directly — no gather; every
            # process participates collectively
            from dalle_pytorch_tpu.utils.checkpoint import \
                save_checkpoint_sharded

            path = f'{path}.orbax'
            save_checkpoint_sharded(path, build_payload(epoch, fetch=False))
            return path
        # every process participates in the fetch (sharded params span
        # non-addressable devices multi-host); only root writes
        payload = build_payload(epoch, fetch=True)
        if not distr_backend.is_root_worker():
            return path
        save_checkpoint(path, payload)
        return path

    last_managed = [-1]  # step of the last managed-save attempt

    def save_managed(step, epoch):
        """Managed checkpoint: ckpt_dir/ckpt-{step:08d}/ with an integrity
        manifest, retried with backoff on transient I/O errors.  A failed
        save is logged, not fatal — the run survives and the next cadence
        (or the interrupt path) writes the next one."""
        if manager is None or step == last_managed[0]:
            return
        last_managed[0] = step
        payload = build_payload(epoch, fetch=not args.sharded_checkpoints)
        if args.sharded_checkpoints or distr_backend.is_root_worker():
            try:
                manager.save(step, payload)
            except OSError as e:
                print(f'[ckpt] managed save at step {step} failed after '
                      f'retries: {e}', file=sys.stderr, flush=True)
        # the ckpt phase watermark: the host-fetched payload is the
        # predicted timeline's snapshot term, live right here
        mem_tracker.snapshot('ckpt', step=step)

    from dalle_pytorch_tpu.utils.profiling import StepTimer, dalle_train_flops

    # BATCH_SIZE is per-host (the loader shards by process); StepTimer's
    # peak spans every chip of every process, so feed it global-batch FLOPs
    timer = StepTimer(flops_per_step=dalle_train_flops(
        dalle_cfg, BATCH_SIZE * jax.process_count()))
    # phase-boundary memory watermarks (obs/mem.py, the managed polling
    # surface): "init" here — params + opt state resident, no step run
    # yet — then once per epoch ("step_peak") and after each managed
    # save ("ckpt"), matching the ledger's predicted phase timeline.
    # Never per step: live_arrays() walks every buffer in the process.
    mem_tracker = obs_mem.MemTracker()
    mem_tracker.snapshot('init', step=start_step)
    lr = sched.lr
    global_step = start_step
    # managed on-chip trace window (steps 10-20 of the first trained
    # epoch, past compile + warmup), root process only.  --profile_dir is
    # the legacy alias of --xprof_dir; both route through prof.capture so
    # the trace rides a prof.xprof telemetry span (graftlint OBS003).
    xprof = prof.XprofWindow(
        logdir=args.xprof_dir or args.profile_dir,
        start=min(10, max(len(dl) - 2, 0)),
        stop=min(20, max(len(dl) - 1, 1)))
    if not distr_backend.is_root_worker() or len(dl) < 2:
        xprof.logdir = None  # root-only, like the legacy window
    # preemption-safe shutdown + stall detection (SURVEY.md §5.3 — the
    # reference has neither): SIGTERM/SIGINT checkpoint-and-stop, heartbeat
    # files for external monitors, in-process hung-step watchdog
    stopper = GracefulShutdown()
    heartbeat = (Heartbeat(args.heartbeat_dir,
                           stall_timeout=args.stall_timeout or None,
                           run_id=logger.run_name)
                 if args.heartbeat_dir else None)
    # anomaly policy over the per-step health vectors + hung-step watchdog
    monitor_h = (guardrails.HealthMonitor(
        mode='rollback' if args.health == 'rollback' else
             ('warn' if args.health == 'warn' else 'skip'),
        spike_zscore=args.spike_zscore) if health_on else None)
    watchdog = (guardrails.StepWatchdog(args.step_deadline)
                if args.step_deadline > 0 else None)
    if skip_past is not None and distr_backend.is_root_worker():
        print(f'[guardrails] rollback resume: skipping the data window '
              f'through step {skip_past} (steps {start_step + 1}..'
              f'{skip_past} consumed without updates)')
    interrupted = False
    t0 = time.perf_counter()
    completed = False
    try:
        with stopper:
            for epoch in range(start_epoch, EPOCHS):
                # in-place: the save closures hold this list object.  The
                # first resumed epoch keeps its restored partial losses so
                # the epoch-end plateau step sees the full epoch.
                epoch_losses[:] = (resume_epoch_losses
                                   if epoch == start_epoch else [])
                # one-step-deferred loss logging: materializing the loss each step
                # would block the host on the device (and the device on the host's
                # data loading + log IO).  The pmean dispatch is async; float() of
                # step i's loss happens after step i+1 is already in flight.
                pending = None  # (iter index, device loss)
                # collective stop flag, updated by flush: the preemption
                # check rides the per-step loss collective (one host
                # collective per step, not two)
                stop_poll = [False]

                def flush(pending):
                    if pending is None:
                        return
                    it, sid, loss_dev, hv = pending
                    # average_all here, not at dispatch: the multi-host impl blocks
                    # (process_allgather), which would kill the one-step deferral
                    avg_loss, stop_poll[0] = stopper.average_and_poll(
                        distr_backend, loss_dev)
                    perf = timer.tick(BATCH_SIZE * jax.process_count(),
                                      stall_s=batches.last_wait_s)
                    if monitor_h is None or np.isfinite(avg_loss):
                        # a sentinel-skipped step left params untouched; its
                        # NaN must not poison the plateau epoch mean either
                        epoch_losses.append(avg_loss)
                    logger.step(epoch, it, avg_loss, lr, extra=perf)
                    tel = obs.get()
                    if tel is not None:
                        # the per-step record: timing/MFU/stall (StepTimer)
                        # + the health vector, emitted BEFORE the anomaly
                        # policy observes it so a rollback's health events
                        # causally follow their step in the stream
                        fields = dict(step=sid, epoch=epoch, it=it,
                                      loss=avg_loss, lr=lr, **perf)
                        if hv is not None:
                            fields.update(
                                grad_norm=float(hv['grad_norm']),
                                applied=float(hv['applied']))
                        tel.event('step', 'train', **fields)
                    if monitor_h is not None:
                        # every process sees the same avg_loss (collective)
                        # and the same SPMD health scalars, so the verdict —
                        # and any rollback escape — is collective too
                        monitor_h.observe(sid, loss=avg_loss,
                                          grad_norm=float(hv['grad_norm']),
                                          applied=float(hv['applied']))
                        if monitor_h.wants_rollback:
                            escalate(sid)

                def escalate(sid):
                    """Anomaly escalation: drop the post-mortem bundle, then
                    escape to main()'s rollback loop (--resume auto +
                    data-window skip + LR backoff, budget-bounded)."""
                    if distr_backend.is_root_worker():
                        guardrails.write_anomaly_bundle(
                            args.ckpt_dir, sid, {
                                'reason': monitor_h.rollback_reason,
                                'loss': monitor_h.last_loss,
                                'grad_norm': monitor_h.last_grad_norm,
                                'loss_history': monitor_h.history(),
                                'epoch': epoch,
                                'loader': batches.state_dict(),
                                'rng': [int(v) for v in
                                        np.asarray(jax.device_get(rng))],
                                'config_fingerprint':
                                    config_fingerprint(dalle_cfg.to_dict()),
                                'lr': lr})
                    raise guardrails.RollbackAndSkip(
                        sid, max_rollbacks=args.max_rollbacks,
                        reason=monitor_h.rollback_reason or 'anomaly')

                for i, ((text, images),
                        (text_b, images_b)) in enumerate(batches):
                    # `it` is the TRUE batch index in this epoch's
                    # permutation: a mid-epoch resume skips the consumed
                    # batches, so `i` restarts at 0 while the cadences
                    # (sampling, checkpoints, logs) must continue from
                    # where the interrupted run left off — bitwise replay
                    # depends on every rng split landing at the same `it`
                    it = i + (resume_cursor if epoch == start_epoch else 0)
                    if skip_past is not None and global_step < skip_past:
                        # rollback-and-skip: consume the anomalous data
                        # window without training on it; the rng stream
                        # still advances one split per skipped step so
                        # post-window draws stay deterministic
                        rng, _ = jax.random.split(rng)
                        global_step += 1
                        if heartbeat is not None:  # skipping is progress
                            heartbeat.beat(global_step, epoch=epoch,
                                           health_state='skipping-window')
                        continue
                    # profiler window (ref had no profiler at all —
                    # SURVEY.md §5.1): prof.XprofWindow opens/closes the
                    # managed capture around the step window
                    if xprof.armed and epoch == start_epoch:
                        was_active = xprof.active
                        xprof.on_step(
                            i, sync=lambda: jax.block_until_ready(params))
                        if was_active and not xprof.active:
                            print('profiler trace written to '
                                  f'{xprof.logdir}')
                    if watchdog is not None:
                        # armed across the whole step iteration (dispatch,
                        # previous step's host sync, periodic sample/save) —
                        # any of them can wedge inside a device call
                        watchdog.arm(global_step + 1)
                    rng, step_rng = jax.random.split(rng)
                    if health_on:
                        params, opt_state, loss, health_vec = train_step(
                            params, opt_state, vae_params, text_b, images_b,
                            step_rng,
                            jnp.float32(guardrails.fault_scale_for(
                                global_step + 1)))
                    else:
                        health_vec = None
                        params, opt_state, loss = train_step(
                            params, opt_state, vae_params, text_b, images_b,
                            step_rng)
                    # chaos rehearsal: GRAFT_FAULTS="step_hang:at_step=N"
                    # wedges here, inside the watchdog's armed window
                    faults.maybe_hang(global_step + 1)

                    flush(pending)
                    # raw device loss + health; averaged/classified lazily
                    pending = (it, global_step + 1, loss, health_vec)

                    just_checkpointed = it % 100 == 0
                    if just_checkpointed:
                        # periodic sample (ref :396-412): SPMD computation, so every
                        # process runs it; only root writes the image.  The
                        # caption must be globally consistent — each host's
                        # loader yields different rows, and feeding divergent
                        # "replicated" inputs to one SPMD program is undefined
                        rng, gen_rng = jax.random.split(rng)
                        sample_text = text[:1].astype(np.int32)
                        if jax.process_count() > 1:
                            from jax.experimental import multihost_utils

                            sample_text = multihost_utils.broadcast_one_to_all(
                                sample_text)
                        sample_text = jnp.asarray(sample_text)
                        codes = generate_codes(dalle_dense,
                                               {'params': dense_params_view()},
                                               sample_text, gen_rng, filter_thres=0.9)
                        image = host_fetch(decode_images(vae_params, codes)[0])
                        if distr_backend.is_root_worker():
                            save_image(f'samples/dalle/epoch{epoch}_iter{it}.png', image)
                            decoded = tokenizer.decode(np.asarray(text[0]))
                            logger.log({'image_caption': decoded})
                        save_model('./dalle.pt', epoch)
                        # wandb.save parity (ref :409); no-op for .orbax dirs
                        logger.save_file('./dalle.pt')
                    global_step += 1
                    if args.ckpt_every > 0 and it % args.ckpt_every == 0:
                        # flush first so the checkpointed epoch_losses
                        # include THIS step — a resumed run's epoch mean
                        # must match the uninterrupted one bitwise
                        flush(pending)
                        pending = None
                        save_managed(global_step, epoch)
                    if heartbeat is not None:
                        # health extras ride every beat so tools/monitor.py
                        # can flag a sick run without reading logs; the
                        # loader stall rides too, so an input-bound run is
                        # visible in monitor output
                        heartbeat.beat(global_step, epoch=epoch, loss_iter=it,
                                       loader_stall_s=round(
                                           batches.last_wait_s, 4),
                                       **(monitor_h.beat_extras()
                                          if monitor_h is not None else {}))
                    if watchdog is not None:
                        watchdog.disarm()
                    # chaos rehearsal: GRAFT_FAULTS="sigterm:at_step=N"
                    # delivers a real preemption notice at step N;
                    # "preempt:at_step=N" additionally arms the bounded
                    # grace window (grace_ms) — miss it and the process is
                    # hard-killed with ExitCode.PREEMPT_EXPIRED, exactly
                    # like a scheduler's follow-up SIGKILL
                    faults.maybe_kill(global_step)
                    faults.maybe_preempt(global_step)
                    # multi-process: the collective decision from the last
                    # flush (every process saw the same 2-vector, so every
                    # process breaks at the same step — the collective save
                    # below cannot deadlock); single-process: the local flag,
                    # which is fresher by one step
                    if stop_poll[0] if jax.process_count() > 1 \
                            else stopper.requested:
                        flush(pending)
                        pending = None
                        resume_path = ('./dalle.pt.orbax' if args.sharded_checkpoints
                                       else './dalle.pt')
                        if not just_checkpointed:  # ./dalle.pt is already current
                            resume_path = save_model('./dalle.pt', epoch)
                        # final managed checkpoint for --resume auto (no-op
                        # if this step's cadence save already ran — a torn
                        # result there models dying mid-write, and resume
                        # must fall back, not paper over it)
                        save_managed(global_step, epoch)
                        if distr_backend.is_root_worker():
                            print(f'interrupted at epoch {epoch} iter {it}: resume '
                                  f'checkpoint written to {resume_path} '
                                  f'(--dalle_path {resume_path} to continue; '
                                  f'--resume auto picks the newest valid '
                                  f'managed checkpoint)')
                        interrupted = True
                        break
                flush(pending)
                if interrupted:
                    break

                # per-epoch plateau step on the epoch-mean loss (ref :415-416)
                epoch_loss = float(np.mean(epoch_losses)) if epoch_losses else float('inf')
                lr = sched.step(epoch_loss)
                opt_state = set_learning_rate(opt_state, lr)
                if epoch % 19 == 0:
                    # epoch + 1: this save happens AFTER the epoch-end
                    # plateau step, so a resume from it starts the next
                    # epoch instead of replaying this one
                    save_model(f'./sweep1/{logger.run_name}-{epoch}.pt',
                               epoch + 1)
                if distr_backend.is_root_worker():
                    dt = time.perf_counter() - t0
                    print(f'epoch {epoch} done: loss {epoch_loss:.4f} lr {lr:.2e} '
                          f'({dt:.1f}s elapsed)')
                # steady-state watermark once per epoch: the train-loop
                # residents (params/opt/prefetch) against the HBM limit
                mem_tracker.snapshot('step_peak', step=global_step,
                                     epoch=epoch)

            completed = not interrupted
    finally:
        # a death inside the trace window must still stop the profiler
        # (and close its telemetry span) before the stream shuts down
        xprof.close()
        if manager is not None:
            # join the in-flight async checkpoint write: the process must
            # not exit (or report resume state) with an uncommitted save
            manager.finish()
        # the final save is committed (or was never started): disarm any
        # preemption grace timer so a graceful stop that landed inside the
        # window is not hard-killed moments after
        faults.cancel_preempt_grace()
        if watchdog is not None:
            watchdog.close()
        if heartbeat is not None:
            heartbeat.close(done=completed)
        # run_end folds the StepTimer reservoir percentiles (perf_summary)
        # so obs_report can show p50/p99 step time without replaying every
        # step record; shutdown() also makes rollback relaunches (which
        # re-enter _main in-process) re-init a fresh stream
        obs.emit('run', 'run_end', step=global_step,
                 completed=completed, interrupted=interrupted,
                 **timer.percentiles())
        obs.shutdown()
        if metrics_server is not None:
            metrics_server.close()

    if not interrupted:
        final_path = save_model('./dalle-final.pt', EPOCHS)
        if distr_backend.is_root_worker():
            # wandb artifact upload parity (ref train_dalle.py:430-437)
            logger.log_artifact(final_path, 'trained-dalle')
    logger.finish()


if __name__ == '__main__':
    main()
