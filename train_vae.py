#!/usr/bin/env python
"""Train the DiscreteVAE image tokenizer (stage 1) — TPU-native CLI.

Capability parity with the reference trainer (`/root/reference/train_vae.py`):
same flags (``--image_folder``, ``--image_size`` + distributed flags), same
hard-coded hyperparameters (ref train_vae.py:42-59), same gumbel temperature
anneal / ExponentialLR cadence (ref :211-217), same checkpoint payload
``{'hparams', 'weights'}`` -> ``vae.pt`` (ref :110-119), same observability
surface (loss/lr scalars, soft+hard reconstruction grids, codebook-usage
histogram; ref :185-235) — minus wandb when it isn't installed, in which case
images land in ``./samples/`` and scalars in the text log.

TPU-native redesign: one jitted train step (loss+grad+Adam update fused by
XLA), GSPMD data parallelism from a device mesh instead of
DeepSpeed/Horovod, bf16-ready model, loss averaging via replicated-mean
rather than an explicit NCCL allreduce.
"""
from __future__ import annotations

import argparse
import math
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from dalle_pytorch_tpu import DiscreteVAE, VAEConfig
from dalle_pytorch_tpu.cli import host_fetch, enable_compilation_cache
from dalle_pytorch_tpu.data.dataset import DataLoader, ImageFolderDataset
from dalle_pytorch_tpu.parallel import backend as distributed_utils
from dalle_pytorch_tpu.training import make_optimizer, make_vae_train_step, set_learning_rate
from dalle_pytorch_tpu.utils.checkpoint import save_checkpoint
from dalle_pytorch_tpu.utils.images import save_image_grid
from dalle_pytorch_tpu.utils.logging import TrainLogger
from dalle_pytorch_tpu.utils.schedule import ExponentialDecay, GumbelTemperature


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument('--image_folder', type=str, required=True,
                        help='path to your folder of images for learning the '
                             'discrete VAE and its codebook')
    parser.add_argument('--image_size', type=int, required=False, default=128,
                        help='image size')
    parser = distributed_utils.wrap_arg_parser(parser)
    return parser.parse_args(argv)


def main(argv=None):
    enable_compilation_cache()
    args = parse_args(argv)

    # constants (ref train_vae.py:42-59)
    C = dict(
        EPOCHS=20,
        BATCH_SIZE=8,
        LEARNING_RATE=1e-3,
        LR_DECAY_RATE=0.98,
        NUM_TOKENS=8192,
        NUM_LAYERS=2,
        NUM_RESNET_BLOCKS=2,
        SMOOTH_L1_LOSS=False,
        EMB_DIM=512,
        HID_DIM=256,
        KL_LOSS_WEIGHT=0,
        STARTING_TEMP=1.0,
        TEMP_MIN=0.5,
        ANNEAL_RATE=1e-6,
        NUM_IMAGES_SAVE=4,
    )
    # The reference's sweep workflow was "edit the constants in the file"
    # (SURVEY.md §5.6).  Here sweeps/tests override them via a JSON dict in
    # $DALLE_TPU_HPARAMS without touching the script.
    import json as _json
    import os as _os
    if _os.environ.get('DALLE_TPU_HPARAMS'):
        C.update(_json.loads(_os.environ['DALLE_TPU_HPARAMS']))

    IMAGE_SIZE = args.image_size
    EPOCHS = C['EPOCHS']
    BATCH_SIZE = C['BATCH_SIZE']
    LEARNING_RATE = C['LEARNING_RATE']
    LR_DECAY_RATE = C['LR_DECAY_RATE']

    NUM_TOKENS = C['NUM_TOKENS']
    NUM_LAYERS = C['NUM_LAYERS']
    NUM_RESNET_BLOCKS = C['NUM_RESNET_BLOCKS']
    SMOOTH_L1_LOSS = C['SMOOTH_L1_LOSS']
    EMB_DIM = C['EMB_DIM']
    HID_DIM = C['HID_DIM']
    KL_LOSS_WEIGHT = C['KL_LOSS_WEIGHT']

    STARTING_TEMP = C['STARTING_TEMP']
    TEMP_MIN = C['TEMP_MIN']
    ANNEAL_RATE = C['ANNEAL_RATE']

    NUM_IMAGES_SAVE = C['NUM_IMAGES_SAVE']

    distr_backend = distributed_utils.set_backend_from_args(args)
    distr_backend.initialize()
    distr_backend.check_batch_size(BATCH_SIZE)

    ds = ImageFolderDataset(args.image_folder, image_size=IMAGE_SIZE)
    dl = DataLoader(
        ds, BATCH_SIZE, shuffle=True, drop_last=True,
        shard_num_hosts=jax.process_count(), shard_index=jax.process_index(),
    )
    assert len(ds) > 0, 'folder does not contain any images'
    if distr_backend.is_root_worker():
        print(f'{len(ds)} images found for training')

    vae_params_d = dict(
        image_size=IMAGE_SIZE,
        num_layers=NUM_LAYERS,
        num_tokens=NUM_TOKENS,
        codebook_dim=EMB_DIM,
        hidden_dim=HID_DIM,
        num_resnet_blocks=NUM_RESNET_BLOCKS,
    )
    cfg = VAEConfig(
        **vae_params_d,
        smooth_l1_loss=SMOOTH_L1_LOSS,
        kl_div_loss_weight=KL_LOSS_WEIGHT,
    )
    vae = DiscreteVAE(cfg)

    rng = jax.random.PRNGKey(0)
    rng, init_rng = jax.random.split(rng)
    dummy = jnp.zeros((1, IMAGE_SIZE, IMAGE_SIZE, 3), jnp.float32)
    params = jax.jit(lambda r: vae.init({'params': r, 'gumbel': r}, dummy)['params'])(init_rng)

    part = distr_backend.distribute()
    params = part.shard_params(params)

    tx = make_optimizer(LEARNING_RATE)
    opt_state = jax.jit(tx.init)(params)
    train_step = make_vae_train_step(vae, tx)

    sched = ExponentialDecay(LEARNING_RATE, LR_DECAY_RATE)
    temp_sched = GumbelTemperature(STARTING_TEMP, TEMP_MIN, ANNEAL_RATE)

    logger = TrainLogger(
        project='dalle_tpu_train_vae',
        config=dict(vae_params_d, epochs=EPOCHS, batch_size=BATCH_SIZE,
                    learning_rate=LEARNING_RATE),
    )

    # jitted eval helpers for the periodic "hard reconstruction" probe
    # (ref train_vae.py:187-209): codebook indices -> decode.
    @jax.jit
    def hard_recon(params, images):
        codes = vae.apply({'params': params}, images,
                          method=DiscreteVAE.get_codebook_indices)
        return vae.apply({'params': params}, codes, method=DiscreteVAE.decode), codes

    global_step = 0
    lr = LEARNING_RATE
    temp = STARTING_TEMP
    t_step = time.perf_counter()
    for epoch in range(EPOCHS):
        for i, images in enumerate(dl):
            batch = part.shard_batch(images)
            rng, step_rng = jax.random.split(rng)
            params, opt_state, loss, recons = train_step(
                params, opt_state, batch, step_rng, jnp.asarray(temp, jnp.float32))

            if i % 100 == 0:
                # periodic probes (ref :187-209): SPMD computations run on
                # every process; only root writes files
                k = NUM_IMAGES_SAVE
                hard, codes = hard_recon(params, batch[:k])
                host_imgs = host_fetch(batch[:k])
                host_soft = host_fetch(recons[:k])
                host_hard = host_fetch(hard)
                host_codes = host_fetch(codes)
                weights = host_fetch(params)
                if distr_backend.is_root_worker():
                    save_image_grid(f'samples/vae/epoch{epoch}_iter{i}_original.png',
                                    np.asarray(host_imgs))
                    save_image_grid(f'samples/vae/epoch{epoch}_iter{i}_soft.png',
                                    np.asarray(host_soft))
                    save_image_grid(f'samples/vae/epoch{epoch}_iter{i}_hard.png',
                                    np.asarray(host_hard))
                    codes_np = np.asarray(host_codes).reshape(-1)
                    hist, _ = np.histogram(codes_np, bins=min(512, NUM_TOKENS),
                                           range=(0, NUM_TOKENS))
                    logger.log({
                        'codebook_used_frac': float((hist > 0).mean()),
                        'temperature': temp,
                    })
                    save_checkpoint('vae.pt', {
                        'hparams': cfg.to_dict(), 'weights': weights,
                    })
                    logger.save_file('vae.pt')  # wandb.save parity (ref :221)

                # temperature anneal + lr decay, per-epoch `i % 100` cadence
                # exactly as the reference (ref :211-217 — it also fires at
                # i==0 of every epoch, not on a global-step counter)
                temp = temp_sched.update(global_step)
                lr = sched.step()
                opt_state = set_learning_rate(opt_state, lr)

            if i % 10 == 0:
                avg_loss = float(distr_backend.average_all(loss))
                dt, t_step = time.perf_counter() - t_step, time.perf_counter()
                logger.step(epoch, i, avg_loss, lr,
                            extra={'temperature': temp, 'sec_per_10steps': dt})
            global_step += 1

    weights = host_fetch(params)
    if distr_backend.is_root_worker():
        save_checkpoint('vae-final.pt', {
            'hparams': cfg.to_dict(), 'weights': weights,
        })
        # wandb artifact upload parity (ref train_vae.py:241-253)
        logger.log_artifact('vae-final.pt', 'trained-vae')
    logger.finish()


if __name__ == '__main__':
    main()
