#!/usr/bin/env python
"""Train the DiscreteVAE image tokenizer (stage 1) — TPU-native CLI.

Capability parity with the reference trainer (`/root/reference/train_vae.py`):
same flags (``--image_folder``, ``--image_size`` + distributed flags), same
hard-coded hyperparameters (ref train_vae.py:42-59), same gumbel temperature
anneal / ExponentialLR cadence (ref :211-217), same checkpoint payload
``{'hparams', 'weights'}`` -> ``vae.pt`` (ref :110-119), same observability
surface (loss/lr scalars, soft+hard reconstruction grids, codebook-usage
histogram; ref :185-235) — minus wandb when it isn't installed, in which case
images land in ``./samples/`` and scalars in the text log.

TPU-native redesign: one jitted train step (loss+grad+Adam update fused by
XLA), GSPMD data parallelism from a device mesh instead of
DeepSpeed/Horovod, bf16-ready model, loss averaging via replicated-mean
rather than an explicit NCCL allreduce.
"""
from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from dalle_pytorch_tpu import DiscreteVAE, VAEConfig
from dalle_pytorch_tpu.cli import host_fetch, enable_compilation_cache
from dalle_pytorch_tpu.data.dataset import DataLoader, ImageFolderDataset
from dalle_pytorch_tpu.obs import telemetry as obs
from dalle_pytorch_tpu.parallel import backend as distributed_utils
from dalle_pytorch_tpu.training import make_optimizer, make_vae_train_step, set_learning_rate
from dalle_pytorch_tpu.utils import faults, guardrails
from dalle_pytorch_tpu.utils.checkpoint import save_checkpoint
from dalle_pytorch_tpu.utils.ckpt_manager import (CheckpointManager,
                                                  config_fingerprint)
from dalle_pytorch_tpu.utils.failure import GracefulShutdown, Heartbeat
from dalle_pytorch_tpu.utils.images import save_image_grid
from dalle_pytorch_tpu.utils.logging import TrainLogger
from dalle_pytorch_tpu.utils.schedule import ExponentialDecay, GumbelTemperature


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument('--image_folder', type=str, required=True,
                        help='path to your folder of images for learning the '
                             'discrete VAE and its codebook (with '
                             '--data_format shards: the shard directory '
                             'holding index.json + shard-*.tar)')
    parser.add_argument('--data_format', choices=('folder', 'shards'),
                        default='folder',
                        help="input pipeline: 'folder' lists loose files; "
                             "'shards' streams tar shards (tools/"
                             "make_shards.py --image_only) with per-host "
                             "shard assignment and a fingerprinted resume "
                             "cursor")
    parser.add_argument('--image_size', type=int, required=False, default=128,
                        help='image size')
    parser.add_argument('--resume_path', type=str, default=None,
                        help='resume from a vae.pt checkpoint (its hparams '
                             'win over the script constants; optimizer, '
                             'epoch, lr, and gumbel temperature continue '
                             'exactly — the reference cannot resume VAE '
                             'training at all)')
    parser.add_argument('--heartbeat_dir', type=str, default=None,
                        help='write per-process heartbeat-p{i}.json progress '
                             'files here for external stall/death monitors')
    parser.add_argument('--telemetry_dir', type=str, default=None,
                        help='graftscope run telemetry: append a schema-'
                             'versioned events.jsonl (step records, ckpt/'
                             'health/fault events, spans) here for '
                             'tools/obs_report.py; GRAFT_TELEMETRY=0 '
                             'hard-disables even when set')
    parser.add_argument('--metrics_port', type=int, default=0,
                        help='serve /metrics (Prometheus text) + /healthz '
                             'from an in-process daemon thread on this '
                             'port (+ process index); series are fed by '
                             'the telemetry emit path. 0 disables')
    parser.add_argument('--alerts', action=argparse.BooleanOptionalAction,
                        default=True,
                        help='attach the declarative alert engine (obs/'
                             'alerts.py DEFAULT_RULES) to the telemetry '
                             'stream; fired alerts are emitted as `alert` '
                             'events causally after their cause and '
                             'printed. No-op without --telemetry_dir')
    parser.add_argument('--stall_timeout', type=float, default=0,
                        help='warn on stderr when no step completes for this '
                             'many seconds (0 disables the in-process '
                             'watchdog); requires --heartbeat_dir')
    parser.add_argument('--health', choices=('off', 'warn', 'skip',
                                             'rollback'), default='skip',
                        help='training-health guardrails (see train_dalle '
                             '--health): on-device health vector per step; '
                             'skip masks non-finite updates; rollback '
                             'additionally rolls back to the newest valid '
                             'managed checkpoint on spikes/divergence')
    parser.add_argument('--step_deadline', type=float, default=0,
                        help='hung-step watchdog deadline in seconds '
                             '(first, compile-bearing step exempt); on '
                             'expiry dump stacks and exit with the wedge '
                             'code (75). 0 disables')
    parser.add_argument('--max_rollbacks', type=int, default=3,
                        help='anomaly-recovery budget for --health '
                             'rollback; exhausting it exits 70')
    parser.add_argument('--spike_zscore', type=float, default=8.0,
                        help='robust z-score above which a finite loss '
                             'counts as a spike')
    parser.add_argument('--sharded_checkpoints', action='store_true',
                        help='save Orbax sharded checkpoint dirs '
                             '({name}.orbax) with per-host shard IO instead '
                             'of gathering to process 0; --resume_path '
                             'accepts both formats')
    parser.add_argument('--resume', type=str, default=None,
                        help="'auto': resume from the newest manifest-valid "
                             'checkpoint in --ckpt_dir, skipping torn or '
                             'corrupt ones; any other value is an explicit '
                             'checkpoint path (same as --resume_path)')
    parser.add_argument('--ckpt_dir', type=str, default='./checkpoints',
                        help='managed checkpoint run dir: one '
                             'ckpt-{step:08d}/ per save, each with an '
                             'integrity manifest (per-file crc32) published '
                             'by atomic rename only after the data lands')
    parser.add_argument('--keep_checkpoints', type=int, default=3,
                        help='retention: keep the newest N managed '
                             'checkpoints (0 keeps all)')
    parser.add_argument('--keep_every', type=int, default=0,
                        help='retention: additionally keep every managed '
                             'checkpoint whose step is a multiple of M')
    parser.add_argument('--ckpt_every', type=int, default=100,
                        help='managed-checkpoint cadence in steps (0 '
                             'disables the CheckpointManager entirely)')
    parser.add_argument('--ckpt_async', action=argparse.BooleanOptionalAction,
                        default=True,
                        help='write managed checkpoints from a background '
                             'thread (host snapshot stays synchronous; the '
                             'atomic manifest publish stays the sole commit '
                             'point). --no-ckpt_async restores blocking '
                             'saves; Orbax sharded saves are always '
                             'blocking (collective)')
    parser = distributed_utils.wrap_arg_parser(parser)
    args = parser.parse_args(argv)
    # resolve the declarative ParallelPlan (--plan wins over the individual
    # mesh flags; the VAE trainer has no sp/pp paths, so those plans are
    # rejected here with a real message)
    from dalle_pytorch_tpu.parallel.plan import resolve_plan_args
    try:
        args.run_plan = resolve_plan_args(args)
    except ValueError as e:
        parser.error(str(e))
    if args.stall_timeout and not args.heartbeat_dir:
        parser.error('--stall_timeout requires --heartbeat_dir')
    if args.resume and args.resume_path:
        parser.error('--resume and --resume_path are mutually exclusive '
                     '(--resume auto resolves the checkpoint itself)')
    return args


def main(argv=None):
    """CLI entry: the real run (`_main`) inside the shared rollback-and-
    skip escalation loop (utils/guardrails.run_with_rollback)."""
    argv = list(sys.argv[1:] if argv is None else argv)
    return guardrails.run_with_rollback(_main, argv)


def _main(argv, lr_scale=1.0, skip_past=None):
    enable_compilation_cache()
    args = parse_args(argv)

    # constants (ref train_vae.py:42-59)
    C = dict(
        EPOCHS=20,
        BATCH_SIZE=8,
        LEARNING_RATE=1e-3,
        LR_DECAY_RATE=0.98,
        NUM_TOKENS=8192,
        NUM_LAYERS=2,
        NUM_RESNET_BLOCKS=2,
        SMOOTH_L1_LOSS=False,
        EMB_DIM=512,
        HID_DIM=256,
        KL_LOSS_WEIGHT=0,
        STARTING_TEMP=1.0,
        TEMP_MIN=0.5,
        ANNEAL_RATE=1e-6,
        NUM_IMAGES_SAVE=4,
    )
    # The reference's sweep workflow was "edit the constants in the file"
    # (SURVEY.md §5.6).  Here sweeps/tests override them via a JSON dict in
    # $DALLE_TPU_HPARAMS without touching the script.
    import json as _json
    import os as _os
    # graftlint: disable=ENV001 (JSON-valued: presence of any override dict is the signal)
    if _os.environ.get('DALLE_TPU_HPARAMS'):
        C.update(_json.loads(_os.environ['DALLE_TPU_HPARAMS']))

    IMAGE_SIZE = args.image_size
    EPOCHS = C['EPOCHS']
    BATCH_SIZE = C['BATCH_SIZE']
    LEARNING_RATE = C['LEARNING_RATE']
    LR_DECAY_RATE = C['LR_DECAY_RATE']

    NUM_TOKENS = C['NUM_TOKENS']
    NUM_LAYERS = C['NUM_LAYERS']
    NUM_RESNET_BLOCKS = C['NUM_RESNET_BLOCKS']
    SMOOTH_L1_LOSS = C['SMOOTH_L1_LOSS']
    EMB_DIM = C['EMB_DIM']
    HID_DIM = C['HID_DIM']
    KL_LOSS_WEIGHT = C['KL_LOSS_WEIGHT']

    STARTING_TEMP = C['STARTING_TEMP']
    TEMP_MIN = C['TEMP_MIN']
    ANNEAL_RATE = C['ANNEAL_RATE']

    NUM_IMAGES_SAVE = C['NUM_IMAGES_SAVE']

    distr_backend = distributed_utils.set_backend_from_args(args)
    distr_backend.initialize()
    distr_backend.check_batch_size(BATCH_SIZE)

    # chaos rehearsal hooks (GRAFT_FAULTS) — re-parsed per run so
    # in-process reruns (tests) see the current environment
    faults.install_from_env()

    # crash-consistent managed checkpoints + auto-resume fallback; every
    # manifest records the writing plan + topology (elastic resume)
    from dalle_pytorch_tpu.parallel.plan import (current_topology,
                                                 describe_transition)
    manager = (CheckpointManager(args.ckpt_dir,
                                 keep_last=args.keep_checkpoints,
                                 keep_every=args.keep_every,
                                 sharded=args.sharded_checkpoints,
                                 async_save=args.ckpt_async,
                                 plan=args.run_plan.to_manifest(),
                                 topology=current_topology())
               if args.ckpt_every > 0 else None)
    if args.resume == 'auto':
        info = manager.latest_valid() if manager is not None else None
        if info is not None:
            args.resume_path = str(info.payload)
            if distr_backend.is_root_worker():
                print(f'auto-resume: step {info.step} from {info.payload}')
                transition = describe_transition(
                    info.manifest.get('plan'), args.run_plan,
                    info.manifest.get('topology'))
                if transition:
                    print(f'[resume] {transition}')
        elif distr_backend.is_root_worker():
            print(f'auto-resume: no valid checkpoint under {args.ckpt_dir}; '
                  'starting fresh')
    elif args.resume:
        args.resume_path = args.resume

    # resume (our §5.3 extension — the reference's train_vae.py cannot
    # resume): checkpoint hparams win over the script constants and the CLI
    # --image_size, so this must run before the dataset is built
    resume_ckpt = None
    resume_sharded = None  # Orbax dir: arrays restore direct-to-device later
    if args.resume_path:
        from dalle_pytorch_tpu.utils.checkpoint import (is_sharded_checkpoint,
                                                        load_checkpoint,
                                                        load_sharded_small)

        if is_sharded_checkpoint(args.resume_path):
            # two-phase elastic resume (as in train_dalle): configs/scalars
            # now, arrays straight onto this run's shardings below
            resume_sharded = Path(args.resume_path)
            resume_ckpt = load_sharded_small(resume_sharded)
        else:
            resume_ckpt = jax.tree.map(
                lambda v: np.asarray(v) if hasattr(v, 'devices') else v,
                load_checkpoint(args.resume_path))
        cfg = VAEConfig.from_dict(dict(resume_ckpt['hparams']))
        IMAGE_SIZE = cfg.image_size
        vae_params_d = dict(
            image_size=cfg.image_size, num_layers=cfg.num_layers,
            num_tokens=cfg.num_tokens, codebook_dim=cfg.codebook_dim,
            hidden_dim=cfg.hidden_dim,
            num_resnet_blocks=cfg.num_resnet_blocks,
        )
    else:
        vae_params_d = dict(
            image_size=IMAGE_SIZE,
            num_layers=NUM_LAYERS,
            num_tokens=NUM_TOKENS,
            codebook_dim=EMB_DIM,
            hidden_dim=HID_DIM,
            num_resnet_blocks=NUM_RESNET_BLOCKS,
        )
        cfg = VAEConfig(
            **vae_params_d,
            smooth_l1_loss=SMOOTH_L1_LOSS,
            kl_div_loss_weight=KL_LOSS_WEIGHT,
        )
    vae = DiscreteVAE(cfg)
    if manager is not None:
        manager.fingerprint = config_fingerprint(cfg.to_dict())

    if args.data_format == 'shards':
        # streaming ingestion (data/stream.py): image-only tar shards
        # behind the same iteration contract
        from dalle_pytorch_tpu.data.stream import (ShardStreamDataset,
                                                   StreamingDataLoader)

        ds = ShardStreamDataset(args.image_folder, image_size=IMAGE_SIZE,
                                image_only=True)
        dl = StreamingDataLoader(
            ds, BATCH_SIZE, shuffle=True, drop_last=True,
            shard_num_hosts=jax.process_count(),
            shard_index=jax.process_index(),
        )
    else:
        ds = ImageFolderDataset(args.image_folder, image_size=IMAGE_SIZE)
        dl = DataLoader(
            ds, BATCH_SIZE, shuffle=True, drop_last=True,
            shard_num_hosts=jax.process_count(),
            shard_index=jax.process_index(),
        )
    assert len(ds) > 0, 'folder does not contain any images'
    if distr_backend.is_root_worker():
        print(f'{len(ds)} images found for training')

    rng = jax.random.PRNGKey(0)
    rng, init_rng = jax.random.split(rng)
    # the resolved ParallelPlan builds the mesh + Partitioner: init,
    # restore templates, and the step-output pin all derive from it
    part = distr_backend.distribute(plan=args.run_plan)
    dummy = jnp.zeros((1, IMAGE_SIZE, IMAGE_SIZE, 3), jnp.float32)
    if resume_sharded is not None:
        # templates only: no device allocation before the direct restore
        shapes = jax.eval_shape(
            lambda r: vae.init({'params': r, 'gumbel': r}, dummy)['params'],
            init_rng)
        params = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            shapes, part.param_shardings(shapes))
    elif resume_ckpt is not None:
        params = part.shard_params(
            jax.tree.map(jnp.asarray, resume_ckpt['weights']))
    else:
        params = part.shard_params(jax.jit(
            lambda r: vae.init({'params': r, 'gumbel': r}, dummy)['params']
        )(init_rng))

    tx = make_optimizer(LEARNING_RATE)
    if resume_sharded is not None:
        opt_state = jax.eval_shape(tx.init, params)
        from dalle_pytorch_tpu.utils.checkpoint import load_checkpoint_sharded

        target = dict(resume_ckpt)
        target['weights'] = params
        if 'opt_state' in resume_ckpt:
            target['opt_state'] = [
                sds if saved is ... else saved
                for sds, saved in zip(part.opt_state_templates(opt_state),
                                      resume_ckpt['opt_state'])]
        restored = load_checkpoint_sharded(resume_sharded, target=target)
        params = restored['weights']
        fitted = [
            v if (hasattr(v, 'sharding') and getattr(v, 'ndim', 0) > 0)
            else (jax.device_put(jnp.asarray(v, tmpl.dtype),
                                 part.repl_sharding)
                  if hasattr(tmpl, 'dtype') else v)
            for tmpl, v in zip(jax.tree.leaves(opt_state),
                               restored.get('opt_state', []))]
        opt_state = (jax.tree.unflatten(jax.tree.structure(opt_state), fitted)
                     if fitted else part.init_opt_state(tx, params))
    else:
        opt_state = part.init_opt_state(tx, params)
        if resume_ckpt is not None and 'opt_state' in resume_ckpt:
            opt_state = jax.tree.map(
                lambda tmpl, v: (jnp.asarray(v).astype(tmpl.dtype)
                                 if hasattr(tmpl, 'dtype') else v),
                opt_state,
                jax.tree.unflatten(jax.tree.structure(opt_state),
                                   jax.tree.leaves(resume_ckpt['opt_state'])))
    health_on = args.health != 'off'
    train_step = make_vae_train_step(
        vae, tx, health=health_on,
        guard=args.health in ('skip', 'rollback'), partitioner=part)

    # device-prefetch double buffer (both data formats): batch k+1 is
    # pulled and device-placed while step k runs; checkpoints record
    # batches.state_dict() (the consumed-batch cursor), never the loader's
    # read-ahead cursor
    from dalle_pytorch_tpu.data.stream import DevicePrefetcher

    batches = DevicePrefetcher(dl, place=part.shard_batch, depth=1)

    sched = ExponentialDecay(LEARNING_RATE, LR_DECAY_RATE)
    temp_sched = GumbelTemperature(STARTING_TEMP, TEMP_MIN, ANNEAL_RATE)
    start_epoch = 0
    resume_cursor = 0
    if resume_ckpt is not None:
        start_epoch = int(resume_ckpt.get('epoch', 0))
        sched.lr = float(resume_ckpt.get('lr', LEARNING_RATE))
        temp_sched.value = float(resume_ckpt.get('temperature', STARTING_TEMP))
        opt_state = set_learning_rate(opt_state, sched.lr)
        # exact mid-epoch resume: RNG stream + loader position (same
        # permutation, consumed batches skipped).  A loader snapshot from
        # an earlier epoch (the final checkpoint) just aligns the
        # permutation stream for the next epoch.
        if resume_ckpt.get('rng') is not None:
            rng = jnp.asarray(np.asarray(
                [int(v) for v in resume_ckpt['rng']], dtype=np.uint32))
        resume_loader = resume_ckpt.get('loader')
        if resume_loader is not None and \
                int(dict(resume_loader).get('epoch', -1)) == start_epoch:
            # the loaders coerce their own scalar types (the streaming
            # cursor also carries the shard-list fingerprint, a string,
            # which it validates itself)
            dl.load_state_dict(dict(resume_loader))
            resume_cursor = min(int(dict(resume_loader).get('cursor', 0)),
                                len(dl))
        else:
            dl.epoch = start_epoch
    if lr_scale != 1.0:
        # rollback LR backoff (compounding across relaunches; the restored
        # checkpoint predates the rollback)
        sched.lr *= lr_scale
        opt_state = set_learning_rate(opt_state, sched.lr)
        if distr_backend.is_root_worker():
            print(f'[guardrails] rollback lr backoff: lr={sched.lr:.3e}')

    logger = TrainLogger(
        project='dalle_tpu_train_vae',
        config=dict(vae_params_d, epochs=EPOCHS, batch_size=BATCH_SIZE,
                    learning_rate=LEARNING_RATE),
    )

    # graftscope run telemetry: one events.jsonl per run — the layers
    # below (ckpt manager, guardrails, faults, loader) emit into the
    # installed singleton.  --metrics_port starts /metrics + /healthz
    # (fed by the emit path); --alerts attaches the declarative rule
    # engine so fired alerts land in the same stream after their cause.
    metrics_server = None
    if args.metrics_port:
        from dalle_pytorch_tpu.obs import metrics as obs_metrics
        metrics_server = obs_metrics.serve(
            args.metrics_port + jax.process_index())
    if args.telemetry_dir:
        tel = obs.init(args.telemetry_dir, run_id=logger.run_name,
                       host=jax.process_index())
        if metrics_server is not None:
            tel.attach_metrics(metrics_server.registry)
        if args.alerts:
            from dalle_pytorch_tpu.obs.alerts import AlertEngine
            tel.attach_alerts(AlertEngine())
        obs.emit('run', 'run_start',
                 step=(int(resume_ckpt.get('global_step', 0))
                       if resume_ckpt is not None else 0),
                 epoch=start_epoch,
                 config_fingerprint=config_fingerprint(cfg.to_dict()),
                 resumed_from=args.resume_path or None,
                 trainer='train_vae')
        # predicted-vs-measured: the perf ledger's roofline ceiling for
        # the VAE step (exact geometry fingerprint, else the target row)
        import dataclasses as _dc

        from dalle_pytorch_tpu.obs import prof
        _fp = prof.row_fingerprint({
            **{k: str(v) for k, v in sorted(_dc.asdict(cfg).items())},
            'target': 'vae', 'plan': 'single',
            'batch': BATCH_SIZE * jax.process_count()})
        _pred = prof.predicted_for(fingerprint=_fp, target='vae',
                                   plan='single')
        if _pred is not None:
            obs.emit('prof', 'predicted', target='vae', **_pred)
        # the memory half of the join (graftmem's predicted HBM timeline)
        from dalle_pytorch_tpu.obs import mem as obs_mem
        _mempred = obs_mem.predicted_memory_for(
            fingerprint=_fp, target='vae', plan='single')
        if _mempred is not None:
            obs.emit('mem', 'predicted', target='vae', **_mempred)

    # jitted eval helpers for the periodic "hard reconstruction" probe
    # (ref train_vae.py:187-209): codebook indices -> decode.
    @jax.jit
    def hard_recon(params, images):
        codes = vae.apply({'params': params}, images,
                          method=DiscreteVAE.get_codebook_indices)
        return vae.apply({'params': params}, codes, method=DiscreteVAE.decode), codes

    def vae_payload(weights, opt_leaves, epoch):
        """Checkpoint dict: the reference's ``{'hparams', 'weights'}``
        (train_vae.py:110-119) plus resume-exactness extras (optimizer,
        schedules, position) — loaders that only want hparams/weights
        ignore the rest.  For the msgpack path `weights`/`opt_leaves` must
        already be host arrays: host_fetch is collective (every process
        participates), so callers fetch *before* any root-only branch; the
        Orbax path passes device arrays and shards the IO itself."""
        return {
            'hparams': cfg.to_dict(), 'weights': weights,
            'opt_state': opt_leaves,
            'epoch': epoch, 'global_step': global_step,
            'temperature': temp, 'lr': lr,
            # exact-resume extras (plain scalars; restore without devices)
            'rng': [int(v) for v in np.asarray(jax.device_get(rng))],
            'loader': batches.state_dict(),
        }

    def save_vae_model(path, epoch):
        """Both checkpoint formats: Orbax sharded dirs ({path}.orbax —
        per-host shard IO, every process participates collectively) or
        gathered msgpack (collective fetch, root writes)."""
        if args.sharded_checkpoints:
            from dalle_pytorch_tpu.utils.checkpoint import \
                save_checkpoint_sharded

            path = f'{path}.orbax'
            save_checkpoint_sharded(
                path, vae_payload(params, jax.tree.leaves(opt_state), epoch))
            return path
        weights = host_fetch(params)
        opt_leaves = host_fetch(jax.tree.leaves(opt_state))
        if distr_backend.is_root_worker():
            save_checkpoint(path, vae_payload(weights, opt_leaves, epoch))
        return path

    last_managed = [-1]  # step of the last managed-save attempt

    def save_vae_managed(step, epoch):
        """Managed checkpoint with an integrity manifest (ckpt_dir/
        ckpt-{step:08d}/), retried with backoff; a failed save is logged,
        not fatal."""
        if manager is None or step == last_managed[0]:
            return
        last_managed[0] = step
        if args.sharded_checkpoints:
            payload = vae_payload(params, jax.tree.leaves(opt_state), epoch)
        else:
            payload = vae_payload(host_fetch(params),
                                  host_fetch(jax.tree.leaves(opt_state)),
                                  epoch)
        if args.sharded_checkpoints or distr_backend.is_root_worker():
            try:
                manager.save(step, payload)
            except OSError as e:
                print(f'[ckpt] managed save at step {step} failed after '
                      f'retries: {e}', file=sys.stderr, flush=True)
        # ckpt-phase watermark: the host-fetched payload live alongside
        # the residents is the predicted timeline's snapshot term
        mem_tracker.snapshot('ckpt', step=step)

    global_step = (int(resume_ckpt.get('global_step', 0))
                   if resume_ckpt is not None else 0)
    lr = sched.lr
    temp = temp_sched.value
    interrupted = False
    completed = False
    stop_poll = False  # collective stop flag from the last 10-step poll
    # step timing + the bounded percentile reservoir (flops left None —
    # images/sec is the VAE's throughput surface, MFU is the DALLE one)
    from dalle_pytorch_tpu.utils.profiling import StepTimer

    timer = StepTimer()
    # phase-boundary memory watermarks (obs/mem.py): "init" with params +
    # opt state resident, "ckpt" after each managed save — never per step
    from dalle_pytorch_tpu.obs import mem as obs_mem
    mem_tracker = obs_mem.MemTracker()
    mem_tracker.snapshot('init', step=global_step)
    # preemption-safe shutdown + stall detection (SURVEY.md §5.3)
    stopper = GracefulShutdown()
    heartbeat = (Heartbeat(args.heartbeat_dir,
                           stall_timeout=args.stall_timeout or None,
                           run_id=logger.run_name)
                 if args.heartbeat_dir else None)
    # training-health guardrails: anomaly policy + hung-step watchdog
    monitor_h = (guardrails.HealthMonitor(
        mode='rollback' if args.health == 'rollback' else
             ('warn' if args.health == 'warn' else 'skip'),
        spike_zscore=args.spike_zscore) if health_on else None)
    watchdog = (guardrails.StepWatchdog(args.step_deadline)
                if args.step_deadline > 0 else None)
    if skip_past is not None and distr_backend.is_root_worker():
        print(f'[guardrails] rollback resume: skipping the data window '
              f'through step {skip_past}')
    pending_h = [None]  # (step id, device loss, health vector), 1 deferred

    def observe_health():
        """Feed the previous step's health vector to the anomaly policy —
        one step deferred, like the loss logging, so the host sync never
        stalls the device.  The loss is an output of the one SPMD step
        program, identical on every process, so verdicts are collective."""
        if monitor_h is None or pending_h[0] is None:
            return
        sid, loss_dev, hv = pending_h[0]
        pending_h[0] = None
        monitor_h.observe(sid, loss=float(loss_dev),
                          grad_norm=float(hv['grad_norm']),
                          applied=float(hv['applied']))
        if monitor_h.wants_rollback:
            if distr_backend.is_root_worker():
                guardrails.write_anomaly_bundle(
                    args.ckpt_dir, sid, {
                        'reason': monitor_h.rollback_reason,
                        'loss': monitor_h.last_loss,
                        'grad_norm': monitor_h.last_grad_norm,
                        'loss_history': monitor_h.history(),
                        'loader': batches.state_dict(),
                        'rng': [int(v) for v in
                                np.asarray(jax.device_get(rng))],
                        'config_fingerprint':
                            config_fingerprint(cfg.to_dict()),
                        'lr': lr})
            raise guardrails.RollbackAndSkip(
                sid, max_rollbacks=args.max_rollbacks,
                reason=monitor_h.rollback_reason or 'anomaly')

    t_step = time.perf_counter()
    try:
        with stopper:
            for epoch in range(start_epoch, EPOCHS):
                for i, (images, batch) in enumerate(batches):
                    # `it`: true batch index in this epoch's permutation —
                    # a mid-epoch resume skips consumed batches, so the
                    # cadences below must continue from the interrupted
                    # position, not restart at 0
                    it = i + (resume_cursor if epoch == start_epoch else 0)
                    if skip_past is not None and global_step < skip_past:
                        # rollback-and-skip: consume the anomalous data
                        # window without training on it
                        rng, _ = jax.random.split(rng)
                        global_step += 1
                        if heartbeat is not None:
                            heartbeat.beat(global_step, epoch=epoch,
                                           health_state='skipping-window')
                        continue
                    if watchdog is not None:
                        watchdog.arm(global_step + 1)
                    rng, step_rng = jax.random.split(rng)
                    if health_on:
                        params, opt_state, loss, recons, health_vec = \
                            train_step(params, opt_state, batch, step_rng,
                                       jnp.asarray(temp, jnp.float32),
                                       jnp.float32(guardrails.fault_scale_for(
                                           global_step + 1)))
                    else:
                        health_vec = None
                        params, opt_state, loss, recons = train_step(
                            params, opt_state, batch, step_rng,
                            jnp.asarray(temp, jnp.float32))
                    # chaos rehearsal: GRAFT_FAULTS="step_hang:at_step=N"
                    # wedges here, inside the watchdog's armed window
                    faults.maybe_hang(global_step + 1)
                    observe_health()  # previous step's verdict (deferred)
                    if health_on:
                        pending_h[0] = (global_step + 1, loss, health_vec)

                    if it % 100 == 0:
                        # periodic probes (ref :187-209): SPMD computations run
                        # on every process; only root writes files
                        k = NUM_IMAGES_SAVE
                        hard, codes = hard_recon(params, batch[:k])
                        host_imgs = host_fetch(batch[:k])
                        host_soft = host_fetch(recons[:k])
                        host_hard = host_fetch(hard)
                        host_codes = host_fetch(codes)
                        if distr_backend.is_root_worker():
                            save_image_grid(f'samples/vae/epoch{epoch}_iter{it}_original.png',
                                            np.asarray(host_imgs))
                            save_image_grid(f'samples/vae/epoch{epoch}_iter{it}_soft.png',
                                            np.asarray(host_soft))
                            save_image_grid(f'samples/vae/epoch{epoch}_iter{it}_hard.png',
                                            np.asarray(host_hard))
                            codes_np = np.asarray(host_codes).reshape(-1)
                            hist, _ = np.histogram(codes_np, bins=min(512, NUM_TOKENS),
                                                   range=(0, NUM_TOKENS))
                            logger.log({
                                'codebook_used_frac': float((hist > 0).mean()),
                                'temperature': temp,
                            })
                        save_vae_model('vae.pt', epoch)
                        logger.save_file('vae.pt')  # wandb.save parity (ref :221)

                        # temperature anneal + lr decay, per-epoch `i % 100`
                        # cadence exactly as the reference (ref :211-217 — it
                        # also fires at i==0 of every epoch, not on a
                        # global-step counter)
                        temp = temp_sched.update(global_step)
                        lr = sched.step()
                        opt_state = set_learning_rate(opt_state, lr)

                    # per-step timing/stall EMAs + the percentile reservoir
                    # (host-side arithmetic only — no device sync here)
                    perf = timer.tick(BATCH_SIZE * jax.process_count(),
                                      stall_s=batches.last_wait_s)
                    if it % 10 == 0:
                        # the preemption check rides the existing 10-step loss
                        # collective (multi-host stop latency <= 10 fast VAE
                        # steps, well inside any preemption grace window)
                        avg_loss, stop_poll = stopper.average_and_poll(
                            distr_backend, loss)
                        dt, t_step = time.perf_counter() - t_step, time.perf_counter()
                        logger.step(epoch, it, avg_loss, lr,
                                    extra=dict({'temperature': temp,
                                                'sec_per_10steps': dt},
                                               **perf))
                        tel = obs.get()
                        if tel is not None:
                            # step records at the loss-sync cadence (the VAE
                            # loop only materializes the loss every 10
                            # steps; a per-step host sync would stall the
                            # device just to log)
                            tel.event('step', 'train', step=global_step + 1,
                                      epoch=epoch, it=it, loss=avg_loss,
                                      lr=lr, temperature=temp, **perf)
                    global_step += 1
                    if args.ckpt_every > 0 and it % args.ckpt_every == 0:
                        # observe THIS step's health before it reaches a
                        # manifest: an anomaly must escalate here so the
                        # rollback target is the previous (pre-anomaly)
                        # checkpoint, never this one (train_dalle orders
                        # its flush before save_managed the same way)
                        observe_health()
                        save_vae_managed(global_step, epoch)
                    if heartbeat is not None:
                        heartbeat.beat(global_step, epoch=epoch,
                                       loader_stall_s=round(
                                           batches.last_wait_s, 4),
                                       **(monitor_h.beat_extras()
                                          if monitor_h is not None else {}))
                    if watchdog is not None:
                        watchdog.disarm()
                    # chaos rehearsal: GRAFT_FAULTS="sigterm:at_step=N";
                    # "preempt:at_step=N" additionally arms the bounded
                    # grace window (hard-kill on expiry)
                    faults.maybe_kill(global_step)
                    faults.maybe_preempt(global_step)
                    # multi-process: the collective decision from the last
                    # 10-step poll (symmetric across processes, so the
                    # collective save below cannot deadlock); single-process:
                    # the fresher local flag
                    if stop_poll if jax.process_count() > 1 \
                            else stopper.requested:
                        resume_path = save_vae_model('vae.pt', epoch)
                        # final managed checkpoint for --resume auto (no-op
                        # if this step's cadence save already ran)
                        save_vae_managed(global_step, epoch)
                        if distr_backend.is_root_worker():
                            print(f'interrupted at epoch {epoch} iter {it}: resume '
                                  f'checkpoint written to {resume_path} '
                                  f'(--resume_path {resume_path} to continue; '
                                  f'--resume auto picks the newest valid '
                                  f'managed checkpoint)')
                        interrupted = True
                        break
                if interrupted:
                    break
            completed = not interrupted
    finally:
        if manager is not None:
            # join the in-flight async checkpoint write before exit
            manager.finish()
        # final save committed (or never started): disarm the preemption
        # grace timer so a graceful stop inside the window stays clean
        faults.cancel_preempt_grace()
        if watchdog is not None:
            watchdog.close()
        if heartbeat is not None:
            heartbeat.close(done=completed)
        # run_end carries the StepTimer reservoir percentiles; shutdown
        # lets in-process relaunches (rollback, tests) start a fresh stream
        obs.emit('run', 'run_end', step=global_step, completed=completed,
                 interrupted=interrupted, **timer.percentiles())
        obs.shutdown()
        if metrics_server is not None:
            metrics_server.close()

    if not interrupted:
        final_path = save_vae_model('vae-final.pt', EPOCHS)
        if distr_backend.is_root_worker():
            # wandb artifact upload parity (ref train_vae.py:241-253)
            logger.log_artifact(final_path, 'trained-vae')
    logger.finish()


if __name__ == '__main__':
    main()
