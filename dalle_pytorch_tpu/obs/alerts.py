"""Declarative alerting over the telemetry stream.

A run that is sick but not dead is the failure mode nothing earlier
catches: heartbeats age only when the process stops, guardrails only see
non-finite/spiking losses, and the stream records a stall faithfully
without ever *saying* anything.  This module closes that gap with a small
set of declarative rules evaluated over sliding windows of the event
stream itself:

* **threshold** — the windowed mean of a sampled field crosses a limit
  (``stall_fraction``: the step loop is input-bound; ``slo_attainment``:
  the serve burn-rate shape — attainment is a success ratio, so a
  windowed mean below target IS the burn).
* **ratio_of_median** — the windowed mean falls below a fraction of the
  run's own median so far (``mfu_drop``: a straggler or a thermally
  throttled chip reads as "slower than this very run used to be", no
  absolute threshold needed).
* **ratio_of_ref** — the windowed mean falls below a fraction of a
  reference value another record announced (``mfu_vs_predicted``: the
  trainer emits the roofline-predicted MFU ceiling from the perf ledger
  at run start; measured MFU sustained under half the *predicted*
  ceiling is a sick run even on its very first window — the
  ratio_of_median rule is blind to a run that was born slow).
* **rate** — more than N matching events inside the window
  (``quarantine_rate``: the data diet is rotting faster than the
  per-sample policy can hide).
* **gap** — the monotonic distance between consecutive matching records
  exceeds a limit (``heartbeat_gap``: the stream went quiet mid-run; the
  in-process engine sees it when the next record finally lands, the
  monitor's fleet scan sees it live from outside).

The engine is pure (observe records in, fired alerts out) and stdlib-only;
``Telemetry.attach_alerts`` wires it into the emit path so a fired alert
is emitted back into the SAME stream as an ``alert`` event — with a seq
strictly after the record that tripped it, which is what lets chaos tests
assert cause -> alert ordering from the stream alone — and printed via the
``note()`` operator line.  ``tools/monitor.py --fleet`` runs the same
rules offline over N hosts' stream tails.
"""
from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Rule:
    """One declarative alert rule.

    ``select_kind``/``select_names`` pick the records the rule samples
    (span markers are always skipped); ``field`` names the payload value
    sampled (None counts 1.0 per match; bools coerce to 0/1).  ``kind``
    picks the evaluation: threshold (window mean ``op`` ``limit``),
    ratio_of_median (window mean < ``ratio`` x run median),
    ratio_of_ref (window mean < ``ratio`` x the reference the
    ``ref_kind``/``ref_name`` record announced in ``ref_field`` —
    silent until that record arrives), rate (window count > ``limit``),
    gap (mono gap > ``limit``).  ``cooldown_s`` bounds re-firing so a
    sustained condition is one alert per cooldown, not one per record."""

    name: str
    kind: str
    select_kind: str
    select_names: Optional[Tuple[str, ...]] = None
    field: Optional[str] = None
    op: str = ">"
    limit: float = 0.0
    ratio: float = 0.0
    window_s: float = 60.0
    min_count: int = 3
    cooldown_s: float = 300.0
    describe: str = ""
    ref_kind: Optional[str] = None
    ref_name: Optional[str] = None
    ref_field: Optional[str] = None


DEFAULT_RULES: Tuple[Rule, ...] = (
    Rule(name="stall_fraction", kind="threshold", select_kind="step",
         field="loader_stall_frac", op=">", limit=0.5, window_s=60.0,
         min_count=5,
         describe="input pipeline stalls dominate the step loop"),
    Rule(name="mfu_drop", kind="ratio_of_median", select_kind="step",
         field="mfu", ratio=0.6, window_s=120.0, min_count=5,
         describe="MFU fell well below this run's own median"),
    Rule(name="mfu_vs_predicted", kind="ratio_of_ref", select_kind="step",
         field="mfu", ratio=0.5, window_s=120.0, min_count=5,
         ref_kind="prof", ref_name="predicted", ref_field="mfu",
         describe="measured MFU sustained under half the roofline "
                  "ceiling the perf ledger predicts for this config"),
    Rule(name="slo_attainment", kind="threshold", select_kind="serve",
         select_names=("retire",), field="slo_ok", op="<", limit=0.9,
         window_s=120.0, min_count=10,
         describe="serve SLO attainment burning below target"),
    Rule(name="quarantine_rate", kind="rate", select_kind="data",
         select_names=("sample_quarantine", "shard_quarantine"),
         limit=5.0, window_s=300.0, min_count=1,
         describe="inputs quarantining faster than a rotten few"),
    Rule(name="heartbeat_gap", kind="gap", select_kind="step",
         limit=120.0, window_s=0.0, min_count=1, cooldown_s=60.0,
         describe="the stream went quiet between steps"),
    # min_count=1: watermarks are sparse phase-boundary polls
    # (obs/mem.py MemTracker), and ONE sample under 5% headroom must
    # page before the allocator OOMs, not after three more phases
    Rule(name="hbm_headroom", kind="threshold", select_kind="mem",
         select_names=("watermark",), field="headroom_frac", op="<",
         limit=0.05, window_s=120.0, min_count=1, cooldown_s=600.0,
         describe="HBM headroom under 5% of the device limit — the "
                  "next allocation spike OOMs"),
    # min_count=1: graftscale stamps the CURRENT reversal count on every
    # decision record and control ticks are sparse, so the rule may fire
    # from the very first over-budget sample instead of waiting out
    # three; during a real thrash every record carries the elevated
    # count, so the windowed mean crosses within a tick or two even
    # when calm holds preceded it
    Rule(name="autoscale_flapping", kind="threshold",
         select_kind="autoscale", select_names=("decision",),
         field="flaps", op=">", limit=2.0, window_s=60.0, min_count=1,
         cooldown_s=120.0,
         describe="the autoscaler is reversing direction faster than "
                  "the flap budget — hysteresis is mis-tuned for this "
                  "load shape"),
    Rule(name="saturated_at_max", kind="threshold",
         select_kind="autoscale", select_names=("decision",),
         field="saturated", op=">", limit=0.5, window_s=60.0,
         min_count=3, cooldown_s=120.0,
         describe="the fleet is pinned at max_replicas and still "
                  "overloaded — the brownout ladder is the only "
                  "headroom left"),
)


class _RuleState:
    __slots__ = ("window", "history", "last_match_mono", "last_fire_mono",
                 "ref")

    def __init__(self):
        self.window: Deque[Tuple[float, float]] = deque()  # (mono, value)
        self.history: List[float] = []       # all-time samples (median)
        self.last_match_mono: Optional[float] = None
        self.last_fire_mono: Optional[float] = None
        self.ref: Optional[float] = None     # ratio_of_ref reference value


def _cmp(value: float, op: str, limit: float) -> bool:
    return value > limit if op == ">" else value < limit


class AlertEngine:
    """Feed records in causal order (one host's stream); collect fired
    alerts.  ``active`` keeps the latest firing per rule — what the
    monitor's fleet scan prints."""

    def __init__(self, rules: Tuple[Rule, ...] = DEFAULT_RULES):
        self.rules = tuple(rules)
        self._state: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules}
        self.active: Dict[str, dict] = {}

    def observe(self, rec: dict) -> List[dict]:
        """Evaluate every rule against one record; returns the alerts that
        fired (payload dicts ready to ride an ``alert`` event).  Ignores
        span markers and the alert/clock kinds (no self-triggering)."""
        kind = rec.get("kind")
        if kind in ("alert", "clock") or "ph" in rec:
            return []
        mono = rec.get("mono")
        if mono is None:
            return []
        mono = float(mono)
        fired: List[dict] = []
        for rule in self.rules:
            alert = self._observe_one(rule, rec, kind, mono)
            if alert is not None:
                self.active[rule.name] = alert
                fired.append(alert)
        return fired

    # --- internals --------------------------------------------------------

    def _observe_one(self, rule: Rule, rec: dict, kind: str,
                     mono: float) -> Optional[dict]:
        st = self._state[rule.name]
        if rule.ref_kind is not None and kind == rule.ref_kind \
                and (rule.ref_name is None
                     or rec.get("name") == rule.ref_name):
            raw_ref = rec.get(rule.ref_field)
            if raw_ref is not None:
                st.ref = float(raw_ref)
        matched = (kind == rule.select_kind
                   and (rule.select_names is None
                        or rec.get("name") in rule.select_names))
        value: Optional[float] = None
        if matched:
            if rule.field is None:
                value = 1.0
            else:
                raw = rec.get(rule.field)
                if raw is None:
                    matched = False
                else:
                    value = float(raw)
        gap = None
        if matched:
            if st.last_match_mono is not None:
                gap = mono - st.last_match_mono
            st.last_match_mono = mono
            st.window.append((mono, value))
            st.history.append(value)
        # evict by the OBSERVED clock, so a rule's window drains even on
        # records it does not sample
        while st.window and mono - st.window[0][0] > rule.window_s:
            st.window.popleft()

        verdict = self._evaluate(rule, st, gap)
        if verdict is None:
            return None
        if st.last_fire_mono is not None \
                and mono - st.last_fire_mono < rule.cooldown_s:
            return None
        st.last_fire_mono = mono
        measured, msg = verdict
        return {
            "rule": rule.name, "value": round(measured, 6),
            "limit": rule.ratio if rule.kind in ("ratio_of_median",
                                                 "ratio_of_ref")
            else rule.limit,
            "window_s": rule.window_s, "window_n": len(st.window),
            "cause_seq": rec.get("seq"), "cause_kind": kind,
            "cause_name": rec.get("name"),
            "msg": f"{rule.name}: {msg}"
                   + (f" — {rule.describe}" if rule.describe else ""),
        }

    def _evaluate(self, rule: Rule, st: _RuleState,
                  gap: Optional[float]) -> Optional[Tuple[float, str]]:
        if rule.kind == "gap":
            if gap is not None and gap > rule.limit:
                return gap, f"{gap:.1f}s without a matching record " \
                            f"(limit {rule.limit:g}s)"
            return None
        if len(st.window) < rule.min_count:
            return None
        values = [v for _, v in st.window]
        if rule.kind == "rate":
            n = float(len(values))
            if n > rule.limit:
                return n, f"{int(n)} events in {rule.window_s:g}s " \
                          f"(limit {rule.limit:g})"
            return None
        mean = sum(values) / len(values)
        if rule.kind == "threshold":
            if _cmp(mean, rule.op, rule.limit):
                return mean, f"window mean {mean:.4g} {rule.op} " \
                             f"limit {rule.limit:g}"
            return None
        if rule.kind == "ratio_of_median":
            if len(st.history) < 2 * rule.min_count:
                return None
            ordered = sorted(st.history)
            median = ordered[len(ordered) // 2]
            if median > 0 and mean < rule.ratio * median:
                return mean, f"window mean {mean:.4g} < " \
                             f"{rule.ratio:g} x run median {median:.4g}"
            return None
        if rule.kind == "ratio_of_ref":
            # silent until the reference record arrives (a run without a
            # ledger prediction simply never evaluates this rule)
            if st.ref is None or st.ref <= 0:
                return None
            if mean < rule.ratio * st.ref:
                return mean, f"window mean {mean:.4g} < {rule.ratio:g} x " \
                             f"reference {st.ref:.4g}"
            return None
        raise ValueError(f"unknown rule kind {rule.kind!r}")
