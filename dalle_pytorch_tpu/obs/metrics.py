"""In-process metrics: a stdlib-only registry + /metrics + /healthz.

graftscope's event stream answers "what happened"; a fleet router (and a
human with a Grafana tab) needs "what is true RIGHT NOW" — queue depth,
occupancy, SLO attainment, step cadence — scrapeable without touching the
stream files.  This module is that surface:

* :class:`MetricsRegistry` — counters / gauges / histograms keyed by
  (name, labels).  Fed two ways: **directly** (the serve scheduler sets
  queue-depth/occupancy gauges as it schedules — works with telemetry
  off), and **from the emit path** (``Telemetry.attach_metrics`` routes
  every event through :meth:`MetricsRegistry.observe_event`, deriving
  step gauges and ckpt/fault/alert counters — no second instrumentation
  pass).  Detached, the cost is one attribute check per event: the same
  free-when-off contract as ``GRAFT_TELEMETRY=0``.
* :class:`MetricsServer` — a ``ThreadingHTTPServer`` on a daemon thread
  serving ``/metrics`` (Prometheus text exposition v0.0.4) and
  ``/healthz`` (JSON liveness the babysitter curls).  The render path is
  bounded in tests: a 1k-series scrape must stay under 50 ms.

Stdlib-only like the rest of ``obs``: the endpoint must keep answering on
a box whose accelerator tunnel is wedged — that is when the operator is
staring at the dashboard hardest.
"""
from __future__ import annotations

import http.server
import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import locks

# default histogram buckets: serve latencies span ~ms..minute
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(key: _LabelKey, extra: Tuple[Tuple[str, str], ...] = ()
                   ) -> str:
    items = key + extra
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (k, v.replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n"))
        for k, v in items)
    return "{" + body + "}"


class Counter:
    """Monotonically increasing float (one labeled series)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        self.value += v


class Gauge:
    """Set-to-current-value float (one labeled series)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, v: float = 1.0) -> None:
        self.value += v


class Histogram:
    """Cumulative-bucket histogram (one labeled series)."""

    __slots__ = ("buckets", "counts", "sum", "count", "_lock")

    def __init__(self, buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * len(self.buckets)
        self.sum = 0.0
        self.count = 0
        self._lock = locks.TracedLock("metrics.histogram")

    def observe(self, v: float) -> None:
        with self._lock:
            self.sum += v
            self.count += 1
            # counts are per-bucket; render() accumulates into the
            # cumulative le-series the exposition format wants
            for i, le in enumerate(self.buckets):
                if v <= le:
                    self.counts[i] += 1
                    break


class MetricsRegistry:
    """Thread-safe instrument registry with Prometheus text rendering.

    ``counter/gauge/histogram`` are get-or-create on (name, labels), so
    hot paths call them inline without holding references; creation takes
    the registry lock, subsequent lookups hit a dict."""

    def __init__(self):
        self._lock = locks.TracedLock("metrics.registry")
        # name -> (kind, help, {label_key -> instrument})
        self._families: Dict[str, Tuple[str, str, Dict[_LabelKey, object]]] \
            = {}
        self.created_at = time.monotonic()

    def _get(self, kind: str, name: str, help_: str, labels: Dict[str, str],
             factory: Callable[[], object]):
        key = _label_key(labels)
        # lock-free fast path: after first creation every hot-path call is
        # two dict gets (CPython dict reads are atomic; a racing creation
        # falls through to the locked slow path and setdefault wins once)
        fam = self._families.get(name)  # graftrace: unguarded (hot-path read; a miss or torn view only falls through to the locked setdefault below)
        if fam is not None:
            inst = fam[2].get(key)
            if inst is not None:
                return inst
        with self._lock:
            fam = self._families.setdefault(name, (kind, help_, {}))
            if fam[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam[0]}")
            return fam[2].setdefault(key, factory())  # graftrace: allow=T4 (factory is one of our instrument constructors — Counter/Gauge/Histogram — never caller code, so it cannot re-enter the registry)

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", name, help, labels, Counter)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", name, help, labels, Gauge)

    def histogram(self, name: str, help: str = "",
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                  **labels) -> Histogram:
        return self._get("histogram", name, help, labels,
                         lambda: Histogram(buckets))

    @property
    def series_count(self) -> int:
        with self._lock:
            return sum(len(fam[2]) for fam in self._families.values())

    def render(self) -> str:
        """Prometheus text exposition format v0.0.4.  The family/series
        tables are snapshotted under the registry lock — the /metrics
        scrape thread renders while hot paths register new series, and
        iterating the live dicts would die with "dict changed size during
        iteration".  Instrument values are read lock-free (atomic
        attribute reads; a scrape sees each counter at some recent
        point)."""
        with self._lock:
            families = {name: (fam[0], fam[1], dict(fam[2]))
                        for name, fam in self._families.items()}
        lines: List[str] = []
        for name in sorted(families):
            kind, help_, series = families[name]
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            for key in sorted(series):
                inst = series[key]
                if kind == "histogram":
                    cum = 0
                    for le, n in zip(inst.buckets, inst.counts):
                        cum += n
                        lines.append(
                            f"{name}_bucket"
                            f"{_render_labels(key, (('le', repr(le)),))}"
                            f" {cum}")
                    lines.append(
                        f"{name}_bucket"
                        f"{_render_labels(key, (('le', '+Inf'),))}"
                        f" {inst.count}")
                    lines.append(
                        f"{name}_sum{_render_labels(key)} {inst.sum}")
                    lines.append(
                        f"{name}_count{_render_labels(key)} {inst.count}")
                else:
                    lines.append(
                        f"{name}{_render_labels(key)} {inst.value}")
        return "\n".join(lines) + "\n"

    # --- the emit-path feed (Telemetry.attach_metrics) --------------------

    def observe_event(self, rec: dict) -> None:
        """Derive series from one telemetry record.  Step gauges and
        ckpt/fault/quarantine/health/alert counters live here; serve
        series are DIRECT-instrumented by the scheduler (they must work
        with telemetry off, and deriving them here too would double
        count)."""
        kind = rec.get("kind", "?")
        self.counter("graft_events_total",
                     "telemetry records by kind", kind=kind).inc()
        if kind == "step" and "ph" not in rec:
            self.counter("graft_steps_total", "training steps logged").inc()
            if rec.get("step") is not None:
                self.gauge("graft_step", "last logged global step").set(
                    float(rec["step"]))
            for field, metric, help_ in (
                    ("loss", "graft_step_loss", "last logged loss"),
                    ("step_time_s", "graft_step_time_seconds",
                     "step-time EMA"),
                    ("mfu", "graft_step_mfu", "model FLOPs utilization"),
                    ("loader_stall_frac", "graft_loader_stall_frac",
                     "loader stall fraction of step time")):
                if rec.get(field) is not None:
                    self.gauge(metric, help_).set(float(rec[field]))
        elif kind == "ckpt":
            name = rec.get("name", "?")
            if name == "publish":
                self.counter("graft_ckpt_publishes_total",
                             "committed checkpoint manifests").inc()
            elif name in ("save_failed", "fallback_skip", "save_retry"):
                self.counter("graft_ckpt_incidents_total",
                             "checkpoint retries/failures/fallbacks",
                             incident=name).inc()
        elif kind == "fault":
            self.counter("graft_faults_total", "injected faults fired",
                         site=rec.get("name", "?")).inc()
        elif kind == "data" and str(rec.get("name", "")).endswith(
                "quarantine"):
            self.counter("graft_quarantines_total", "quarantined inputs",
                         what=rec.get("name", "?")).inc()
        elif kind == "health" and rec.get("name") not in (None, "ok"):
            self.counter("graft_health_verdicts_total",
                         "non-ok health verdicts",
                         verdict=rec.get("name", "?")).inc()
        elif kind == "alert":
            self.counter("graft_alerts_total", "alert rules fired",
                         rule=rec.get("name", "?")).inc()
        elif kind == "prof" and rec.get("name") == "predicted":
            # the roofline ceiling the perf ledger predicts for this
            # config — scrape beside graft_step_mfu for the
            # predicted-vs-measured panel
            if rec.get("mfu") is not None:
                self.gauge("graft_predicted_mfu",
                           "roofline-predicted MFU ceiling "
                           "(PERF_LEDGER.json)").set(float(rec["mfu"]))
        elif kind == "mem" and rec.get("name") == "watermark":
            # MemTracker phase-boundary polls (obs/mem.py) — the HBM
            # panel the hbm_headroom alert watches
            if rec.get("used_bytes") is not None:
                self.gauge("graft_hbm_used_bytes",
                           "device memory in use at the last "
                           "mem.watermark").set(float(rec["used_bytes"]))
            if rec.get("peak_bytes") is not None:
                self.gauge("graft_hbm_peak_bytes",
                           "high-watermark device memory").set(
                    float(rec["peak_bytes"]))
            if rec.get("headroom_bytes") is not None:
                self.gauge("graft_hbm_headroom_bytes",
                           "bytes of HBM left before the limit").set(
                    float(rec["headroom_bytes"]))


class _Handler(http.server.BaseHTTPRequestHandler):
    # the server instance carries .registry / .health_fn / .started_at

    def do_GET(self):  # noqa: N802 (http.server API)
        if self.path.split("?")[0] == "/metrics":
            body = self.server.registry.render().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif self.path.split("?")[0] == "/healthz":
            payload = {"ok": True,
                       "uptime_s": round(
                           time.monotonic() - self.server.started_at, 3),
                       "series": self.server.registry.series_count}
            if self.server.health_fn is not None:
                try:
                    payload.update(self.server.health_fn())
                # graftlint: disable=EXC001 (liveness must answer even when the health callback is broken; the error is reported in-band)
                except Exception as e:
                    payload.update(ok=False, error=repr(e))
            body = (json.dumps(payload) + "\n").encode()
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class MetricsServer:
    """/metrics + /healthz on a daemon thread.  ``port=0`` binds an
    ephemeral port (tests); the bound port is ``self.port``."""

    def __init__(self, port: int, registry: MetricsRegistry, *,
                 health_fn: Optional[Callable[[], dict]] = None,
                 host: str = "0.0.0.0"):
        self.registry = registry
        self._httpd = http.server.ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.registry = registry
        self._httpd.health_fn = health_fn
        self._httpd.started_at = time.monotonic()
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="graft-metrics",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


# --- module singleton: how the serve scheduler participates ----------------

_active_registry: Optional[MetricsRegistry] = None


def init(registry: Optional[MetricsRegistry] = None) -> MetricsRegistry:
    """Install the process-wide registry (the serve scheduler and anything
    else that direct-instruments looks it up via :func:`active`)."""
    global _active_registry
    _active_registry = registry if registry is not None else MetricsRegistry()
    return _active_registry


def active() -> Optional[MetricsRegistry]:
    """The installed registry, or None — direct-instrumentation sites
    guard with ``if reg is not None`` so the detached path is one module
    attribute read."""
    return _active_registry


def shutdown() -> None:
    global _active_registry
    _active_registry = None


def serve(port: int, registry: Optional[MetricsRegistry] = None, *,
          health_fn: Optional[Callable[[], dict]] = None) -> MetricsServer:
    """Start the endpoint over ``registry`` (default: the installed one,
    installing a fresh one if none)."""
    reg = registry or active() or init()
    return MetricsServer(port, reg, health_fn=health_fn)
