"""graftscope: unified run telemetry (DESIGN.md §14) + the fleet layer
(DESIGN.md §16).

``telemetry`` is the write side (run-scoped JSONL event stream + spans +
the module-level singleton every layer emits into), ``report`` and
``trace_export`` are the read side (run report, Perfetto/Chrome trace).
The fleet layer rides the same stream: ``align`` solves per-host clock
models from beacons and merges N streams onto one timebase, ``metrics``
exposes an in-process /metrics + /healthz endpoint fed by the emit path,
and ``alerts`` evaluates declarative threshold/burn-rate rules over
sliding windows, emitting ``alert`` events back into the stream.
Stdlib-only by design: every half of this must be writable and readable
on a box whose accelerator tunnel is wedged.
"""
from . import align, alerts, metrics, telemetry
from .align import LaneClock, merge_streams, solve_alignment
from .report import build_fleet_report, build_report, render_text
from .telemetry import (EVENT_SCHEMA, SCHEMA_VERSION, Telemetry,
                        clock_beacon_payload, emit, get, init, note,
                        read_events, shutdown, span)
from .trace_export import to_chrome_trace

__all__ = [
    "telemetry", "align", "alerts", "metrics", "Telemetry", "EVENT_SCHEMA",
    "SCHEMA_VERSION", "init", "get", "shutdown", "emit", "span", "note",
    "read_events", "clock_beacon_payload", "build_report",
    "build_fleet_report", "render_text", "to_chrome_trace", "LaneClock",
    "merge_streams", "solve_alignment",
]
