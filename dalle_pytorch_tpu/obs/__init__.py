"""graftscope: unified run telemetry (DESIGN.md §14).

``telemetry`` is the write side (run-scoped JSONL event stream + spans +
the module-level singleton every layer emits into), ``report`` and
``trace_export`` are the read side (run report, Perfetto/Chrome trace).
Stdlib-only by design: the stream must be writable and readable on a box
whose accelerator tunnel is wedged.
"""
from . import telemetry
from .report import build_report, render_text
from .telemetry import (EVENT_SCHEMA, SCHEMA_VERSION, Telemetry, emit, get,
                        init, note, read_events, shutdown, span)
from .trace_export import to_chrome_trace

__all__ = [
    "telemetry", "Telemetry", "EVENT_SCHEMA", "SCHEMA_VERSION",
    "init", "get", "shutdown", "emit", "span", "note", "read_events",
    "build_report", "render_text", "to_chrome_trace",
]
