"""graftscope telemetry core: one structured event stream per run.

Eight PRs of observability grew *fragmented*: step metrics in ``StepTimer``
EMAs, health in heartbeat JSON, checkpoint narration in stderr prints,
serve latency in ``GenerationServer.stats()``.  None of it survives the
process, and none of it can answer the operator's question after a death:
"what happened to this run, and where did the time go?"  This module is
the single answer surface — a crash-durable, schema-versioned JSONL event
stream every layer appends to, that ``tools/obs_report.py`` replays into a
run report or a Perfetto timeline.

Design constraints, in order:

* **Crash-durable** — every record is ONE ``os.write`` to an ``O_APPEND``
  fd (no userspace buffering): whatever the process managed to emit before
  a kill is on disk, and a torn final line (the only possible tear) is
  skipped by :func:`read_events`, never fatal.  No fsync — durability to
  the OS, not to the platter; the stream is diagnostics, not a commit
  record (those stay with ``CheckpointManager``).
* **Cheap when on, free when off** — an enabled ``event()`` is one dict,
  one ``json.dumps``, one syscall (bounded in tests/test_obs.py); the
  disabled path is a single attribute check with NO allocation, NO I/O
  (``span()`` returns a shared singleton).  The hard off-switch
  ``GRAFT_TELEMETRY=0`` wins over any CLI flag.
* **Correlatable** — every record carries ``run`` (run id), ``host``
  (process index), ``pid``, ``thread``, and a per-process ``seq`` that
  totally orders one host's records even when wall clocks wobble; spans
  pair a ``ph: B`` record with its ``ph: E`` by ``sid`` (the B record's
  seq), so a kill inside a span leaves a *visible* unfinished span rather
  than silence.
* **Bounded** — ``rotate_bytes`` rotates the active file to
  ``events.jsonl.N`` (``keep_rotated`` newest kept), so a week-long serve
  process cannot fill the disk.
* **jax-free** — this module imports only the stdlib, so every tool
  (monitor, obs_report, the babysitter) can read or tail a stream on a
  box whose TPU tunnel is wedged — which is exactly when the stream is
  needed (the BACKEND001 lesson, applied to observability).

The module-level singleton (``init`` / ``get`` / ``emit`` / ``span`` /
``note``) is how library layers participate without plumbing a handle
through every constructor: trainers ``init()`` once, everything else
emits into whatever is active (or no-ops).  :func:`note` is the sanctioned
replacement for the hot paths' operator prints (graftlint OBS001): the
stderr line the operator sees AND the event the stream keeps are one call.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from pathlib import Path
from typing import Iterable, List, Optional

from ..utils import locks

SCHEMA_VERSION = 1

# envelope keys every record carries; payload fields must not collide
# (event() lets the envelope win, so a colliding field is silently dropped
# — keep payload keys out of this set)
ENVELOPE_KEYS = ("v", "run", "host", "pid", "seq", "t", "mono", "thread",
                 "kind", "name")

# the contract tests/test_obs.py validates emitted records against; bump
# SCHEMA_VERSION on breaking changes (readers skip records with v > theirs)
EVENT_SCHEMA = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "type": "object",
    "required": list(ENVELOPE_KEYS),
    "properties": {
        "v": {"type": "integer", "minimum": 1},
        "run": {"type": "string"},
        "host": {"type": "integer", "minimum": 0},
        "pid": {"type": "integer", "minimum": 0},
        "seq": {"type": "integer", "minimum": 1},
        "t": {"type": "number"},
        "mono": {"type": "number"},
        "thread": {"type": "string"},
        "kind": {"type": "string"},
        "name": {"type": "string"},
        "ph": {"enum": ["B", "E"]},          # span begin/end markers
        "sid": {"type": "integer"},          # E only: the paired B's seq
        "dur_s": {"type": "number"},         # E only: monotonic duration
    },
}


def _env_disabled() -> bool:
    """The hard off-switch: ``GRAFT_TELEMETRY`` set to an OFF value
    (``0/false/no/off``, any case — env_flag semantics, restated here so
    this module stays stdlib-only) disables telemetry regardless of CLI
    flags."""
    val = os.environ.get("GRAFT_TELEMETRY")
    if val is None:
        return False
    return val.strip().lower() in ("", "0", "false", "no", "off")


def _env_clock_skew() -> float:
    """``GRAFT_CLOCK_SKEW_S``: test-only wall-clock skew injection (added
    to every envelope ``t`` and beacon ``wall`` this process stamps) so
    chaos/CI runs can rehearse a fleet whose hosts disagree about the
    time — the exact condition ``align.py``'s solver must undo.  Never
    set in production; real skew comes free."""
    try:
        return float(os.environ.get("GRAFT_CLOCK_SKEW_S", ""))
    except ValueError:  # unset, empty, or junk: no injected skew
        return 0.0


# per-process boot nonce: names THIS process's monotonic clock, because a
# monotonic reading is only comparable to another from the same boot of
# the same process — heartbeats and clock beacons both carry it so the
# offset solver never pairs mono values across a restart
_BOOT = f"{os.getpid():x}-{time.time_ns() & 0xFFFFFFFFFF:010x}"


class _NullSpan:
    """Shared no-op context manager: the disabled ``span()`` path returns
    this singleton — no per-call allocation."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Context manager emitting a ``ph: B`` record on entry and a paired
    ``ph: E`` (``sid`` = the B's seq, ``dur_s`` = monotonic delta) on exit.
    An exception rides out on the E record (``ok: false`` + ``error``); a
    process death inside the span leaves the B unpaired — the torn-span
    signature obs_report and the Perfetto exporter surface explicitly."""

    __slots__ = ("_tel", "_kind", "_name", "_fields", "_sid", "_t0")

    def __init__(self, tel: "Telemetry", kind: str, name: str, fields: dict):
        self._tel = tel
        self._kind = kind
        self._name = name
        self._fields = fields

    def __enter__(self) -> "_Span":
        self._t0 = time.monotonic()
        self._sid = self._tel.event(self._kind, self._name, ph="B",
                                    **self._fields)
        return self

    def __exit__(self, etype, evalue, tb) -> bool:
        extra = {} if etype is None else {"error": repr(evalue)}
        self._tel.event(self._kind, self._name, ph="E", sid=self._sid,
                        dur_s=time.monotonic() - self._t0,
                        ok=etype is None, **extra)
        return False


class Telemetry:
    """One process's half of a run's event stream.

    Process 0 writes ``events.jsonl``; other hosts write
    ``events-p{host}.jsonl`` next to it (the heartbeat-file convention) —
    :func:`read_events` merges any number of them.  Thread-safe: the step
    loop, the async checkpoint writer, serve driver threads and prefetch
    workers all emit into the same instance (an ``RLock``, so a signal
    handler interrupting an in-flight ``event()`` on the main thread can
    still emit its own record instead of deadlocking).
    """

    def __init__(self, directory, run_id: Optional[str] = None, *,
                 host: int = 0, rotate_bytes: int = 64 << 20,
                 keep_rotated: int = 4, beacon_every: int = 256,
                 enabled: bool = True):
        self.host = int(host)
        self.pid = os.getpid()
        self.boot = _BOOT
        self.rotate_bytes = int(rotate_bytes)
        self.keep_rotated = int(keep_rotated)
        # clock beacons: every `beacon_every` records (and on the first
        # one) a `clock.beacon` rides the stream — the wall<->monotonic
        # offset pair + boot nonce align.py's solver runs on, re-emitted
        # periodically so rotation pruning never drops the last one.
        # 0 disables (tests that pin exact stream shapes).
        self.beacon_every = int(beacon_every)
        self._last_beacon = -self.beacon_every  # first event emits one
        self._clock_skew = _env_clock_skew()
        # shared-file rendezvous dir (GRAFT_CLOCK_RDV): when set, beacons
        # also carry `ref` = a shared filesystem's mtime clock, giving
        # hosts with no common workload a common reference (see
        # rendezvous())
        # graftlint: disable=ENV001 (GRAFT_CLOCK_RDV is a path: truthiness here is presence-of-value, not a boolean flag)
        self._rdv_dir = os.environ.get("GRAFT_CLOCK_RDV") or None
        # optional attach points (see attach_metrics / attach_alerts):
        # None keeps the emit path allocation-free, exactly like the
        # GRAFT_TELEMETRY=0 contract
        self._metrics = None
        self._alerts = None
        self._in_hook = False
        self._lock = locks.TracedRLock("telemetry")
        self._seq = 0
        self._fd: Optional[int] = None
        self._bytes = 0
        if not enabled or _env_disabled():
            self.dir = None
            self.path = None
            self.run_id = run_id or "disabled"
            return
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        if run_id is None:
            # content-free fallback identity: start time + pid is unique
            # enough to tell two restarts of one supervisor apart
            run_id = time.strftime("run-%Y%m%d-%H%M%S") + f"-{self.pid}"
        self.run_id = str(run_id)
        name = "events.jsonl" if self.host == 0 else f"events-p{self.host}.jsonl"
        self.path = self.dir / name
        self._fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                           0o644)
        try:
            self._bytes = os.fstat(self._fd).st_size
        except OSError:
            self._bytes = 0

    @classmethod
    def disabled(cls) -> "Telemetry":
        """A telemetry that never writes: the allocation-free off path."""
        return cls(None, enabled=False)

    @property
    def enabled(self) -> bool:
        return self._fd is not None  # graftrace: unguarded (free-when-off contract: one atomic attribute read; _fd only transitions via close/rotate and a stale view is indistinguishable from a racing close)

    @property
    def seq(self) -> int:
        """Sequence number of the last emitted record (0 before any) —
        what heartbeats ride so monitors can line a stalled host up with
        its telemetry tail."""
        return self._seq  # graftrace: unguarded (monotonic watermark: an int read is atomic and heartbeats only need "some recent seq", never an exact one)

    # --- emission ---------------------------------------------------------

    def event(self, kind: str, name: str, **fields) -> Optional[int]:
        """Append one record; returns its ``seq`` (None when disabled).
        Payload ``fields`` must be JSON-serializable (anything else is
        stringified) and must not collide with :data:`ENVELOPE_KEYS`."""
        if self._fd is None:  # graftrace: unguarded (the documented free-when-off fast path: one attribute check, no lock; a record racing close() is dropped, which close() already implies)
            return None
        with self._lock:
            self._seq += 1
            seq = self._seq
            rec = dict(fields)
            rec.update(v=SCHEMA_VERSION, run=self.run_id, host=self.host,
                       pid=self.pid, seq=seq, t=time.time() + self._clock_skew,
                       mono=time.monotonic(),
                       thread=threading.current_thread().name,
                       kind=kind, name=name)
            line = (json.dumps(rec, separators=(",", ":"), default=str)
                    + "\n").encode()
            try:
                os.write(self._fd, line)  # graftrace: allow=T2 (deliberate: the lock IS the serializer for the O_APPEND stream — one writer at a time keeps records whole; writes are line-sized and local)
            except OSError:
                # a full/broken disk must never take the run down with it:
                # telemetry is diagnostics, losing it is the lesser failure
                return seq
            self._bytes += len(line)
            if self._bytes > self.rotate_bytes:
                self._rotate_locked()
            # attach hooks: the metrics feed and the alert engine both see
            # the record AFTER it landed, so anything they emit (an alert
            # record) gets a LATER seq — causally ordered after its cause.
            # The metrics feed never emits, so it runs unguarded (and
            # therefore counts nested alert records too); `_in_hook`
            # keeps the alert engine out of its own emissions.  Detached
            # (None) hooks cost one attribute check — the same
            # free-when-off contract as GRAFT_TELEMETRY=0.
            if self._metrics is not None:
                self._metrics.observe_event(rec)
            if self._alerts is not None and not self._in_hook:
                self._in_hook = True
                try:
                    self._fire_alerts_locked(self._alerts.observe(rec))
                finally:
                    self._in_hook = False
            if self.beacon_every > 0 \
                    and seq - self._last_beacon >= self.beacon_every:
                self._emit_beacon_locked()
        return seq

    def span(self, kind: str, name: str, **fields):
        """Context manager for a timed span (B/E record pair)."""
        if self._fd is None:  # graftrace: unguarded (free-when-off fast path, same contract as event())
            return _NULL_SPAN
        return _Span(self, kind, name, fields)

    # --- fleet clock model (align.py's write side) ------------------------

    def clock_beacon(self) -> dict:
        """This instant's wall<->monotonic offset pair + boot nonce — the
        payload `clock.beacon` records and heartbeats carry so the offset
        solver can place this host on the fleet timebase even when the
        host dies between telemetry rotations."""
        return {"wall": time.time() + self._clock_skew,
                "mono": time.monotonic(), "boot": self.boot}

    def _emit_beacon_locked(self) -> None:
        """Emit one `clock.beacon` record (called with the lock held; the
        cadence counter is advanced FIRST so the beacon's own event() call
        cannot recurse)."""
        self._last_beacon = self._seq + 1
        payload = self.clock_beacon()
        if self._rdv_dir is not None:
            ref = self._rendezvous_ref()
            if ref is not None:
                payload["ref"] = ref
        self.event("clock", "beacon", **payload)

    def _rendezvous_ref(self) -> Optional[float]:
        """Shared-file rendezvous: (re)write this host's marker file in
        the shared dir and read back its mtime — the filesystem server's
        clock, one reference every host observes — so hosts with no
        common workload (disjoint serve replicas) still align.  None on
        any filesystem error: rendezvous is opportunistic."""
        try:
            d = Path(self._rdv_dir)
            d.mkdir(parents=True, exist_ok=True)
            f = d / f"rdv-h{self.host}-{self.boot}"
            f.write_text(json.dumps(
                {"run": self.run_id, "host": self.host, "boot": self.boot}))
            return float(f.stat().st_mtime)
        except OSError:
            return None

    def rendezvous(self, shared_dir) -> Optional[float]:
        """Explicitly rendezvous against ``shared_dir`` (a directory on a
        filesystem all hosts mount) and emit a ref-bearing beacon.  The
        env ``GRAFT_CLOCK_RDV`` arms the same thing on the periodic
        beacon cadence."""
        if self._fd is None:  # graftrace: unguarded (free-when-off fast path, same contract as event())
            return None
        with self._lock:
            prev = self._rdv_dir
            self._rdv_dir = str(shared_dir)
            try:
                self._emit_beacon_locked()
            finally:
                self._rdv_dir = prev if prev is not None else str(shared_dir)
        return None

    # --- attach points ----------------------------------------------------

    def attach_metrics(self, registry) -> None:
        """Feed every emitted record to ``registry.observe_event`` (see
        obs/metrics.py) — the emit path IS the metrics pipeline, so the
        /metrics endpoint needs no second instrumentation pass."""
        self._metrics = registry

    def attach_alerts(self, engine) -> None:
        """Run ``engine.observe`` (see obs/alerts.py) over every emitted
        record; fired alerts are emitted back into this stream as
        ``alert`` events (seq AFTER the cause record) and printed the way
        note() prints."""
        self._alerts = engine

    def _fire_alerts_locked(self, fired) -> None:
        for alert in fired or ():
            msg = alert.get("msg") or alert.get("rule", "alert")
            _print_note("[alert]", msg, "stderr")
            self.event("alert", str(alert.get("rule", "?")), **alert)

    # --- rotation / lifecycle --------------------------------------------

    def _rotate_locked(self) -> None:
        """Rename the active file to ``<name>.N`` (N = newest) and start a
        fresh one; prune rotated files beyond ``keep_rotated``.  Called
        with the lock held."""
        existing = sorted(
            (int(p.name.rsplit(".", 1)[1]), p)
            for p in self.dir.glob(self.path.name + ".*")
            if p.name.rsplit(".", 1)[1].isdigit())
        nxt = (existing[-1][0] + 1) if existing else 1
        os.close(self._fd)
        self._fd = None
        rotated_to = self.path.with_name(f"{self.path.name}.{nxt}")
        os.replace(self.path, rotated_to)
        rotated = existing + [(nxt, rotated_to)]
        for _, p in rotated[:max(len(rotated) - self.keep_rotated, 0)]:
            try:
                p.unlink()
            except OSError:
                pass
        self._fd = os.open(self.path,  # graftrace: allow=T2 (rotation happens at most once per rotate_bytes of output; reopening under the lock is what keeps racing writers off the renamed file)
                           os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        self._bytes = 0

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None


# --- module-level singleton: how library layers participate ---------------

_active: Optional[Telemetry] = None
_active_lock = locks.TracedLock("telemetry.active")


def init(directory, run_id: Optional[str] = None, **kwargs) -> Telemetry:
    """Install the process-wide telemetry (closing any previous one).
    Honors the ``GRAFT_TELEMETRY=0`` hard off-switch: the returned
    instance is then disabled and nothing is installed, so every
    downstream ``emit``/``span``/``note`` stays on the free path."""
    global _active
    tel = Telemetry(directory, run_id=run_id, **kwargs)
    with _active_lock:
        if _active is not None:
            _active.close()
        _active = tel if tel.enabled else None
    return tel


def get() -> Optional[Telemetry]:
    """The active telemetry, or None — hot loops hold the result and guard
    with ``if tel is not None`` so the disabled path allocates nothing."""
    return _active


def shutdown() -> None:
    """Close and uninstall the active telemetry (trainer exit paths; also
    what makes in-process reruns — rollback relaunches, tests — start a
    fresh stream instead of appending to a closed fd)."""
    global _active
    with _active_lock:
        if _active is not None:
            _active.close()
        _active = None


def emit(kind: str, name: str, **fields) -> Optional[int]:
    """Emit into the active telemetry, if any."""
    tel = _active
    if tel is None:
        return None
    return tel.event(kind, name, **fields)


def span(kind: str, name: str, **fields):
    """Span on the active telemetry; the shared no-op when none."""
    tel = _active
    if tel is None:
        return _NULL_SPAN
    return tel.span(kind, name, **fields)


def _print_note(prefix: str, msg: str, stream: str) -> None:
    """The operator-line half of note() — also what fired alerts print."""
    out = sys.stdout if stream == "stdout" else sys.stderr
    print(f"{prefix} {msg}", file=out, flush=True)


def note(kind: str, name: str, msg: str, *, prefix: Optional[str] = None,
         stream: str = "stderr", **fields) -> None:
    """Operator message + telemetry event in one call — the OBS001
    replacement for bare prints in step/serve/ckpt hot paths.

    Prints ``{prefix} {msg}`` (prefix defaults to ``[{kind}]``) to stderr
    (or stdout for the legacy warning surfaces that monitors scrape), and
    emits a ``kind``/``name`` event carrying ``msg`` + ``fields`` when a
    telemetry is active.  The print half is unconditional: the stream is
    *additional* observability, never a replacement for the line a human
    tails."""
    _print_note(prefix if prefix is not None else f"[{kind}]", msg, stream)
    tel = _active
    if tel is not None:
        tel.event(kind, name, msg=msg, **fields)


def clock_beacon_payload() -> dict:
    """The heartbeat-side clock payload: the active telemetry's beacon if
    one is installed, else a fresh (wall, mono, boot) triple with the same
    skew-injection semantics — so heartbeats carry alignment material even
    on a run with telemetry off."""
    tel = _active
    if tel is not None:
        return tel.clock_beacon()
    return {"wall": time.time() + _env_clock_skew(),
            "mono": time.monotonic(), "boot": _BOOT}


# --- read side ------------------------------------------------------------


def _iter_stream_files(path: Path) -> List[Path]:
    """Event files under ``path``: the file itself, or a directory's
    ``events*.jsonl*`` members (rotated parts included), rotation-ordered
    so records come out in emission order per host."""
    if path.is_file():
        # an active-segment path brings its rotated siblings
        # (<name>.1 .. <name>.N, oldest first) so merge/report see the
        # full history, not just the live segment — a week-long run's
        # events.jsonl is only the tail of its own story
        rotated = sorted(
            (int(p.name.rsplit(".", 1)[1]), p)
            for p in path.parent.glob(path.name + ".*")
            if p.name.rsplit(".", 1)[1].isdigit())
        return [p for _, p in rotated] + [path]

    def order(p: Path):
        tail = p.name.rsplit(".", 1)[1]
        # rotated parts (events.jsonl.N) precede the active file
        return (p.name.split(".jsonl")[0],
                int(tail) if tail.isdigit() else 1 << 30)

    return sorted(path.glob("events*.jsonl*"), key=order)


def read_events(paths: Iterable) -> List[dict]:
    """Parse one or more event files / stream directories into records.

    Torn trailing lines (the crash signature of the O_APPEND discipline)
    and records newer than this reader's schema are skipped, never fatal —
    the reader exists precisely for post-crash streams.  Records are
    returned sorted by (run, host, seq): total per-host causal order, with
    wall time (``t``) left to consumers that align across hosts."""
    records: List[dict] = []
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    for p in paths:
        for f in _iter_stream_files(Path(p)):
            try:
                data = f.read_text(errors="replace")
            except OSError:
                continue
            for line in data.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn write: skip, keep reading
                if not isinstance(rec, dict) or \
                        rec.get("v", 0) > SCHEMA_VERSION:
                    continue
                records.append(rec)
    records.sort(key=lambda r: (str(r.get("run", "")), r.get("host", 0),
                                r.get("seq", 0)))
    return records
