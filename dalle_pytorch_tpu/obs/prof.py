"""graftprof: per-module roofline attribution + the committed perf ledger
(DESIGN.md §18).

The write side of the repo's perf observability: models wrap their cost
centers in ``scope(name)`` (a ``jax.named_scope`` carrying the
``graftprof:`` prefix), :func:`attribute` walks a traced jaxpr and sums
analytic ``flops`` / ``bytes`` per scope (innermost scope wins; backward
equations keep their forward scope through jvp/transpose name-stack
wrapping; ``scan`` bodies multiply by trip count), and :func:`roofline`
folds the totals into the chip spec table to predict step time
(max(FLOP-time, byte-time)) and the MFU ceiling.  ``tools/graftprof.py``
sweeps every train-step factory × plan plus decode/serve-tick and
commits the rows to ``PERF_LEDGER.json``; :func:`diff_ledger` is the CI
drift gate (>2% flops / >5% bytes without a ledger update = red).

Like the rest of ``obs/``, module-level imports are stdlib-only — jax is
imported lazily inside the functions that trace or capture, so the read
side (reports, the drift diff, ledger plumbing) runs on a box whose
accelerator tunnel is wedged.
"""
from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
import re
import time
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

# --- scope taxonomy -------------------------------------------------------

SCOPE_PREFIX = "graftprof:"

#: The cost centers the models annotate (DESIGN.md §18 taxonomy).  A scope
#: not in this tuple still attributes (the walker matches the prefix, not
#: the table) — the table is the documented contract and what the ledger
#: rows enumerate.
SCOPES = ("embed", "attn-qkv", "attn-scores", "attn-cache", "attn-out",
          "ff", "logits-head", "vae-conv", "optimizer", "decode-step",
          "serve-tick", "spec-draft", "spec-verify")

#: Residual bucket for equations under no scope.
UNATTRIBUTED = "unattributed"

_SCOPE_RE = re.compile(r"graftprof:([a-z0-9_-]+)")
_NAME_RE = re.compile(r"^[a-z0-9][a-z0-9_-]*$")


class ProfError(RuntimeError):
    """Attribution / ledger contract violation."""


class CoverageError(ProfError):
    """Unattributed residual above the gate — a cost center lost its
    scope (or a new one landed without annotation)."""


def scope(name: str):
    """``jax.named_scope`` carrying the graftprof prefix — the one way
    model code marks a cost center.  Returns a context manager usable as
    a decorator (``named_scope`` is both)."""
    if not _NAME_RE.match(name):
        raise ProfError(f"bad scope name {name!r}: lowercase slug expected")
    import jax

    return jax.named_scope(SCOPE_PREFIX + name)


# --- the jaxpr cost walker ------------------------------------------------

# Pure data movement: XLA's HloCostAnalysis charges these zero flops (the
# bytes still count), so the walker mirrors it — the 2%-of-compiled gate
# in tests/test_prof.py is calibrated against this table.
_ZERO_FLOP = frozenset((
    "reshape", "broadcast_in_dim", "transpose", "squeeze", "expand_dims",
    "slice", "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "rev", "gather", "iota", "copy", "stop_gradient", "convert_element_type",
    "bitcast_convert_type", "split", "select_n",
))

# Transcendentals land in HloCostAnalysis's separate counter, not flops.
_TRANSCENDENTAL = frozenset((
    "exp", "exp2", "expm1", "log", "log1p", "tanh", "logistic", "sqrt",
    "rsqrt", "cbrt", "erf", "erfc", "erf_inv", "sin", "cos", "tan", "pow",
))


def _aval_nums(aval) -> Tuple[int, int]:
    """(element count, byte size) of one abstract value; (0, 0) for
    non-array avals (tokens)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0, 0
    size = 1
    for d in shape:
        size *= int(d)
    return size, size * dtype.itemsize


def _eqn_scope(eqn) -> Optional[str]:
    """Innermost graftprof scope on the equation's name stack, or None.
    The stack survives autodiff as ``transpose(jvp(graftprof:ff))`` —
    the regex sees through the wrapping, and the LAST match is the
    innermost scope, so nested scopes (decode-step around attn-cache)
    attribute to the tighter one."""
    src = getattr(eqn, "source_info", None)
    stack = getattr(src, "name_stack", None)
    if stack is None:
        return None
    found = _SCOPE_RE.findall(str(stack))
    return found[-1] if found else None


def _sub_jaxprs(params: dict) -> Iterator[object]:
    # lint/spmd.py's structural matcher: every higher-order primitive
    # (pjit/scan/while/cond/shard_map/remat/custom_*) carries its nested
    # jaxprs under different param keys
    for val in params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            inner = getattr(v, "jaxpr", None)
            if inner is not None:
                yield inner
            elif hasattr(v, "eqns"):
                yield v


def _eqn_cost(eqn) -> Tuple[int, int]:
    """(flops, bytes) of one first-order equation.  dot_general =
    2·out·K (K = contracted extent), conv = 2·out·(kernel/out_features),
    other math = one flop per output element; bytes = operands + outputs
    at jaxpr-level shapes (pre-fusion traffic — an upper bound on the
    fused program's bytes_accessed, stable across XLA versions, which is
    what a drift gate needs)."""
    prim = eqn.primitive.name
    out_size = out_bytes = 0
    for v in eqn.outvars:
        s, b = _aval_nums(getattr(v, "aval", None))
        out_size += s
        out_bytes += b
    in_bytes = 0
    for v in eqn.invars:
        _, b = _aval_nums(getattr(v, "aval", None))
        in_bytes += b

    if prim == "dot_general":
        (lhs_contract, _), _ = eqn.params["dimension_numbers"]
        lhs_shape = eqn.invars[0].aval.shape
        k = 1
        for i in lhs_contract:
            k *= int(lhs_shape[i])
        flops = 2 * out_size * k
    elif prim == "conv_general_dilated":
        rhs = eqn.invars[1].aval
        dn = eqn.params["dimension_numbers"]
        out_features = int(rhs.shape[dn.rhs_spec[0]])
        rhs_size, _ = _aval_nums(rhs)
        flops = 2 * out_size * (rhs_size // max(out_features, 1))
    elif prim in _ZERO_FLOP or prim in _TRANSCENDENTAL:
        flops = 0
    else:
        flops = out_size
    return flops, in_bytes + out_bytes


def _walk(jaxpr, inherited: Optional[str], mult: int,
          acc: Dict[str, List[int]]) -> None:
    for eqn in jaxpr.eqns:
        sc = _eqn_scope(eqn) or inherited
        subs = list(_sub_jaxprs(eqn.params))
        if subs:
            m = mult
            if eqn.primitive.name == "scan":
                m = mult * int(eqn.params.get("length", 1))
            # cond branches are all walked (summed) — conservative, and
            # the models keep real cost out of cond bodies
            for sub in subs:
                _walk(sub, sc, m, acc)
            continue
        flops, nbytes = _eqn_cost(eqn)
        if not flops and not nbytes:
            continue
        cell = acc.setdefault(sc or UNATTRIBUTED, [0, 0])
        cell[0] += flops * mult
        cell[1] += nbytes * mult


def attribute(jaxpr, *, default_scope: Optional[str] = None,
              scale: int = 1) -> dict:
    """Walk a (closed) jaxpr and attribute analytic flops/bytes per
    graftprof scope.

    ``scale`` multiplies every number — ``shard_map`` plans trace one
    shard's program, so callers pass the mesh device count to recover
    the global figures.  Returns a JSON-ready dict: per-scope numbers,
    totals, and the unattributed residual fractions the ≤5% coverage
    gate reads."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr
    acc: Dict[str, List[int]] = {}
    _walk(inner, default_scope, 1, acc)
    scopes = {name: {"flops": f * scale, "bytes": b * scale}
              for name, (f, b) in sorted(acc.items())
              if name != UNATTRIBUTED}
    un_f, un_b = acc.get(UNATTRIBUTED, (0, 0))
    total_f = sum(s["flops"] for s in scopes.values()) + un_f * scale
    total_b = sum(s["bytes"] for s in scopes.values()) + un_b * scale
    return {
        "scopes": scopes,
        "unattributed": {"flops": un_f * scale, "bytes": un_b * scale},
        "total": {"flops": total_f, "bytes": total_b},
        "residual": {
            "flops": (un_f * scale / total_f) if total_f else 0.0,
            "bytes": (un_b * scale / total_b) if total_b else 0.0,
        },
    }


def attribute_fn(fn, *args, default_scope: Optional[str] = None,
                 scale: int = 1) -> dict:
    """``attribute(jax.make_jaxpr(fn)(*args))`` — args may be
    ShapeDtypeStructs (abstract trace, nothing executes)."""
    import jax

    return attribute(jax.make_jaxpr(fn)(*args),
                     default_scope=default_scope, scale=scale)


def check_coverage(attr: dict, max_residual: float = 0.05,
                   label: str = "program") -> None:
    """The coverage gate: unattributed flops AND bytes residual ≤ 5% —
    a new cost center must be scoped before its row can land."""
    res = attr["residual"]
    bad = {k: v for k, v in res.items() if v > max_residual}
    if bad:
        detail = ", ".join(f"{k} {v:.1%}" for k, v in sorted(bad.items()))
        raise CoverageError(
            f"graftprof coverage [{label}]: unattributed residual {detail} "
            f"exceeds {max_residual:.0%} — a cost center is missing its "
            "scope() annotation (SCOPES taxonomy, DESIGN.md §18)")


# --- chip specs + roofline ------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Per-device peaks.  ``hbm_bytes`` mirrors lint/spmd.py's
    CHIP_HBM_BYTES (pinned by tests/test_prof.py so the two tables
    cannot drift)."""

    devices: int
    peak_flops: float  # FLOP/s per device (bf16 MXU)
    hbm_bw: float      # bytes/s per device
    hbm_bytes: int     # capacity per device

    @property
    def ridge(self) -> float:
        """Arithmetic intensity (flops/byte) where the roofline bends."""
        return self.peak_flops / self.hbm_bw


CHIP_SPECS: Dict[str, ChipSpec] = {
    "v4-8": ChipSpec(devices=4, peak_flops=275e12, hbm_bw=1228e9,
                     hbm_bytes=32 * 1024 ** 3),
    "v5e-4": ChipSpec(devices=4, peak_flops=197e12, hbm_bw=819e9,
                      hbm_bytes=16 * 1024 ** 3),
}


def roofline(attr: dict, chip: str, *,
             traffic_bytes: Optional[int] = None,
             devices: Optional[int] = None) -> dict:
    """Fold an attribution into the chip's roofline.

    ``traffic_bytes`` is the PER-DEVICE HBM stream of one step — callers
    with a compiled program pass its memory-analysis sum (args + outputs
    + temps, opt0-stable); without one the walker's global bytes divided
    across devices stand in.  Predicted step time = max(FLOP-time,
    byte-time); predicted MFU is the ceiling measured MFU is judged
    against (obs_report's predicted-vs-measured section)."""
    if chip not in CHIP_SPECS:
        raise ProfError(f"unknown chip {chip!r}; known: "
                        f"{sorted(CHIP_SPECS)}")
    spec = CHIP_SPECS[chip]
    n = devices or spec.devices
    flops = attr["total"]["flops"]
    if traffic_bytes is None:
        traffic_bytes = attr["total"]["bytes"] // max(n, 1)
    flop_time = flops / (spec.peak_flops * n)
    byte_time = traffic_bytes / spec.hbm_bw
    pred = max(flop_time, byte_time)
    scopes = {}
    for name, cell in attr["scopes"].items():
        intensity = cell["flops"] / cell["bytes"] if cell["bytes"] else 0.0
        scopes[name] = {
            "intensity": round(intensity, 3),
            "bound": "flop" if intensity >= spec.ridge else "byte",
        }
    return {
        "chip": chip,
        "devices": n,
        "ridge": round(spec.ridge, 2),
        "flop_time_s": flop_time,
        "byte_time_s": byte_time,
        "pred_step_time_s": pred,
        "bound": "byte" if byte_time > flop_time else "flop",
        "predicted_mfu": (flop_time / pred) if pred else 0.0,
        "traffic_bytes": int(traffic_bytes),
        "scopes": scopes,
    }


# --- config fingerprint + ledger ------------------------------------------

LEDGER_NAME = "PERF_LEDGER.json"
LEDGER_SCHEMA_VERSION = 1


def row_fingerprint(payload: dict) -> str:
    """12-hex-char key of one (target, plan, geometry) point: sha256 of
    the canonical JSON (sorted keys, no whitespace, non-JSON values
    stringified).  Predicted and measured rows meet on this key."""
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def fingerprint_payload(config, **extra) -> dict:
    """Canonical fingerprint payload for a config dataclass (or dict) plus
    the run point (``target=``, ``plan=``, ``batch=``, ...): dataclass
    fields stringified and sorted, sweep knobs appended raw.  Every
    producer — tools/graftprof.py predicted rows, the trainers'
    ``prof.predicted`` lookup, bench.py / tools/perf_ab.py measured
    appends — builds this SAME dict so their rows meet on one key."""
    import dataclasses

    d = dict(config) if isinstance(config, dict) else dataclasses.asdict(config)
    return {**{k: str(v) for k, v in sorted(d.items())}, **extra}


def ledger_path(root: Optional[os.PathLike] = None) -> Path:
    """Resolve the ledger file: GRAFT_PERF_LEDGER env override (tests,
    scratch sweeps) > ``root``/PERF_LEDGER.json > repo root next to this
    package."""
    env = os.environ.get("GRAFT_PERF_LEDGER")
    if env:
        return Path(env)
    if root is not None:
        return Path(root) / LEDGER_NAME
    return Path(__file__).resolve().parent.parent.parent / LEDGER_NAME


def load_ledger(path: Optional[os.PathLike] = None) -> dict:
    p = Path(path) if path is not None else ledger_path()
    if not p.exists():
        return {"v": LEDGER_SCHEMA_VERSION, "rows": {}}
    doc = json.loads(p.read_text())
    if doc.get("v", 0) > LEDGER_SCHEMA_VERSION:
        raise ProfError(
            f"perf ledger {p} has schema v{doc.get('v')} > "
            f"{LEDGER_SCHEMA_VERSION} — update the tree before diffing")
    doc.setdefault("rows", {})
    return doc


def save_ledger(ledger: dict, path: Optional[os.PathLike] = None) -> Path:
    """Atomic publish (tmp + rename), rows sorted by fingerprint so the
    committed file diffs cleanly."""
    p = Path(path) if path is not None else ledger_path()
    doc = dict(ledger)
    doc["v"] = LEDGER_SCHEMA_VERSION
    doc["rows"] = {k: doc["rows"][k] for k in sorted(doc["rows"])}
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_name(p.name + ".tmp")
    tmp.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n")
    os.replace(tmp, p)
    return p


def predicted_row(*, target: str, plan: str, chip: str, config: dict,
                  attr: dict, roof: dict,
                  compiled: Optional[dict] = None) -> dict:
    """One ledger row.  ``config`` is the fingerprint payload (geometry +
    batch + dtype + plan) — the same dict a measured run must hash to
    land beside this prediction."""
    fp = row_fingerprint(config)
    row = {
        "fingerprint": fp,
        "target": target,
        "plan": plan,
        "chip": chip,
        "config": config,
        "scopes": attr["scopes"],
        "unattributed": attr["unattributed"],
        "total": attr["total"],
        "residual": {k: round(v, 4) for k, v in attr["residual"].items()},
        "roofline": {
            "pred_step_time_s": roof["pred_step_time_s"],
            "predicted_mfu": round(roof["predicted_mfu"], 4),
            "bound": roof["bound"],
            "ridge": roof["ridge"],
            "traffic_bytes": roof["traffic_bytes"],
            "devices": roof["devices"],
        },
    }
    if compiled is not None:
        row["compiled"] = {k: int(v) for k, v in sorted(compiled.items())}
    return row


def upsert_predicted(ledger: dict, row: dict) -> None:
    """Install/refresh a predicted row, preserving any measured rows
    already recorded under the fingerprint."""
    old = ledger["rows"].get(row["fingerprint"])
    if old and old.get("measured"):
        row = dict(row, measured=old["measured"])
    ledger["rows"][row["fingerprint"]] = row


def append_measured(measured: dict, *, fingerprint: Optional[str] = None,
                    config: Optional[dict] = None, target: str = "",
                    path: Optional[os.PathLike] = None,
                    keep_last: int = 8) -> dict:
    """Append one measured row (tok/s / img/s + MFU from a real run)
    under the prediction's fingerprint — read-modify-write, atomic
    publish.  A fingerprint with no predicted row still lands (stub row)
    so a bench round never loses data waiting for a sweep."""
    if fingerprint is None:
        if config is None:
            raise ProfError("append_measured needs fingerprint or config")
        fingerprint = row_fingerprint(config)
    p = Path(path) if path is not None else ledger_path()
    ledger = load_ledger(p)
    row = ledger["rows"].setdefault(
        fingerprint, {"fingerprint": fingerprint, "target": target,
                      "config": config or {}})
    hist = row.setdefault("measured", [])
    hist.append(dict(measured, t=round(time.time(), 3)))
    del hist[:-keep_last]
    save_ledger(ledger, p)
    return row


# --- the CI drift gate ----------------------------------------------------

FLOPS_TOL = 0.02
BYTES_TOL = 0.05


def _rel(a: float, b: float) -> float:
    return abs(a - b) / max(abs(a), abs(b), 1.0)


def diff_ledger(committed: dict, recomputed: Dict[str, dict],
                flops_tol: float = FLOPS_TOL,
                bytes_tol: float = BYTES_TOL) -> List[str]:
    """Diff HEAD's recomputed predicted rows against the committed
    ledger.  Returns human-readable problems (empty = green): missing /
    extra fingerprints, per-scope or total flops drift > 2%, bytes drift
    > 5%, and compiled-stat drift (bytes_accessed / live buffers /
    donated bytes) at the byte tolerance — the broken twins (a hoisted
    full-cache convert, a dropped int8 scale plane, a dropped donation)
    all land in one of these.  Measured rows never gate."""
    problems = []
    old_rows = {fp: r for fp, r in committed.get("rows", {}).items()
                if "total" in r}  # measured-only stubs don't gate
    for fp in sorted(set(old_rows) - set(recomputed)):
        r = old_rows[fp]
        problems.append(
            f"{fp} ({r.get('target')}/{r.get('plan')}): in the ledger but "
            "no longer produced by the sweep — remove it with "
            "`graftprof --update` if the target was retired")
    for fp in sorted(set(recomputed) - set(old_rows)):
        r = recomputed[fp]
        problems.append(
            f"{fp} ({r.get('target')}/{r.get('plan')}): new row not in the "
            "committed ledger — run `graftprof --update` and commit")
    for fp in sorted(set(old_rows) & set(recomputed)):
        old, new = old_rows[fp], recomputed[fp]
        label = f"{fp} ({new.get('target')}/{new.get('plan')})"

        def _gate(what, a, b, tol):
            d = _rel(a, b)
            if d > tol:
                problems.append(
                    f"{label}: {what} drifted {d:.1%} "
                    f"(ledger {a:.4g} -> HEAD {b:.4g}, tol {tol:.0%}) — "
                    "a perf-relevant change landed without a ledger "
                    "update; rerun `graftprof --update` and commit the "
                    "diff if intended")

        for name in sorted(set(old.get("scopes", {}))
                           | set(new.get("scopes", {}))):
            o = old.get("scopes", {}).get(name, {"flops": 0, "bytes": 0})
            n = new.get("scopes", {}).get(name, {"flops": 0, "bytes": 0})
            _gate(f"scope {name} flops", o["flops"], n["flops"], flops_tol)
            _gate(f"scope {name} bytes", o["bytes"], n["bytes"], bytes_tol)
        _gate("total flops", old["total"]["flops"], new["total"]["flops"],
              flops_tol)
        _gate("total bytes", old["total"]["bytes"], new["total"]["bytes"],
              bytes_tol)
        for field in sorted(set(old.get("compiled", {}))
                            & set(new.get("compiled", {}))):
            tol = flops_tol if field == "flops" else bytes_tol
            _gate(f"compiled {field}", old["compiled"][field],
                  new["compiled"][field], tol)
    return problems


# --- graftscope integration ----------------------------------------------


def predicted_for(*, fingerprint: Optional[str] = None,
                  target: Optional[str] = None, plan: Optional[str] = None,
                  path: Optional[os.PathLike] = None) -> Optional[dict]:
    """Look up the predicted-MFU fields for a run: exact fingerprint
    first, else the (target, plan) row — geometry tweaks still get the
    plan's ceiling as a reference.  Returns the ``prof.predicted`` event
    payload (fingerprint / chip / mfu / pred_step_time_s / bound) or
    None when the ledger has nothing relevant."""
    try:
        ledger = load_ledger(path)
    except (OSError, ValueError, ProfError):
        return None
    rows = ledger.get("rows", {})
    row = rows.get(fingerprint) if fingerprint else None
    if row is None and target:
        for r in rows.values():
            if (r.get("target") == target and "roofline" in r
                    and (plan is None or r.get("plan") == plan)):
                row = r
                break
    if row is None or "roofline" not in row:
        return None
    roof = row["roofline"]
    return {
        "fingerprint": row["fingerprint"],
        "exact": row["fingerprint"] == fingerprint,
        "chip": row.get("chip"),
        "mfu": roof["predicted_mfu"],
        "pred_step_time_s": roof["pred_step_time_s"],
        "bound": roof["bound"],
    }


def predicted_serve_bytes_per_token(cfg, num_slots: int) -> int:
    """Per-decoded-token HBM stream of one serve tick: the whole arena's
    cache read (int8 payloads + f32 scale planes counted —
    ``profiling.dalle_decode_cache_bytes``) amortized over the slots a
    full tick advances.  GenerationServer.stats() and the /metrics serve
    instruments export this beside the measured occupancy."""
    from ..utils.profiling import dalle_decode_cache_bytes

    return int(dalle_decode_cache_bytes(cfg, num_slots)
               // max(num_slots, 1))


def predicted_spec_speedup(cfg, accepted_k: Optional[float] = None) -> dict:
    """Cost-model speedup of self-speculative decode (graftspec).

    Decode is HBM-bandwidth-bound (PERF.md round 5), so step time ≈ the
    weight+cache byte stream; self-speculation amortizes ONE full-depth
    stream over ``accepted_k`` committed tokens at the price of ``K - 1``
    draft streams through the first ``spec_draft_depth`` blocks:

        bytes/token  =  full_stream * (1 + (K-1) * draft_frac) / accepted_k
        speedup      =  accepted_k / (1 + (K-1) * draft_frac)

    with ``draft_frac = spec_draft_depth / depth`` (the head re-runs per
    draft but is byte-small next to the stack).  ``accepted_k`` defaults
    to the neutral prior of half the span, ``(K + 1) / 2``; the A/B
    stage (``gen_spec_ab``) replaces the prior with a measured rate.
    Returns the dict the graftprof serve/decode spec rows embed."""
    k = cfg.spec_k
    draft_frac = cfg.spec_draft_depth / cfg.depth
    if accepted_k is None:
        accepted_k = (k + 1) / 2.0
    overhead = 1.0 + (k - 1) * draft_frac
    return {
        "spec_k": k,
        "spec_draft_depth": cfg.spec_draft_depth,
        "draft_frac": round(draft_frac, 4),
        "assumed_accepted_k": round(float(accepted_k), 4),
        "stream_overhead": round(overhead, 4),
        "predicted_speedup": round(float(accepted_k) / overhead, 4),
        # acceptance rate below which the drafts cost more than they buy
        "breakeven_accepted_k": round(overhead, 4),
    }


# --- managed on-chip capture (the OBS003 contract) ------------------------


@contextlib.contextmanager
def capture(logdir):
    """The repo's ONE managed ``jax.profiler`` entry point (graftlint
    OBS003 flags direct calls elsewhere): wraps start/stop_trace in a
    ``prof.xprof`` telemetry span so the on-chip trace window lands
    correlated in the Perfetto fleet merge."""
    import jax

    from . import telemetry

    logdir = str(logdir)
    with telemetry.span("prof", "xprof", logdir=logdir):
        jax.profiler.start_trace(logdir)
        try:
            yield logdir
        finally:
            jax.profiler.stop_trace()


class XprofWindow:
    """Arm an on-chip trace around a step window — the ``GRAFT_XPROF`` /
    ``--xprof_dir`` hook both trainers drive.

    ``logdir`` falls back to the GRAFT_XPROF env var (unset/empty =
    disarmed, so production runs pay one attribute check per step);
    the window defaults to steps [start, stop) with
    ``GRAFT_XPROF_WINDOW=a:b`` overriding.  ``on_step(i, sync)`` opens
    the capture at the window start and closes it (after ``sync()``
    drains the device queue) at the end; ``close()`` is the exit-path
    safety net."""

    def __init__(self, logdir=None, start: int = 10, stop: int = 20):
        self.logdir = str(logdir) if logdir else (
            os.environ.get("GRAFT_XPROF") or None)  # graftlint: disable=ENV001 (path-valued var: empty/unset mean off)
        window = os.environ.get("GRAFT_XPROF_WINDOW", "")
        if window:
            a, _, b = window.partition(":")
            start, stop = int(a), int(b or int(a) + 10)
        self.start, self.stop = start, stop
        self._cm = None

    @property
    def armed(self) -> bool:
        return self.logdir is not None

    @property
    def active(self) -> bool:
        return self._cm is not None

    def on_step(self, i: int, sync=None) -> None:
        if self.logdir is None:
            return
        if self._cm is None and self.start <= i < self.stop:
            self._cm = capture(self.logdir)
            self._cm.__enter__()
        elif self._cm is not None and i >= self.stop:
            self.close(sync)

    def close(self, sync=None) -> None:
        if self._cm is None:
            return
        try:
            if sync is not None:
                sync()
        finally:
            cm, self._cm = self._cm, None
            cm.__exit__(None, None, None)
