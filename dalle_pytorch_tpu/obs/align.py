"""Cross-host clock alignment: merge N per-host streams onto one timebase.

Each host stamps its records with its OWN wall clock (envelope ``t``) and
its OWN monotonic clock (``mono``).  Within a host the monotonic clock is
authoritative for durations; across hosts nothing is: wall clocks skew by
seconds and drift by ms/minute, so a naive merge puts host B's step 40
before host A's step 39 and every cross-host latency reads as noise.
This module estimates, per (run, host) lane, a clock model

    fleet_t  =  t  -  (offset + drift * (mono - mono0))

and rewrites timestamps through it, so ``trace_export`` can put N hosts
on one Perfetto timeline (one pid lane per host) and ``report`` can build
a fleet view (straggler ranking, merged SLO attainment) whose cross-host
deltas mean something.

Anchor sources, best first:

1. **Rendezvous beacons** — ``clock.beacon`` records carrying ``ref``: a
   shared filesystem's mtime clock observed at the beacon (armed by
   ``GRAFT_CLOCK_RDV`` or ``Telemetry.rendezvous``).  Every host that has
   them aligns to the fs clock independently: works for hosts with no
   common workload at all (disjoint serve replicas).
2. **Matched step anchors** — in a data-parallel fleet, global step k
   completes on every host at (collective-bounded) the same instant, so
   per-step wall times pair across hosts: offset = median of the pairwise
   deltas vs the reference lane, drift fit over the host's mono axis when
   the anchors span enough time.
3. **Fallback** — align the lanes' first records and say so (``method:
   "fallback"``, unbounded residual): a merge is still more readable than
   N disjoint files, but the report marks it untrusted.

Every lane reports a **residual-skew bound**: the max |residual| of its
anchors after the fit (floored at 1 ms for single-anchor fits).  The
fleet report prints it; the acceptance test asserts recovered skew stays
inside it.  Stdlib-only, like the rest of ``obs``.
"""
from __future__ import annotations

import dataclasses
import json
import re
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .telemetry import read_events

# a single rendezvous/step anchor still carries clock-resolution +
# scheduling jitter; never report a bound tighter than this
MIN_BOUND_S = 1e-3

# one stream lane.  The leading elements disambiguate the SOURCE (merge
# prepends the path index: two --merge dirs are two hosts even when both
# trainers picked the same timestamp-derived run id); the last two are
# always (run id, host index).
LaneKey = Tuple[str, int]


@dataclasses.dataclass
class LaneClock:
    """One lane's solved clock model + its provenance."""

    run: str
    orig_host: int
    lane: int                 # fleet host index (pid lane after merge)
    offset: float = 0.0       # seconds this lane's wall clock runs ahead
    drift: float = 0.0        # d(offset)/d(mono): seconds of skew per second
    mono0: float = 0.0        # mono origin the drift term is anchored at
    bound: Optional[float] = 0.0   # residual-skew bound; None = unbounded
    method: str = "reference"
    anchors: int = 0
    boot: Optional[str] = None

    def fleet_t(self, t: float, mono: Optional[float]) -> float:
        m = self.mono0 if mono is None else float(mono)
        return float(t) - (self.offset + self.drift * (m - self.mono0))

    def summary(self) -> dict:
        return {"run": self.run, "host": self.orig_host, "lane": self.lane,
                "offset_s": round(self.offset, 6),
                "drift_s_per_s": round(self.drift, 9),
                "residual_bound_s": (None if self.bound is None
                                     else round(self.bound, 6)),
                "method": self.method, "anchors": self.anchors,
                "boot": self.boot}


def _lane_key(rec: dict) -> LaneKey:
    return str(rec.get("run", "")), int(rec.get("host", 0))


def split_lanes(events: Iterable[dict]) -> "Dict[LaneKey, List[dict]]":
    """Group parsed records into per-(run, host) lanes, insertion-ordered
    (dict preserves it), each lane already seq-ordered by read_events."""
    lanes: Dict[LaneKey, List[dict]] = {}
    for rec in events:
        lanes.setdefault(_lane_key(rec), []).append(rec)
    return lanes


def _fit(deltas: Sequence[float], monos: Sequence[float]
         ) -> Tuple[float, float, float, float]:
    """Fit delta = offset + drift*(mono - mono0); returns (offset, drift,
    mono0, bound).  Drift only enters with >= 3 anchors spanning > 1 s of
    mono — below that a line through noise invents drift that is worse
    than none."""
    mono0 = monos[0] if monos else 0.0
    ordered = sorted(deltas)
    offset = ordered[len(ordered) // 2]
    drift = 0.0
    span = (max(monos) - min(monos)) if monos else 0.0
    if len(deltas) >= 3 and span > 1.0:
        xs = [m - mono0 for m in monos]
        n = float(len(xs))
        mx = sum(xs) / n
        my = sum(deltas) / n
        var = sum((x - mx) ** 2 for x in xs)
        if var > 0:
            drift = sum((x - mx) * (d - my)
                        for x, d in zip(xs, deltas)) / var
            offset = my - drift * mx
    resid = [abs(d - (offset + drift * (m - mono0)))
             for d, m in zip(deltas, monos)]
    return offset, drift, mono0, max([MIN_BOUND_S] + resid)


def _rendezvous_anchors(lane: List[dict]) -> Tuple[List[float], List[float]]:
    """(delta, mono) pairs from ref-bearing beacons: delta = the lane's
    wall reading minus the shared-fs reference at the same instant."""
    deltas, monos = [], []
    for r in lane:
        if r.get("kind") == "clock" and r.get("ref") is not None:
            wall = r.get("wall", r.get("t"))
            if wall is None or r.get("mono") is None:
                continue
            deltas.append(float(wall) - float(r["ref"]))
            monos.append(float(r["mono"]))
    return deltas, monos


def _step_times(lane: List[dict]) -> "Dict[int, Tuple[float, float]]":
    """step id -> (t, mono) of the FIRST step record for it (resume
    re-emissions would otherwise smear the anchor)."""
    out: Dict[int, Tuple[float, float]] = {}
    for r in lane:
        if r.get("kind") != "step" or "ph" in r:
            continue
        s, t, m = r.get("step"), r.get("t"), r.get("mono")
        if s is None or t is None or m is None:
            continue
        out.setdefault(int(s), (float(t), float(m)))
    return out


def solve_alignment(lanes: "Dict[LaneKey, List[dict]]"
                    ) -> "Dict[LaneKey, LaneClock]":
    """Solve one clock model per lane.  Lane order fixes the fleet host
    indices; the first lane without rendezvous anchors becomes the step-
    matching reference (offset 0 by definition — the fleet timebase is
    either the shared-fs clock, when rendezvous exists, or the reference
    lane's wall clock)."""
    clocks: Dict[LaneKey, LaneClock] = {}
    keys = list(lanes)
    for i, key in enumerate(keys):
        lane = lanes[key]
        boot = next((r.get("boot") for r in lane
                     if r.get("kind") == "clock" and r.get("boot")), None)
        clocks[key] = LaneClock(run=str(key[-2]), orig_host=int(key[-1]),
                                lane=i, boot=boot)

    # pass 1: rendezvous lanes align to the shared-fs clock directly
    aligned: set = set()
    for key in keys:
        deltas, monos = _rendezvous_anchors(lanes[key])
        if deltas:
            off, drift, mono0, bound = _fit(deltas, monos)
            clocks[key] = dataclasses.replace(
                clocks[key], offset=off, drift=drift, mono0=mono0,
                bound=bound, method="rendezvous", anchors=len(deltas))
            aligned.add(key)

    # pass 2: remaining lanes match step anchors against a reference lane
    # (prefer an already-aligned one, so mixed fleets share one timebase)
    remaining = [k for k in keys if k not in aligned]
    if not remaining:
        return clocks
    ref_key = next((k for k in keys if k in aligned), remaining[0])
    ref_clock = clocks[ref_key]
    ref_steps = _step_times(lanes[ref_key])
    for key in remaining:
        if key == ref_key:
            clocks[key] = dataclasses.replace(
                clocks[key], method="reference", anchors=len(ref_steps))
            continue
        steps = _step_times(lanes[key])
        common = sorted(set(steps) & set(ref_steps))
        if common:
            # pair against the reference on the FLEET timebase, so a
            # rendezvous-aligned reference still anchors step-only lanes
            deltas = [steps[s][0]
                      - ref_clock.fleet_t(*ref_steps[s]) for s in common]
            monos = [steps[s][1] for s in common]
            off, drift, mono0, bound = _fit(deltas, monos)
            clocks[key] = dataclasses.replace(
                clocks[key], offset=off, drift=drift, mono0=mono0,
                bound=bound, method="steps", anchors=len(common))
            continue
        # fallback: align first records, report the bound as unknown
        lane = lanes[key]
        t0 = next((r.get("t") for r in lane if r.get("t") is not None), None)
        ref0 = next((ref_clock.fleet_t(r["t"], r.get("mono"))
                     for r in lanes[ref_key] if r.get("t") is not None),
                    None)
        off = (float(t0) - float(ref0)) if t0 is not None \
            and ref0 is not None else 0.0
        clocks[key] = dataclasses.replace(
            clocks[key], offset=off, bound=None, method="fallback",
            anchors=0)
    return clocks


def align_lane(lane: List[dict], clock: LaneClock) -> List[dict]:
    """Rewrite one lane's records onto the fleet timebase: ``t`` becomes
    fleet time (the host's raw stamp survives as ``t_raw``), ``host``
    becomes the fleet lane index (the stream's own index survives as
    ``orig_host``) — so downstream consumers (report, trace_export) need
    no changes to see one host per lane."""
    out = []
    for r in lane:
        r2 = dict(r)
        t = r.get("t")
        if t is not None:
            r2["t_raw"] = t
            r2["t"] = clock.fleet_t(float(t), r.get("mono"))
        r2["orig_host"] = r.get("host", 0)
        r2["host"] = clock.lane
        out.append(r2)
    return out


def heartbeat_offsets(hb_dir) -> "Dict[int, dict]":
    """Monitor-side anchors from heartbeat files: each
    ``heartbeat-p{i}.json`` carries the clock payload (wall/mono/boot —
    utils/failure.py rides it on every beat) and the FILE's mtime is the
    monitor-side filesystem clock at the moment of the write, a
    rendezvous-grade common reference.  ``offset = payload wall - mtime``
    places the host on the monitor's timebase even when the host died
    between telemetry rotations and its stream has no surviving beacon.
    Returns {process index: {offset, boot, age_s}}."""
    out: Dict[int, dict] = {}
    now = time.time()
    for p in Path(hb_dir).glob("heartbeat-p*.json"):
        m = re.fullmatch(r"heartbeat-p(\d+)", p.stem)
        if not m:
            continue
        try:
            info = json.loads(p.read_text())
            mtime = p.stat().st_mtime
        except (OSError, ValueError):
            continue
        clock = info.get("clock")
        if not isinstance(clock, dict) or clock.get("wall") is None:
            continue
        out[int(m.group(1))] = {
            "offset": float(clock["wall"]) - float(mtime),
            "boot": clock.get("boot"),
            # graftlint: disable=OBS002 (cross-clock by design: heartbeat mtime is wall material; a monotonic reading cannot compare against it)
            "age_s": now - float(mtime),
        }
    return out


def merge_streams(paths: Sequence) -> Tuple[List[dict], List[LaneClock]]:
    """The ``obs_report --merge`` entry: read each path (stream dir or
    file, rotated parts included), solve the fleet clock model, and
    return (aligned records sorted on the fleet timebase, lane clocks).
    Lane indices follow path order, then host order inside a path."""
    lanes: Dict[tuple, List[dict]] = {}
    for i, p in enumerate(paths):
        for key, lane in split_lanes(read_events(p)).items():
            # the path index keeps two sources apart even when both
            # trainers derived the same timestamp run id (the concurrent-
            # launch collision the CI fleet smoke hits)
            lanes.setdefault((i,) + key, []).extend(lane)
    clocks = solve_alignment(lanes)
    merged: List[dict] = []
    for key, lane in lanes.items():
        merged.extend(align_lane(lane, clocks[key]))
    merged.sort(key=lambda r: (r.get("t", 0.0), r.get("host", 0),
                               r.get("seq", 0)))
    return merged, [clocks[k] for k in lanes]
