"""Perfetto export: the event stream as a Chrome trace (trace-event JSON).

``ui.perfetto.dev`` / ``chrome://tracing`` load the emitted document
directly, putting spans from every thread of every host on ONE zoomable
timeline — the step loop, the async checkpoint writer, and the serve
driver side by side, which is exactly the view the wedged-tunnel
post-mortems never had.

Mapping:

* trace ``pid``   = the record's ``host`` (process index); a process
  metadata event names it with the run id.
* trace ``tid``   = a stable small integer per (host, thread name), named
  by a thread metadata event — so "ckpt-async-700" and "MainThread" read
  as themselves.
* span B/E pairs  = one complete ``ph: "X"`` slice (ts from the B record's
  wall clock, dur from the E record's monotonic delta).  An UNPAIRED B —
  the kill-inside-a-span signature — becomes an instant marked
  ``(unfinished)`` so the death site is visible, not silent.
* other events    = thread-scoped instants (``ph: "i"``); ``step`` records
  additionally emit counter tracks (``ph: "C"``) for loss / step time /
  MFU / loader stall, so the perf trajectory is a plot over the same
  timeline.

Timestamps are wall-clock microseconds (``t``), the only clock comparable
across hosts; within a host, record ``seq`` already total-orders events
for readers that need causality tighter than clock resolution.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

# counter tracks derived from step records: (field, track name)
_STEP_COUNTERS = (("loss", "loss"), ("step_time_s", "step_time_s"),
                  ("mfu", "mfu"), ("loader_stall_s", "loader_stall_s"))


def _payload(rec: dict) -> dict:
    """The record minus its envelope — what lands in the trace ``args``."""
    from .telemetry import ENVELOPE_KEYS

    skip = set(ENVELOPE_KEYS) | {"ph", "sid", "dur_s"}
    return {k: v for k, v in rec.items() if k not in skip}


def to_chrome_trace(events: List[dict]) -> dict:
    """Build the trace-event document from parsed records (the output of
    :func:`telemetry.read_events`)."""
    trace: List[dict] = []
    tids: Dict[Tuple[int, str], int] = {}
    named_pids: Dict[int, str] = {}

    def tid_for(host: int, thread: str) -> int:
        key = (host, thread)
        if key not in tids:
            tids[key] = len(tids) + 1
            trace.append({"ph": "M", "name": "thread_name", "pid": host,
                          "tid": tids[key], "args": {"name": thread}})
        return tids[key]

    # index span begins by (host, seq) so E records find their B
    begins: Dict[Tuple[int, int], dict] = {}
    for rec in events:
        if rec.get("ph") == "B" and rec.get("seq") is not None:
            begins[(rec.get("host", 0), rec["seq"])] = rec

    closed: set = set()
    for rec in events:
        host = rec.get("host", 0)
        if host not in named_pids:
            named_pids[host] = str(rec.get("run", ""))
            # aligned fleet merges relabel `host` to a unique lane index
            # (align.align_lane) and keep the stream's own index in
            # `orig_host` — name the pid lane with the original identity
            trace.append({"ph": "M", "name": "process_name", "pid": host,
                          "args": {"name": f"{rec.get('run', '')} "
                                           f"(host "
                                           f"{rec.get('orig_host', host)})"}})
        tid = tid_for(host, str(rec.get("thread", "?")))
        name = f"{rec.get('kind', '?')}.{rec.get('name', '?')}"
        ts = float(rec.get("t", 0.0)) * 1e6
        if rec.get("ph") == "E":
            b = begins.get((host, rec.get("sid", -1)))
            if b is not None:
                closed.add((host, rec["sid"]))
                trace.append({
                    "ph": "X", "name": name, "cat": str(rec.get("kind", "")),
                    "pid": host, "tid": tid_for(host, str(b.get("thread",
                                                               "?"))),
                    "ts": float(b.get("t", 0.0)) * 1e6,
                    "dur": max(float(rec.get("dur_s", 0.0)) * 1e6, 1.0),
                    "args": {**_payload(b), **_payload(rec)}})
            continue
        if rec.get("ph") == "B":
            continue  # emitted when its E arrives (or as unfinished below)
        trace.append({"ph": "i", "s": "t", "name": name,
                      "cat": str(rec.get("kind", "")), "pid": host,
                      "tid": tid, "ts": ts, "args": _payload(rec)})
        if rec.get("kind") == "step":
            for field, track in _STEP_COUNTERS:
                if rec.get(field) is not None:
                    trace.append({"ph": "C", "name": track, "pid": host,
                                  "tid": tid, "ts": ts,
                                  "args": {track: float(rec[field])}})

    # unpaired span begins: the process/thread died inside — surface it
    for (host, seq), b in begins.items():
        if (host, seq) in closed:
            continue
        name = f"{b.get('kind', '?')}.{b.get('name', '?')} (unfinished)"
        trace.append({"ph": "i", "s": "t", "name": name,
                      "cat": str(b.get("kind", "")), "pid": host,
                      "tid": tid_for(host, str(b.get("thread", "?"))),
                      "ts": float(b.get("t", 0.0)) * 1e6,
                      "args": _payload(b)})

    trace.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0.0)))
    return {"traceEvents": trace, "displayTimeUnit": "ms"}
