"""graftmem: device-memory attribution + the committed HBM ledger
(DESIGN.md §19) — the memory-side twin of :mod:`obs.prof`.

**Predicted side.**  :func:`peak_live` runs a linear-scan liveness walk
over a traced jaxpr — every variable is live from the equation that
produces it to its last use (arguments for the whole call: XLA holds arg
buffers unless donated) — and reports the peak resident bytes together
with a snapshot of WHO was live at the peak: resident *planes* (params /
opt-state / weights / arena / args, labelled from the caller's argument
trees) and per-``prof.scope`` *activations* (the producing equation's
innermost graftprof scope).  Phase builders fold the walk and the
opt0-compiled memory stats (``lint/spmd.py`` S4 conventions, donation
credit from the S2-verified alias audit) into the memory timeline one
run actually traverses::

    init          params + opt state resident (compiled argument bytes)
    step_peak     args + outputs + temps − donation credit
    ckpt          step_peak + forfeited donation credit (the async
                  snapshot pins the old state, so XLA cannot alias it
                  into the next step's outputs)
    serve_steady  weights + arena planes (int8 payload AND f32 scale
                  planes — they are real arena state) + tick transients

:func:`headroom_verdict` folds a timeline against ``prof.CHIP_SPECS``
HBM per chip (same 0.9 allocator-fragmentation margin as S4's
``check_hbm_budget``); ``tools/graftmem.py`` sweeps every train-step
factory × plan plus decode / serve-tick and commits the result as
``memory`` rows merged into the SAME ``PERF_LEDGER.json`` fingerprints
graftprof owns.  :func:`diff_memory` is the CI drift gate: >5% peak
bytes in any phase without a ledger update goes red, naming the scope
or plane that moved most.

**Measured side.**  :class:`MemTracker` is the repo's ONE managed entry
point over ``jax.live_arrays()`` / the allocator stats behind
``jax.profiler.device_memory_profile`` (graftlint MEM001 flags direct
calls elsewhere, mirroring OBS003's discipline for trace windows): it
polls at phase boundaries, emits ``mem.watermark`` telemetry records
(→ ``graft_hbm_{used,peak,headroom}_bytes`` gauges via
``obs/metrics.py``, the ``hbm_headroom`` alert rule, and obs_report's
"memory (predicted vs measured)" section), and carries the serve leak
gate: :meth:`MemTracker.baseline` after warmup, then
:meth:`MemTracker.check_baseline` after admit/retire churn or a chaos
drill — live-buffer count and bytes must return to the baseline, or a
retire path is keeping a cache reference.

Like the rest of ``obs/``, module-level imports are stdlib-only — jax is
imported lazily inside the functions that trace or poll, so the read
side (ledger diffs, reports) runs on a box whose accelerator tunnel is
wedged.
"""
from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from . import prof, telemetry

#: The phase timeline the ledger rows enumerate (serve rows carry
#: serve_steady; train rows the first three).
PHASES = ("init", "step_peak", "ckpt", "serve_steady")

#: Resident-plane labels (vs. activation scopes, which come from the
#: graftprof SCOPES taxonomy).
PLANES = ("params", "opt-state", "weights", "arena", "args", "consts")

#: Same allocator-fragmentation margin as lint/spmd.check_hbm_budget.
HBM_MARGIN = 0.9

#: The drift-gate tolerance: >5% peak bytes per phase = red.
MEM_BYTES_TOL = 0.05

# internal label for a sub-jaxpr's invars — they alias the enclosing
# equation's operands, which the outer walk already counts
_OPERANDS = "_operands"


class MemError(RuntimeError):
    """Memory attribution / ledger / tracker contract violation."""


class LeakError(MemError):
    """Live buffers did not return to the post-warmup baseline."""


# --- aval plumbing ---------------------------------------------------------


def _nbytes(v) -> int:
    """Byte size of a jaxpr atom (Var / Literal / anything with an aval
    or shape+dtype)."""
    aval = getattr(v, "aval", v)
    return prof._aval_nums(aval)[1]


def tree_bytes(tree) -> int:
    """Total bytes of a pytree of arrays / ShapeDtypeStructs / avals."""
    import jax

    return sum(_nbytes(leaf) for leaf in jax.tree.leaves(tree))


def arg_planes(*pairs) -> List[Tuple[str, int]]:
    """Expand ``(label, tree)`` pairs into the per-flat-leaf plane spec
    :func:`peak_live` maps onto the jaxpr's invars (flattening order ==
    positional argument order)."""
    import jax

    return [(label, len(jax.tree.leaves(tree))) for label, tree in pairs]


# --- the peak-live walker --------------------------------------------------


def _live_walk(jaxpr, default_scope: Optional[str],
               invar_labels: Optional[Sequence[Tuple[str, int]]]) -> dict:
    """Linear-scan liveness over one (open) jaxpr.

    Returns ``peak_bytes`` (authoritative), ``peak_snapshot`` (label ->
    bytes live at the peak — attribution, not guaranteed to sum to the
    peak when a sub-jaxpr's internal transient dominates), and
    ``invar_bytes``.  Higher-order equations (pjit/scan/while/cond/...)
    contribute their body's internal peak beyond its operands; ``scan``
    reuses its per-trip buffers, so — unlike the flops walker — nothing
    multiplies by trip count."""
    eqns = jaxpr.eqns
    n = len(eqns)
    last: Dict[object, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not hasattr(v, "val"):  # skip Literals
                last[v] = i
    for v in jaxpr.outvars:
        if not hasattr(v, "val"):
            last[v] = n  # outputs live to the end

    live: Dict[object, Tuple[int, str]] = {}
    by_label: Dict[str, int] = {}
    live_total = 0

    def _add(v, label: str) -> None:
        nonlocal live_total
        if hasattr(v, "val") or v in live:
            return
        b = _nbytes(v)
        if not b:
            return
        live[v] = (b, label)
        by_label[label] = by_label.get(label, 0) + b
        live_total += b

    def _drop(v) -> None:
        nonlocal live_total
        ent = live.pop(v, None)
        if ent is None:
            return
        b, label = ent
        by_label[label] -= b
        if not by_label[label]:
            del by_label[label]
        live_total -= b

    flat_labels: List[str] = []
    for label, count in (invar_labels or ()):
        flat_labels.extend([label] * count)
    invar_bytes = 0
    for j, v in enumerate(jaxpr.invars):
        invar_bytes += _nbytes(v)
        last.setdefault(v, n)  # argument buffers persist for the call
        _add(v, flat_labels[j] if j < len(flat_labels) else "args")
    for v in jaxpr.constvars:
        invar_bytes += _nbytes(v)
        last.setdefault(v, n)
        _add(v, "consts")

    dying: Dict[int, List[object]] = {}
    for v, i in last.items():
        dying.setdefault(i, []).append(v)

    peak = live_total
    peak_snap = dict(by_label)
    for i, eqn in enumerate(eqns):
        sc = _eqn_label(eqn, default_scope)
        out_b = sum(_nbytes(v) for v in eqn.outvars)
        inner_extra = 0
        inner_snap: Optional[dict] = None
        for sub in prof._sub_jaxprs(eqn.params):
            r = _live_walk(sub, sc, [(_OPERANDS, len(sub.invars))])
            extra = max(0, r["peak_bytes"] - r["invar_bytes"])
            if extra > inner_extra:
                inner_extra = extra
                inner_snap = {k: b for k, b in r["peak_snapshot"].items()
                              if k != _OPERANDS}
        transient = live_total + out_b + inner_extra
        if transient > peak:
            peak = transient
            peak_snap = dict(by_label)
            peak_snap[sc] = peak_snap.get(sc, 0) + out_b
            if inner_snap:
                for k, b in inner_snap.items():
                    peak_snap[k] = peak_snap.get(k, 0) + b
        for v in eqn.outvars:
            if last.get(v, -1) > i:
                _add(v, sc)
        for v in dying.get(i, ()):
            _drop(v)
    return {"peak_bytes": peak, "peak_snapshot": peak_snap,
            "invar_bytes": invar_bytes}


def _eqn_label(eqn, default_scope: Optional[str]) -> str:
    return prof._eqn_scope(eqn) or default_scope or prof.UNATTRIBUTED


def peak_live(jaxpr, *, default_scope: Optional[str] = None,
              planes: Optional[Sequence[Tuple[str, int]]] = None) -> dict:
    """Peak resident bytes of a (closed) jaxpr with a who-was-live
    attribution.

    ``planes`` maps leading flattened invars to resident-plane labels
    (build with :func:`arg_planes`); the remainder label ``args``.
    Returns a JSON-ready dict: ``peak_bytes``, ``planes`` (resident
    argument planes at the peak), ``scopes`` (activation bytes per
    graftprof scope at the peak), and ``resident_bytes`` (all planes —
    what persists between steps)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr
    r = _live_walk(inner, default_scope, planes)
    plane_set = set(PLANES) | {lbl for lbl, _ in (planes or ())}
    out_planes = {k: b for k, b in sorted(r["peak_snapshot"].items())
                  if k in plane_set}
    scopes = {k: b for k, b in sorted(r["peak_snapshot"].items())
              if k not in plane_set}
    return {
        "peak_bytes": int(r["peak_bytes"]),
        "planes": out_planes,
        "scopes": scopes,
        "resident_bytes": int(sum(out_planes.values())),
    }


def peak_live_fn(fn, *args, default_scope: Optional[str] = None,
                 planes: Optional[Sequence[Tuple[str, int]]] = None) -> dict:
    """``peak_live(jax.make_jaxpr(fn)(*args))`` — args may be
    ShapeDtypeStructs (abstract trace, nothing executes)."""
    import jax

    return peak_live(jax.make_jaxpr(fn)(*args),
                     default_scope=default_scope, planes=planes)


# --- phase timelines -------------------------------------------------------


def train_phases(compiled: dict) -> Dict[str, int]:
    """The per-device memory timeline of one train step from its
    opt0-compiled stats (graftprof's ``compiled`` row fields: argument /
    output / temp bytes + the donation-audit credit standing in for the
    alias stat opt0 zeroes)."""
    a = int(compiled["argument_bytes"])
    o = int(compiled["output_bytes"])
    t = int(compiled["temp_bytes"])
    don = int(compiled.get("donated_bytes", 0))
    return {
        "init": a,
        "step_peak": a + o + t - don,
        "ckpt": a + o + t,
    }


def analytic_train_phases(*, params_bytes: int, opt_bytes: int,
                          walker_peak_bytes: int, resident_bytes: int,
                          devices: int = 1,
                          shard_factor: int = 1) -> Dict[str, int]:
    """The chip-free stand-in for rows too slow to compile (the same
    carve-out graftprof's decode row takes): resident state divided by
    the plan's shard factor, activations = the walker's global peak
    minus resident planes divided across devices.  An approximation —
    held stable by construction, which is what the drift gate needs."""
    init = (params_bytes + opt_bytes) // max(shard_factor, 1)
    act = max(0, walker_peak_bytes - resident_bytes) // max(devices, 1)
    return {
        "init": init,
        "step_peak": init + act,
        "ckpt": 2 * init + act,  # snapshot pins the state: no donation
    }


def decode_phases(*, params_bytes: int, walker_peak_bytes: int
                  ) -> Dict[str, int]:
    """Decode scan: weights resident, plus the scan's internal peak
    (caches + per-step transients) from the liveness walk."""
    return {"init": int(params_bytes),
            "step_peak": int(walker_peak_bytes)}


def serve_phases(*, walker_peak_bytes: int) -> Dict[str, int]:
    """Serve steady state IS the tick's peak-live: weights + the whole
    arena (int8 payloads and their f32 scale planes are both real state)
    + tick transients, all resident for as long as the server is up."""
    return {"serve_steady": int(walker_peak_bytes)}


# --- headroom verdict ------------------------------------------------------


def headroom_verdict(phases: Dict[str, int], chip: str,
                     margin: float = HBM_MARGIN) -> dict:
    """Fold a phase timeline against one chip's per-device HBM.  ``fits``
    uses the same 0.9 margin as S4's check_hbm_budget — allocator
    fragmentation eats the rest."""
    if chip not in prof.CHIP_SPECS:
        raise MemError(f"unknown chip {chip!r}; known: "
                       f"{sorted(prof.CHIP_SPECS)}")
    hbm = prof.CHIP_SPECS[chip].hbm_bytes
    peak_phase = max(phases, key=lambda k: phases[k])
    peak = int(phases[peak_phase])
    return {
        "chip": chip,
        "hbm_bytes": int(hbm),
        "margin": margin,
        "peak_phase": peak_phase,
        "peak_bytes": peak,
        "headroom_bytes": int(hbm - peak),
        "headroom_frac": round(1.0 - peak / hbm, 4),
        "fits": peak <= margin * hbm,
    }


# --- ledger memory rows (merged under graftprof's fingerprints) ------------


def memory_row(*, phases: Dict[str, int], planes: Dict[str, int],
               scopes: Dict[str, int], walker_peak_bytes: int,
               devices: int = 1, chips: Sequence[str] = ("v4-8", "v5e-4"),
               note: Optional[str] = None) -> dict:
    """One ``memory`` sub-row: the phase timeline, the peak-live
    attribution, and a headroom verdict per chip spec."""
    row = {
        "phases": {k: int(v) for k, v in phases.items()},
        "planes": {k: int(v) for k, v in sorted(planes.items())},
        "scopes": {k: int(v) for k, v in sorted(scopes.items())},
        "walker_peak_bytes": int(walker_peak_bytes),
        "devices": int(devices),
        "headroom": {chip: headroom_verdict(phases, chip)
                     for chip in chips},
    }
    if note:
        row["note"] = note
    return row


def upsert_memory(ledger: dict, fingerprint: str, memrow: dict, *,
                  target: str = "", plan: str = "") -> None:
    """Merge a memory sub-row into the ledger row under ``fingerprint``
    — the graftprof fields (scopes/total/roofline/compiled/measured) are
    never clobbered, and measured memory history is preserved across
    recomputes (the upsert_predicted contract, one level down)."""
    row = ledger["rows"].setdefault(
        fingerprint, {"fingerprint": fingerprint, "target": target,
                      "plan": plan})
    old = row.get("memory", {})
    if old.get("measured"):
        memrow = dict(memrow, measured=old["measured"])
    row["memory"] = memrow


def append_measured_memory(snap: dict, *, fingerprint: str,
                           path: Optional[os.PathLike] = None,
                           keep_last: int = 8) -> dict:
    """Append one measured watermark (a :meth:`MemTracker.snapshot`
    dict from a real chip) under the prediction's fingerprint —
    read-modify-write, atomic publish, bounded history.  Measured rows
    never gate."""
    p = Path(path) if path is not None else prof.ledger_path()
    ledger = prof.load_ledger(p)
    row = ledger["rows"].setdefault(
        fingerprint, {"fingerprint": fingerprint, "target": ""})
    mem = row.setdefault("memory", {})
    hist = mem.setdefault("measured", [])
    hist.append(dict(snap, t=round(time.time(), 3)))
    del hist[:-keep_last]
    prof.save_ledger(ledger, p)
    return row


def diff_memory(committed: dict, recomputed: Dict[str, dict],
                bytes_tol: float = MEM_BYTES_TOL) -> List[str]:
    """The CI drift gate: diff HEAD's recomputed memory rows against the
    committed ledger.  A phase whose peak bytes drifted >5% goes red
    with the guilty scope/plane named (the attribution entry that moved
    most); missing/extra fingerprints surface too.  Rows without a
    predicted memory sub-row (graftprof-only rows, measured-only stubs)
    never gate."""
    problems: List[str] = []
    old_rows = {fp: r for fp, r in committed.get("rows", {}).items()
                if "phases" in r.get("memory", {})}
    for fp in sorted(set(old_rows) - set(recomputed)):
        r = old_rows[fp]
        problems.append(
            f"{fp} ({r.get('target')}/{r.get('plan')}): memory row in the "
            "ledger but no longer produced by the sweep — remove it with "
            "`graftmem --update` if the target was retired")
    for fp in sorted(set(recomputed) - set(old_rows)):
        problems.append(
            f"{fp}: new memory row not in the committed ledger — run "
            "`graftmem --update` and commit")
    for fp in sorted(set(old_rows) & set(recomputed)):
        old = old_rows[fp]["memory"]
        new = recomputed[fp]
        label = (f"{fp} ({old_rows[fp].get('target')}"
                 f"/{old_rows[fp].get('plan')})")
        guilty = _guilty_entry(old, new)
        for phase in sorted(set(old["phases"]) | set(new.get("phases", {}))):
            a = old["phases"].get(phase, 0)
            b = new.get("phases", {}).get(phase, 0)
            d = prof._rel(a, b)
            if d > bytes_tol:
                problems.append(
                    f"{label}: phase {phase} peak bytes drifted {d:.1%} "
                    f"(ledger {a:.4g} -> HEAD {b:.4g}, tol "
                    f"{bytes_tol:.0%}){guilty} — a memory-relevant change "
                    "landed without a ledger update; rerun `graftmem "
                    "--update` and commit the diff if intended")
    return problems


def _guilty_entry(old: dict, new: dict) -> str:
    """Name the scope/plane whose peak-live bytes moved most — the
    attribution half of a phase-drift message."""
    worst, worst_d, worst_delta = None, 0.0, 0
    for table in ("scopes", "planes"):
        keys = set(old.get(table, {})) | set(new.get(table, {}))
        for k in keys:
            a = old.get(table, {}).get(k, 0)
            b = new.get(table, {}).get(k, 0)
            d = prof._rel(a, b)
            if d > worst_d:
                worst, worst_d, worst_delta = k, d, b - a
    if worst is None or worst_d == 0.0:
        return ""
    sign = "+" if worst_delta >= 0 else "-"
    return (f" — guilty scope: {worst} ({sign}{abs(worst_delta):.4g} "
            f"bytes, {worst_d:.1%})")


def predicted_memory_for(*, fingerprint: Optional[str] = None,
                         target: Optional[str] = None,
                         plan: Optional[str] = None,
                         chip: str = "v4-8",
                         path: Optional[os.PathLike] = None
                         ) -> Optional[dict]:
    """Ledger lookup for a run's predicted memory timeline — exact
    fingerprint first, then the (target, plan) row (prof.predicted_for's
    fallback contract).  Returns the ``mem.predicted`` event payload or
    None when the ledger has nothing relevant."""
    try:
        ledger = prof.load_ledger(path)
    except (OSError, ValueError, prof.ProfError):
        return None
    rows = ledger.get("rows", {})
    row = rows.get(fingerprint) if fingerprint else None
    if (row is None or "phases" not in row.get("memory", {})) and target:
        for r in rows.values():
            if (r.get("target") == target and "phases" in r.get("memory", {})
                    and (plan is None or r.get("plan") == plan)):
                row = r
                break
    if row is None or "phases" not in row.get("memory", {}):
        return None
    mem = row["memory"]
    verdict = mem.get("headroom", {}).get(chip)
    out = {
        "fingerprint": row["fingerprint"],
        "exact": row["fingerprint"] == fingerprint,
        "chip": chip,
        "phases": dict(mem["phases"]),
    }
    if verdict:
        out.update(peak_phase=verdict["peak_phase"],
                   peak_bytes=verdict["peak_bytes"],
                   headroom_bytes=verdict["headroom_bytes"],
                   headroom_frac=verdict["headroom_frac"],
                   fits=verdict["fits"])
    return out


# --- the measured side: the one managed poll point (MEM001) ----------------


def live_buffer_stats() -> dict:
    """Count + bytes of every live jax array in the process — the
    repo's ONE ``jax.live_arrays()`` call site (graftlint MEM001).
    Works on any backend, which is what lets the serve leak gate run
    chip-free in CI."""
    import jax

    count = 0
    total = 0
    for a in jax.live_arrays():
        count += 1
        try:
            total += int(a.nbytes)
        except (AttributeError, TypeError):  # deleted-under-us / exotic
            pass
    return {"count": count, "bytes": total}


def device_memory_stats() -> List[dict]:
    """Per-device allocator stats where the backend exposes them
    (TPU/GPU ``Device.memory_stats``, the same counters
    ``jax.profiler.device_memory_profile`` aggregates); ``[]`` on CPU.
    The one managed surface over those counters (MEM001)."""
    import jax

    out = []
    for d in jax.devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # graftlint: disable=EXC001 (backend-optional API: CPU raises/returns None; absence just means no device counters)
            stats = None
        if not stats:
            continue
        out.append({
            "id": int(d.id),
            "kind": str(getattr(d, "device_kind", "?")),
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0)),
        })
    return out


def write_device_memory_profile(path) -> str:
    """Dump the backend's pprof memory profile to ``path`` — the managed
    ``jax.profiler.device_memory_profile`` passthrough for deep dives."""
    import jax

    blob = jax.profiler.device_memory_profile()
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_bytes(blob)
    return str(p)


def host_rss_bytes() -> Optional[int]:
    """Resident set size of this process from /proc (linux); None where
    that is unavailable."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return None
    return None


def heartbeat_snapshot() -> dict:
    """The compact memory fields a heartbeat carries (utils/failure.py):
    host RSS always, summed per-device used/peak when the backend
    exposes allocator stats — enough for ``monitor`` to show a dying
    host's memory trajectory without parsing a telemetry stream."""
    out: dict = {}
    rss = host_rss_bytes()
    if rss:
        out["rss_mb"] = round(rss / 1e6, 1)
    try:
        devs = device_memory_stats()
    except Exception:  # graftlint: disable=EXC001 (heartbeats must never die on a wedged backend probe; the snapshot just goes without device fields)
        devs = []
    if devs:
        out["hbm_used_mb"] = round(
            sum(d["bytes_in_use"] for d in devs) / 1e6, 1)
        out["hbm_peak_mb"] = round(
            sum(d["peak_bytes_in_use"] for d in devs) / 1e6, 1)
    return out


def _collect_garbage() -> None:
    import gc

    gc.collect()


class MemTracker:
    """Managed phase-boundary memory watermarks + the leak gate.

    Mirrors ``prof.capture``'s one-entry-point contract for the polling
    APIs: every watermark lands as a ``mem.watermark`` telemetry record
    (phase, live buffer count/bytes, per-device used/peak, host RSS,
    headroom against the HBM limit), which ``obs/metrics.py`` turns
    into the ``graft_hbm_*`` gauges and the ``hbm_headroom`` alert rule
    watches.  ``hbm_bytes`` pins the limit explicitly (tests, CPU);
    ``chip`` reads it from ``prof.CHIP_SPECS``; with neither, the limit
    comes from device ``bytes_limit`` when the backend reports one.

    The leak gate: :meth:`baseline` after warmup captures the reference
    live-buffer census (after a GC pass, so dead python references
    don't count); :meth:`check_baseline` after churn re-polls and
    raises :class:`LeakError` if count or bytes grew past tolerance —
    the contract serve chaos rows (admit/retire ×N, mid-decode kill,
    rolling restart) hold in CI."""

    def __init__(self, hbm_bytes: Optional[int] = None,
                 chip: Optional[str] = None, emit: bool = True):
        if hbm_bytes is None and chip is not None:
            if chip not in prof.CHIP_SPECS:
                raise MemError(f"unknown chip {chip!r}; known: "
                               f"{sorted(prof.CHIP_SPECS)}")
            hbm_bytes = prof.CHIP_SPECS[chip].hbm_bytes
        self.hbm_bytes = hbm_bytes
        self.emit = emit
        self._peak = 0
        self._baseline: Optional[dict] = None

    def snapshot(self, phase: str, **extra) -> dict:
        """Poll live buffers + device counters at one phase boundary and
        emit the ``mem.watermark`` record."""
        live = live_buffer_stats()
        devs = device_memory_stats()
        used = (sum(d["bytes_in_use"] for d in devs) if devs
                else live["bytes"])
        dev_peak = sum(d["peak_bytes_in_use"] for d in devs)
        self._peak = max(self._peak, used, dev_peak)
        rec = {
            "phase": phase,
            "live_count": live["count"],
            "live_bytes": live["bytes"],
            "used_bytes": int(used),
            "peak_bytes": int(self._peak),
            "devices": len(devs),
        }
        rss = host_rss_bytes()
        if rss:
            rec["rss_bytes"] = rss
        limit = self.hbm_bytes
        if limit is None and devs:
            limit = sum(d["bytes_limit"] for d in devs) // len(devs) or None
        if limit:
            rec["hbm_limit_bytes"] = int(limit)
            rec["headroom_bytes"] = int(limit - used)
            rec["headroom_frac"] = round(1.0 - used / limit, 4)
        if self.emit:
            telemetry.emit("mem", "watermark", **rec, **extra)
        return rec

    # --- the leak gate ----------------------------------------------------

    def baseline(self, phase: str = "baseline", **extra) -> dict:
        """Capture the post-warmup reference census (GC first: python
        garbage is not a device leak)."""
        _collect_garbage()
        self._baseline = self.snapshot(phase, **extra)
        return self._baseline

    def check_baseline(self, label: str = "", *, tol_count: int = 0,
                       tol_bytes: int = 0,
                       phase: str = "leak-check") -> dict:
        """Re-poll and compare against :meth:`baseline`.  Raises
        :class:`LeakError` when live buffers grew past tolerance;
        returns the delta dict (also emitted as ``mem.leak_check``)."""
        if self._baseline is None:
            raise MemError("check_baseline before baseline(): capture the "
                           "post-warmup census first")
        _collect_garbage()
        snap = self.snapshot(phase)
        d_count = snap["live_count"] - self._baseline["live_count"]
        d_bytes = snap["live_bytes"] - self._baseline["live_bytes"]
        ok = d_count <= tol_count and d_bytes <= tol_bytes
        if self.emit:
            telemetry.emit("mem", "leak_check", label=label, ok=ok,
                           count_delta=d_count, bytes_delta=d_bytes,
                           baseline_count=self._baseline["live_count"],
                           baseline_bytes=self._baseline["live_bytes"])
        if not ok:
            raise LeakError(
                f"leak gate [{label or 'serve'}]: live buffers grew by "
                f"{d_count} arrays / {d_bytes} bytes over the post-warmup "
                f"baseline ({self._baseline['live_count']} arrays, "
                f"{self._baseline['live_bytes']} bytes) — a retire/stop "
                "path is keeping a cache reference (DESIGN.md §19 "
                "leak-gate contract)")
        return {"ok": ok, "count_delta": d_count, "bytes_delta": d_bytes}
