"""Run-report aggregation over a telemetry stream (tools/obs_report.py).

Turns the raw event stream into the answers an operator actually asks
after (or during) a run: where did the time go (step-time/MFU/stall
trajectory + the StepTimer reservoir percentiles), was it healthy (verdict
timeline, rollbacks, watchdog fires), did the checkpoints keep up (publish
cadence, save durations, fallbacks), how did serving do (p50/p99 latency
per SLO class, attainment, preemptions), and what was injected or broke
(fault + quarantine events).  Stdlib-only, like the rest of ``obs`` — it
must run on the box whose accelerator just wedged.
"""
from __future__ import annotations

from typing import Dict, List, Optional


def _pct(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile, stdlib-only (no numpy on the read side)."""
    if not values:
        return None
    ordered = sorted(values)
    idx = min(int(round((q / 100.0) * (len(ordered) - 1))), len(ordered) - 1)
    return float(ordered[idx])


def _span_pairs(events: List[dict]) -> List[dict]:
    """Matched span pairs as merged dicts (B fields + dur_s/ok from E)."""
    begins = {(r.get("host", 0), r.get("seq")): r
              for r in events if r.get("ph") == "B"}
    out = []
    for r in events:
        if r.get("ph") != "E":
            continue
        b = begins.pop((r.get("host", 0), r.get("sid")), None)
        if b is not None:
            merged = dict(b)
            merged.update(dur_s=r.get("dur_s"), ok=r.get("ok", True))
            out.append(merged)
    # whatever stayed in `begins` is a torn span (death inside it)
    out.sort(key=lambda r: (r.get("host", 0), r.get("seq", 0)))
    return out


def _torn_spans(events: List[dict]) -> List[dict]:
    ended = {(r.get("host", 0), r.get("sid")) for r in events
             if r.get("ph") == "E"}
    return [r for r in events if r.get("ph") == "B"
            and (r.get("host", 0), r.get("seq")) not in ended]


def build_report(events: List[dict]) -> dict:
    """Aggregate parsed records (telemetry.read_events output) into the
    run-report dict ``render_text`` prints and ``--format json`` emits."""
    by_kind: Dict[str, int] = {}
    for r in events:
        by_kind[r.get("kind", "?")] = by_kind.get(r.get("kind", "?"), 0) + 1

    runs: Dict[str, dict] = {}
    for r in events:
        run = runs.setdefault(str(r.get("run", "?")), {
            "hosts": set(), "t_first": None, "t_last": None, "records": 0})
        run["hosts"].add(r.get("host", 0))
        run["records"] += 1
        t = r.get("t")
        if t is not None:
            run["t_first"] = t if run["t_first"] is None \
                else min(run["t_first"], t)
            run["t_last"] = t if run["t_last"] is None \
                else max(run["t_last"], t)
    for run in runs.values():
        run["hosts"] = sorted(run["hosts"])
        run["wall_s"] = (run["t_last"] - run["t_first"]
                         if run["t_first"] is not None else None)

    # --- steps ------------------------------------------------------------
    steps = [r for r in events if r.get("kind") == "step" and "ph" not in r]
    losses = [float(r["loss"]) for r in steps if r.get("loss") is not None]
    step_report: dict = {"records": len(steps)}
    if steps:
        sids = [int(r["step"]) for r in steps if r.get("step") is not None]
        step_report.update(
            first_step=min(sids) if sids else None,
            last_step=max(sids) if sids else None,
            loss_first=losses[0] if losses else None,
            loss_last=losses[-1] if losses else None,
            loss_min=min(losses) if losses else None,
            step_time_p50=_pct([float(r["step_time_s"]) for r in steps
                                if r.get("step_time_s") is not None], 50),
            mfu_last=next((float(r["mfu"]) for r in reversed(steps)
                           if r.get("mfu") is not None), None),
            stall_frac_mean=(lambda v: sum(v) / len(v) if v else None)(
                [float(r["loader_stall_frac"]) for r in steps
                 if r.get("loader_stall_frac") is not None]))
    # the StepTimer reservoir percentiles ride run_end / perf_summary events
    perf = [r for r in events if r.get("name") in ("perf_summary", "run_end")
            and r.get("step_time_p50") is not None]
    if perf:
        step_report["reservoir"] = {
            k: perf[-1].get(k) for k in ("step_time_p50", "step_time_p99",
                                         "stall_p50", "stall_p99",
                                         "reservoir_n")
            if perf[-1].get(k) is not None}

    # --- health -----------------------------------------------------------
    health = [r for r in events if r.get("kind") == "health"]
    verdicts: Dict[str, int] = {}
    for r in health:
        verdicts[r.get("name", "?")] = verdicts.get(r.get("name", "?"), 0) + 1
    health_report = {
        "verdicts": verdicts,
        "timeline": [{"step": r.get("step"), "name": r.get("name"),
                      "loss": r.get("loss"), "host": r.get("host", 0)}
                     for r in health
                     if r.get("name") not in ("ok",)][:50],
    }

    # --- checkpoints --------------------------------------------------------
    ckpt = [r for r in events if r.get("kind") == "ckpt"]
    publishes = [r for r in ckpt if r.get("name") == "publish"]
    pub_steps = sorted(int(r["step"]) for r in publishes
                       if r.get("step") is not None)
    pub_times = sorted(float(r["t"]) for r in publishes if r.get("t"))
    save_spans = [r for r in _span_pairs(ckpt) if r.get("name") == "save"]
    ckpt_report = {
        "publishes": len(publishes),
        "publish_steps": pub_steps[-20:],
        "cadence_s": ((pub_times[-1] - pub_times[0]) / (len(pub_times) - 1)
                      if len(pub_times) > 1 else None),
        "save_dur_p50": _pct([float(r["dur_s"]) for r in save_spans
                              if r.get("dur_s") is not None], 50),
        "save_dur_max": max((float(r["dur_s"]) for r in save_spans
                             if r.get("dur_s") is not None), default=None),
        "fallback_skips": sum(r.get("name") == "fallback_skip" for r in ckpt),
        "failed_saves": sum(r.get("name") == "save_failed" for r in ckpt),
        "torn_saves": len([r for r in _torn_spans(ckpt)
                           if r.get("name") == "save"]),
    }

    # --- serve --------------------------------------------------------------
    serve = [r for r in events if r.get("kind") == "serve"]
    retires = [r for r in serve if r.get("name") == "retire"]
    classes = sorted({str(r.get("slo")) for r in retires}) or []
    per_class = {}
    for slo in classes:
        rows = [r for r in retires if str(r.get("slo")) == slo]
        lat = [float(r["latency_s"]) for r in rows
               if r.get("latency_s") is not None]
        waits = [float(r["queue_wait_s"]) for r in rows
                 if r.get("queue_wait_s") is not None]
        judged = [r for r in rows if r.get("slo_ok") is not None]
        per_class[slo] = {
            "completed": len(rows),
            "latency_p50": _pct(lat, 50), "latency_p99": _pct(lat, 99),
            "queue_wait_mean": sum(waits) / len(waits) if waits else None,
            "attainment": (sum(bool(r["slo_ok"]) for r in judged)
                           / len(judged) if judged else None),
        }
    # tick records may be SAMPLED aggregates (GenerationServer
    # tick_sample > 1): each carries `ticks` = how many decode ticks it
    # covers (absent = the legacy 1:1 record) and `active_sum` = the
    # occupied-slot-ticks of the window — sum those, never count records
    ticks = [r for r in serve if r.get("name") == "tick"]
    covered = sum(int(r.get("ticks", 1)) for r in ticks)
    slot_ticks = sum(
        int(r["active_sum"]) if r.get("active_sum") is not None
        else int(r.get("active", 0)) * int(r.get("ticks", 1))
        for r in ticks)
    # spec decode: tick records carry `tokens` (committed this window,
    # variable under speculation) next to `active_sum` (slot-ticks) —
    # their ratio is the measured accepted-K the cost model predicts
    tick_tokens = sum(int(r["tokens"]) for r in ticks
                      if r.get("tokens") is not None)
    has_spec = any(r.get("spec") for r in ticks)
    # prefix cache: one `prefix` record per admission (hit flag +
    # RUNNING totals) — counts sum, totals read off the LAST record
    prefix_recs = [r for r in serve if r.get("name") == "prefix"]
    prefix_report = None
    if prefix_recs:
        hits = sum(bool(r.get("hit")) for r in prefix_recs)
        prefix_report = {
            "lookups": len(prefix_recs),
            "hits": hits,
            "hit_rate": hits / len(prefix_recs),
            "entries": prefix_recs[-1].get("entries"),
            "prefill_flops_saved": prefix_recs[-1].get("flops_saved"),
        }
    serve_report = {
        "submitted": sum(r.get("name") == "submit" for r in serve),
        "completed": len(retires),
        "failed": sum(r.get("name") == "fail" for r in serve),
        "preemptions": sum(r.get("name") == "preempt" for r in serve),
        "ticks": covered,
        "tick_records": len(ticks),
        "occupied_slot_ticks": slot_ticks,
        "decoded_tokens": sum(int(r.get("tokens", 0)) for r in retires),
        "accepted_k": (tick_tokens / slot_ticks
                       if has_spec and slot_ticks else None),
        "prefix": prefix_report,
        "by_class": per_class,
    }

    # --- roofline: predicted vs measured ------------------------------------
    # trainers emit one `prof.predicted` record at run start (the perf
    # ledger's roofline ceiling for their config fingerprint); joined here
    # with the StepTimer's measured MFU it answers "is this run as fast as
    # this code CAN go" rather than "as fast as it used to go"
    prof_rows = [r for r in events if r.get("kind") == "prof"
                 and r.get("name") == "predicted" and "ph" not in r]
    prof_report: Optional[dict] = None
    if prof_rows:
        p = prof_rows[-1]
        measured = step_report.get("mfu_last")
        predicted = p.get("mfu")
        prof_report = {
            "fingerprint": p.get("fingerprint"),
            "exact": p.get("exact"),
            "chip": p.get("chip"),
            "predicted_mfu": predicted,
            "pred_step_time_s": p.get("pred_step_time_s"),
            "bound": p.get("bound"),
            "measured_mfu": measured,
            "measured_step_time_p50": step_report.get("step_time_p50"),
            "attained_frac": (float(measured) / float(predicted)
                              if measured is not None and predicted
                              else None),
        }

    # --- memory: predicted vs measured --------------------------------------
    # MemTracker emits `mem.watermark` at phase boundaries (obs/mem.py)
    # and trainers emit one `mem.predicted` record (the ledger's memory
    # timeline for their fingerprint); the join answers "is this run's
    # HBM where the ledger says it should be, and how close to the edge"
    marks = [r for r in events if r.get("kind") == "mem"
             and r.get("name") == "watermark" and "ph" not in r]
    mem_pred = [r for r in events if r.get("kind") == "mem"
                and r.get("name") == "predicted" and "ph" not in r]
    mem_report: Optional[dict] = None
    if marks or mem_pred:
        by_phase: dict = {}
        for r in marks:  # last watermark per phase wins
            by_phase[str(r.get("phase", "?"))] = {
                k: r.get(k) for k in
                ("live_count", "live_bytes", "used_bytes", "peak_bytes",
                 "rss_bytes", "headroom_bytes", "headroom_frac")
                if r.get(k) is not None}
        leaks = [r for r in events if r.get("kind") == "mem"
                 and r.get("name") == "leak_check" and "ph" not in r]
        mem_report = {
            "watermarks": by_phase,
            "peak_bytes": max((int(r.get("peak_bytes", 0)) for r in marks),
                              default=None),
            "headroom_frac_min": min(
                (float(r["headroom_frac"]) for r in marks
                 if r.get("headroom_frac") is not None), default=None),
            "predicted": ({k: mem_pred[-1].get(k) for k in
                           ("fingerprint", "exact", "chip", "phases",
                            "peak_phase", "peak_bytes", "headroom_frac",
                            "fits")} if mem_pred else None),
            "leak_checks": {"total": len(leaks),
                            "failed": sum(not r.get("ok", True)
                                          for r in leaks)},
        }

    # --- faults / data ------------------------------------------------------
    faults = [{"site": r.get("name"), "action": r.get("action"),
               "step": r.get("step"), "hits": r.get("hits"),
               "host": r.get("host", 0)}
              for r in events if r.get("kind") == "fault"][:50]
    data = [r for r in events if r.get("kind") == "data"]
    data_report = {
        "sample_quarantines": sum(r.get("name") == "sample_quarantine"
                                  for r in data),
        "shard_quarantines": sum(r.get("name") == "shard_quarantine"
                                 for r in data),
        "loader_stalls": sum(r.get("name") == "loader_stall" for r in data),
    }

    # --- locks (graftrace witness) ------------------------------------------
    # one kind="lock" event per lock name (locks.emit_telemetry), plus one
    # "order_graph" verdict event; last record per (host, name) wins — the
    # stats are cumulative counters, not deltas
    lock_events = [r for r in events if r.get("kind") == "lock"]
    per_lock: Dict[tuple, dict] = {}
    graph = None
    for r in lock_events:
        if r.get("name") == "order_graph":
            graph = r
        else:
            per_lock[(r.get("host", 0), r.get("name", "?"))] = r
    lock_rows = sorted(
        ({"name": name, "host": host,
          "acquires": int(r.get("acquires", 0)),
          "contended": int(r.get("contended", 0)),
          "wait_s": float(r.get("wait_s", 0.0)),
          "held_s": float(r.get("held_s", 0.0)),
          "held_max_s": float(r.get("held_max_s", 0.0))}
         for (host, name), r in per_lock.items()),
        key=lambda row: -row["held_s"])
    lock_report = {
        "locks": lock_rows[:20],
        "contended_total": sum(row["contended"] for row in lock_rows),
        "order_graph": (None if graph is None else {
            "edges": graph.get("edges"),
            "acyclic": graph.get("acyclic"),
            "cycle": graph.get("cycle"),
        }),
    }

    return {
        "records": len(events),
        "by_kind": by_kind,
        "runs": runs,
        "steps": step_report,
        "health": health_report,
        "ckpt": ckpt_report,
        "serve": serve_report,
        "prof": prof_report,
        "mem": mem_report,
        "faults": faults,
        "data": data_report,
        "locks": lock_report,
        "torn_spans": [{"kind": r.get("kind"), "name": r.get("name"),
                        "host": r.get("host", 0), "seq": r.get("seq")}
                       for r in _torn_spans(events)][:20],
    }


def build_fleet_report(events: List[dict], clocks) -> dict:
    """The fleet view over ALIGNED, merged records (``align.merge_streams``
    output): everything :func:`build_report` aggregates — serve p50/p99
    and attainment per SLO class, ckpt/fault/quarantine rollups — now
    spans every host, plus the cross-host sections only an aligned
    timebase makes meaningful:

    * per-lane clock provenance (offset/drift/residual bound/method),
    * the global step timeline: for every step seen on >= 2 lanes, the
      fleet-time spread between the first and last host to log it,
    * straggler ranking: lanes ordered by their mean lag behind the
      fastest host at each common step,
    * active-alert rollup per lane.
    """
    rep = build_report(events)

    by_lane: Dict[int, List[dict]] = {}
    for r in events:
        by_lane.setdefault(int(r.get("host", 0)), []).append(r)

    lane_rows = []
    for c in clocks:
        lane = by_lane.get(c.lane, [])
        steps = [r for r in lane if r.get("kind") == "step"
                 and "ph" not in r and r.get("step") is not None]
        alerts = [r.get("name") for r in lane if r.get("kind") == "alert"]
        lane_rows.append(dict(
            c.summary(), records=len(lane),
            last_step=max((int(r["step"]) for r in steps), default=None),
            alerts=sorted(set(alerts)), alert_count=len(alerts)))

    # step timeline on the fleet timebase
    step_t: Dict[int, Dict[int, float]] = {}
    for r in events:
        if r.get("kind") != "step" or "ph" in r or r.get("step") is None \
                or r.get("t") is None:
            continue
        per = step_t.setdefault(int(r["step"]), {})
        per.setdefault(int(r.get("host", 0)), float(r["t"]))
    common = {s: per for s, per in step_t.items() if len(per) >= 2}
    spreads = sorted((max(per.values()) - min(per.values()))
                     for per in common.values())
    lags: Dict[int, List[float]] = {}
    for per in common.values():
        first = min(per.values())
        for lane, t in per.items():
            lags.setdefault(lane, []).append(t - first)
    stragglers = sorted(
        ({"lane": lane, "mean_lag_s": sum(v) / len(v),
          "max_lag_s": max(v), "steps": len(v)}
         for lane, v in lags.items()),
        key=lambda row: -row["mean_lag_s"])
    last_steps = [row["last_step"] for row in lane_rows
                  if row["last_step"] is not None]
    rep["fleet"] = {
        "lanes": lane_rows,
        "common_steps": len(common),
        "step_spread_p50_s": _pct(spreads, 50),
        "step_spread_max_s": spreads[-1] if spreads else None,
        "stragglers": stragglers,
        "steps_behind": (max(last_steps) - min(last_steps)
                         if len(last_steps) > 1 else None),
    }
    return rep


def _fmt(v, nd: int = 4) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def render_text(report: dict) -> str:
    """The human half: one screen answering "what happened to this run"."""
    lines: List[str] = []
    lines.append(f"== graftscope run report "
                 f"({report['records']} records) ==")
    for run_id, run in report["runs"].items():
        lines.append(f"run {run_id}: hosts {run['hosts']}, "
                     f"{run['records']} records, "
                     f"wall {_fmt(run['wall_s'])}s")
    lines.append("kinds: " + ", ".join(
        f"{k}={v}" for k, v in sorted(report["by_kind"].items())))

    s = report["steps"]
    lines.append("-- training --")
    if s.get("records"):
        lines.append(
            f"steps {s.get('first_step')}..{s.get('last_step')} "
            f"({s['records']} records): loss "
            f"{_fmt(s.get('loss_first'))} -> {_fmt(s.get('loss_last'))} "
            f"(min {_fmt(s.get('loss_min'))}), step_time p50 "
            f"{_fmt(s.get('step_time_p50'))}s, mfu {_fmt(s.get('mfu_last'))},"
            f" stall frac {_fmt(s.get('stall_frac_mean'))}")
        res = s.get("reservoir")
        if res:
            lines.append(
                f"reservoir (n={res.get('reservoir_n')}): step_time "
                f"p50 {_fmt(res.get('step_time_p50'))}s / p99 "
                f"{_fmt(res.get('step_time_p99'))}s, stall p50 "
                f"{_fmt(res.get('stall_p50'))}s / p99 "
                f"{_fmt(res.get('stall_p99'))}s")
    else:
        lines.append("no step records")

    h = report["health"]
    lines.append("-- health --")
    if h["verdicts"]:
        lines.append("verdicts: " + ", ".join(
            f"{k}={v}" for k, v in sorted(h["verdicts"].items())))
        for t in h["timeline"][:10]:
            lines.append(f"  step {t['step']} host {t['host']}: {t['name']} "
                         f"(loss {_fmt(t['loss'])})")
    else:
        lines.append("no health events")

    c = report["ckpt"]
    lines.append("-- checkpoints --")
    lines.append(
        f"publishes {c['publishes']} (steps {c['publish_steps']}), cadence "
        f"{_fmt(c['cadence_s'])}s, save dur p50 {_fmt(c['save_dur_p50'])}s "
        f"max {_fmt(c['save_dur_max'])}s, fallback skips "
        f"{c['fallback_skips']}, failed {c['failed_saves']}, torn "
        f"{c['torn_saves']}")

    sv = report["serve"]
    lines.append("-- serve --")
    if sv["submitted"] or sv["completed"]:
        lines.append(
            f"requests {sv['submitted']} submitted / {sv['completed']} "
            f"completed / {sv['failed']} failed, preemptions "
            f"{sv['preemptions']}, ticks {sv['ticks']}, tokens "
            f"{sv['decoded_tokens']}")
        if sv.get("accepted_k") is not None:
            lines.append(
                f"  spec decode: accepted-K {_fmt(sv['accepted_k'])} "
                f"per active slot-tick")
        pref = sv.get("prefix")
        if pref:
            lines.append(
                f"  prefix cache: {pref['hits']}/{pref['lookups']} hits "
                f"(rate {_fmt(pref['hit_rate'])}), entries "
                f"{pref['entries']}, prefill FLOPs saved "
                f"{_fmt(pref['prefill_flops_saved'])}")
        for slo, row in sv["by_class"].items():
            lines.append(
                f"  {slo}: n={row['completed']} p50 "
                f"{_fmt(row['latency_p50'])}s p99 {_fmt(row['latency_p99'])}s"
                f" wait {_fmt(row['queue_wait_mean'])}s attainment "
                f"{_fmt(row['attainment'])}")
    else:
        lines.append("no serve events")

    prof = report.get("prof")
    if prof:
        lines.append("-- roofline (predicted vs measured) --")
        lines.append(
            f"ledger {prof.get('fingerprint')} "
            f"({'exact' if prof.get('exact') else 'plan-level'}, chip "
            f"{prof.get('chip')}): predicted mfu "
            f"{_fmt(prof.get('predicted_mfu'))} "
            f"({prof.get('bound')}-bound, step "
            f"{_fmt(prof.get('pred_step_time_s'))}s)")
        lines.append(
            f"measured: mfu {_fmt(prof.get('measured_mfu'))}, step_time p50 "
            f"{_fmt(prof.get('measured_step_time_p50'))}s -> attained "
            f"{_fmt(prof.get('attained_frac'))} of ceiling")

    memr = report.get("mem")
    if memr:
        lines.append("-- memory (predicted vs measured) --")
        pred = memr.get("predicted")
        if pred:
            phases = pred.get("phases") or {}
            phase_txt = " ".join(
                f"{k}={int(v) / 2**20:.0f}MiB"
                for k, v in sorted(phases.items()))
            lines.append(
                f"ledger {pred.get('fingerprint')} "
                f"({'exact' if pred.get('exact') else 'plan-level'}, chip "
                f"{pred.get('chip')}): {phase_txt} -> peak "
                f"@{pred.get('peak_phase')}, headroom "
                f"{_fmt(pred.get('headroom_frac'))}"
                f"{'' if pred.get('fits') else ' (DOES NOT FIT)'}")
        for phase, w in memr.get("watermarks", {}).items():
            used = w.get("used_bytes")
            lines.append(
                f"  {phase}: used "
                f"{'-' if used is None else f'{used / 2**20:.0f}MiB'}"
                f" live {w.get('live_count', '-')} bufs"
                + (f", headroom {_fmt(w['headroom_frac'])}"
                   if w.get("headroom_frac") is not None else ""))
        peak = memr.get("peak_bytes")
        lk = memr.get("leak_checks", {})
        lines.append(
            f"measured peak {'-' if peak is None else f'{peak / 2**20:.0f}MiB'}"
            + (f", min headroom {_fmt(memr['headroom_frac_min'])}"
               if memr.get("headroom_frac_min") is not None else "")
            + (f"; leak checks {lk.get('total', 0)} "
               f"({lk.get('failed', 0)} FAILED)" if lk.get("total") else ""))

    if report["faults"]:
        lines.append("-- injected faults --")
        for f in report["faults"][:10]:
            lines.append(f"  {f['site']}:{f['action']} step {f['step']} "
                         f"(hit {f['hits']}, host {f['host']})")
    d = report["data"]
    if any(d.values()):
        lines.append(f"-- data -- sample quarantines "
                     f"{d['sample_quarantines']}, shard quarantines "
                     f"{d['shard_quarantines']}, loader stalls "
                     f"{d['loader_stalls']}")
    lk = report.get("locks") or {}
    if lk.get("locks"):
        lines.append("-- locks (graftrace witness) --")
        for row in lk["locks"][:8]:  # already sorted by held time, desc
            lines.append(
                f"  {row['name']} (host {row['host']}): "
                f"{row['acquires']} acquires, {row['contended']} contended "
                f"(wait {_fmt(row['wait_s'])}s), held {_fmt(row['held_s'])}s "
                f"total / {_fmt(row['held_max_s'])}s max")
        graph = lk.get("order_graph")
        if graph is not None:
            lines.append(
                f"  order graph: {graph.get('edges')} edge(s), "
                + ("acyclic" if graph.get("acyclic")
                   else f"CYCLE: {graph.get('cycle')}"))
    if report["torn_spans"]:
        lines.append("-- torn spans (death inside) --")
        for t in report["torn_spans"][:10]:
            lines.append(f"  {t['kind']}.{t['name']} host {t['host']} "
                         f"seq {t['seq']}")

    fleet = report.get("fleet")
    if fleet:
        lines.append("-- fleet (aligned timebase) --")
        for lane in fleet["lanes"]:
            bound = lane["residual_bound_s"]
            lines.append(
                f"  lane {lane['lane']} = {lane['run']} "
                f"(host {lane['host']}): offset {_fmt(lane['offset_s'])}s "
                f"drift {_fmt(lane['drift_s_per_s'])}/s "
                f"±{'unbounded' if bound is None else _fmt(bound) + 's'} "
                f"[{lane['method']}, {lane['anchors']} anchors], "
                f"last step {lane['last_step']}"
                + (f", ALERTS: {', '.join(lane['alerts'])}"
                   if lane["alerts"] else ""))
        lines.append(
            f"step timeline: {fleet['common_steps']} common steps, "
            f"spread p50 {_fmt(fleet['step_spread_p50_s'])}s / max "
            f"{_fmt(fleet['step_spread_max_s'])}s, steps behind "
            f"{_fmt(fleet['steps_behind'])}")
        for row in fleet["stragglers"][:5]:
            lines.append(
                f"  straggler lane {row['lane']}: mean lag "
                f"{_fmt(row['mean_lag_s'])}s (max {_fmt(row['max_lag_s'])}s "
                f"over {row['steps']} steps)")
    return "\n".join(lines) + "\n"
