"""ctypes bindings to the native host-ops library (native/host_ops.cpp).

Loads ``libdalle_host.so`` (building it with ``make -C native`` on first use
if a toolchain is available) and exposes the fused
crop+bilinear-resize+normalize and the threaded batch collate.  Every entry
point degrades gracefully: callers check ``available()`` and fall back to
the PIL/numpy path when the library can't be built or loaded.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

from ..utils import locks

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libdalle_host.so"

_lock = locks.TracedLock("native.load")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        # env_flag semantics: DALLE_TPU_NO_NATIVE=0 must mean "native ON"
        # (imported lazily — this module stays importable without jax)
        from ..utils.helpers import env_flag

        if env_flag("DALLE_TPU_NO_NATIVE"):
            return None
        def build() -> bool:
            try:
                subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True,
                               capture_output=True, timeout=120)
                return True
            except (OSError, subprocess.SubprocessError):
                return False

        def probe():
            try:
                lib = ctypes.CDLL(str(_LIB_PATH))
                return lib if lib.dalle_host_ops_version() == 3 else None
            except (OSError, AttributeError):
                return None

        lib = probe() if _LIB_PATH.exists() else None
        if lib is None:
            # missing or stale .so: delete first — make would consider a
            # newer-mtime stale binary up to date, and dlopen caches the old
            # inode, so an in-place rebuild could never be picked up
            try:
                _LIB_PATH.unlink(missing_ok=True)
            except OSError:  # read-only install: degrade to pure Python
                return None
            if not build():
                return None
            lib = probe()
            if lib is None:
                return None

        lib.crop_resize_normalize_u8_mt.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.POINTER(ctypes.c_float), ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
        ]
        lib.batch_collate_f32.argtypes = [
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)), ctypes.c_int,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_float), ctypes.c_int,
        ]
        lib.bpe_create.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.bpe_create.restype = ctypes.c_void_p
        lib.bpe_destroy.argtypes = [ctypes.c_void_p]
        lib.bpe_encode_word.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ]
        lib.bpe_encode_word.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def crop_resize_normalize(img_u8: np.ndarray, top: float, left: float,
                          ch: float, cw: float, out_size: int,
                          nthreads: int = 0) -> Optional[np.ndarray]:
    """Fused crop box -> bilinear resize -> [0,1] f32, or None if the native
    library is unavailable.  `img_u8` is [h, w, 3] uint8 (C-contiguous)."""
    lib = _load()
    if lib is None:
        return None
    img_u8 = np.ascontiguousarray(img_u8, dtype=np.uint8)
    h, w, c = img_u8.shape
    assert c == 3, "RGB input expected"
    out = np.empty((out_size, out_size, 3), np.float32)
    if nthreads <= 0:
        nthreads = min(4, os.cpu_count() or 1)
    lib.crop_resize_normalize_u8_mt(
        img_u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        h, w, w * 3,
        ctypes.c_float(top), ctypes.c_float(left), ctypes.c_float(ch),
        ctypes.c_float(cw),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out_size, out_size, nthreads)
    return out


class BpeEngine:
    """Native byte-level BPE merge loop in vocab-id space.

    Construct with the merge rules as id triples (first, second, merged) in
    rank order; `encode_word` maps a word's symbol ids to its merged BPE
    ids with exact parity to SimpleTokenizer's Python loop.  Use
    `BpeEngine.create` which returns None when the library is unavailable.
    """

    def __init__(self, lib, handle):
        self._lib = lib
        self._handle = handle

    @classmethod
    def create(cls, pairs_a, pairs_b, merged) -> Optional["BpeEngine"]:
        lib = _load()
        if lib is None:
            return None
        a = np.ascontiguousarray(pairs_a, dtype=np.int32)
        b = np.ascontiguousarray(pairs_b, dtype=np.int32)
        c = np.ascontiguousarray(merged, dtype=np.int32)
        assert a.shape == b.shape == c.shape and a.ndim == 1
        handle = lib.bpe_create(
            len(a), a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            b.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            c.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if not handle:
            return None
        return cls(lib, handle)

    def encode_word(self, symbol_ids) -> list:
        ids = np.ascontiguousarray(symbol_ids, dtype=np.int32)
        out = np.empty(len(ids), np.int32)
        n = self._lib.bpe_encode_word(
            self._handle, ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(ids), out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(out))
        assert n >= 0, "bpe_encode_word: output capacity exceeded"
        return out[:n].tolist()

    def __del__(self):
        lib, handle = getattr(self, "_lib", None), getattr(self, "_handle", None)
        if lib is not None and handle:
            lib.bpe_destroy(handle)
            self._handle = None


def batch_collate(samples: list, nthreads: int = 0) -> Optional[np.ndarray]:
    """Stack same-shape f32 arrays into one batch via the threaded native
    memcpy, or None if unavailable (caller falls back to np.stack)."""
    lib = _load()
    if lib is None or not samples:
        return None
    arrs = [np.ascontiguousarray(s, dtype=np.float32) for s in samples]
    shape = arrs[0].shape
    if any(a.shape != shape for a in arrs):
        return None
    elems = int(np.prod(shape))
    out = np.empty((len(arrs),) + shape, np.float32)
    ptrs = (ctypes.POINTER(ctypes.c_float) * len(arrs))(
        *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for a in arrs])
    if nthreads <= 0:
        nthreads = min(8, os.cpu_count() or 1)
    lib.batch_collate_f32(
        ptrs, len(arrs), elems,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), nthreads)
    return out
