"""Bundled CUB data artifacts and their integrity check.

The repo ships the two data files the reference's CUB CLIs expect
(ref genrank.py:20-22, generate.py's captions default): the 7800-token
CUB BPE vocab and `cub_2011_test_captions.pkl` (a pandas DataFrame of
30k real CUB test captions).  The captions file is a *pickle* — a format
that executes arbitrary code on load — and it originates outside this
repo, so every in-repo load of it goes through
:func:`load_captions_pickle`, which refuses to unpickle a file carrying
the bundled artifact's name unless its sha256 matches the digest
recorded here (r4 advisor finding: never routinely execute an unpinned
untrusted binary).  A *user-supplied* pickle under a different name is
the user's own trust decision, exactly as in the reference CLI, and is
loaded as-is.
"""
from __future__ import annotations

import hashlib
from pathlib import Path

CUB_CAPTIONS_NAME = "cub_2011_test_captions.pkl"
# sha256 of the bundled artifact, recorded at bundle time (round 4).
CUB_CAPTIONS_SHA256 = (
    "efde620efb1fb3d9504661341a309388ba225eb0ae9eb241bfa8456c15db9f25")


def load_captions_pickle(path):
    """pd.read_pickle with an integrity gate on the bundled artifact.

    If ``path`` names the bundled CUB captions file (by basename), its
    sha256 must equal :data:`CUB_CAPTIONS_SHA256` — a swapped or
    corrupted copy raises before any pickle bytecode runs.  Other
    filenames load unverified (user-supplied eval sets).
    """
    import io

    import pandas as pd

    path = Path(path)
    if path.name == CUB_CAPTIONS_NAME:
        # hash and unpickle the SAME in-memory bytes: re-reading from disk
        # after hashing would leave a swap window between the two reads
        data = path.read_bytes()
        digest = hashlib.sha256(data).hexdigest()
        if digest != CUB_CAPTIONS_SHA256:
            raise ValueError(
                f"{path} does not match the recorded sha256 of the bundled "
                f"CUB captions artifact (got {digest[:12]}…, expected "
                f"{CUB_CAPTIONS_SHA256[:12]}…); refusing to unpickle an "
                f"unverified binary")
        return pd.read_pickle(io.BytesIO(data))
    return pd.read_pickle(path)
