"""Text tokenizers (pure Python / numpy — no torch, no JAX).

Capability parity with the reference's three tokenizers
(`/root/reference/dalle_pytorch/tokenizer.py`):

* ``SimpleTokenizer`` — the OpenAI CLIP byte-level BPE (vocab 49408), built
  from a merges text file.  The merges file itself is *data* we do not bundle;
  pass ``bpe_path`` explicitly (the reference ships it at
  ``dalle_pytorch/data/bpe_simple_vocab_16e6.txt``).
* ``HugTokenizer`` — wraps a HuggingFace ``tokenizers`` JSON file (the fork's
  CUB-200 BPE, ``cub200_bpe_vsize_7800.json``; ref tokenizer.py:156-190).
* ``ChineseTokenizer`` — ``bert-base-chinese`` wordpiece (ref
  tokenizer.py:194-225).  Gated: requires network/cache to load.

Shared contract (ref tokenizer.py:135-150): ``tokenize(texts, context_length,
truncate_text)`` returns an int32 numpy array ``[batch, context_length]``
padded with 0; raises if a text overflows and ``truncate_text`` is False.
"""
from __future__ import annotations

import html
from functools import lru_cache
from pathlib import Path

import numpy as np
import regex as re

try:  # optional text fixer, matches reference behavior when present
    import ftfy

    def _fix_text(t: str) -> str:
        return ftfy.fix_text(t)
except ImportError:  # pragma: no cover - environment without ftfy
    def _fix_text(t: str) -> str:
        return t


@lru_cache()
def bytes_to_unicode():
    """Reversible byte -> printable-unicode-char table (standard GPT-2/CLIP
    byte-level BPE alphabet; ref tokenizer.py:22-33)."""
    printable = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    chars = printable[:]
    offset = 0
    for b in range(256):
        if b not in printable:
            printable.append(b)
            chars.append(256 + offset)
            offset += 1
    return dict(zip(printable, [chr(c) for c in chars]))


def _pairs_of(word):
    return set(zip(word[:-1], word[1:]))


def basic_clean(text: str) -> str:
    text = _fix_text(text)
    text = html.unescape(html.unescape(text))
    return text.strip()


def whitespace_clean(text: str) -> str:
    return re.sub(r"\s+", " ", text).strip()


class _TokenizerBase:
    """Shared pad/truncate batching contract (ref tokenizer.py:135-150)."""

    vocab_size: int

    def encode(self, text: str):  # -> list[int]
        raise NotImplementedError

    def decode(self, tokens) -> str:
        raise NotImplementedError

    def tokenize(self, texts, context_length: int = 256, truncate_text: bool = False) -> np.ndarray:
        if isinstance(texts, str):
            texts = [texts]
        result = np.zeros((len(texts), context_length), dtype=np.int32)
        for i, text in enumerate(texts):
            tokens = list(self.encode(text))
            if len(tokens) > context_length:
                if truncate_text:
                    tokens = tokens[:context_length]
                else:
                    raise RuntimeError(
                        f"Input {texts[i]} is too long for context length {context_length}"
                    )
            result[i, : len(tokens)] = tokens
        return result


class SimpleTokenizer(_TokenizerBase):
    """OpenAI CLIP byte-level BPE (ref tokenizer.py:53-150).

    Vocab layout: 256 byte chars, 256 byte chars + ``</w>``, one token per
    merge rule, then ``<|startoftext|>`` / ``<|endoftext|>`` -> 49408 total
    with the standard CLIP merges file.
    """

    SOT, EOT = "<|startoftext|>", "<|endoftext|>"

    def __init__(self, bpe_path: str | Path):
        bpe_path = Path(bpe_path)
        assert bpe_path.exists(), f"BPE merges file {bpe_path} does not exist"
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}

        lines = bpe_path.read_text(encoding="utf8").split("\n")
        # CLIP convention: skip header line, keep first 49152-256-2 merges.
        merges = [tuple(m.split()) for m in lines[1 : 49152 - 256 - 2 + 1]]

        vocab = list(self.byte_encoder.values())
        vocab += [v + "</w>" for v in vocab]
        vocab += ["".join(m) for m in merges]
        vocab += [self.SOT, self.EOT]

        self.encoder = {tok: i for i, tok in enumerate(vocab)}
        self.decoder = {i: tok for tok, i in self.encoder.items()}
        self.bpe_ranks = {m: i for i, m in enumerate(merges)}
        self.vocab_size = len(vocab)
        self._cache = {self.SOT: self.SOT, self.EOT: self.EOT}
        # native merge engine (id-space BPE loop in C++, native/host_ops.cpp),
        # created lazily on first encode() so construction never waits on a
        # library build; None after creation failed -> pure-Python fallback
        # Keep only well-formed, *reachable* rules: a pair can only ever
        # fire if both pieces are themselves vocab symbols (byte chars or
        # earlier merge results), so dropping the rest is semantics-free —
        # relative rank order, all that greedy merging consults, survives.
        self._rules = [m for m in merges
                       if len(m) == 2 and m[0] in self.encoder
                       and m[1] in self.encoder]
        self._native = None
        self._native_tried = False
        self._native_cache: dict = {}
        self.pat = re.compile(
            r"""<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d|[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+""",
            re.IGNORECASE,
        )

    @property
    def _engine(self):
        if not self._native_tried:
            self._native_tried = True
            if self._rules:
                from .native import BpeEngine

                self._native = BpeEngine.create(
                    [self.encoder[a] for a, _ in self._rules],
                    [self.encoder[b] for _, b in self._rules],
                    [self.encoder[a + b] for a, b in self._rules])
        return self._native

    def _bpe(self, token: str) -> str:
        if token in self._cache:
            return self._cache[token]
        word = tuple(token[:-1]) + (token[-1] + "</w>",)
        pairs = _pairs_of(word)
        if not pairs:
            return token + "</w>"
        while True:
            bigram = min(pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if bigram not in self.bpe_ranks:
                break
            first, second = bigram
            merged = []
            i = 0
            while i < len(word):
                if word[i] == first and i < len(word) - 1 and word[i + 1] == second:
                    merged.append(first + second)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = tuple(merged)
            if len(word) == 1:
                break
            pairs = _pairs_of(word)
        out = " ".join(word)
        self._cache[token] = out
        return out

    def _bpe_ids_native(self, token: str):
        """Merged BPE ids for one pre-tokenized word via the native engine:
        byte symbols map straight to vocab ids (last one carries </w>), the
        C++ merge loop does the rest — no string splits/joins."""
        cached = self._native_cache.get(token)
        if cached is not None:
            return cached
        symbols = [self.encoder[c] for c in token[:-1]]
        symbols.append(self.encoder[token[-1] + "</w>"])
        out = self._engine.encode_word(symbols)
        self._native_cache[token] = out
        return out

    def encode(self, text: str):
        ids = []
        text = whitespace_clean(basic_clean(text)).lower()
        for token in re.findall(self.pat, text):
            token = "".join(self.byte_encoder[b] for b in token.encode("utf-8"))
            if token in (self.SOT, self.EOT):
                ids.append(self.encoder[token])
            elif self._engine is not None:
                ids.extend(self._bpe_ids_native(token))
            else:
                ids.extend(self.encoder[t] for t in self._bpe(token).split(" "))
        return ids

    def decode(self, tokens, remove_start_end: bool = True) -> str:
        tokens = np.asarray(tokens).reshape(-1).tolist()
        if remove_start_end:
            special = {self.encoder[self.SOT], self.encoder[self.EOT], 0}
            tokens = [t for t in tokens if t not in special]
        text = "".join(self.decoder[t] for t in tokens)
        raw = bytearray(self.byte_decoder[c] for c in text)
        return raw.decode("utf-8", errors="replace").replace("</w>", " ")


class HugTokenizer(_TokenizerBase):
    """HuggingFace `tokenizers` JSON wrapper (ref tokenizer.py:156-190)."""

    def __init__(self, bpe_path: str | Path):
        from tokenizers import Tokenizer

        bpe_path = Path(bpe_path)
        assert bpe_path.exists(), f"BPE json path {bpe_path} does not exist"
        self.tokenizer = Tokenizer.from_file(str(bpe_path))
        self.vocab_size = self.tokenizer.get_vocab_size()

    def encode(self, text: str):
        return self.tokenizer.encode(text).ids

    def decode(self, tokens) -> str:
        tokens = np.asarray(tokens).reshape(-1).tolist()
        tokens = [t for t in tokens if t != 0]
        return self.tokenizer.decode(tokens, skip_special_tokens=True)


class ChineseTokenizer(_TokenizerBase):
    """bert-base-chinese wordpiece (ref tokenizer.py:194-225). Requires the
    HF model to be available locally (no network in this environment)."""

    def __init__(self):
        from transformers import BertTokenizer

        self.tokenizer = BertTokenizer.from_pretrained("bert-base-chinese")
        self.vocab_size = self.tokenizer.vocab_size

    def encode(self, text: str):
        return self.tokenizer.encode(text, add_special_tokens=False)

    def decode(self, tokens) -> str:
        tokens = np.asarray(tokens).reshape(-1).tolist()
        tokens = [t for t in tokens if t != 0]
        return self.tokenizer.decode(tokens)
