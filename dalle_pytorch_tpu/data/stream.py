"""Streaming ingestion: webdataset-style tar shards behind the DataLoader
contract.

The folder datasets (data/dataset.py) list every file up front and read one
file per member — fine for CUB's 11k birds on one host, fatal at corpus
scale: a million-sample dataset is two million inodes, every host lists all
of them, and shared-filesystem metadata becomes the input bottleneck.  This
module replaces the *storage* layer while keeping the *iteration* contract:

* **Shard format** — plain tar files of ``(image, caption)`` members plus an
  ``index.json`` manifest recording, per shard, its size + crc32 and every
  member's byte offset inside the tar (``tools/make_shards.py`` writes
  both).  The offsets make the shard set randomly addressable: a sample
  read is one ``pread`` per member, no tar scan — so the global shuffle
  that keeps training order identical to the folder loader costs nothing
  extra.
* **Per-host shard assignment** — host ``h`` of ``H`` owns shards
  ``[h::H]``: each host stores/reads ONLY its own shards (the point of
  sharding a corpus), and every host runs the same number of steps per
  epoch (the batch count is the min over hosts, so SPMD loops stay
  collective).  On one host this degrades to the folder loader's exact
  permutation, which is what the cross-format bitwise tests pin.
* **Iteration contract** — :class:`StreamingDataLoader` subclasses
  ``DataLoader``: same bounded worker pool, same ordered prefetch with
  backpressure, same cursor semantics.  ``state_dict()`` extends the
  (seed, epoch, cursor) cursor with the **shard-list fingerprint** (crc32
  over every shard's name/size/crc32) and the (shard, member) coordinate of
  the next unconsumed sample — resume refuses a changed shard list loudly
  instead of silently training on different data, and mid-shard resume
  replays bitwise (same permutation, consumed batches skipped).
* **Degradation** — a failing shard read (``shard_read`` faultpoint:
  transient failure or a truncated member) is retried once, then the whole
  shard is quarantined (logged, capped at max(1, 5%) of the shard list —
  the cap trips loudly) and the walk continues in the next healthy shard,
  mirroring the per-sample quarantine policy of ``TextImageDataset``.

:class:`DevicePrefetcher` is the last host stall remover: it pulls (and
optionally device-places) the next batch while the current step runs, and
meters the time the step loop actually waited on the input pipeline — the
``loader_stall_s`` metric the heartbeat/monitor/bench surfaces report, so
an input-bound run is visible instead of mislabeled "slow chip".
"""
from __future__ import annotations

import json
import os
import threading
import zlib
from bisect import bisect_right
from pathlib import Path
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..obs import telemetry
from ..utils import faults
from ..utils import locks
from ..utils.helpers import atomic_write_json
from .dataset import DataLoader, IMAGE_EXTS, center_crop_resize, make_pair

INDEX_NAME = "index.json"
INDEX_SCHEMA = 1


class ShardIndexError(RuntimeError):
    """The shard set is unusable (missing/short/changed shards, bad index)."""


def _decode_image_bytes(data: bytes):
    """Bytes -> RGB PIL image, decode forced NOW (mirrors
    ``dataset._load_image``: the retry/quarantine handler must see
    truncated-member errors here, not lazily mid-augmentation)."""
    import io

    from PIL import Image

    img = Image.open(io.BytesIO(data))
    img.load()
    if img.mode != "RGB":
        img = img.convert("RGB")
    return img


def shard_fingerprint(shards: Sequence[dict]) -> str:
    """crc32 over every shard's identity (name, size, crc32) — the cursor
    contract's "same shard list" check.  Member offsets are implied by the
    shard bytes, so this is exactly the identity a resume must match."""
    blob = json.dumps([[s["name"], int(s["size"]), s["crc32"]]
                       for s in shards]).encode()
    return f"{zlib.crc32(blob):08x}"


class ShardIndex:
    """Parsed + size-checked ``index.json`` over a directory of tar shards.

    Size mismatches are caught at open (cheap stat per shard — a truncated
    shard fails before training starts); full per-shard crc32 verification
    is :meth:`verify` (make_shards ``--verify``, tests) since crc'ing a
    multi-GB corpus at every trainer start would be its own stall.
    """

    def __init__(self, root, check_sizes: bool = True):
        self.root = Path(root)
        ipath = self.root / INDEX_NAME
        if not ipath.is_file():
            raise ShardIndexError(f"no {INDEX_NAME} under {self.root} — "
                                  "build shards with tools/make_shards.py")
        try:
            index = json.loads(ipath.read_text())
        except (json.JSONDecodeError, OSError) as e:
            raise ShardIndexError(f"unreadable {ipath}: {e}") from e
        if int(index.get("schema", 0)) > INDEX_SCHEMA:
            raise ShardIndexError(
                f"index schema {index.get('schema')} is newer than this "
                f"build's {INDEX_SCHEMA}")
        self.shards: List[dict] = list(index["shards"])
        if not self.shards:
            raise ShardIndexError(f"{ipath} lists no shards")
        self.has_captions = bool(index.get("has_captions", False))
        self.fingerprint = shard_fingerprint(self.shards)
        # cumulative sample counts: locate() maps a global index to its
        # (shard, member) coordinate with one bisect
        counts = [int(s["count"]) for s in self.shards]
        self._cum = np.cumsum(counts)
        self.num_samples = int(self._cum[-1])
        if check_sizes:
            for s in self.shards:
                p = self.shard_path(s["name"])
                if not p.is_file():
                    raise ShardIndexError(f"shard {s['name']} missing under "
                                          f"{self.root}")
                size = p.stat().st_size
                if size != int(s["size"]):
                    raise ShardIndexError(
                        f"shard {s['name']} is {size} bytes, index says "
                        f"{s['size']} (truncated or swapped?)")

    def shard_path(self, name: str) -> Path:
        return self.root / name

    def locate(self, g: int) -> tuple:
        """Global sample index -> (shard index, member index)."""
        s = int(bisect_right(self._cum, g))
        prev = int(self._cum[s - 1]) if s else 0
        return s, g - prev

    def shard_start(self, s: int) -> int:
        """Global index of shard ``s``'s first sample."""
        return int(self._cum[s - 1]) if s else 0

    def verify(self) -> None:
        """Full integrity pass: every shard's bytes match the recorded
        crc32.  Raises :class:`ShardIndexError` on the first mismatch."""
        for s in self.shards:
            p = self.shard_path(s["name"])
            crc = 0
            with open(p, "rb") as f:
                while True:
                    buf = f.read(1 << 20)
                    if not buf:
                        break
                    crc = zlib.crc32(buf, crc)
            if f"{crc:08x}" != s["crc32"]:
                raise ShardIndexError(f"shard {s['name']} fails its crc32 "
                                      "(corrupt)")


class ShardStreamDataset:
    """Random-access (image, caption) samples out of a tar shard set.

    ``item(idx, epoch)`` matches ``TextImageDataset.item`` bitwise when the
    shards were built from the same folder (make_shards preserves the
    sorted-key sample order and this class reuses the one shared
    decode/augment sequence, ``dataset.make_pair``).  ``image_only=True``
    yields center-cropped images exactly like ``ImageFolderDataset`` (the
    VAE trainer's diet).
    """

    def __init__(self, root, tokenizer=None, text_len: int = 256,
                 image_size: int = 128, resize_ratio: float = 0.6,
                 truncate_captions: bool = False, image_only: bool = False,
                 seed: int = 0):
        self.index = ShardIndex(root)
        if not image_only and not self.index.has_captions:
            raise ShardIndexError(
                f"shard set {root} has no captions; rebuild with captions "
                "or use --image_only mode (train_vae)")
        self.tokenizer = tokenizer
        self.text_len = text_len
        self.image_size = image_size
        self.resize_ratio = resize_ratio
        self.truncate_captions = truncate_captions
        self.image_only = image_only
        self.seed = seed
        self._fds: dict = {}
        self._fd_lock = locks.TracedLock("stream.fds")
        # shard-granular quarantine, mirroring TextImageDataset's per-sample
        # policy: skip what keeps failing, but a rotten shard SET must still
        # fail loudly — the cap is on shards, not samples, because one bad
        # shard takes all of its samples with it.
        self._quarantined: set = set()
        self._quarantine_lock = locks.TracedLock("stream.quarantine")
        self.max_quarantine = max(1, len(self.index.shards) // 20)

    def __len__(self):
        return self.index.num_samples

    def close(self) -> None:
        with self._fd_lock:
            for fd in self._fds.values():
                try:
                    os.close(fd)
                except OSError:
                    pass
            self._fds.clear()

    def _fd_for(self, s: int) -> int:
        """Cached read-only fd per shard; ``os.pread`` is positionless, so
        one fd serves every prefetch worker concurrently."""
        with self._fd_lock:
            fd = self._fds.get(s)
            if fd is None:
                fd = os.open(self.index.shard_path(
                    self.index.shards[s]["name"]), os.O_RDONLY)
                self._fds[s] = fd
            return fd

    def _read_bytes(self, s: int, offset: int, size: int) -> bytes:
        data = os.pread(self._fd_for(s), size, offset)
        if len(data) != size:
            raise OSError(f"short read from shard "
                          f"{self.index.shards[s]['name']} at {offset}: "
                          f"{len(data)}/{size} bytes")
        return data

    def _quarantine(self, s: int, err: Exception) -> None:
        with self._quarantine_lock:
            self._quarantined.add(s)
            n = len(self._quarantined)
        name = self.index.shards[s]["name"]
        telemetry.note(
            "data", "shard_quarantine",
            f"quarantining shard {name} "
            f"({n}/{self.max_quarantine} quarantined): {err}",
            prefix="warning:", stream="stdout", shard=name, quarantined=n)
        if n > self.max_quarantine:
            raise RuntimeError(
                f"ShardStreamDataset: {n} shards quarantined (cap "
                f"{self.max_quarantine}) — the shard set is rotten, "
                "refusing to silently train on what is left")

    def _read_sample(self, g: int, rng):
        """One sample at global index ``g``.  The ``shard_read`` faultpoint
        fires per attempt: ``fail_after``/``every`` model transient I/O
        failures, ``truncate`` hands back a half-read image member (the
        torn-shard case a crc would catch offline) — both must end in the
        retry/quarantine path, never a crashed epoch."""
        s, j = self.index.locate(g)
        actions = faults.fire("shard_read")
        rec = self.index.shards[s]["samples"][j]
        img_bytes = self._read_bytes(s, int(rec["image_offset"]),
                                     int(rec["image_size"]))
        if "truncate" in actions:
            img_bytes = img_bytes[: max(len(img_bytes) // 2, 1)]
        if self.image_only:
            return center_crop_resize(_decode_image_bytes(img_bytes),
                                      self.image_size)
        caption = self._read_bytes(s, int(rec["caption_offset"]),
                                   int(rec["caption_size"])).decode("utf-8")
        return make_pair(caption, lambda: _decode_image_bytes(img_bytes),
                         self.tokenizer, self.text_len,
                         self.truncate_captions, self.image_size,
                         self.resize_ratio, rng)

    def __getitem__(self, idx: int):
        return self.item(idx, 0)

    def item(self, idx: int, epoch: int):
        # per-call Generator seeded by (seed, GLOBAL idx, epoch): identical
        # construction to TextImageDataset.item, so the folder and shard
        # formats draw the same caption lines and crops for the same sample
        rng = np.random.default_rng((self.seed, idx, epoch))
        n = len(self)
        g = idx % n
        # walk: retry the sample once, then quarantine its SHARD and hop to
        # the next shard's first sample (a dead shard must cost one hop,
        # not one failed attempt per sample it holds).  Bounded by the
        # shard count plus a few retries; the quarantine cap bounds it too.
        for _ in range(len(self.index.shards) + 8):
            s, _j = self.index.locate(g)
            if s in self._quarantined:
                g = self.index.shard_start(
                    (s + 1) % len(self.index.shards)) % n
                continue
            last_err = None
            for _retry in range(2):
                try:
                    return self._read_sample(g, rng)
                except (OSError, ValueError) as e:
                    last_err = e
            self._quarantine(s, last_err)
        raise RuntimeError(
            f"ShardStreamDataset: no readable shard found walking from "
            f"index {idx} — check the shard directory")


def _normalize_fp(value) -> Optional[str]:
    if value is None:
        return None
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    return str(value)


class StreamingDataLoader(DataLoader):
    """``DataLoader`` over a :class:`ShardStreamDataset` with shard-granular
    host assignment and a fingerprinted resume cursor.

    Host ``h`` of ``H`` owns shards ``[h::H]`` and never opens another
    host's shards.  Every host runs ``min_h(len(own_h) // batch)`` batches
    per epoch so the SPMD step loops stay collective even when shard sizes
    differ.  On one host the epoch order is the folder loader's exact
    permutation — the property the cross-format bitwise tests pin.
    Batching, the bounded worker pool, ordered prefetch, and the cursor
    bookkeeping are all inherited; only *which indices make an epoch* and
    the state_dict contract differ.
    """

    def __init__(self, dataset: ShardStreamDataset, batch_size: int,
                 shuffle: bool = True, drop_last: bool = True, seed: int = 0,
                 shard_num_hosts: int = 1, shard_index: int = 0,
                 num_workers: int = 8, prefetch: int = 4):
        super().__init__(dataset, batch_size, shuffle=shuffle,
                         drop_last=drop_last, seed=seed,
                         shard_num_hosts=shard_num_hosts,
                         shard_index=shard_index, num_workers=num_workers,
                         prefetch=prefetch)
        index = dataset.index
        n_shards = len(index.shards)
        if shard_num_hosts > n_shards:
            raise ShardIndexError(
                f"{shard_num_hosts} hosts but only {n_shards} shards — "
                "rebuild with more (smaller) shards so every host owns at "
                "least one")
        # deterministic round-robin shard ownership + the collective batch
        # count (min over hosts) — computed once from the index, identically
        # on every host
        per_host_counts = []
        for h in range(shard_num_hosts):
            per_host_counts.append(sum(
                int(index.shards[s]["count"])
                for s in range(h, n_shards, shard_num_hosts)))
        self._own_shards = list(range(shard_index, n_shards, shard_num_hosts))
        self._own = np.concatenate([
            np.arange(index.shard_start(s),
                      index.shard_start(s) + int(index.shards[s]["count"]))
            for s in self._own_shards])
        if drop_last:
            self._n_batches = min(per_host_counts) // batch_size
        else:
            self._n_batches = -(-min(per_host_counts) // batch_size)

    def __len__(self):
        return self._n_batches

    def _indices_for_epoch(self, epoch: int) -> np.ndarray:
        own = self._own
        if self.shuffle:
            rng = np.random.default_rng(self.seed + epoch)
            own = own[rng.permutation(len(own))]
        if self.drop_last:
            own = own[: self._n_batches * self.batch_size]
        return own

    def _epoch_indices(self) -> np.ndarray:
        return self._indices_for_epoch(self.epoch)

    # --- cursor contract -------------------------------------------------

    def state_dict(self) -> dict:
        """The folder loader's (seed, epoch, cursor) triple PLUS the shard
        cursor: the shard-list fingerprint (resume must see the same shard
        set) and the (shard, offset) coordinate of the next unconsumed
        sample — redundant with (seed, epoch, cursor) for replay, but it
        is the operator-readable "where in the corpus was I" answer and a
        cross-check that the restored permutation still maps to the same
        physical bytes."""
        state = super().state_dict()
        state["fingerprint"] = self.ds.index.fingerprint
        indices = self._indices_for_epoch(state["epoch"])
        pos = state["cursor"] * self.batch_size
        if 0 <= pos < len(indices):
            s, j = self.ds.index.locate(int(indices[pos]))
            state["shard"], state["offset"] = int(s), int(j)
        else:  # epoch boundary: nothing left to consume
            state["shard"], state["offset"] = -1, -1
        return state

    def load_state_dict(self, state: dict) -> None:
        state = dict(state)
        fp = _normalize_fp(state.pop("fingerprint", None))
        if fp is not None and fp != self.ds.index.fingerprint:
            raise ShardIndexError(
                f"resume cursor was written against shard fingerprint {fp} "
                f"but this shard set is {self.ds.index.fingerprint} — the "
                "shard list changed; a bitwise resume is impossible "
                "(rebuild the shards or start fresh)")
        state.pop("shard", None)
        state.pop("offset", None)
        super().load_state_dict(state)
        # cross-check the diagnostic coordinate when present: a stale index
        # with the same fingerprint cannot happen (fingerprint covers size
        # + crc), so this only guards cursor arithmetic drift
        # (intentionally no hard failure — replay is pinned by the triple)


class DevicePrefetcher:
    """Double-buffer between a loader and the step loop: pull (and
    optionally device-place) the next batch while the current step runs,
    and meter what the step loop actually waited.

    Yields ``(host_batch, placed_batch)`` when ``place`` is given (the
    trainers pass ``Partitioner.shard_batch``), else ``host_batch``.
    ``depth`` batches are pulled ahead; ``jax.device_put`` is async, so a
    placed batch costs host time only when the *host-side* pipeline is the
    bottleneck — which is exactly what ``last_wait_s`` then shows.

    Cursor correctness: the wrapped loader counts batches it *produced*,
    which runs ``depth`` ahead of what the trainer has consumed — a
    checkpoint recording the producer cursor would SKIP never-trained
    batches on resume.  This wrapper snapshots ``loader.state_dict()`` at
    pull time of each batch and republishes the snapshot of the batch
    currently held by the consumer, so ``state_dict()`` here is always the
    resume-correct cursor.  Trainers must checkpoint THIS state_dict, not
    the loader's.
    """

    def __init__(self, loader, place: Optional[Callable] = None,
                 depth: int = 1, stall_event_s: float = 1.0):
        self.loader = loader
        self.place = place
        self.depth = max(0, int(depth))
        # substantial stalls (>= stall_event_s of host wait for one batch)
        # become discrete telemetry events; the continuous metric still
        # rides every step record via last_wait_s, so this only marks the
        # outliers an operator would want on the timeline
        self.stall_event_s = float(stall_event_s)
        self._state: Optional[dict] = None
        self.last_wait_s = 0.0
        self.total_wait_s = 0.0
        self.batches = 0

    def state_dict(self) -> dict:
        if self._state is not None:
            return dict(self._state)
        return self.loader.state_dict()

    def load_state_dict(self, state: dict) -> None:
        self._state = None
        self.loader.load_state_dict(state)

    def __len__(self):
        return len(self.loader)

    def __iter__(self):
        import time
        from collections import deque

        inner = iter(self.loader)
        pending: deque = deque()
        waited = [0.0]  # host time spent pulling since the last yield

        def pull() -> bool:
            t0 = time.perf_counter()
            try:
                batch = next(inner)
            except StopIteration:
                return False
            placed = self.place(batch) if self.place is not None else None
            snap = self.loader.state_dict()
            waited[0] += time.perf_counter() - t0
            pending.append((batch, placed, snap))
            return True

        for _ in range(1 + self.depth):
            if not pull():
                break
        while pending:
            batch, placed, snap = pending.popleft()
            self._state = snap
            self.last_wait_s, waited[0] = waited[0], 0.0
            self.total_wait_s += self.last_wait_s
            self.batches += 1
            if self.last_wait_s >= self.stall_event_s:
                telemetry.emit("data", "loader_stall",
                               wait_s=self.last_wait_s, batch=self.batches)
            yield (batch, placed) if self.place is not None else batch
            pull()


# --- shard building (the library half of tools/make_shards.py) ------------


def _discover_pairs(folder: Path):
    """(key, image path, caption path) triples by file stem, exactly the
    pairing rule of ``TextImageDataset`` — sorted keys, so global sample
    index ``g`` in the shard set equals index ``g`` of the folder dataset
    (the property the cross-format bitwise tests rest on)."""
    text_files = {p.stem: p for p in folder.rglob("*.txt")}
    image_files = {p.stem: p for p in folder.rglob("*")
                   if p.suffix.lower() in IMAGE_EXTS}
    keys = sorted(image_files.keys() & text_files.keys())
    return [(k, image_files[k], text_files[k]) for k in keys]


def _discover_images(folder: Path):
    """Images in sorted-path order, the ``ImageFolderDataset`` rule."""
    paths = sorted(p for p in folder.rglob("*")
                   if p.suffix.lower() in IMAGE_EXTS)
    # keys must be unique and filesystem-safe: relative path with / -> __
    return [(str(p.relative_to(folder).with_suffix("")).replace("/", "__"),
             p, None) for p in paths]


def build_shards(src, out, samples_per_shard: int = 512,
                 image_only: bool = False) -> dict:
    """Convert a folder dataset into tar shards + an ``index.json``.

    Deterministic end to end: samples in sorted-key order, fixed tar
    metadata (mtime 0, uid/gid 0, USTAR-compatible GNU format), member
    bytes copied verbatim — rebuilding from the same folder reproduces the
    same shard bytes and therefore the same fingerprint.  Shard files land
    via temp + ``os.replace`` and the index publishes LAST (the index is
    the shard set's manifest: a crash mid-build leaves temps, never a
    readable-but-wrong shard set).  Returns the index dict.
    """
    import io
    import tarfile

    src, out = Path(src), Path(out)
    samples = (_discover_images(src) if image_only else _discover_pairs(src))
    if not samples:
        raise ShardIndexError(f"no samples found under {src}")
    out.mkdir(parents=True, exist_ok=True)
    samples_per_shard = max(1, int(samples_per_shard))
    shards = []
    for si in range(0, len(samples), samples_per_shard):
        chunk = samples[si:si + samples_per_shard]
        name = f"shard-{si // samples_per_shard:06d}.tar"
        tmp = out / f".tmp-{name}"
        member_names = []
        with tarfile.open(tmp, "w", format=tarfile.GNU_FORMAT) as tar:
            for key, img_path, txt_path in chunk:
                for path, suffix in ((img_path, img_path.suffix.lower()),
                                     (txt_path, ".txt")):
                    if path is None:
                        continue
                    data = path.read_bytes()
                    ti = tarfile.TarInfo(name=f"{key}{suffix}")
                    ti.size = len(data)
                    ti.mtime = 0
                    ti.uid = ti.gid = 0
                    ti.uname = ti.gname = ""
                    tar.addfile(ti, io.BytesIO(data))
                member_names.append(key)
        # second pass over the finished tar: record every member's payload
        # offset (offset_data) for pread-addressable sample reads, and the
        # shard's size + crc32 for the index manifest
        offsets = {}
        with tarfile.open(tmp, "r") as tar:
            for m in tar.getmembers():
                offsets[m.name] = (int(m.offset_data), int(m.size))
        crc = 0
        size = 0
        with open(tmp, "rb") as f:
            while True:
                buf = f.read(1 << 20)
                if not buf:
                    break
                size += len(buf)
                crc = zlib.crc32(buf, crc)
        recs = []
        for key, img_path, txt_path in chunk:
            img_name = f"{key}{img_path.suffix.lower()}"
            rec = {"key": key, "image": img_name,
                   "image_offset": offsets[img_name][0],
                   "image_size": offsets[img_name][1]}
            if txt_path is not None:
                rec.update(caption=f"{key}.txt",
                           caption_offset=offsets[f"{key}.txt"][0],
                           caption_size=offsets[f"{key}.txt"][1])
            recs.append(rec)
        os.replace(tmp, out / name)
        shards.append({"name": name, "count": len(chunk), "size": size,
                       "crc32": f"{crc:08x}", "samples": recs})
    index = {"schema": INDEX_SCHEMA, "num_samples": len(samples),
             "has_captions": not image_only, "shards": shards}
    # the index IS the shard set's commit record — atomic publish, last
    atomic_write_json(out / INDEX_NAME, index)
    return index
