"""Data pipeline: image folders and paired text-image datasets.

Capability parity with the reference's two datasets:
* ``ImageFolderDataset`` — resize + center-crop image folder for VAE training
  (`/root/reference/train_vae.py:71-79`, torchvision ``ImageFolder``).
* ``TextImageDataset`` — pairs ``*.txt`` caption files with images by file
  stem, samples a random caption line, RandomResizedCrop
  (`/root/reference/train_dalle.py:201-247`).

Design: pure Python/numpy/PIL producers feeding a threaded prefetcher
(`Prefetcher`).  Outputs are numpy NHWC float32 in [0, 1] — device transfer
and sharding happen in the train loop (parallel/backend.py), keeping the
loader host-only.  Per-host sharding (`shard_num_hosts``/``shard_index``)
replaces torch's ``DistributedSampler`` (`train_dalle.py:261-269`).
"""
from __future__ import annotations

import threading
from pathlib import Path
from typing import Iterator, Optional, Sequence

import numpy as np

from ..obs import telemetry
from ..utils import faults
from ..utils import locks

IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp")


def _load_image(path: Path):
    from PIL import Image

    # faultpoint: GRAFT_FAULTS="sample_read:every=K" makes every K-th read
    # raise, rehearsing the retry/quarantine degradation path below
    faults.fire("sample_read")
    img = Image.open(path)
    img.load()  # force the decode now — PIL is lazy, and the dataset's
    # skip-bad-sample handler must see truncated-file errors here
    if img.mode != "RGB":
        img = img.convert("RGB")
    return img


def _crop_resize_f32(img, top: float, left: float, ch: float, cw: float,
                     size: int) -> np.ndarray:
    """Crop box -> bilinear `size`x`size` -> [0,1] f32.  Uses the fused
    native kernel (data/native.py) when the library is available, else the
    PIL three-pass path."""
    from . import native

    if native.available():
        out = native.crop_resize_normalize(
            np.asarray(img, np.uint8), top, left, ch, cw, size)
        if out is not None:
            return out
    from PIL import Image

    # one rounding for each origin so width/height stay exactly round(cw/ch)
    l, t = round(left), round(top)
    cropped = img.crop((l, t, l + round(cw), t + round(ch)))
    return np.asarray(cropped.resize((size, size), Image.BILINEAR),
                      np.float32) / 255.0


def center_crop_resize(img, size: int) -> np.ndarray:
    """Resize-shortest-side + center crop (ref train_vae.py:71-79) as one
    source-space center-square crop -> [size, size, 3] f32."""
    w, h = img.size
    side = min(w, h)
    left, top = (w - side) / 2.0, (h - side) / 2.0
    return _crop_resize_f32(img, top, left, side, side, size)


def random_resized_crop(img, size: int, rng: np.random.Generator,
                        scale=(0.6, 1.0), ratio=(1.0, 1.0)) -> np.ndarray:
    """RandomResizedCrop with the reference's settings: area scale in
    ``(resize_ratio, 1)``, aspect ratio fixed to 1 (train_dalle.py:227)."""
    w, h = img.size
    area = w * h
    for _ in range(10):
        target_area = area * rng.uniform(scale[0], scale[1])
        aspect = np.exp(rng.uniform(np.log(ratio[0]), np.log(ratio[1])))
        cw = int(round(np.sqrt(target_area * aspect)))
        ch = int(round(np.sqrt(target_area / aspect)))
        if 0 < cw <= w and 0 < ch <= h:
            left = int(rng.integers(0, w - cw + 1))
            top = int(rng.integers(0, h - ch + 1))
            return _crop_resize_f32(img, top, left, ch, cw, size)
    return center_crop_resize(img, size)  # fallback, as torchvision does


def make_pair(caption_text: str, load_image, tokenizer, text_len: int,
              truncate_captions: bool, image_size: int, resize_ratio: float,
              rng: np.random.Generator):
    """The ONE (caption, image) sample-decode sequence, shared by the folder
    dataset and the streaming shard reader (data/stream.py) so the two
    formats stay bitwise-interchangeable: caption line draw FIRST, then
    tokenize, then the (possibly failing) image load, then the crop draws —
    any reordering changes which rng draw feeds which decision and breaks
    the cross-format equality the streaming tests pin.  ``load_image`` is a
    thunk so a failed image read happens *after* the caption draw, exactly
    as the folder path has always sequenced it."""
    descriptions = [line for line in caption_text.split("\n") if line.strip()]
    if not descriptions:
        raise ValueError("empty caption text")
    description = descriptions[int(rng.integers(len(descriptions)))]
    tokens = tokenizer.tokenize(
        description, text_len, truncate_text=truncate_captions)[0]
    img = load_image()
    arr = random_resized_crop(img, image_size, rng,
                              scale=(resize_ratio, 1.0))
    return tokens, arr


class ImageFolderDataset:
    """Recursively lists images under `folder`; yields [H, W, 3] float32."""

    def __init__(self, folder: str | Path, image_size: int = 128, train: bool = True):
        self.paths = sorted(
            p for p in Path(folder).rglob("*") if p.suffix.lower() in IMAGE_EXTS
        )
        assert len(self.paths) > 0, f"no images found under {folder}"
        self.image_size = image_size
        self.train = train

    def __len__(self):
        return len(self.paths)

    def __getitem__(self, idx: int) -> np.ndarray:
        img = _load_image(self.paths[idx])
        return center_crop_resize(img, self.image_size)


class TextImageDataset:
    """Stem-paired (caption txt, image) dataset (train_dalle.py:201-247)."""

    def __init__(self, folder: str | Path, tokenizer, text_len: int = 256,
                 image_size: int = 128, resize_ratio: float = 0.6,
                 truncate_captions: bool = False, seed: int = 0):
        path = Path(folder)
        text_files = {p.stem: p for p in path.rglob("*.txt")}
        image_files = {
            p.stem: p for p in path.rglob("*") if p.suffix.lower() in IMAGE_EXTS
        }
        keys = sorted(image_files.keys() & text_files.keys())
        self.keys = keys
        self.text_files = {k: text_files[k] for k in keys}
        self.image_files = {k: image_files[k] for k in keys}
        self.tokenizer = tokenizer
        self.text_len = text_len
        self.image_size = image_size
        self.resize_ratio = resize_ratio
        self.truncate_captions = truncate_captions
        self.seed = seed
        self.epoch = 0  # set by the DataLoader each epoch (set_epoch)
        # graceful degradation: samples whose reads keep failing are
        # quarantined (skipped for the rest of the run) instead of killing
        # a pod-scale job over one unreadable JPEG — but a *rotten* dataset
        # must still fail loudly, so the quarantine is capped.
        self._quarantined: set = set()
        self._quarantine_lock = locks.TracedLock("dataset.quarantine")
        self.max_quarantine = max(8, len(keys) // 20)

    def __len__(self):
        return len(self.keys)

    def set_epoch(self, epoch: int) -> None:
        """Epoch for plain ``ds[i]`` access (DistributedSampler-style).  The
        DataLoader does NOT rely on this mutable state — it passes the epoch
        explicitly via :meth:`item` at submit time, so overlapping iterators
        / shared datasets cannot race the augmentation seeding."""
        self.epoch = int(epoch)

    def __getitem__(self, idx: int):
        return self.item(idx, self.epoch)

    def _quarantine(self, key: str, err: Exception) -> None:
        """Mark a sample as unreadable for the rest of the run (logged,
        capped).  Raises once the cap trips: a run skipping >5% of its data
        is training on a different dataset and must fail loudly."""
        with self._quarantine_lock:
            self._quarantined.add(key)
            n = len(self._quarantined)
        telemetry.note(
            "data", "sample_quarantine",
            f"quarantining sample {key} "
            f"({n}/{self.max_quarantine} quarantined): {err}",
            prefix="warning:", stream="stdout", key=key, quarantined=n)
        if n > self.max_quarantine:
            raise RuntimeError(
                f"TextImageDataset: {n} samples quarantined (cap "
                f"{self.max_quarantine}) — the dataset folder is rotten, "
                "refusing to silently train on what is left")

    def _read_sample(self, key: str, rng):
        try:
            return make_pair(
                self.text_files[key].read_text(),
                lambda: _load_image(self.image_files[key]),
                self.tokenizer, self.text_len, self.truncate_captions,
                self.image_size, self.resize_ratio, rng)
        except ValueError as e:
            if "empty caption text" in str(e):
                raise ValueError(
                    f"empty caption file {self.text_files[key]}") from None
            raise

    def item(self, idx: int, epoch: int):
        # fresh per-call Generator: numpy Generators are not thread-safe and
        # this runs concurrently under the prefetching DataLoader.  Seeding
        # by (seed, idx, epoch) — each index is visited once per epoch —
        # makes augmentation reproducible across runs and thread schedules
        # (a shared draw counter would depend on both).
        rng = np.random.default_rng((self.seed, idx, epoch))

        # graceful degradation: retry the sample once (transient I/O — NFS
        # blips, injected faults — usually passes on the second read), then
        # quarantine it and walk to a neighboring index rather than aborting
        # the epoch on one corrupt image / empty caption.
        max_attempts = min(len(self), 16)
        for attempt in range(max_attempts):
            key = self.keys[(idx + attempt) % len(self)]
            if key in self._quarantined:
                continue
            last_err = None
            for retry in range(2):
                try:
                    return self._read_sample(key, rng)
                except (OSError, ValueError) as e:
                    last_err = e
            self._quarantine(key, last_err)
        raise RuntimeError(
            f"TextImageDataset: {max_attempts} consecutive samples failed to "
            f"load starting at index {idx} — check the dataset folder")


class DataLoader:
    """Shuffling, batching, host-sharding iterator with threaded prefetch.

    `shard_num_hosts`/`shard_index` give each JAX process a disjoint slice of
    every epoch's permutation with drop-last semantics — the GSPMD analog of
    torch's DistributedSampler (ref train_dalle.py:261-269).
    """

    def __init__(self, dataset, batch_size: int, shuffle: bool = True,
                 drop_last: bool = True, seed: int = 0,
                 shard_num_hosts: int = 1, shard_index: int = 0,
                 num_workers: int = 8, prefetch: int = 4):
        self.ds = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.epoch = 0
        self.shard_num_hosts = shard_num_hosts
        self.shard_index = shard_index
        self.num_workers = num_workers
        self.prefetch = prefetch
        self._iter_epoch = 0   # epoch of the in-flight iterator
        self._cursor = 0       # batches delivered this epoch (incl. skipped)
        self._skip = 0         # batches to skip at the next __iter__ (resume)

    # --- exact mid-epoch resume ------------------------------------------

    def state_dict(self) -> dict:
        """Position snapshot for exact resume: (seed, epoch, cursor) pin the
        permutation and the batch inside it, so a run killed at step N
        restarts at step N+1 with the same sample order — the loader is
        seeded-deterministic, so three ints are the whole state."""
        return {"seed": int(self.seed), "epoch": int(self._iter_epoch),
                "cursor": int(self._cursor)}

    def load_state_dict(self, state: dict) -> None:
        """Restore a :meth:`state_dict`: the next ``__iter__`` replays the
        recorded epoch's permutation and skips the already-consumed
        batches.  A cursor at the epoch boundary (``cursor == len(self)``)
        yields an empty epoch — the trainer replays its epoch-end
        bookkeeping (scheduler step) exactly once and moves on, which is
        what a checkpoint written after the last batch but before the
        epoch-end step requires."""
        self.seed = int(state.get("seed", self.seed))
        epoch = int(state.get("epoch", 0))
        cursor = int(state.get("cursor", 0))
        self.epoch = epoch
        self._iter_epoch = epoch
        self._cursor = cursor
        self._skip = cursor

    def __len__(self):
        n = len(self.ds) // self.shard_num_hosts
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _epoch_indices(self) -> np.ndarray:
        n = len(self.ds)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            idx = rng.permutation(n)
        else:
            idx = np.arange(n)
        per_host = n // self.shard_num_hosts
        return idx[self.shard_index * per_host : (self.shard_index + 1) * per_host]

    def _fetch(self, idx: int, epoch: int):
        """One item, with the epoch threaded explicitly into augmentation
        seeding when the dataset supports it — captured per iterator, so
        overlapping/abandoned iterators can't race each other's epochs."""
        if hasattr(self.ds, "item"):
            return self.ds.item(int(idx), epoch)
        return self.ds[int(idx)]

    def __iter__(self) -> Iterator:
        indices = self._epoch_indices()
        epoch = self.epoch
        self.epoch += 1
        self._iter_epoch = epoch
        batches = [
            indices[i : i + self.batch_size]
            for i in range(0, len(indices) - self.batch_size + 1, self.batch_size)
        ]
        if not self.drop_last and len(indices) % self.batch_size:
            batches.append(indices[-(len(indices) % self.batch_size):])

        # resume skip: drop the batches a restored run already consumed;
        # _cursor keeps counting from the skip offset so a checkpoint taken
        # mid-epoch records the TRUE position in the permutation
        skip, self._skip = self._skip, 0
        self._cursor = skip
        batches = batches[skip:]

        if self.num_workers <= 0:
            inner = (self._collate([self._fetch(i, epoch) for i in b])
                     for b in batches)
        else:
            inner = self._prefetch_iter(batches, epoch)
        for batch in inner:
            # incremented BEFORE the yield: while the train loop holds batch
            # k, state_dict() reports cursor k+1 — exactly the batches a
            # checkpoint written after this step must skip on resume
            self._cursor += 1
            yield batch

    def _collate(self, items):
        from . import native

        def stack(col):
            if (col and isinstance(col[0], np.ndarray)
                    and col[0].dtype == np.float32):
                out = native.batch_collate(list(col))
                if out is not None:
                    return out
            return np.stack(col)

        if isinstance(items[0], tuple):
            cols = list(zip(*items))
            return tuple(stack(c) for c in cols)
        return stack(items)

    def _prefetch_iter(self, batches, epoch: int):
        """Ordered prefetch with real backpressure: at most `prefetch`
        batches are in flight; the consumer blocks on the next future."""
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        def load(batch_idx):
            return self._collate([self._fetch(i, epoch) for i in batch_idx])

        with ThreadPoolExecutor(max_workers=self.num_workers) as ex:
            pending = deque()
            it = iter(batches)
            for b in batches[: self.prefetch]:
                pending.append(ex.submit(load, b))
                next(it)
            while pending:
                yield pending.popleft().result()
                nxt = next(it, None)
                if nxt is not None:
                    pending.append(ex.submit(load, nxt))
