"""Shared plumbing for the CLI entry scripts (train_vae / train_dalle /
generate / genrank): tokenizer selection, checkpoint reconstitution, chunked
generation, and multi-host-safe host fetches.

One implementation instead of the reference's per-script copies
(tokenizer selection: ref train_dalle.py:105-112 vs generate.py:59-66;
model loading: ref generate.py:72-87 vs genrank.py:25-44).
"""
from __future__ import annotations

from pathlib import Path
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import DALLE, DALLEConfig, DiscreteVAE, VAEConfig
from .data.tokenizer import ChineseTokenizer, HugTokenizer, SimpleTokenizer
from .utils.helpers import env_flag
from .models.dalle import (decode_codes, generate_codes, prefill_codes,
                           tile_prefill)
from .utils.checkpoint import (load_checkpoint, migrate_head_kernels,
                               migrate_qkv_kernels)


def enable_compilation_cache(path: Optional[str] = None,
                             min_compile_secs: float = 1.0) -> None:
    """Persistent XLA compilation cache: TPU first-compiles run 20-40s, so
    CLI reruns (resume, generate sweeps, genrank over checkpoint lists)
    should pay that once.  Off when DALLE_TPU_NO_COMPILE_CACHE is set.
    First configuration wins: a later call (e.g. a tool invoked in-process
    by a test after tests/conftest.py configured the cache) never silently
    retunes the threshold or redirects the directory."""
    import os

    if env_flag("DALLE_TPU_NO_COMPILE_CACHE"):
        return
    # graftlint: disable=ENV001 (path-valued var: empty/unset mean default)
    path = path or os.environ.get(
        "DALLE_TPU_COMPILE_CACHE", os.path.expanduser("~/.cache/dalle_tpu_xla"))
    try:
        if jax.config.jax_compilation_cache_dir:
            return  # already configured in this process: first wins
        # the dir knob goes LAST: it is the on/off switch, so a partial
        # configuration (an older jax missing one of the optional knobs
        # below) must leave the cache off — making the except-branch's
        # "run uncached" message true rather than leaving an enabled,
        # unbounded cache behind
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
        # LRU-bound the on-disk cache: the persistent cache never evicts by
        # default, so long-lived dev boxes / CI caches would accrete stale
        # HLO entries forever (a full test-suite run writes ~8 MB)
        jax.config.update("jax_compilation_cache_max_size", 256 * 2**20)
        jax.config.update("jax_compilation_cache_dir", path)
    except AttributeError as e:  # older jax without the knobs: run uncached
        import sys

        print(f"compilation cache unavailable: {e}", file=sys.stderr)


def apply_platform_env() -> None:
    """Honor an explicit ``JAX_PLATFORMS=cpu`` over a sitecustomize-registered
    PJRT plugin.

    The axon TPU tunnel's ``register()`` (run from sitecustomize at
    interpreter start) pins ``jax_platforms`` to the tunnel backend
    in-process, which silently overrides a ``JAX_PLATFORMS=cpu`` passed in
    the environment — and when the tunnel is wedged, backend init then
    hangs forever inside the first ``jax.devices()`` with no exception.
    CPU-only tools (loss curves, converters) call this right after
    importing jax so the documented env contract holds.

    Deliberately one-directional: only a cpu-first env value is applied.
    The ambient environment carries ``JAX_PLATFORMS=axon`` everywhere, so
    re-applying a non-cpu value would *undo* an in-process
    ``jax.config.update("jax_platforms", "cpu")`` made by a host that then
    calls a tool's main() (tests/conftest.py does exactly that) — flipping
    the suite onto the tunnel backend mid-run.
    """
    import os

    p = os.environ.get("JAX_PLATFORMS", "")
    if p.split(",")[0].strip() == "cpu":
        jax.config.update("jax_platforms", p)


def select_tokenizer(bpe_path: Optional[str], chinese: bool = False):
    """Tokenizer priority matching the reference (train_dalle.py:105-112):
    explicit BPE file > chinese > CLIP SimpleTokenizer.  The CLIP merges txt
    is data we don't bundle, so the default also needs --bpe_path; json
    selects the HF tokenizer, anything else the CLIP BPE."""
    if bpe_path is not None:
        if str(bpe_path).endswith('.json'):
            return HugTokenizer(bpe_path)
        return SimpleTokenizer(bpe_path)
    if chinese:
        return ChineseTokenizer()
    raise SystemExit(
        '--bpe_path is required: pass the CUB HF-tokenizer json '
        '(cub200_bpe_vsize_7800.json) or a CLIP merges txt '
        '(bpe_simple_vocab_16e6.txt)')


def load_dalle_checkpoint(dalle_path: str | Path, taming: bool = False):
    """Rebuild DALLE + VAE from a checkpoint (ref generate.py:72-87), with
    the same VAE priority: stored custom VAE hparams > pretrained
    (OpenAI dVAE, or VQGAN when `taming`).

    Returns (dalle, cfg, params, vae, vae_params).
    """
    dalle_path = Path(dalle_path)
    assert dalle_path.exists(), 'trained DALL-E must exist'
    ckpt = load_checkpoint(dalle_path)
    dalle_params = dict(ckpt['hparams'])
    dalle_params.pop('vae', None)  # legacy cleanup (ref generate.py:75)
    vae_hparams = ckpt.get('vae_params')

    if vae_hparams is not None:
        vae = DiscreteVAE(VAEConfig.from_dict(dict(vae_hparams)))
        vae_weights = ckpt.get('vae_weights')
        vae_params = (jax.tree.map(jnp.asarray, vae_weights)
                      if vae_weights is not None else None)
    else:
        from .models.pretrained_vae import OpenAIDiscreteVAE, VQGanVAE1024

        vae = VQGanVAE1024() if taming else OpenAIDiscreteVAE()
        vae._require_params()
        vae_params = None

    cfg = DALLEConfig.from_dict(dalle_params)
    dalle = DALLE(cfg)
    weights = migrate_qkv_kernels(ckpt['weights'], dim_head=cfg.dim_head)
    weights = migrate_head_kernels(weights, cfg.total_text_tokens)
    params = jax.tree.map(jnp.asarray, weights)
    return dalle, cfg, params, vae, vae_params


def make_decode_fn(vae, vae_params):
    """Jitted codes -> [b, h, w, 3] float images in [0, 1]."""

    @jax.jit
    def decode(codes):
        if isinstance(vae, DiscreteVAE):
            return vae.apply({'params': vae_params}, codes,
                             method=DiscreteVAE.decode)
        return vae.decode(codes)

    return decode


def iter_generated_chunks(dalle, params, text_tokens: np.ndarray, *,
                          batch_size: int, top_k: float, rng,
                          temperature: float = 1.0,
                          top_p: Optional[float] = None):
    """Sample image codes for [n, text_seq_len] tokens in ``batch_size``
    chunks.  Returns ``(chunks, rng)`` where ``chunks`` yields
    ``(codes [batch_size, image_seq_len] device array, n_valid)`` — codes
    stay on device so downstream consumers (the VAE decode, genrank's fused
    CLIP scorer) can keep the whole pipeline as device arrays.

    **Shared prompt prefill**: when every row is the same prompt (the
    generate/genrank candidate fan-out builds tokens as
    ``np.repeat(prompt, num_images)``), the prompt is prefilled ONCE at
    batch 1 and the resulting KV caches broadcast across the candidate
    batch (``models.dalle.tile_prefill``) — exact, because the prompt
    positions' k/v never depend on the sampled continuation.  Each chunk
    then pays only the decode scan instead of decode + a redundant
    full-sequence prefill forward.  Requests with distinct prompts (the
    pickled-captions eval mode) keep the per-chunk ``generate_codes``
    path, padding the last chunk to hold one compiled shape.
    """
    n = text_tokens.shape[0]
    if n == 0:
        return iter(()), rng
    # one short request compiles at its natural size; padding only pays for
    # itself when it saves a recompile across multiple chunks
    batch_size = min(batch_size, n)
    n_chunks = -(-n // batch_size)
    keys = jax.random.split(rng, n_chunks + 1)
    rng_out, keys = keys[0], keys[1:]
    shared = bool(np.all(np.asarray(text_tokens) == text_tokens[:1]))

    if shared:
        decode_fn = jax.jit(lambda p, fl, c, k: decode_codes(
            dalle, p, fl, c, k, filter_thres=top_k, temperature=temperature,
            top_p=top_p))

        def gen_shared():
            first1, caches1 = jax.jit(
                lambda p, t: prefill_codes(dalle, p, t))(
                    {'params': params},
                    jnp.asarray(text_tokens[:1], jnp.int32))
            first, caches = tile_prefill(first1, caches1, batch_size)
            for i in range(n_chunks):
                codes = decode_fn({'params': params}, first, caches, keys[i])
                yield codes, min(batch_size, n - i * batch_size)

        return gen_shared(), rng_out

    def gen_distinct():
        for i in range(n_chunks):
            chunk = text_tokens[i * batch_size: (i + 1) * batch_size]
            pad = batch_size - chunk.shape[0]
            if pad:
                chunk = np.concatenate([chunk, np.repeat(chunk[-1:], pad, 0)])
            codes = generate_codes(dalle, {'params': params},
                                   jnp.asarray(chunk, jnp.int32), keys[i],
                                   filter_thres=top_k,
                                   temperature=temperature, top_p=top_p)
            yield codes, batch_size - pad

    return gen_distinct(), rng_out


def generate_chunked(dalle, params, decode, text_tokens: np.ndarray, *,
                     batch_size: int, top_k: float, rng,
                     temperature: float = 1.0, top_p: Optional[float] = None,
                     desc: str = 'generating'):
    """Generate images for [n, text_seq_len] tokens in `batch_size` chunks
    (`iter_generated_chunks` semantics: one shared prompt prefill when all
    rows are identical).  Returns (images [n, h, w, 3], rng).
    """
    outs = []
    n = text_tokens.shape[0]
    chunks, rng = iter_generated_chunks(
        dalle, params, text_tokens, batch_size=batch_size, top_k=top_k,
        rng=rng, temperature=temperature, top_p=top_p)
    done = 0
    for codes, n_valid in chunks:
        images = np.asarray(jax.device_get(decode(codes)))
        outs.append(images[:n_valid])
        done += n_valid
        print(f'{desc}: {done}/{n}', flush=True)
    return (np.concatenate(outs) if outs else np.zeros((0,))), rng


def host_fetch(tree):
    """Fetch a (possibly GSPMD-sharded) pytree to host numpy, multi-host
    safe.  Every process must call this together (collective): arrays that
    span non-addressable devices — including arrays replicated over a
    multi-host mesh — are reassembled with a tiled allgather so each
    process ends up holding the FULL global value (root then writes the
    file); only leaves living entirely on this process's devices are plain
    device fetches."""
    if jax.process_count() == 1:
        return jax.device_get(tree)
    from jax.experimental import multihost_utils

    def fetch(leaf):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            # tiled=True: concatenate the per-process shards back into the
            # logical global array (tiled=False would stack a bogus leading
            # process axis — and rejects global arrays outright)
            return multihost_utils.process_allgather(leaf, tiled=True)
        return jax.device_get(leaf)

    return jax.tree.map(fetch, tree)
