"""Transformer stack: LayerScale / PreNorm / GEGLU-FF blocks + executors.

Capability parity with `/root/reference/dalle_pytorch/transformer.py`:
* LayerScale with depth-staged init (0.1 / 1e-5 / 1e-6 for layer index <=18 /
  <=24 / >24; ref :28-42);
* PreNorm + GEGLU feed-forward, mult=4 (ref :44-69);
* per-layer attention type cycled from ``attn_types`` (ref :93-109);
* executor choice: sequential residual stack or reversible two-stream
  (ref :116-120), with the kwarg router semantics that only attention layers
  receive ``mask`` (ref :117-118).

TPU-native deltas: optional `jax.checkpoint` rematerialization per block
(the standard XLA memory-saving move), a true O(1)-activation reversible
executor built on `jax.custom_vjp` (ops/reversible.py) replacing torch's
autograd.Function + RNG replay, and a KV-cache `decode_step` used by the
jitted sampler.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..obs import prof
from ..utils.helpers import cast_tuple, default
from .attention import AttnPattern, MultiHeadAttention
from .reversible import reversible_sequence, reversible_sequence_naive


def layerscale_init(layer_index: int) -> float:
    """ref transformer.py:28-42 (arg is 1-based layer index)."""
    if layer_index <= 18:
        return 0.1
    if layer_index <= 24:
        return 1e-5
    return 1e-6


class AttnBlock(nn.Module):
    """LayerScale(PreNorm(attention)) (ref transformer.py:111-113)."""

    pattern: AttnPattern
    dim: int
    layer_index: int
    heads: int = 8
    dim_head: int = 64
    dropout: float = 0.0
    use_pallas: bool = False
    pallas_block_q: int = 128
    pallas_block_k: int = 128
    ring_axis: Optional[str] = None
    sp_impl: str = "ring"
    sliced_kv_decode: bool = True
    aligned_span_decode: bool = True
    dtype: Any = jnp.float32

    def setup(self):
        self.norm = nn.LayerNorm(dtype=jnp.float32, name="norm")
        self.attn = MultiHeadAttention(
            pattern=self.pattern, dim=self.dim, heads=self.heads,
            dim_head=self.dim_head, dropout=self.dropout,
            use_pallas=self.use_pallas,
            pallas_block_q=self.pallas_block_q,
            pallas_block_k=self.pallas_block_k,
            ring_axis=self.ring_axis,
            sp_impl=self.sp_impl,
            sliced_kv_decode=self.sliced_kv_decode,
            aligned_span_decode=self.aligned_span_decode, dtype=self.dtype,
            name="attn",
        )
        self.scale = self.param(
            "scale",
            lambda key, shape: jnp.full(shape, layerscale_init(self.layer_index)),
            (1, 1, self.dim),
        )

    def __call__(self, x, mask=None, deterministic: bool = True,
                 return_kv: bool = False):
        with prof.scope("attn-qkv"):
            normed = self.norm(x).astype(x.dtype)
        out = self.attn(normed, mask=mask,
                        deterministic=deterministic, return_kv=return_kv)
        if return_kv:
            h, kv = out
            with prof.scope("attn-out"):
                return h * self.scale.astype(h.dtype), kv
        with prof.scope("attn-out"):
            return out * self.scale.astype(out.dtype)

    def decode_step(self, x, cache_k, cache_v, index, mask=None,
                    write_pos=None, qw=None):
        with prof.scope("attn-qkv"):
            normed = self.norm(x).astype(x.dtype)
        h, ck, cv = self.attn.decode_step(
            normed, cache_k, cache_v, index, mask=mask,
            write_pos=write_pos, qw=qw
        )
        with prof.scope("attn-out"):
            return h * self.scale.astype(h.dtype), ck, cv

    def decode_span(self, x, cache_k, cache_v, qpos, rot, valid, qw=None):
        """K-token speculative span (see MultiHeadAttention.decode_span);
        same norm -> attn -> layerscale shape as :meth:`decode_step`."""
        with prof.scope("attn-qkv"):
            normed = self.norm(x).astype(x.dtype)
        h, ck, cv = self.attn.decode_span(
            normed, cache_k, cache_v, qpos, rot, valid, qw=qw)
        with prof.scope("attn-out"):
            return h * self.scale.astype(h.dtype), ck, cv


class FFBlock(nn.Module):
    """LayerScale(PreNorm(GEGLU feed-forward)) (ref transformer.py:53-69)."""

    dim: int
    layer_index: int
    mult: int = 4
    dropout: float = 0.0
    dtype: Any = jnp.float32

    def setup(self):
        inner = int(self.dim * self.mult)
        self.norm = nn.LayerNorm(dtype=jnp.float32, name="norm")
        self.dense_in = nn.Dense(inner * 2, dtype=self.dtype, name="dense_in")
        self.dense_out = nn.Dense(self.dim, dtype=self.dtype, name="dense_out")
        self.drop = nn.Dropout(self.dropout)
        self.scale = self.param(
            "scale",
            lambda key, shape: jnp.full(shape, layerscale_init(self.layer_index)),
            (1, 1, self.dim),
        )

    def __call__(self, x, deterministic: bool = True, qw=None):
        """``qw`` (decode path only, ``weights_int8``): this layer's
        session-quantized kernels ``{"ff_in": (int8, scale, bias),
        "ff_out": ...}`` — the GEGLU runs with int8 multiplicands and f32
        accumulation instead of touching the f32 params."""
        from .quant import qdense

        with prof.scope("ff"):
            normed = self.norm(x).astype(x.dtype)
            if qw is not None:
                h = qdense(normed, *qw["ff_in"]).astype(x.dtype)
            else:
                h = self.dense_in(normed)
            h, gates = jnp.split(h, 2, axis=-1)
            h = h * nn.gelu(gates)
            h = self.drop(h, deterministic=deterministic)
            if qw is not None:
                h = qdense(h, *qw["ff_out"]).astype(x.dtype)
            else:
                h = self.dense_out(h)
            return h * self.scale.astype(h.dtype)


class MoEFFBlock(nn.Module):
    """LayerScale(PreNorm(MoE feed-forward)) — the FFBlock with its GEGLU
    swapped for a top-k routed expert mixture (ops/moe.py).  The switch
    load-balance loss is sown into the ``losses`` collection as
    ``moe_aux``; training loops read it via ``mutable=['losses']``."""

    dim: int
    layer_index: int
    num_experts: int = 8
    top_k: int = 2
    mult: int = 4
    dropout: float = 0.0
    dispatch: str = "dense"
    capacity_factor: float = 1.25
    capacity_group: int = 1024
    dtype: Any = jnp.float32

    def setup(self):
        from .moe import MoEFeedForward

        self.norm = nn.LayerNorm(dtype=jnp.float32, name="norm")
        self.moe = MoEFeedForward(
            dim=self.dim, num_experts=self.num_experts, top_k=self.top_k,
            mult=self.mult, dropout=self.dropout, dispatch=self.dispatch,
            capacity_factor=self.capacity_factor,
            capacity_group=self.capacity_group, dtype=self.dtype,
            name="moe")
        self.scale = self.param(
            "scale",
            lambda key, shape: jnp.full(shape, layerscale_init(self.layer_index)),
            (1, 1, self.dim),
        )

    def __call__(self, x, deterministic: bool = True):
        with prof.scope("ff"):
            h, aux = self.moe(self.norm(x).astype(x.dtype),
                              deterministic=deterministic)
            self.sow("losses", "moe_aux", aux)
            return h * self.scale.astype(h.dtype)


class Transformer(nn.Module):
    """Depth x (attn, ff) residual stack with cycled attention variants
    (ref transformer.py:71-123)."""

    dim: int
    depth: int
    seq_len: int
    causal: bool = True
    heads: int = 8
    dim_head: int = 64
    ff_mult: int = 4
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    attn_types: Optional[Tuple[str, ...]] = None
    image_fmap_size: Optional[int] = None
    text_len: Optional[int] = None     # text positions incl <bos>
    reversible: bool = False
    reversible_naive: bool = False  # test hook: plain-autodiff two-stream
    use_remat: bool = False
    use_pallas: bool = False   # Pallas flash/block-sparse attention kernels
    pallas_block_q: int = 128
    pallas_block_k: int = 128
    ring_axis: Optional[str] = None  # sequence-parallel axis (inside shard_map)
    sp_impl: str = "ring"            # 'ring' | 'ulysses' (all-to-all)
    sliced_kv_decode: bool = True    # decode gathers only reachable keys
    aligned_span_decode: bool = True  # serve-path circular reads as spans
    ff_experts: int = 0        # >1: MoE feed-forward with this many experts
    ff_expert_top_k: int = 2
    ff_expert_dispatch: str = "dense"        # 'dense' | 'capacity'
    ff_expert_capacity_factor: float = 1.25
    ff_expert_capacity_group: int = 1024
    sparse_layout_seed: int = 0
    dtype: Any = jnp.float32

    def setup(self):
        attn_types = cast_tuple(default(self.attn_types, ("full",)))
        fmap = default(self.image_fmap_size, 0)
        text_len = default(
            self.text_len,
            self.seq_len + 1 - fmap * fmap if fmap else self.seq_len + 1,
        )
        attn_blocks = []
        ff_blocks = []
        for ind in range(self.depth):
            variant = attn_types[ind % len(attn_types)]
            pattern = AttnPattern(
                variant=variant, seq_len=self.seq_len, text_len=text_len,
                fmap=fmap, causal=self.causal,
                layout_seed=self.sparse_layout_seed + ind,
            )
            attn_blocks.append(AttnBlock(
                pattern=pattern, dim=self.dim, layer_index=ind + 1,
                heads=self.heads, dim_head=self.dim_head,
                dropout=self.attn_dropout, use_pallas=self.use_pallas,
                pallas_block_q=self.pallas_block_q,
                pallas_block_k=self.pallas_block_k,
                ring_axis=self.ring_axis, sp_impl=self.sp_impl,
                sliced_kv_decode=self.sliced_kv_decode,
                aligned_span_decode=self.aligned_span_decode,
                dtype=self.dtype,
                name=f"layers_{ind}_attn",
            ))
            if self.ff_experts > 1:
                ff_blocks.append(MoEFFBlock(
                    dim=self.dim, layer_index=ind + 1,
                    num_experts=self.ff_experts, top_k=self.ff_expert_top_k,
                    mult=self.ff_mult, dropout=self.ff_dropout,
                    dispatch=self.ff_expert_dispatch,
                    capacity_factor=self.ff_expert_capacity_factor,
                    capacity_group=self.ff_expert_capacity_group,
                    dtype=self.dtype, name=f"layers_{ind}_ff",
                ))
            else:
                ff_blocks.append(FFBlock(
                    dim=self.dim, layer_index=ind + 1, mult=self.ff_mult,
                    dropout=self.ff_dropout, dtype=self.dtype,
                    name=f"layers_{ind}_ff",
                ))
        self.attn_blocks = attn_blocks
        self.ff_blocks = ff_blocks

    def _block_apply(self, x, ind: int, mask, deterministic: bool):
        """One (attn, ff) residual block — a method so lifted transforms
        (nn.remat) can thread params AND mutable collections (MoE's sown
        aux losses) through it; a raw jax.checkpoint closure would leak
        tracers out of any sown value."""
        x = x + self.attn_blocks[ind](x, mask=mask, deterministic=deterministic)
        x = x + self.ff_blocks[ind](x, deterministic=deterministic)
        return x

    def __call__(self, x, mask=None, deterministic: bool = True,
                 return_kv: bool = False):
        if self.reversible and not self.is_initializing():
            return self._reversible_call(x, mask, deterministic, return_kv)

        use_remat = (self.use_remat and not self.is_initializing()
                     and not return_kv)
        remat_block = nn.remat(
            Transformer._block_apply, static_argnums=(2, 4)) if use_remat else None

        kvs = []
        for ind in range(self.depth):
            if return_kv:
                h, kv = self.attn_blocks[ind](
                    x, mask=mask, deterministic=deterministic, return_kv=True)
                kvs.append(kv)
                x = x + h
                x = x + self.ff_blocks[ind](x, deterministic=deterministic)
            elif use_remat:
                x = remat_block(self, x, ind, mask, deterministic)
            else:
                x = self._block_apply(x, ind, mask, deterministic)
        if return_kv:
            return x, kvs
        return x

    def _reversible_call(self, x, mask, deterministic, return_kv: bool = False):
        """Two-stream reversible executor (ref reversible.py:143-157):
        duplicate the channels, run y1 = x1 + f(x2); y2 = x2 + g(y1), output
        the mean of both streams.  O(1) activation memory via custom_vjp."""
        # custom_vjp functions cannot close over traced values, so a (traced)
        # padding mask rides inside the differentiable f-params pytree as a
        # float leaf (where() grads wrt the condition are zero; the cotangent
        # is computed and discarded).
        mask_f = None if mask is None else mask.astype(jnp.float32)
        f_fns, f_params, g_fns, g_params = [], [], [], []
        for attn, ff in zip(self.attn_blocks, self.ff_blocks):
            unbound_attn, attn_vars = attn.unbind()
            unbound_ff, ff_vars = ff.unbind()

            def f_fn(p, h, m=unbound_attn):
                key_mask = None if p.get("mask") is None else p["mask"] > 0.5
                return m.apply({"params": p["params"]}, h, mask=key_mask,
                               deterministic=deterministic)

            def g_fn(p, h, m=unbound_ff):
                return m.apply({"params": p}, h, deterministic=deterministic)

            f_fns.append(f_fn)
            f_params.append({"params": attn_vars["params"], "mask": mask_f})
            g_fns.append(g_fn)
            g_params.append(ff_vars["params"])

        assert deterministic or (self.attn_dropout == 0 and self.ff_dropout == 0), (
            "the reversible executor requires deterministic blocks (no dropout); "
            "the reference replays RNG state instead (reversible.py:20-50)"
        )
        assert self.ff_experts <= 1, (
            "the reversible executor's custom_vjp cannot thread the MoE "
            "load-balance aux losses; sowing would silently no-op"
        )
        if return_kv:
            # prefill path (no grads): run the two-stream loop inline so each
            # attention's k/v can be captured for the KV cache.
            x1 = x2 = x
            kvs = []
            for attn, ff in zip(self.attn_blocks, self.ff_blocks):
                h, kv = attn(x2, mask=mask, deterministic=deterministic,
                             return_kv=True)
                kvs.append(kv)
                x1 = x1 + h
                x2 = x2 + ff(x1, deterministic=deterministic)
            return (x1 + x2) / 2, kvs
        executor = (reversible_sequence_naive if self.reversible_naive
                    else reversible_sequence)
        y1, y2 = executor(
            tuple(f_fns), tuple(g_fns), tuple(f_params), tuple(g_params), x, x
        )
        return (y1 + y2) / 2

    def decode_init_cache(self, batch: int, dtype=None):
        """Zeroed KV caches, one (k, v) pair per layer: [b, h, seq_len, dh]."""
        dtype = dtype or self.dtype
        shape = (batch, self.heads, self.seq_len, self.dim_head)
        return [
            (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(self.depth)
        ]

    def decode_step(self, x, caches, index, mask=None, write_pos=None,
                    qweights=None):
        """Single-token pass: x [b, 1, dim], per-layer KV caches, traced
        absolute position `index`.  Returns (out, new_caches).

        ``write_pos`` enables the phase-aligned serving mode (``index``
        may be per-row, caches rotated, one shared physical write column —
        see MultiHeadAttention.decode_step).  ``qweights`` is the
        per-layer list of session-quantized int8 kernels
        (models/dalle.py::quantize_decode_weights) consumed by the
        attention projections and the FF blocks under ``weights_int8``.

        Mirrors the executor the model trains with: residual stack, or the
        reversible two-stream recurrence (whose attention reads the x2
        stream — caches must match what training computed)."""
        qws = qweights if qweights is not None else [None] * self.depth
        new_caches = []
        if self.reversible:
            x1 = x2 = x
            for attn, ff, (ck, cv), qw in zip(self.attn_blocks,
                                              self.ff_blocks, caches, qws):
                h, ck, cv = attn.decode_step(x2, ck, cv, index, mask=mask,
                                             write_pos=write_pos, qw=qw)
                x1 = x1 + h
                # MoE FF blocks take no qw (weights_int8 asserts them away)
                x2 = x2 + (ff(x1, qw=qw) if qw is not None else ff(x1))
                new_caches.append((ck, cv))
            return (x1 + x2) / 2, new_caches
        for attn, ff, (ck, cv), qw in zip(self.attn_blocks, self.ff_blocks,
                                          caches, qws):
            h, ck, cv = attn.decode_step(x, ck, cv, index, mask=mask,
                                         write_pos=write_pos, qw=qw)
            x = x + h
            x = x + (ff(x, qw=qw) if qw is not None else ff(x))
            new_caches.append((ck, cv))
        return x, new_caches

    def decode_span(self, x, caches, qpos, rot, valid, depth_limit=None,
                    qweights=None):
        """K-token speculative span pass: x [b, K, dim] at logical
        positions ``qpos`` [b, K], per-row cache rotation ``rot`` [b],
        write-validity ``valid`` [b, K].  Returns (out, new_caches).

        ``depth_limit`` (static) runs only the FIRST that many blocks —
        the self-speculative shallow-exit draft; the untouched deeper
        layers' caches pass through unchanged, and the verify pass
        (depth_limit=None) later overwrites every span position at every
        layer, so a draft's partial writes never outlive their tick.

        Residual executor only: the reversible two-stream recurrence
        feeds each attention the x2 stream, whose value at a span
        position depends on the previous position's FF output — a K-wide
        pass can't form it without sequentializing, which is exactly what
        the span exists to avoid."""
        assert not self.reversible, (
            "speculative span decode supports the residual executor only; "
            "the reversible two-stream recurrence is inherently sequential "
            "across positions")
        depth = self.depth if depth_limit is None else depth_limit
        assert 0 < depth <= self.depth, (
            f"depth_limit {depth_limit} outside (0, {self.depth}]")
        qws = qweights if qweights is not None else [None] * self.depth
        new_caches = list(caches)
        for ind in range(depth):
            attn, ff, qw = self.attn_blocks[ind], self.ff_blocks[ind], qws[ind]
            ck, cv = new_caches[ind]
            h, ck, cv = attn.decode_span(x, ck, cv, qpos, rot, valid, qw=qw)
            x = x + h
            x = x + (ff(x, qw=qw) if qw is not None else ff(x))
            new_caches[ind] = (ck, cv)
        return x, new_caches
