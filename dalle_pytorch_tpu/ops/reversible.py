"""Reversible (RevNet) sequence executor with O(1) activation memory.

TPU-native replacement for the reference's torch implementation
(`/root/reference/dalle_pytorch/reversible.py:54-157`): a custom-vjp function
whose backward *reconstructs* each block's inputs from its outputs —
``x2 = y2 - g(y1); x1 = y1 - f(x2)`` — instead of storing activations
(ref ``backward_pass`` algebra at reversible.py:70-106).

Where torch needs CPU+CUDA RNG state capture/replay to make dropout match
between forward and recompute (ref ``Deterministic``, reversible.py:20-50),
JAX's explicit RNG threading makes recomputation deterministic by
construction; the executor itself is deterministic (callers must run blocks
without stateful randomness, which holds for the models here — dropout is
disabled under the reversible executor).

`f_fns[i]`/`g_fns[i]` are pure ``(params, x) -> y`` functions (the attention
and feed-forward blocks); params are explicit pytrees so gradients flow.
"""
from __future__ import annotations

import functools
from typing import Callable, Tuple

import jax


def _forward(f_fns, g_fns, f_params, g_params, x1, x2):
    for f, g, pf, pg in zip(f_fns, g_fns, f_params, g_params):
        x1 = x1 + f(pf, x2)
        x2 = x2 + g(pg, x1)
    return x1, x2


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def reversible_sequence(f_fns: Tuple[Callable, ...], g_fns: Tuple[Callable, ...],
                        f_params, g_params, x1, x2):
    """Run the two-stream reversible stack; returns (y1, y2)."""
    return _forward(f_fns, g_fns, f_params, g_params, x1, x2)


def _fwd(f_fns, g_fns, f_params, g_params, x1, x2):
    y1, y2 = _forward(f_fns, g_fns, f_params, g_params, x1, x2)
    # Only the *outputs* and params are saved — no per-layer activations.
    return (y1, y2), (f_params, g_params, y1, y2)


def _bwd(f_fns, g_fns, res, cts):
    f_params, g_params, y1, y2 = res
    dy1, dy2 = cts
    df_params, dg_params = [], []

    for f, g, pf, pg in zip(f_fns[::-1], g_fns[::-1],
                            list(f_params)[::-1], list(g_params)[::-1]):
        # invert g: x2 = y2 - g(y1), accumulate its vjp into dy1
        gy1, g_vjp = jax.vjp(g, pg, y1)
        x2 = y2 - gy1
        dpg, dy1_from_g = g_vjp(dy2)
        dy1 = dy1 + dy1_from_g

        # invert f: x1 = y1 - f(x2), accumulate its vjp into dy2
        fx2, f_vjp = jax.vjp(f, pf, x2)
        x1 = y1 - fx2
        dpf, dx2_from_f = f_vjp(dy1)
        dy2 = dy2 + dx2_from_f

        df_params.append(dpf)
        dg_params.append(dpg)
        y1, y2 = x1, x2

    return tuple(df_params[::-1]), tuple(dg_params[::-1]), dy1, dy2


reversible_sequence.defvjp(_fwd, _bwd)


def reversible_sequence_naive(f_fns, g_fns, f_params, g_params, x1, x2):
    """Same two-stream forward under plain autodiff (stores activations).
    Used when the input needs kwargs custom_vjp can't carry (e.g. a traced
    padding mask at generation prefill) and for gradient-equivalence tests."""
    return _forward(f_fns, g_fns, f_params, g_params, x1, x2)
