"""Mixture-of-Experts feed-forward with expert parallelism.

The reference has no MoE (its FF is a single GEGLU block,
`/root/reference/dalle_pytorch/transformer.py:53-69`); this is scaling
headroom alongside the framework's other mesh axes (dp/fsdp/tp in mesh.py,
sp in ring.py/ulysses.py, pp in pipeline.py): widen FF *capacity* (params)
by ``num_experts`` while the top-k router keeps each token's output a
mixture of k experts.

TPU-native design choices:
* **dense one-hot dispatch** — combine weights are a [tokens, experts]
  matrix multiplied through stacked expert kernels with einsum.  No
  scatter/gather, no dynamic shapes: everything is MXU matmuls that GSPMD
  shards cleanly.  NOTE: dense dispatch computes every expert for every
  token, so FF *FLOPs* scale with ``num_experts`` (the savings are in
  params-per-token statistics, not compute); capacity-factor token
  dropping — the TPU trick that makes FLOPs scale with ``top_k`` — is the
  designated later optimization.
* **expert parallelism by sharding annotation** — expert-stacked kernels
  carry a leading ``num_experts`` axis; `Partitioner`-style regex rules or
  an explicit `with_sharding_constraint` put that axis on an ``ep`` mesh
  axis and XLA inserts the all-to-alls.  The module itself stays a pure
  function — same philosophy as the rest of the framework (the reference's
  NCCL machinery became shardings, SURVEY.md §2.3).
* **router in f32**, switch-style load-balance auxiliary loss (mean
  fraction x mean probability per expert), returned separately so callers
  weight it.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEFeedForward(nn.Module):
    """Top-k routed GEGLU expert FF: drop-in for FFBlock's inner compute.

    Output = sum over selected experts of gate * expert_ff(x); with
    ``num_experts=1`` this reduces exactly to a single GEGLU FF (up to the
    router's constant gate of 1.0).
    """

    dim: int
    num_experts: int = 8
    top_k: int = 2
    mult: int = 4
    dropout: float = 0.0   # on the expert inner activations (FFBlock parity)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        """x: [b, n, dim] -> (y: [b, n, dim], aux_loss: scalar f32)."""
        e, d = self.num_experts, self.dim
        inner = int(d * self.mult)
        k = min(self.top_k, e)

        # --- router (f32 for a stable softmax) ---
        router = nn.Dense(e, dtype=jnp.float32, name="router")
        logits = router(x.astype(jnp.float32))  # [b, n, e]
        probs = jax.nn.softmax(logits, axis=-1)

        # top-k combine weights, renormalized over the selected experts
        top_p, top_idx = jax.lax.top_k(probs, k)               # [b, n, k]
        onehot = jax.nn.one_hot(top_idx, e, dtype=probs.dtype)  # [b, n, k, e]
        combine = (top_p[..., None] * onehot).sum(axis=-2)      # [b, n, e]
        combine = combine / jnp.clip(
            combine.sum(axis=-1, keepdims=True), 1e-9)

        # --- switch-style load-balance loss (f32) ---
        # fraction of tokens whose top-1 lands on each expert x mean prob
        top1 = jax.nn.one_hot(jnp.argmax(probs, axis=-1), e, dtype=jnp.float32)
        aux = (top1.mean(axis=(0, 1)) * probs.mean(axis=(0, 1))).sum() * e

        # --- expert-stacked GEGLU kernels: leading axis e shards on 'ep' ---
        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, d, inner * 2)).astype(self.dtype)
        b_in = self.param("b_in", nn.initializers.zeros,
                          (e, inner * 2)).astype(self.dtype)
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, inner, d)).astype(self.dtype)
        b_out = self.param("b_out", nn.initializers.zeros,
                           (e, d)).astype(self.dtype)

        xc = x.astype(self.dtype)
        # dense dispatch: every expert sees every token; the combine matrix
        # zeroes the non-routed ones.  [b, n, d] x [e, d, 2i] -> [b, n, e, 2i]
        h = jnp.einsum("bnd,edi->bnei", xc, w_in) + b_in
        h, gates = jnp.split(h, 2, axis=-1)
        h = h * nn.gelu(gates)
        # dropout on the inner activation, matching FFBlock's placement
        # (between the GEGLU gate and the output projection)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        y = jnp.einsum("bnei,eid->bned", h, w_out) + b_out  # [b, n, e, d]
        y = jnp.einsum("bned,bne->bnd", y, combine.astype(self.dtype))
        return y.astype(x.dtype), aux.astype(jnp.float32)


def ep_shard_moe_params(params: dict, mesh, ep_axis: str = "ep"):
    """NamedSharding tree putting every MoE expert-stacked leaf's leading
    axis on ``ep_axis`` and replicating everything else.  Feed to
    `jax.device_put` / `jit(..., in_shardings=...)`."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def spec_for(path, leaf):
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if any(n in ("w_in", "b_in", "w_out", "b_out") for n in names):
            return NamedSharding(mesh, P(ep_axis))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec_for, params)
