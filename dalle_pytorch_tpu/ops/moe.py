"""Mixture-of-Experts feed-forward with expert parallelism.

The reference has no MoE (its FF is a single GEGLU block,
`/root/reference/dalle_pytorch/transformer.py:53-69`); this is scaling
headroom alongside the framework's other mesh axes (dp/fsdp/tp in mesh.py,
sp in ring.py/ulysses.py, pp in pipeline.py): widen FF *capacity* (params)
by ``num_experts`` while the top-k router keeps each token's output a
mixture of k experts.

TPU-native design choices:
* **two dispatch modes**, both static-shaped and einsum-only (no
  scatter/gather, no dynamic shapes — everything is MXU matmuls that GSPMD
  shards cleanly):
  - ``dispatch='dense'``: every expert sees every token; the combine
    matrix zeroes the non-routed outputs.  FF *FLOPs* scale with
    ``num_experts`` — simplest and exact, right at small expert counts.
  - ``dispatch='capacity'``: GShard/Switch-style fixed expert capacity
    within token *groups* of ``capacity_group`` tokens: per group,
    ``C = ceil(top_k · g / e · capacity_factor)`` slots per expert.
    One-hot dispatch/combine tensors [G, g, e, C] route each token to a
    slot (position-in-expert via cumsum, no sort); tokens over a group's
    capacity are DROPPED for that expert (their residual passes
    through).  Grouping keeps dispatch memory and FLOPs linear in token
    count (≈ T·k·cf·g dispatch-matmul elements) — ungrouped [T, e, C]
    dispatch would be quadratic in T.  Expert FF FLOPs scale with
    ``top_k · capacity_factor`` instead of ``num_experts``.
* **expert parallelism by sharding annotation** — expert-stacked kernels
  carry a leading ``num_experts`` axis; `Partitioner`-style regex rules or
  an explicit `with_sharding_constraint` put that axis on an ``ep`` mesh
  axis and XLA inserts the all-to-alls.  The module itself stays a pure
  function — same philosophy as the rest of the framework (the reference's
  NCCL machinery became shardings, SURVEY.md §2.3).
* **router in f32**, switch-style load-balance auxiliary loss (mean
  fraction x mean probability per expert), returned separately so callers
  weight it.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEFeedForward(nn.Module):
    """Top-k routed GEGLU expert FF: drop-in for FFBlock's inner compute.

    Output = sum over selected experts of gate * expert_ff(x); with
    ``num_experts=1`` this reduces exactly to a single GEGLU FF (up to the
    router's constant gate of 1.0).
    """

    dim: int
    num_experts: int = 8
    top_k: int = 2
    mult: int = 4
    dropout: float = 0.0   # on the expert inner activations (FFBlock parity)
    dispatch: str = "dense"        # 'dense' | 'capacity'
    capacity_factor: float = 1.25  # only used by 'capacity' dispatch
    capacity_group: int = 1024     # tokens per dispatch group ('capacity')
    dtype: Any = jnp.float32

    def _expert_geglu(self, deterministic):
        """Returns the stacked-expert GEGLU: input flows through per-expert
        kernels with the expert axis named 'e' in the caller's einsum
        specs."""
        e, d = self.num_experts, self.dim
        inner = int(d * self.mult)
        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, d, inner * 2)).astype(self.dtype)
        b_in = self.param("b_in", nn.initializers.zeros,
                          (e, inner * 2)).astype(self.dtype)
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, inner, d)).astype(self.dtype)
        b_out = self.param("b_out", nn.initializers.zeros,
                           (e, d)).astype(self.dtype)

        def ff(h, in_spec, out_spec, expert_leading=False):
            # biases align on (e, last): in the [e, C, ...] layout the
            # expert axis leads, so give them a slot axis to broadcast over
            bi = b_in[:, None] if expert_leading else b_in
            bo = b_out[:, None] if expert_leading else b_out
            # graftlint: disable=DOT001 (uniform: h and w_in are both cast to self.dtype)
            h = jnp.einsum(in_spec, h, w_in) + bi
            h, gates = jnp.split(h, 2, axis=-1)
            h = h * nn.gelu(gates)
            # dropout on the inner activation, matching FFBlock's placement
            # (between the GEGLU gate and the output projection)
            h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
            # graftlint: disable=DOT001 (uniform: h and w_out are both cast to self.dtype)
            return jnp.einsum(out_spec, h, w_out) + bo

        return ff

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        """x: [b, n, dim] -> (y: [b, n, dim], aux_loss: scalar f32)."""
        e = self.num_experts
        k = min(self.top_k, e)
        assert self.dispatch in ("dense", "capacity"), (
            f"unknown MoE dispatch {self.dispatch!r}")

        # --- router (f32 for a stable softmax) ---
        router = nn.Dense(e, dtype=jnp.float32, name="router")
        logits = router(x.astype(jnp.float32))  # [b, n, e]
        probs = jax.nn.softmax(logits, axis=-1)

        # top-k combine weights, renormalized over the selected experts
        top_p, top_idx = jax.lax.top_k(probs, k)               # [b, n, k]
        onehot = jax.nn.one_hot(top_idx, e, dtype=probs.dtype)  # [b, n, k, e]
        combine = (top_p[..., None] * onehot).sum(axis=-2)      # [b, n, e]
        combine = combine / jnp.clip(
            combine.sum(axis=-1, keepdims=True), 1e-9)

        # --- switch-style load-balance loss (f32) ---
        # fraction of tokens whose top-1 lands on each expert x mean prob
        top1 = jax.nn.one_hot(jnp.argmax(probs, axis=-1), e, dtype=jnp.float32)
        aux = (top1.mean(axis=(0, 1)) * probs.mean(axis=(0, 1))).sum() * e

        ff = self._expert_geglu(deterministic)
        xc = x.astype(self.dtype)

        if self.dispatch == "dense":
            # every expert sees every token; combine zeroes the non-routed
            y = ff(xc, "bnd,edi->bnei", "bnei,eid->bned")  # [b, n, e, d]
            # graftlint: disable=DOT001 (uniform: combine is cast to y's self.dtype)
            y = jnp.einsum("bned,bne->bnd", y, combine.astype(self.dtype))
            return y.astype(x.dtype), aux.astype(jnp.float32)

        # --- capacity dispatch (GShard/Switch): per-group C slots/expert ---
        b, n, d = x.shape
        T = b * n
        g = min(self.capacity_group, T)
        G = -(-T // g)  # ceil
        Tp = G * g
        C = max(1, int(-(-k * g * self.capacity_factor // e)))  # ceil

        def pad(arr):
            return jnp.pad(arr, ((0, Tp - T),) + ((0, 0),) * (arr.ndim - 1))

        flat_gate = pad(combine.reshape(T, e)).reshape(G, g, e)
        flat_idx = pad(top_idx.reshape(T, k)).reshape(G, g, k)
        xf = pad(xc.reshape(T, d)).reshape(G, g, d)
        # padding tokens must not consume capacity slots
        valid = pad(jnp.ones((T, 1), jnp.int32)).reshape(G, g, 1)

        # slot assignment: per routing priority j, position-in-expert via a
        # cumulative count over token order within the group (no sort,
        # static shapes); one_hot(pos, C) is all-zero past capacity, which
        # is exactly the drop
        counts = jnp.zeros((G, e), jnp.int32)
        dispatch = jnp.zeros((G, g, e, C), self.dtype)
        for j in range(k):
            oh = jax.nn.one_hot(flat_idx[..., j], e, dtype=jnp.int32) * valid
            pos = jnp.cumsum(oh, axis=1) - oh + counts[:, None]   # [G, g, e]
            pos_tok = (pos * oh).sum(-1)                          # [G, g]
            slot = jax.nn.one_hot(pos_tok, C, dtype=self.dtype)   # [G, g, C]
            dispatch = dispatch + (oh.astype(self.dtype)[..., None]
                                   * slot[:, :, None, :])
            counts = counts + oh.sum(axis=1)

        combine_slots = dispatch * flat_gate.astype(self.dtype)[..., None]
        # graftlint: disable=DOT001 (uniform: dispatch is built in self.dtype, xf cast to it)
        expert_in = jnp.einsum("gtec,gtd->gecd", dispatch, xf)  # [G, e, C, d]
        y = ff(expert_in, "gecd,edi->geci", "geci,eid->gecd",
               expert_leading=True)                             # [G, e, C, d]
        # graftlint: disable=DOT001 (uniform: combine_slots and y are both self.dtype)
        out = jnp.einsum("gtec,gecd->gtd", combine_slots, y)    # dropped -> 0
        out = out.reshape(Tp, d)[:T]
        return out.reshape(b, n, d).astype(x.dtype), aux.astype(jnp.float32)


def ep_shard_moe_params(params: dict, mesh, ep_axis: str = "ep"):
    """NamedSharding tree putting every MoE expert-stacked leaf's leading
    axis on ``ep_axis`` and replicating everything else.  Feed to
    `jax.device_put` / `jit(..., in_shardings=...)`."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def spec_for(path, leaf):
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if any(n in ("w_in", "b_in", "w_out", "b_out") for n in names):
            return NamedSharding(mesh, P(ep_axis))  # graftlint: disable=PLAN001 (expert banks shard over the ep axis by POSITION (leading expert dim), which a path-regex rule table cannot express)
        return NamedSharding(mesh, P())  # graftlint: disable=PLAN001 (router/norm leaves replicate on the ep mesh — the ep plan owns its inner axis, outside PARTITION_RULES by design)

    return jax.tree_util.tree_map_with_path(spec_for, params)
