"""Int8 quantization primitives for the decode/serve path.

The decode loop is measured HBM-bandwidth-bound (PERF.md: sliced-KV
2.16x, bf16 cache <=0.6x cache I/O — every win so far cut *bytes*), so
the next multiplicative lever is storing the two dominant byte streams
at one byte per element: the KV caches (``DALLEConfig.kv_cache_int8``)
and the decode-path weight matrices (``DALLEConfig.weights_int8``).
This module is the shared math; the consumers are
``ops/attention.py`` (cache write/read on both decode paths),
``models/dalle.py`` (prefill quantization + one-shot weight
quantization per generate session) and ``serve/engine.py`` (the slot
arena's int8 planes).

Scale-layout contract (DESIGN.md §12):

* **KV caches** — symmetric per-head scales: an int8 values tensor
  ``[b, heads, n, dh]`` rides with an f32 scale plane ``[b, heads, 1,
  1]`` (per *slot* per head in the serve arena, where the batch axis is
  slots).  The scale is computed once at prefill write time over the
  whole prefilled cache; later single-token decode writes quantize with
  that frozen scale and SATURATE (new outliers clip at +-127 rather
  than rescaling — rescaling would rewrite the whole cache and defeat
  the byte cut).  A cache entry is the pair ``(values int8, scale
  f32)`` wherever a plain array was before; every consumer goes through
  :func:`split_cache` so the two layouts share one code path.
* **Weights** — symmetric per-output-channel scales: kernel ``[in,
  ...out]`` quantizes along ``axis=0`` to int8 with an f32 scale of
  shape ``[1, ...out]``.  Quantization happens ONCE per generate/serve
  session (:func:`models.dalle.quantize_decode_weights`); the decode
  program's weight inputs are then int8 + scales, never the f32
  originals.
* **Dots** — the int8 tensor is a *multiplicand*: every contraction
  runs ``int8 x bf16`` (or f32) operands with
  ``preferred_element_type=f32`` accumulation and applies the scale to
  the (small) f32 *product*, so XLA never sees — and can never hoist —
  a dequantized full-cache or full-weight copy (the exact failure mode
  the bf16 cache work caught, pinned by contract_check C3).
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

QMAX = 127.0
# floor for the symmetric scale: an all-zero tensor (a fresh arena slot,
# a zero-padded prefill tail) must quantize to zeros, not NaNs
_EPS = 1e-12

CacheLike = Union[jax.Array, Tuple[jax.Array, jax.Array]]


def quantize_symmetric(x, axis, *, eps: float = _EPS):
    """Symmetric int8 quantization of ``x`` over ``axis`` (kept as size-1
    dims in the returned f32 scale): ``x ~= q * scale``."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    s = jnp.maximum(s, eps) / QMAX
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -QMAX, QMAX)
    return q.astype(jnp.int8), s


def quantize_per_head(kv) -> Tuple[jax.Array, jax.Array]:
    """KV-cache quantization: ``[b, heads, n, dh]`` -> (int8 values,
    f32 ``[b, heads, 1, 1]`` scale) — the cache-entry layout the decode
    paths consume (one scale per head per sequence/slot)."""
    return quantize_symmetric(kv, axis=(2, 3))


def quantize_weight(w, axis: int = 0) -> Tuple[jax.Array, jax.Array]:
    """Per-output-channel weight quantization: reduce over the input
    ``axis`` so every output column keeps its own dynamic range."""
    return quantize_symmetric(w, axis=axis)


def split_cache(cache: CacheLike):
    """``(values, scale)`` of a cache entry: the int8 pair as-is, a plain
    f32/bf16 array as ``(array, None)`` — every cache consumer branches
    on the returned scale instead of the config flag, so the two layouts
    cannot drift."""
    if isinstance(cache, (tuple, list)):
        values, scale = cache
        return values, scale
    return cache, None


def cache_values(cache: CacheLike) -> jax.Array:
    return split_cache(cache)[0]


def requantize(new, scale: Optional[jax.Array], dtype):
    """A single decode-step k/v row, prepared for its cache write: cast
    for plain caches, saturating int8 quantization under the entry's
    frozen scale for quantized ones."""
    if scale is None:
        return new.astype(dtype)
    q = jnp.clip(jnp.round(new.astype(jnp.float32) / scale), -QMAX, QMAX)
    return q.astype(jnp.int8)


def cache_write(cache: CacheLike, new, start) -> CacheLike:
    """``dynamic_update_slice`` of one decode-step row into a cache entry
    of either layout (the scale plane is write-position-invariant)."""
    values, scale = split_cache(cache)
    updated = jax.lax.dynamic_update_slice(
        values, requantize(new, scale, values.dtype), start)
    if scale is None:
        return updated
    return (updated, scale)


def cache_write_rows(cache: CacheLike, new, rows, valid) -> CacheLike:
    """Write a K-wide span of decode rows into a cache entry of either
    layout at PER-ROW physical columns — the speculative-decode commit
    (``ops/attention.py::MultiHeadAttention.decode_span``).

    ``new`` is ``[b, heads, K, dh]``; ``rows`` ``[b, K]`` int32 gives each
    batch row's K physical cache columns (consecutive logical positions
    through the row's rotation, so the K indices within a row are always
    distinct); ``valid`` ``[b, K]`` bool keeps the resident value where
    False (positions past the row's remaining sequence must not wrap-write
    into live columns).  Unlike :func:`cache_write` this lowers to a
    scatter (per-row columns can't share one dynamic_update_slice) — the
    speculative path amortizes that cost over the K tokens it commits,
    and the greedy/serve tick keeps the aligned single-column write."""
    values, scale = split_cache(cache)
    q = requantize(new, scale, values.dtype)
    # invalid lanes re-write their current value: a gather+select keeps
    # the scatter's index set static (distinct within each row), which a
    # masked index would not
    cur = jnp.take_along_axis(values, rows[:, None, :, None], axis=2)
    upd = jnp.where(valid[:, None, :, None], q, cur)
    b = values.shape[0]
    updated = values.at[jnp.arange(b)[:, None], :, rows, :].set(
        upd.transpose(0, 2, 1, 3))
    if scale is None:
        return updated
    return (updated, scale)


def scaled_qdot(einsum_spec: str, a, qb, scale=None, *,
                mul_dtype=jnp.bfloat16):
    """Contraction with an int8 multiplicand: ``a`` (activations /
    attention weights) is cast to ``mul_dtype`` and contracted DIRECTLY
    against the int8 tensor with f32 accumulation; the f32 scale then
    multiplies the (small) product.  Keeping ``qb`` int8 inside the dot
    is the load-bearing property: upcasting it first would hand XLA a
    full-size dequantized copy to hoist out of the decode loop
    (contract_check C3 pins its absence)."""
    out = jnp.einsum(einsum_spec, a.astype(mul_dtype), qb,
                     preferred_element_type=jnp.float32)
    if scale is not None:
        out = out * scale
    return out


def qdense(x, qkernel, scale, bias=None, *, mul_dtype=jnp.bfloat16):
    """Quantized dense layer: ``x @ qkernel * scale (+ bias)`` with the
    int8 kernel as a direct multiplicand (f32 accumulation).  ``scale``
    is the per-output-channel plane ``[1, ...out]``; ``bias`` stays
    f32."""
    spec = {2: "...a,ab->...b", 4: "...a,abcd->...bcd"}[qkernel.ndim]
    out = jnp.einsum(spec, x.astype(mul_dtype), qkernel,
                     preferred_element_type=jnp.float32)
    out = out * scale
    if bias is not None:
        out = out + bias
    return out


def circular_slice_in_dim(values, start, size: int, axis: int = 2,
                          prefix=None):
    """Read a length-``size`` circular span ``[start, start+size) mod n``
    along ``axis`` with ONE dynamic_slice of HBM (plus a static prefix
    slice shared by every caller), then a cheap in-tile reorder.

    The rotated serve caches (ops/attention.py::_decode_step_aligned)
    need per-row circular windows; a general per-row gather touches the
    cache one key-row at a time, while this form reads two CONTIGUOUS
    blocks — ``hi`` at ``min(start, n - size)`` (covers the whole span
    when it doesn't wrap, its tail ``[start, n)`` when it does) and the
    static prefix ``[0, size)`` (covers the wrapped head) — and
    reassembles the span IN LOGICAL ORDER from the 2*size-element tile.
    The reorder is a take over the extracted tile, not the cache, so
    HBM sees only the block reads.  (The wrapped head has length
    ``start + size - n < size``, so it always fits the static prefix —
    any ``size <= n`` works.)

    ``prefix`` lets a vmapped caller hoist the row-invariant static
    prefix ``values[..., :size, :]`` OUT of the per-row map — it is read
    once for the whole batch, so the per-row dynamic work is exactly one
    span."""
    n = values.shape[axis]
    assert size <= n, f"span of {size} exceeds the cache length {n}"
    start = jnp.remainder(start, n)
    lo_bound = jnp.minimum(start, n - size)
    hi = jax.lax.dynamic_slice_in_dim(values, lo_bound, size, axis=axis)
    lo = (prefix if prefix is not None
          else jax.lax.slice_in_dim(values, 0, size, axis=axis))
    tile = jnp.concatenate([hi, lo], axis=axis)
    pos = start + jnp.arange(size)
    idx = jnp.where(pos < n, pos - lo_bound, size + pos - n)
    return jnp.take(tile, idx, axis=axis)
