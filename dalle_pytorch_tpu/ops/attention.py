"""Attention variants, unified as *pattern-masked* attention.

The reference implements four attention layers as separate torch modules
(`/root/reference/dalle_pytorch/attention.py`):

* ``Attention`` — full causal softmax attention (:27-66);
* ``SparseConvCausalAttention`` — image attends all text + a causal local
  kernel_size x kernel_size (dilated) neighborhood, via ``F.unfold``
  (:70-176);
* ``SparseAxialCausalAttention`` — image attends all text + causally along
  its row (axis=0) or column (axis=1) (:180-282);
* ``SparseAttention`` — DeepSpeed ``SparseSelfAttention`` CUDA/Triton kernel
  with ``VariableSparsityConfig`` (block 16, local window, random blocks,
  global text blocks, unidirectional) (:284-342).

TPU-native redesign: every variant is a *boolean attention pattern* over
absolute sequence positions.  One predicate (`_allowed`) defines each
pattern; it is evaluated three ways:

1. as a static dense [n, n] mask (numpy at trace time) for training — at the
   reference's sequence lengths (~1104) a dense masked softmax attention is
   already MXU-optimal, and XLA fuses the mask;
2. as a traced single row for the KV-cache decode step inside ``lax.scan``
   (the reference has no KV cache and reruns the full forward per token,
   dalle_pytorch.py:400-415 — we keep output parity, not work parity);
3. (later rounds) as a block mask feeding the Pallas flash/block-sparse
   kernels in ``ops/attention_pallas.py``.

Positions use the *padded* grid of the reference (:98-102): length
``seq_len + 1`` where the first ``text_len = text_seq_len + 1`` positions are
text (incl <bos>) and the rest is the ``fmap x fmap`` image raster.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..obs import prof
from ..utils.helpers import max_neg_value
from .quant import (cache_write, cache_write_rows, circular_slice_in_dim,
                    qdense, scaled_qdot, split_cache)

VARIANTS = ("full", "axial_row", "axial_col", "conv_like", "sparse")


def make_variable_sparse_layout(
    num_blocks: int,
    global_blocks: int,
    num_random_blocks: int,
    local_window_blocks: Tuple[int, ...] = (4,),
    causal: bool = True,
    seed: int = 0,
) -> np.ndarray:
    """Block-level layout with DeepSpeed ``VariableSparsityConfig`` semantics
    (ref attention.py:296-312): local windows, per-row random blocks, global
    (column-attended) text blocks, optionally unidirectional.  Deterministic
    via `seed` — the TPU analog of the kernel's fixed random layout.
    """
    layout = np.zeros((num_blocks, num_blocks), dtype=bool)

    # local windows: consecutive row groups attend within their own group;
    # the last window size repeats to cover the sequence.
    sizes = list(local_window_blocks)
    start = 0
    i = 0
    while start < num_blocks:
        w = sizes[i] if i < len(sizes) else sizes[-1]
        end = min(start + w, num_blocks)
        layout[start:end, start:end] = True
        start = end
        i += 1

    # random blocks: per block-row, `num_random_blocks` random block-columns
    # (restricted to <= row when causal).
    rng = np.random.default_rng(seed)
    for row in range(num_blocks):
        hi = row + 1 if causal else num_blocks
        cols = rng.integers(0, hi, size=num_random_blocks)
        layout[row, cols] = True

    # global blocks: every row attends the global (text) block-columns.
    layout[:, :global_blocks] = True

    if causal:
        layout &= np.tril(np.ones((num_blocks, num_blocks), dtype=bool))
    return layout


@dataclasses.dataclass(frozen=True)
class AttnPattern:
    """Static description of one layer's attention pattern."""

    variant: str
    seq_len: int          # transformer seq len (text_seq_len + image_seq_len)
    text_len: int         # text positions incl <bos> = text_seq_len + 1
    fmap: int             # image feature-map side; fmap**2 = image_seq_len
    causal: bool = True   # CLIP encoders use bidirectional 'full' attention
    kernel: int = 5       # conv_like kernel size (ref attention.py:71)
    dilation: int = 1
    block: int = 16       # sparse block size (ref attention.py:292)
    num_random_blocks: Optional[int] = None
    layout_seed: int = 0

    def __post_init__(self):
        assert self.variant in VARIANTS, f"unknown attention variant {self.variant}"
        if self.variant == "conv_like":
            assert self.kernel % 2 == 1, "kernel size must be odd"

    @property
    def padded_len(self) -> int:
        return self.seq_len + 1

    def block_layout(self) -> Optional[np.ndarray]:
        if self.variant != "sparse":
            return None
        n = self.padded_len
        nb = (n + self.block - 1) // self.block
        # defaults from the reference wrapper (attention.py:299-300):
        # random blocks = seq_len // block // 4, global blocks cover the text.
        num_random = (
            self.num_random_blocks
            if self.num_random_blocks is not None
            else self.seq_len // self.block // 4
        )
        global_blocks = -(-self.text_len // self.block)  # ceil
        return make_variable_sparse_layout(
            nb, global_blocks, num_random, causal=True, seed=self.layout_seed
        )


def _allowed(pattern: AttnPattern, i, j, xp, layout=None):
    """The pattern predicate: may query position `i` attend key position `j`?

    Works for both numpy (broadcast grid, static) and jnp (traced row).
    `i`/`j` are absolute positions on the padded grid.
    """
    T, W = pattern.text_len, pattern.fmap
    causal = (j <= i) if pattern.causal else (j == j)
    v = pattern.variant

    if v == "full":
        return causal

    if v == "sparse":
        if layout is None:
            layout = pattern.block_layout()
        lay = xp.asarray(layout)
        return causal & lay[i // pattern.block, j // pattern.block]

    # text queries attend text causally only (ref attention.py:113-123)
    text_q_allowed = causal & (j < T)

    # image query / key raster coordinates
    ri, ci = (i - T) // W, (i - T) % W
    rj, cj = (j - T) // W, (j - T) % W

    if v == "axial_row":
        img_pat = (rj == ri) & (cj <= ci)
    elif v == "axial_col":
        img_pat = (cj == ci) & (rj <= ri)
    elif v == "conv_like":
        pad = ((pattern.kernel - 1) * pattern.dilation + 1) // 2
        dr, dc = rj - ri, cj - ci
        in_window = (
            (xp.abs(dr) <= pad)
            & (xp.abs(dc) <= pad)
            & (dr % pattern.dilation == 0)
            & (dc % pattern.dilation == 0)
        )
        img_pat = in_window & causal
    else:  # pragma: no cover
        raise ValueError(v)

    img_q_allowed = xp.where(j < T, True, img_pat)
    return xp.where(i < T, text_q_allowed, img_q_allowed)


def dense_pattern_mask(pattern: AttnPattern, n_q: int, n_k: int) -> np.ndarray:
    """Static [n_q, n_k] boolean mask (True = attend), built with numpy at
    trace time so it becomes an XLA constant."""
    i = np.arange(n_q)[:, None]
    j = np.arange(n_k)[None, :]
    layout = pattern.block_layout()
    return np.asarray(_allowed(pattern, i, j, np, layout=layout))


def pattern_mask_row(pattern: AttnPattern, index, n_k: int,
                     layout: Optional[jax.Array] = None) -> jax.Array:
    """Traced mask row for decode: which of the `n_k` cached keys may the
    query at (traced) position `index` attend?"""
    j = jnp.arange(n_k)
    return _allowed(pattern, index, j, jnp, layout=layout)


def decode_key_positions(
        pattern: AttnPattern, index
) -> Optional[Tuple[jax.Array, jax.Array, bool]]:
    """Candidate key positions for ONE decode query at (traced) `index`.

    Decode queries are always image positions (only image tokens are
    sampled), and for the axial/conv patterns their reachable key set is a
    small, position-computable subset of the cache: all text plus the
    query's raster row / column / causal neighborhood rows.  Returning that
    superset (exactness is restored by ``_allowed`` over the returned
    positions) lets the decode step GATHER ~10% of the KV cache instead of
    streaming all of it through the masked dots — the decode loop is HBM-
    bandwidth-bound, so cache traffic is the throughput (the training path
    is unaffected; dense-masked attention there is MXU-optimal).

    Returns traced ``(positions [m] int32, valid [m] bool, contiguous)``
    with m static and ``contiguous`` a STATIC bool, or None for variants
    whose reachable set isn't smaller (full) or isn't position-local
    (sparse's random blocks).

    When ``contiguous`` is True the image segment ``positions[T:]`` is the
    ascending run ``positions[T] + arange(...)`` — the decode step then
    reads it with one ``dynamic_slice`` (cheap on TPU) instead of a general
    gather.  Contiguous candidate windows are CLIPPED into the raster
    (never just range-clipped at gather time): an out-of-image candidate
    clipped independently of its reported position would ALIAS onto a text
    position the text segment already carries, pass ``_allowed`` and
    double-count that key in the softmax.  Clipping the window start keeps
    reported positions == read positions; any extra in-window keys the
    query can't reach (shifted conv windows near the raster top, an
    image-row window under a text-region query) are exact-masked by
    ``_allowed``.  ``valid`` carries the residual validity for the strided
    (non-contiguous) conv case, whose out-of-raster rows can't be clipped
    without breaking the stride.
    """
    T, W = pattern.text_len, pattern.fmap
    v = pattern.variant
    ii = index - T
    ri, ci = ii // W, ii % W
    contiguous = False
    if v == "axial_row":
        # clip into the raster: a text-region query (legal through the
        # public decode_step API) has ri < 0; row 0's keys are then read
        # but fully masked by _allowed (text queries reach no image keys)
        row0 = jnp.clip(ri, 0, W - 1)
        img = T + row0 * W + jnp.arange(W)
        img_valid = jnp.ones((W,), bool)
        contiguous = True
    elif v == "axial_col":
        # ci = ii % W is non-negative even for text-region queries (jnp
        # remainder semantics), so every candidate is a real image position
        img = T + ci + jnp.arange(W) * W
        img_valid = jnp.ones((W,), bool)
    elif v == "conv_like":
        pad = ((pattern.kernel - 1) * pattern.dilation + 1) // 2
        # causality kills every row below the query's, so candidates are
        # the query row and the window rows above it, at the dilation
        # stride; each row is taken whole (W keys) and the window's column
        # extent is enforced by the predicate
        n_rows = pad // pattern.dilation + 1
        if pattern.dilation == 1:
            # contiguous ascending window [row0, row0 + n_rows), clipped
            # into the raster; shifted-in future rows are _allowed-masked.
            # A window taller than the raster (big kernel on a tiny fmap)
            # degenerates to the whole raster — never a negative clip bound
            n_rows = min(n_rows, W)
            row0 = jnp.clip(ri - (n_rows - 1), 0, W - n_rows)
            rows = row0 + jnp.arange(n_rows)
            img_valid = jnp.ones((n_rows * W,), bool)
            contiguous = True
        else:
            rows = ri - pattern.dilation * jnp.arange(n_rows)
            img_valid = jnp.broadcast_to(
                ((rows >= 0) & (rows < W))[:, None], (n_rows, W)).reshape(-1)
        img = (T + rows[:, None] * W + jnp.arange(W)[None, :]).reshape(-1)
    else:  # full: everything is reachable; sparse: random blocks aren't local
        return None
    positions = jnp.concatenate([jnp.arange(T), img]).astype(jnp.int32)
    valid = jnp.concatenate([jnp.ones((T,), bool), img_valid])
    return positions, valid, contiguous


def _scope_key_pad(pattern: AttnPattern, key_mask, n_k: int):
    """Per-variant scope of a [b, m] key padding mask (True = keep) -> [b,
    n_k] bool.  Parity: the full variant applies it to every key
    (attention.py:51-54); sparse variants apply it to the text keys only
    (:99-102, :208-211) — positions beyond its scope are kept."""
    if pattern.variant != "full":
        key_mask = key_mask[:, : pattern.text_len]
    m = key_mask.shape[1]
    if m >= n_k:
        return key_mask[:, :n_k]
    return jnp.pad(key_mask, ((0, 0), (0, n_k - m)), constant_values=True)


def _merge_key_pad_mask(pattern: AttnPattern, allow, key_mask):
    """`allow` is [..., n_q, n_k]; returns [b, 1, n_q, n_k]-broadcastable
    boolean mask with the scoped key padding applied."""
    if key_mask is None:
        return allow
    pad = _scope_key_pad(pattern, key_mask, allow.shape[-1])
    return allow & pad[:, None, None, :]


class MultiHeadAttention(nn.Module):
    """One attention layer of any variant (see module docstring).

    Projections follow the reference shapes (`attention.py:27-41`): fused QKV
    without bias, output projection with bias + dropout.  Softmax runs in
    f32 regardless of the activation dtype (bf16-safe).
    """

    pattern: AttnPattern
    dim: int = 256
    heads: int = 8
    dim_head: int = 64
    dropout: float = 0.0
    use_pallas: bool = False
    pallas_block_q: int = 128   # Pallas tile sizes; sweep via
    pallas_block_k: int = 128   # tools/perf_ab.py pallas-b* variants
    ring_axis: Optional[str] = None  # sequence-parallel axis (inside shard_map)
    sp_impl: str = "ring"            # 'ring' (k/v rotation) | 'ulysses' (all-to-all)
    sliced_kv_decode: bool = True    # decode reads only reachable keys
    #   (decode_key_positions); False streams the full cache — the A/B
    #   control for the sliced path, selectable per-build so the choice is
    #   part of the traced config, never a monkeypatch around the compile
    aligned_span_decode: bool = True  # serve-path sliced reads as circular
    #   dynamic_slice spans (<=2 per row) instead of the per-key vmapped
    #   gather; bit-identical (same key order/masks), False is the A/B
    #   control — again part of the traced config
    dtype: Any = jnp.float32

    def setup(self):
        # fused QKV as a [dim, 3, heads, dh] DenseGeneral: the (3,) axis is
        # never sharded, so splitting q/k/v is a free unsharded-axis index,
        # and tensor parallelism shards the heads axis cleanly (a flat
        # [dim, 3*inner] kernel sharded on tp makes the q/k/v split a
        # cross-shard slice that GSPMD can only fully rematerialize)
        self.to_qkv = nn.DenseGeneral(
            features=(3, self.heads, self.dim_head), axis=-1, use_bias=False,
            dtype=self.dtype, name="to_qkv")
        self.to_out = nn.Dense(self.dim, use_bias=True, dtype=self.dtype, name="to_out")
        self.drop = nn.Dropout(self.dropout)

    def _qkv(self, x):
        with prof.scope("attn-qkv"):
            qkv = self.to_qkv(x)  # [b, n, 3, heads, dh]
            qkv = qkv.transpose(2, 0, 3, 1, 4)  # [3, b, heads, n, dh]
            return qkv[0], qkv[1], qkv[2]

    def _key_pad_bias(self, mask, n):
        """[b, m] bool key mask -> additive f32 [b, n] bias, same scoping as
        the dense path (`_scope_key_pad`)."""
        if mask is None:
            return None
        pad = _scope_key_pad(self.pattern, mask, n)
        return jnp.where(pad, 0.0, -1e30).astype(jnp.float32)

    def __call__(self, x, mask=None, deterministic: bool = True,
                 return_kv: bool = False):
        b, n, _ = x.shape
        q, k, v = self._qkv(x)

        if self.ring_axis is not None and not self.is_initializing():
            # sequence parallelism: x is this device's sequence shard and we
            # are inside a shard_map over `ring_axis` — exact attention via
            # k/v ring rotation (parallel/ring.py) or head<->sequence
            # all-to-all (parallel/ulysses.py).  During flax init there is
            # no shard_map (the axis name is unbound), so init falls through
            # to dense attention — the param tree is identical either way,
            # which is what lets sp checkpoints stay topology-free.
            assert mask is None, (
                "sequence-parallel attention does not take a key padding "
                "mask; fold it into the token stream instead")
            assert self.sp_impl in ("ring", "ulysses"), (
                f"unknown sp_impl {self.sp_impl!r}")
            if self.sp_impl == "ulysses":
                from ..parallel.ulysses import ulysses_attention as sp_attn
            else:
                from ..parallel.ring import ring_attention as sp_attn
            with prof.scope("attn-scores"):
                out = sp_attn(q, k, v, axis_name=self.ring_axis,
                              pattern=self.pattern,
                              causal=self.pattern.causal)
        elif self.use_pallas:
            from .attention_pallas import flash_pattern_attention

            # the kernels lower through Mosaic only on TPU; anywhere else
            # (CPU tests, GPU) fall back to the interpreter
            assert self.pallas_block_q >= 1 and self.pallas_block_k >= 1, (
                f"invalid Pallas block sizes {self.pallas_block_q}x"
                f"{self.pallas_block_k}")
            with prof.scope("attn-scores"):
                out = flash_pattern_attention(
                    q, k, v, self.pattern,
                    key_pad_bias=self._key_pad_bias(mask, n),
                    block_q=self.pallas_block_q, block_k=self.pallas_block_k,
                    interpret=jax.default_backend() != "tpu")
        else:
            with prof.scope("attn-scores"):
                scale = self.dim_head ** -0.5
                dots = jnp.einsum("bhid,bhjd->bhij", q * scale, k,
                                  preferred_element_type=jnp.float32)
                allow = jnp.asarray(dense_pattern_mask(self.pattern, n, n))[None, None]
                allow = _merge_key_pad_mask(self.pattern, allow, mask)
                dots = jnp.where(allow, dots, max_neg_value(dots.dtype))
                attn = jax.nn.softmax(dots, axis=-1).astype(x.dtype)
                # graftlint: disable=DOT001 (uniform: attn is cast to x.dtype above, matching v; parity pinned by tests/attention_refs)
                out = jnp.einsum("bhij,bhjd->bhid", attn, v)

        with prof.scope("attn-out"):
            out = out.astype(x.dtype)
            out = out.transpose(0, 2, 1, 3).reshape(b, n, self.heads * self.dim_head)
            out = self.to_out(out)
            out = self.drop(out, deterministic=deterministic)
        if return_kv:
            return out, (k, v)
        return out

    def _qkv_decode(self, x, qw):
        """Decode-path QKV projection: the f32/bf16 kernel, or — under
        ``weights_int8`` — the session-quantized int8 kernel as a direct
        dot multiplicand (ops/quant.py::qdense; per-output-channel scales
        applied to the small product, never to the kernel)."""
        if qw is None:
            return self._qkv(x)
        with prof.scope("attn-qkv"):
            q8, s = qw["qkv"]                   # [dim, 3, h, dh] int8
            qkv = qdense(x, q8, s).astype(self.dtype)
            qkv = qkv.transpose(2, 0, 3, 1, 4)  # [3, b, heads, n, dh]
            return qkv[0], qkv[1], qkv[2]

    def _out_proj(self, out, qw):
        with prof.scope("attn-out"):
            if qw is None:
                return self.to_out(out)
            q8, s, bias = qw["out"]
            return qdense(out, q8, s, bias).astype(self.dtype)

    def _cache_dots(self, q_scaled, k_sub, k_scale):
        """q·k over a cache read of either storage layout.  Plain caches
        keep the calibrated form (multiplicands in the cache dtype, f32
        accumulation); int8 caches keep the int8 tensor as the
        multiplicand and apply the per-head scale to the f32 dots —
        either way no full-precision cache copy ever exists for XLA to
        hoist (contract_check C2/C3)."""
        if k_scale is None:
            return jnp.einsum("bhid,bhjd->bhij",
                              q_scaled.astype(k_sub.dtype), k_sub,
                              preferred_element_type=jnp.float32)
        return scaled_qdot("bhid,bhjd->bhij", q_scaled, k_sub, k_scale)

    def decode_step(self, x, cache_k, cache_v, index, mask=None,
                    write_pos=None, qw=None):
        """Single-token decode with KV cache.

        x: [b, 1, dim]; cache_k/v: [b, heads, n_cache, dim_head] — or,
        under ``kv_cache_int8``, the pair ``(values int8, scale f32
        [b, heads, 1, 1])`` (ops/quant.py); `index` is the traced
        absolute position of this token.  Returns (out, new_k, new_v).

        ``write_pos`` selects the PHASE-ALIGNED mode the serving arena
        (serve/engine.py) runs in: ``index`` may then be a per-sequence
        ``[b]`` vector (continuous batching: every sequence sits at its own
        depth) while all rows write their k/v at the SAME physical cache
        column ``write_pos`` (a traced scalar — the arena clock mod
        n_cache).  Each row's cache is stored rotated by
        ``r = (write_pos - index) mod n_cache``, so the one shared-column
        ``dynamic_update_slice`` IS each row's logically-next position —
        a per-row write position would lower to an XLA scatter, which
        copies the whole cache on backends that don't alias it (measured
        ~2x the decode step on CPU; the arena admit establishes the
        rotation by rolling the prefilled caches once).  Masks translate
        physical -> logical per row; with ``write_pos=None`` (the static
        sampler) behavior is bit-identical to before the serve work.

        ``qw`` (``weights_int8``) carries this layer's session-quantized
        projection kernels ``{"qkv": (int8, scale), "out": (int8, scale,
        bias)}`` — models/dalle.py::quantize_decode_weights builds it
        once per generate/serve session.
        """
        b = x.shape[0]
        q, k, v = self._qkv_decode(x, qw)  # [b, h, 1, dh]
        if write_pos is not None:
            return self._decode_step_aligned(x, q, k, v, cache_k, cache_v,
                                             index, write_pos, mask, qw)
        with prof.scope("attn-cache"):
            cache_k = cache_write(cache_k, k, (0, 0, index, 0))
            cache_v = cache_write(cache_v, v, (0, 0, index, 0))
            k_vals, k_scale = split_cache(cache_k)
            v_vals, v_scale = split_cache(cache_v)
        n_k = k_vals.shape[2]
        scale = self.dim_head ** -0.5
        sliced = (decode_key_positions(self.pattern, index)
                  if self.sliced_kv_decode else None)
        if sliced is not None:
            # sliced-cache decode: read only the reachable keys (text +
            # row/col/neighborhood) — the decode loop is HBM-bound on cache
            # reads, and the axial/conv patterns reach ~10% of the cache.
            # Same math as the dense path: softmax over the masked subset
            # equals softmax over the masked full row (excluded entries
            # contribute exp(-inf) = 0).
            positions, valid, contiguous = sliced
            T = self.pattern.text_len
            if contiguous:
                # text prefix (static slice) + one dynamic_slice for the
                # image window — cheaper on TPU than a general gather.  The
                # window start is clamped so the slice stays inside the
                # cache (the padded grid is one longer than the cache, so
                # the last image row's window overruns by one), and the
                # mask is computed from the positions ACTUALLY read — a
                # clamp-shifted window must never be scored under the
                # unshifted positions.  Shifted-in keys below T would
                # duplicate the text segment, hence the img_actual >= T
                # validity.
                m_img = positions.shape[0] - T
                start = jnp.clip(positions[T], 0, n_k - m_img)
                img_actual = start + jnp.arange(m_img)
                positions = jnp.concatenate(
                    [jnp.arange(T), img_actual]).astype(jnp.int32)
                valid = jnp.concatenate(
                    [jnp.ones((T,), bool), img_actual >= T])

                def seg(cache):
                    return jnp.concatenate(
                        [cache[:, :, :T],
                         jax.lax.dynamic_slice_in_dim(cache, start, m_img,
                                                      axis=2)], axis=2)

                with prof.scope("attn-cache"):
                    k_sub, v_sub = seg(k_vals), seg(v_vals)
                safe = positions  # all in [0, n_k) by the clamp above
            else:
                valid = valid & (positions >= 0) & (positions < n_k)
                safe = jnp.clip(positions, 0, n_k - 1)
                with prof.scope("attn-cache"):
                    k_sub = jnp.take(k_vals, safe, axis=2)  # [b, h, m, dh]
                    v_sub = jnp.take(v_vals, safe, axis=2)
            with prof.scope("attn-scores"):
                dots = self._cache_dots(q * scale, k_sub, k_scale)
                row = (_allowed(self.pattern, index, positions, jnp)
                       & valid)[None, None, None, :]
                if mask is not None:
                    pad = _scope_key_pad(self.pattern, mask, n_k)
                    row = row & jnp.take(pad, safe, axis=1)[:, None, None, :]
                dots = jnp.where(row, dots, max_neg_value(dots.dtype))
                attn = jax.nn.softmax(dots, axis=-1)  # f32
                out = self._attn_v(attn, v_sub, v_scale, x.dtype)
                out = out.transpose(0, 2, 1, 3).reshape(
                    b, 1, self.heads * self.dim_head)
            return self._out_proj(out, qw), cache_k, cache_v
        with prof.scope("attn-scores"):
            dots = self._cache_dots(q * scale, k_vals, k_scale)
            layout = self.pattern.block_layout()
            row = pattern_mask_row(
                self.pattern, index, n_k,
                layout=jnp.asarray(layout) if layout is not None else None,
            )[None, None, None, :]
            row = _merge_key_pad_mask(self.pattern, row, mask)
            dots = jnp.where(row, dots, max_neg_value(dots.dtype))
            attn = jax.nn.softmax(dots, axis=-1)  # f32
            out = self._attn_v(attn, v_vals, v_scale, x.dtype)
            out = out.transpose(0, 2, 1, 3).reshape(
                b, 1, self.heads * self.dim_head)
        return self._out_proj(out, qw), cache_k, cache_v

    def _decode_step_aligned(self, x, q, k, v, cache_k, cache_v, index,
                             write_pos, mask, qw=None):
        """Phase-aligned decode (see ``decode_step``): per-row logical
        ``index`` [b] (or scalar, broadcast), one shared physical write
        column ``write_pos``.  Row caches are rotated by
        ``r = (write_pos - index) mod n``; attention reads the full cache
        in physical order (sums are order-free) and masks by the LOGICAL
        position of each physical column, which also hides the previous
        resident's stale keys (they map to logical positions the causal
        pattern can't reach).

        Sliced reads through the rotation: with ``aligned_span_decode``
        (default) each row's circular window is read as at most TWO
        contiguous ``dynamic_slice`` spans (text prefix + image window,
        each via ops/quant.py::circular_slice_in_dim, reassembled in
        logical order) — bit-identical to the per-key vmapped gather (the
        False control) because key order, values at valid lanes, and
        masks are all equal; only the HBM access pattern differs.
        Non-contiguous windows (axial_col, dilated conv) keep the
        gather."""
        assert mask is None, (
            "phase-aligned decode does not take a key padding mask; serve "
            "requests carry fully-valid prompts")
        b = x.shape[0]
        n_k = split_cache(cache_k)[0].shape[2]
        scale = self.dim_head ** -0.5
        idx = jnp.broadcast_to(jnp.asarray(index, jnp.int32), (b,))
        r = jnp.remainder(write_pos - idx, n_k)  # [b] rotation per row
        # the ONE aligned write: every row's next token lands in the same
        # physical column, so this stays a dynamic_update_slice (in-place
        # under donation) instead of a scatter
        with prof.scope("attn-cache"):
            cache_k = cache_write(cache_k, k, (0, 0, write_pos, 0))
            cache_v = cache_write(cache_v, v, (0, 0, write_pos, 0))
            k_vals, k_scale = split_cache(cache_k)
            v_vals, v_scale = split_cache(cache_v)
        out = self._aligned_read(q, k_vals, k_scale, v_vals, v_scale,
                                 idx, r, x.dtype)
        out = out.transpose(0, 2, 1, 3).reshape(b, 1, self.heads * self.dim_head)
        return self._out_proj(out, qw), cache_k, cache_v

    def _aligned_read(self, q, k_vals, k_scale, v_vals, v_scale, idx, r,
                      out_dtype):
        """The read half of the phase-aligned decode step: one query per
        row (``q`` [b, heads, 1, dh]) at logical position ``idx`` [b]
        against row caches rotated by ``r`` [b].  Returns the attended
        values [b, heads, 1, dh].

        Shared verbatim between :meth:`_decode_step_aligned` (the greedy
        serve tick) and :meth:`decode_span` (the speculative draft/verify
        passes, which fold their K span queries into the batch axis) —
        one program means the two paths consume bitwise-identical masked
        softmaxes, which is what lets the spec-decode bit-equality tests
        extend the greedy harness unchanged."""
        n_k = k_vals.shape[2]
        scale = self.dim_head ** -0.5
        sliced = (decode_key_positions(self.pattern, jnp.int32(0))
                  if self.sliced_kv_decode else None)
        if sliced is not None:
            # batched positions: every row computes its own reachable set
            # (decode_key_positions is shape-static over index, so the
            # vmap is one gathered program, not b programs)
            positions, valid, _ = jax.vmap(
                lambda i: decode_key_positions(self.pattern, i))(idx)
            valid = valid & (positions >= 0) & (positions < n_k)
            T = self.pattern.text_len
            if sliced[2] and self.aligned_span_decode:
                # span reads: per row, the text prefix is the circular
                # span [r, r+T) and the image window [pos[T]+r, ...+m)
                # — two block reads instead of T+m key gathers.  Values
                # at out-of-range lanes (the padded grid's one-position
                # overrun) differ from the gather path's clamped reads
                # but are masked to -inf either way, so the softmax
                # consumes identical arrays lane-for-lane.
                m_img = positions.shape[1] - T
                img_start = positions[:, T] + r

                def spans(cache):
                    # the static prefixes are row-invariant: slice them
                    # once for the whole batch, outside the per-row map
                    text_lo = jax.lax.slice_in_dim(cache, 0, T, axis=2)
                    img_lo = jax.lax.slice_in_dim(cache, 0, m_img, axis=2)
                    text = jax.vmap(lambda c, s, lo: circular_slice_in_dim(
                        c, s, T, axis=1, prefix=lo))(cache, r, text_lo)
                    img = jax.vmap(lambda c, s, lo: circular_slice_in_dim(
                        c, s, m_img, axis=1, prefix=lo))(cache, img_start,
                                                         img_lo)
                    return jnp.concatenate([text, img], axis=2)

                with prof.scope("attn-cache"):
                    k_sub, v_sub = spans(k_vals), spans(v_vals)
            else:
                safe = jnp.clip(positions, 0, n_k - 1)
                phys = jnp.remainder(safe + r[:, None], n_k)     # [b, m]
                with prof.scope("attn-cache"):
                    k_sub = jnp.take_along_axis(
                        k_vals, phys[:, None, :, None], axis=2)  # [b,h,m,dh]
                    v_sub = jnp.take_along_axis(
                        v_vals, phys[:, None, :, None], axis=2)
            with prof.scope("attn-scores"):
                dots = self._cache_dots(q * scale, k_sub, k_scale)
                row = (_allowed(self.pattern, idx[:, None], positions, jnp)
                       & valid)[:, None, None, :]
                dots = jnp.where(row, dots, max_neg_value(dots.dtype))
                attn = jax.nn.softmax(dots, axis=-1)  # f32
                return self._attn_v(attn, v_sub, v_scale, out_dtype)
        with prof.scope("attn-scores"):
            dots = self._cache_dots(q * scale, k_vals, k_scale)
            logical = jnp.remainder(
                jnp.arange(n_k, dtype=jnp.int32)[None, :] - r[:, None],
                n_k)
            layout = self.pattern.block_layout()
            row = _allowed(self.pattern, idx[:, None], logical, jnp,
                           layout=(jnp.asarray(layout)
                                   if layout is not None else None))
            dots = jnp.where(row[:, None, None, :], dots,
                             max_neg_value(dots.dtype))
            attn = jax.nn.softmax(dots, axis=-1)  # f32
            return self._attn_v(attn, v_vals, v_scale, out_dtype)

    def decode_span(self, x, cache_k, cache_v, qpos, rot, valid, qw=None):
        """K-token span pass with KV cache — the speculative-decode
        primitive (draft steps run it at K=1 through a depth-limited
        stack; the verify scores all K positions in one weight-stream
        pass).

        x: [b, K, dim] embeddings of the span tokens; ``qpos`` [b, K]
        int32 logical absolute positions (consecutive per row); ``rot``
        [b] each row's cache rotation ((write_col - index) mod n — zeros
        for the static sampler, the admit-time rotation in the serve
        arena); ``valid`` [b, K] bool gates the cache writes (a position
        past the row's remaining sequence would wrap-write into a live
        column).  Returns (out [b, K, dim-equivalent], new_k, new_v).

        All K k/v rows are written BEFORE any read, so query j sees its
        own and every earlier span position's fresh keys; later span
        positions are causally masked.  The reads fold the K queries into
        the batch axis and run :meth:`_aligned_read` — the exact program
        the greedy serve tick reads with — so a span query at position p
        produces bitwise the same output as a greedy step at p over the
        same cache (batch-shape invariance of the per-row program, the
        property the serve bit-equality tests already pin)."""
        b, K, _ = x.shape
        q, k, v = self._qkv_decode(x, qw)  # [b, h, K, dh]
        n_k = split_cache(cache_k)[0].shape[2]
        idx = qpos.astype(jnp.int32)
        r = jnp.remainder(jnp.asarray(rot, jnp.int32), n_k)  # [b]
        phys = jnp.remainder(idx + r[:, None], n_k)          # [b, K]
        with prof.scope("attn-cache"):
            cache_k = cache_write_rows(cache_k, k, phys, valid)
            cache_v = cache_write_rows(cache_v, v, phys, valid)
            k_vals, k_scale = split_cache(cache_k)
            v_vals, v_scale = split_cache(cache_v)
        # fold the span into the batch axis: row (b, j) of the folded
        # batch is one greedy-shaped query at logical position qpos[b, j]
        # against (a broadcast view of) row b's cache
        B = b * K
        qf = q.transpose(0, 2, 1, 3).reshape(B, self.heads, 1, self.dim_head)
        idx_f = idx.reshape(B)
        r_f = jnp.repeat(r, K)

        def fold(a):
            return None if a is None else jnp.repeat(a, K, axis=0)

        out = self._aligned_read(qf, fold(k_vals), fold(k_scale),
                                 fold(v_vals), fold(v_scale),
                                 idx_f, r_f, x.dtype)
        out = out.transpose(0, 2, 1, 3).reshape(
            b, K, self.heads * self.dim_head)
        return self._out_proj(out, qw), cache_k, cache_v

    @staticmethod
    def _attn_v(attn, v, v_scale, out_dtype):
        """Decode-step attn (f32) x cached-v contraction.

        When the cache dtype differs from the activation dtype (the
        kv_cache_bf16 case: f32 activations, bf16 storage) the
        multiplicands stay in the CACHE dtype with f32 ACCUMULATION
        (preferred_element_type) — the MXU's native bf16-in/f32-acc mode.
        Upcasting v to the activation dtype instead would let XLA hoist
        the convert through the cache update and materialize a full f32
        copy of the bf16 cache (measured: it more than doubles the decode
        step's cache bytes, defeating DALLEConfig.kv_cache_bf16 entirely).
        Int8 caches (``v_scale`` present) follow the same discipline one
        level down: the int8 values are the multiplicand, the per-head
        scale multiplies the small f32 product.  When the dtypes already
        match, the contraction keeps the exact form the decode-byte gates
        are calibrated against."""
        if v_scale is not None:
            return scaled_qdot("bhij,bhjd->bhid", attn, v,
                               v_scale).astype(out_dtype)
        if v.dtype == out_dtype:
            # graftlint: disable=DOT001 (uniform: guarded by v.dtype == out_dtype, attn cast to it)
            return jnp.einsum("bhij,bhjd->bhid", attn.astype(out_dtype), v)
        return jnp.einsum("bhij,bhjd->bhid", attn.astype(v.dtype), v,
                          preferred_element_type=jnp.float32
                          ).astype(out_dtype)
