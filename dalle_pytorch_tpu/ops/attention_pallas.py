"""Pallas TPU flash attention with block-sparse pattern skipping.

This is the framework's flagship custom kernel, replacing the reference's
DeepSpeed ``SparseSelfAttention`` CUDA/Triton block-sparse kernel
(`/root/reference/dalle_pytorch/attention.py:284-342`) — and, beyond parity,
accelerating *every* attention variant (full / axial_row / axial_col /
conv_like / sparse), since they are all boolean patterns over absolute
positions (see ``ops/attention.py``).

Design (TPU-first):
* **flash**: online-softmax accumulation over key blocks — the [n, n]
  attention matrix is never materialized in HBM.  At the reference's CUB
  geometry (b16 h8 n1104) the dense f32 scores alone are ~624 MB/step of
  HBM traffic; this kernel keeps them in VMEM tiles.
* **block-sparse skipping**: a static block summary (0 = skip, >0 = compute)
  derived from the pattern predicate lets the kernel skip disallowed key
  blocks entirely — axial patterns touch O(n·sqrt(n)) instead of O(n^2)
  score entries, matching the asymptotics DeepSpeed's kernel gave the
  reference.
* **keys/values stay VMEM-resident** per (batch*head) program: at n≈1104,
  dh=64 they fit comfortably (~0.6 MB), so the inner loop does no HBM
  traffic at all.
* full custom VJP: flash backward (dq then dk/dv) with the same block
  skipping, using the saved logsumexp rows.

All shapes are padded to block multiples with masked-off (never-attended)
positions; softmax runs in f32 regardless of input dtype.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import AttnPattern, dense_pattern_mask

NEG_INF = -1e30  # finite mask value: keeps (s - lse) well-defined everywhere


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.lru_cache(maxsize=64)
def _pattern_blocks(pattern: AttnPattern, n: int, n_pad: int,
                    block_q: int, block_k: int):
    """Static (trace-time) mask + block summary for a pattern at length n.

    Returns (mask [n_pad, n_pad] bool, bsum [NQ, NK] int32) where
    bsum[qb, kb] = 0 if no (i, j) in the block may attend, else 1.
    """
    mask = np.zeros((n_pad, n_pad), dtype=bool)
    mask[:n, :n] = dense_pattern_mask(pattern, n, n)
    nq, nk = n_pad // block_q, n_pad // block_k
    bsum = np.zeros((nq, nk), dtype=np.int32)
    for qb in range(nq):
        for kb in range(nk):
            blk = mask[qb * block_q:(qb + 1) * block_q,
                       kb * block_k:(kb + 1) * block_k]
            bsum[qb, kb] = 1 if blk.any() else 0
    return mask, bsum


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(bsum_ref, q_ref, k_ref, v_ref, mask_ref, bias_ref,
                o_ref, lse_ref, *, scale: float, block_k: int, nk: int):
    qb = pl.program_id(1)
    q = q_ref[0]  # [bq, dh], input dtype (MXU takes bf16 with f32 accum)
    bq = q.shape[0]

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)

    def body(kb, carry):
        def compute(carry):
            m, l, acc = carry
            start = pl.multiple_of(kb * block_k, block_k)
            k_blk = k_ref[0, pl.ds(start, block_k), :]
            v_blk = v_ref[0, pl.ds(start, block_k), :]
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [bq, bk]
            s = s + bias_ref[0, 0, pl.ds(start, block_k)][None, :]
            mblk = mask_ref[:, pl.ds(start, block_k)]
            s = jnp.where(mblk, s, NEG_INF)

            m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            # rows with every key masked have s == m_new == NEG_INF, where
            # exp(s - m_new) = 1 would leak uniform attention onto
            # disallowed keys — force those terms to 0 (l then stays 0 and
            # the lse=+inf guard below takes over)
            p = jnp.where(s <= NEG_INF * 0.5, 0.0, p)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + p.sum(axis=1, keepdims=True)
            acc_new = acc * alpha + jax.lax.dot_general(
                p, v_blk.astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        return jax.lax.cond(bsum_ref[qb, kb] > 0, compute, lambda c: c, carry)

    m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    # rows with no attendable key (padding): lse = +inf so bwd's
    # exp(s - lse) is exactly 0
    lse = jnp.where(l == 0.0, jnp.inf, m + jnp.log(l_safe))
    lse_ref[0, 0, :] = lse[:, 0]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(bsum_ref, q_ref, k_ref, v_ref, mask_ref, bias_ref,
                   do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale: float, block_k: int, nk: int):
    qb = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0, :][:, None]      # [bq, 1]
    delta = delta_ref[0, 0, :][:, None]  # [bq, 1]
    dq0 = jnp.zeros(q.shape, jnp.float32)

    def body(kb, dq):
        def compute(dq):
            start = pl.multiple_of(kb * block_k, block_k)
            k_blk = k_ref[0, pl.ds(start, block_k), :]
            v_blk = v_ref[0, pl.ds(start, block_k), :]
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            s = s + bias_ref[0, 0, pl.ds(start, block_k)][None, :]
            mblk = mask_ref[:, pl.ds(start, block_k)]
            s = jnp.where(mblk, s, NEG_INF)
            p = jnp.exp(s - lse)                      # [bq, bk]
            dp = jax.lax.dot_general(
                do, v_blk.astype(jnp.float32), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)   # [bq, bk]
            ds = p * (dp - delta)
            return dq + jax.lax.dot_general(
                ds, k_blk.astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale

        return jax.lax.cond(bsum_ref[qb, kb] > 0, compute, lambda d: d, dq)

    dq = jax.lax.fori_loop(0, nk, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(bsum_ref, q_ref, k_ref, v_ref, mask_ref, bias_ref,
                    do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                    *, scale: float, block_q: int, nq: int):
    kb = pl.program_id(1)
    k_blk = k_ref[0]   # [bk, dh]
    v_blk = v_ref[0]
    bias = bias_ref[0, 0, :][None, :]  # [1, bk] — bias over this key block
    dk0 = jnp.zeros(k_blk.shape, jnp.float32)
    dv0 = jnp.zeros(v_blk.shape, jnp.float32)

    def body(qb, carry):
        def compute(carry):
            dk, dv = carry
            start = pl.multiple_of(qb * block_q, block_q)
            q = q_ref[0, pl.ds(start, block_q), :]
            do = do_ref[0, pl.ds(start, block_q), :].astype(jnp.float32)
            lse = lse_ref[0, 0, pl.ds(start, block_q)][:, None]
            delta = delta_ref[0, 0, pl.ds(start, block_q)][:, None]
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [bq, bk]
            s = s + bias
            mblk = mask_ref[pl.ds(start, block_q), :]
            s = jnp.where(mblk, s, NEG_INF)
            p = jnp.exp(s - lse)
            dv_new = dv + jax.lax.dot_general(
                p, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)          # [bk, dh]
            dp = jax.lax.dot_general(
                do, v_blk.astype(jnp.float32), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)          # [bq, bk]
            ds = p * (dp - delta)
            dk_new = dk + jax.lax.dot_general(
                ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [bk, dh]
            return dk_new, dv_new

        return jax.lax.cond(bsum_ref[qb, kb] > 0, compute, lambda c: c, carry)

    dk, dv = jax.lax.fori_loop(0, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------


# Every (batch*head, q-or-k-block) program in the three kernels below
# writes its own disjoint output block exactly once (accumulation happens
# only inside the per-program fori_loop), so both grid axes are parallel —
# this lets Mosaic pipeline/reorder programs freely (megacore splits on
# v4/v5p; no-op on single-tensorcore chips).
# CompilerParams was TPUCompilerParams before jax 0.5.x — accept either so
# the module imports across the jax versions CI and the chip box run
_PARALLEL_GRID = getattr(pltpu, "CompilerParams",
                         getattr(pltpu, "TPUCompilerParams", None))(
    dimension_semantics=("parallel", "parallel"))


def _smem_spec(shape):
    return pl.BlockSpec(shape, lambda ib, iq: (0, 0), memory_space=pltpu.SMEM)


def _call_fwd(q, k, v, mask, bsum, bias, *, scale, block_q, block_k,
              interpret):
    bh, n_pad, dh = q.shape
    nq, nk = bsum.shape
    heads_bias = bias.shape[0]  # bias is [b, 1, n_pad]; bh = b * h
    h = bh // heads_bias

    kernel = functools.partial(_fwd_kernel, scale=scale, block_k=block_k,
                               nk=nk)
    return pl.pallas_call(
        kernel,
        grid=(bh, nq),
        in_specs=[
            _smem_spec((nq, nk)),
            pl.BlockSpec((1, block_q, dh), lambda ib, iq: (ib, iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n_pad, dh), lambda ib, iq: (ib, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n_pad, dh), lambda ib, iq: (ib, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_q, n_pad), lambda ib, iq: (iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, n_pad), lambda ib, iq: (jax.lax.div(ib, h), 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dh), lambda ib, iq: (ib, iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda ib, iq: (ib, 0, iq),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n_pad, dh), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, n_pad), jnp.float32),
        ],
        compiler_params=_PARALLEL_GRID,
        interpret=interpret,
    )(bsum, q, k, v, mask, bias)


def _call_bwd(q, k, v, mask, bsum, bias, do, lse, delta, *, scale, block_q,
              block_k, interpret):
    bh, n_pad, dh = q.shape
    nq, nk = bsum.shape
    h = bh // bias.shape[0]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_k=block_k, nk=nk),
        grid=(bh, nq),
        in_specs=[
            _smem_spec((nq, nk)),
            pl.BlockSpec((1, block_q, dh), lambda ib, iq: (ib, iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n_pad, dh), lambda ib, iq: (ib, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n_pad, dh), lambda ib, iq: (ib, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_q, n_pad), lambda ib, iq: (iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, n_pad), lambda ib, iq: (jax.lax.div(ib, h), 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, dh), lambda ib, iq: (ib, iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda ib, iq: (ib, 0, iq),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda ib, iq: (ib, 0, iq),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, dh), lambda ib, iq: (ib, iq, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, n_pad, dh), q.dtype),
        compiler_params=_PARALLEL_GRID,
        interpret=interpret,
    )(bsum, q, k, v, mask, bias, do, lse, delta)

    def kv_spec(_):
        return pl.BlockSpec((1, block_k, dh), lambda ib, ik: (ib, ik, 0),
                            memory_space=pltpu.VMEM)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                          nq=nq),
        grid=(bh, nk),
        in_specs=[
            _smem_spec((nq, nk)),
            pl.BlockSpec((1, n_pad, dh), lambda ib, ik: (ib, 0, 0),
                         memory_space=pltpu.VMEM),
            kv_spec(None),
            kv_spec(None),
            pl.BlockSpec((n_pad, block_k), lambda ib, ik: (0, ik),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k), lambda ib, ik: (jax.lax.div(ib, h), 0, ik),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n_pad, dh), lambda ib, ik: (ib, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, n_pad), lambda ib, ik: (ib, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, n_pad), lambda ib, ik: (ib, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[kv_spec(None), kv_spec(None)],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n_pad, dh), q.dtype),
            jax.ShapeDtypeStruct((bh, n_pad, dh), q.dtype),
        ],
        compiler_params=_PARALLEL_GRID,
        interpret=interpret,
    )(bsum, q, k, v, mask, bias, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _flash_attention(pattern: AttnPattern, block_q: int, block_k: int,
                     interpret: bool, q, k, v, bias):
    out, _ = _flash_fwd(pattern, block_q, block_k, interpret, q, k, v, bias)
    return out


def _padded_len(n: int, block_q: int, block_k: int) -> int:
    """The kernel's actual padded sequence length — shared with the VMEM
    guard so its estimate can never diverge from what _prepare allocates."""
    n_pad = _round_up(n, max(block_q, block_k))
    n_pad = _round_up(n_pad, block_q)
    return _round_up(n_pad, block_k)


def _prepare(pattern, block_q, block_k, q, bias):
    b, h, n, dh = q.shape
    n_pad = _padded_len(n, block_q, block_k)
    mask_np, bsum_np = _pattern_blocks(pattern, n, n_pad, block_q, block_k)
    mask = jnp.asarray(mask_np)
    bsum = jnp.asarray(bsum_np)
    if bias is None:
        bias_p = jnp.zeros((b, 1, n_pad), jnp.float32)
    else:
        bias_p = jnp.pad(bias.astype(jnp.float32),
                         ((0, 0), (0, n_pad - n)))[:, None, :]
    return n_pad, mask, bsum, bias_p


def _flash_fwd(pattern, block_q, block_k, interpret, q, k, v, bias):
    b, h, n, dh = q.shape
    scale = dh ** -0.5
    n_pad, mask, bsum, bias_p = _prepare(pattern, block_q, block_k, q, bias)

    def flat_pad(t):
        t = t.reshape(b * h, n, dh)
        return jnp.pad(t, ((0, 0), (0, n_pad - n), (0, 0)))

    qf, kf, vf = flat_pad(q), flat_pad(k), flat_pad(v)
    o, lse = _call_fwd(qf, kf, vf, mask, bsum, bias_p, scale=scale,
                       block_q=block_q, block_k=block_k, interpret=interpret)
    out = o[:, :n, :].reshape(b, h, n, dh)
    return out, (qf, kf, vf, bias_p, o, lse)


def _flash_bwd(pattern, block_q, block_k, interpret, residuals, g):
    qf, kf, vf, bias_p, o, lse = residuals
    bh, n_pad, dh = qf.shape
    b = bias_p.shape[0]
    h = bh // b
    n = g.shape[2]
    scale = dh ** -0.5
    mask_np, bsum_np = _pattern_blocks(pattern, n, n_pad, block_q, block_k)
    mask, bsum = jnp.asarray(mask_np), jnp.asarray(bsum_np)

    do = jnp.pad(g.reshape(bh, n, dh), ((0, 0), (0, n_pad - n), (0, 0)))
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]  # [bh, 1, n_pad]

    dq, dk, dv = _call_bwd(qf, kf, vf, mask, bsum, bias_p, do, lse, delta,
                           scale=scale, block_q=block_q, block_k=block_k,
                           interpret=interpret)

    def unflat(t):
        return t[:, :n, :].reshape(b, h, n, dh)

    dbias = jnp.zeros((b, n), jnp.float32)  # pad bias is non-trainable
    return unflat(dq), unflat(dk), unflat(dv), dbias


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


# Per-core VMEM is ~16 MB on current TPUs; the kernel keeps each program's
# full-sequence K/V (plus the padded [n_pad, n_pad] bool mask tile rows)
# VMEM-resident, which is the right call at the CUB geometry (n=1104:
# ~0.6 MB K/V) but stops scaling with n.  Budget conservatively at half of
# VMEM so q/o/acc tiles, the mask and double-buffering still fit.
VMEM_BUDGET_BYTES = 8 * 1024 * 1024


def _vmem_resident_bytes(n_pad: int, dh: int, itemsize: int,
                         block_q: int) -> int:
    # K + V [n_pad, dh] + mask rows [block_q, n_pad] (bool) per program
    return 2 * n_pad * dh * itemsize + block_q * n_pad


def flash_pattern_attention(q, k, v, pattern: AttnPattern,
                            key_pad_bias: Optional[jax.Array] = None, *,
                            block_q: int = 128, block_k: int = 128,
                            interpret: bool = False) -> jax.Array:
    """Block-sparse flash attention for any `AttnPattern`.

    q/k/v: [b, heads, n, dim_head]; `key_pad_bias` is an optional additive
    f32 [b, n] key bias (0 keep / -1e30 drop) carrying the per-sample key
    padding mask.  Returns [b, heads, n, dim_head] in q's dtype.

    Raises ValueError when the sequence is long enough that the
    VMEM-resident K/V design would overflow the per-core budget — callers
    should fall back to the dense-masked XLA path (or sequence parallelism,
    parallel/ring.py) instead of letting Mosaic fail opaquely mid-compile.
    The guard only applies to real TPU compilation; the interpreter
    (CPU/GPU correctness runs) has no VMEM limit.
    """
    b, _, n, dh = q.shape
    if (block_q % 128 or block_k % 128) and not interpret:
        # Mosaic requires the last block dim be a multiple of the 128-lane
        # width (the lse output [b, h, n] blocks the q axis in its last
        # dim; k blocks stream through the same lanes) — sub-128 tiles
        # fail deep inside lowering, so reject them at the API edge.
        # Measured failure: perf_ab pallas-b64, 2026-08-02 (chip-logs).
        raise ValueError(
            f"block_q/block_k must be multiples of the TPU lane width 128 "
            f"(got {block_q}/{block_k})")
    n_pad = _padded_len(n, block_q, block_k)
    resident = _vmem_resident_bytes(n_pad, dh, q.dtype.itemsize, block_q)
    if resident > VMEM_BUDGET_BYTES and not interpret:
        raise ValueError(
            f"flash_pattern_attention keeps full-sequence K/V VMEM-resident: "
            f"n={n} (padded {n_pad}), dh={dh} needs ~{resident / 1e6:.1f} MB "
            f"of the ~{VMEM_BUDGET_BYTES / 1e6:.0f} MB budget. Use the dense "
            "path (use_pallas=False) or sequence parallelism (ring_axis) "
            "for sequences this long.")
    if key_pad_bias is None:
        key_pad_bias = jnp.zeros((b, n), jnp.float32)
    return _flash_attention(pattern, block_q, block_k, interpret,
                            q, k, v, key_pad_bias)
