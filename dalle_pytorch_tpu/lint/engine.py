"""graftlint engine: file walking, pragma suppression, baseline, fixes.

The engine is jax-free and runs in milliseconds per file — it must stay
importable and fast on a bare CPU box (CI's lint job budget is seconds;
the chip babysitter runs it before every queue arm).
"""
from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
import zlib
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .rules import RULES, FileCtx

# `# graftlint: disable=ENV001,DOT001 (reason why the rule does not apply)`
# — may trail other comment text (`# pragma: no cover — graftlint: ...`),
# but must end the line so the justification is unambiguous
_PRAGMA_RE = re.compile(
    r"graftlint:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
    r"\s*(?:\((.*)\))?\s*$")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    rule: str
    line: int          # 1-based start line of the flagged statement
    col: int
    message: str
    line_text: str = ""
    end_line: int = 0  # 1-based end line (pragma scope for multi-line stmts)

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: " \
               f"{self.rule} {self.message}"


def fingerprint(f: Finding) -> str:
    """Line-number-independent identity for baseline entries: file + rule +
    crc32 of the stripped source line, so unrelated edits above a baselined
    finding don't invalidate the baseline."""
    crc = zlib.crc32(f.line_text.strip().encode())
    return f"{f.path}::{f.rule}::{crc:08x}"


def _parse_pragmas(src: str) -> Tuple[Dict[int, Set[str]], List[Finding],
                                      List[Tuple[int, Set[str], str]]]:
    """Map line -> set of disabled rules, PRAGMA001 findings for pragmas
    missing the mandatory justification, and the justified pragma entries
    ``(line, rules, comment)`` themselves (for unused-suppression
    accounting)."""
    disabled: Dict[int, Set[str]] = {}
    errors: List[Finding] = []
    pragmas: List[Tuple[int, Set[str], str]] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        comments = [(t.start[0], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError):
        return disabled, errors, pragmas
    for line, comment in comments:
        m = _PRAGMA_RE.search(comment)
        if not m:
            continue
        rules = {r.strip().upper() for r in m.group(1).split(",")}
        reason = (m.group(2) or "").strip()
        if not reason:
            errors.append(Finding(
                path="", rule="PRAGMA001", line=line, col=0,
                message="graftlint pragma without a justification: write "
                        "'# graftlint: disable=RULE (why the rule does not "
                        "apply here)'",
                line_text=comment, end_line=line))
            continue
        disabled.setdefault(line, set()).update(rules)
        pragmas.append((line, rules, comment))
    return disabled, errors, pragmas


def _suppressing_lines(f: Finding, disabled: Dict[int, Set[str]]) -> List[int]:
    """The pragma lines that suppress this finding: the line above the
    flagged statement, any line of the statement, or its first line."""
    lines = range(f.line - 1, max(f.end_line, f.line) + 1)
    return [ln for ln in lines
            if f.rule in disabled.get(ln, ()) or "ALL" in disabled.get(ln, ())]


def lint_source(src: str, path: str = "<string>",
                select: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run the (selected) rules over one source string.  Returns findings
    with pragma suppression already applied; unsuppressable engine
    findings are included: PRAGMA001 (justification-less pragmas) and
    PRAGMA002 (justified pragmas that suppress nothing — stale
    suppressions outlive refactors and silently blind the rule they once
    excused; PRAGMA002 is only judged when every rule the pragma names was
    actually run, so ``--select`` subsets don't misreport)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(path=path, rule="PARSE001", line=e.lineno or 1,
                        col=(e.offset or 1) - 1,
                        message=f"file does not parse: {e.msg}",
                        line_text="", end_line=e.lineno or 1)]
    lines = src.splitlines()
    ctx = FileCtx(path=path, tree=tree, lines=lines)
    disabled, pragma_errors, pragmas = _parse_pragmas(src)

    findings: List[Finding] = []
    selected = None if select is None else {r.upper() for r in select}
    rules = RULES if selected is None else {
        k: v for k, v in RULES.items() if k in selected}
    for rule_name, rule_fn in rules.items():
        for node, message in rule_fn(ctx):
            line = getattr(node, "lineno", 1)
            text = lines[line - 1] if 0 < line <= len(lines) else ""
            findings.append(Finding(
                path=path, rule=rule_name, line=line,
                col=getattr(node, "col_offset", 0), message=message,
                line_text=text,
                end_line=getattr(node, "end_lineno", line) or line))
    kept: List[Finding] = []
    used_pragma_lines: Set[int] = set()
    for f in findings:
        hit = _suppressing_lines(f, disabled)
        if hit:
            used_pragma_lines.update(hit)
        else:
            kept.append(f)
    for line, prules, comment in pragmas:
        if line in used_pragma_lines:
            continue
        judgeable = selected is None or (
            "ALL" not in prules and prules <= selected)
        if not judgeable:
            continue
        kept.append(Finding(
            path=path, rule="PRAGMA002", line=line, col=0,
            message=f"unused suppression: this pragma disables "
                    f"{','.join(sorted(prules))} but suppresses no finding "
                    "— the code it excused is gone; delete the pragma",
            line_text=comment, end_line=line))
    kept.extend(dataclasses.replace(e, path=path) for e in pragma_errors)
    return sorted(kept, key=lambda f: (f.line, f.col, f.rule))


_SKIP_DIRS = {"__pycache__", ".git", ".cache", "node_modules", ".venv"}


def iter_python_files(paths: Sequence[str]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            out.extend(sorted(
                f for f in path.rglob("*.py")
                if not any(part in _SKIP_DIRS for part in f.parts)))
        elif path.suffix == ".py":
            out.append(path)
    return out


def lint_paths(paths: Sequence[str],
               select: Optional[Iterable[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for f in iter_python_files(paths):
        findings.extend(lint_source(f.read_text(), path=str(f),
                                    select=select))
    return findings


# --- baseline ------------------------------------------------------------


def load_baseline(path) -> Set[str]:
    p = Path(path)
    if not p.exists():
        return set()
    data = json.loads(p.read_text())
    return set(data.get("suppressed", []))


def write_baseline(findings: Sequence[Finding], path) -> None:
    entries = sorted({fingerprint(f) for f in findings})
    Path(path).write_text(json.dumps(
        {"comment": "graftlint baseline — known findings grandfathered in; "
                    "regenerate with tools/graftlint.py --write-baseline",
         "suppressed": entries}, indent=2) + "\n")


def filter_baseline(findings: Sequence[Finding],
                    baseline: Set[str]) -> List[Finding]:
    return [f for f in findings if fingerprint(f) not in baseline]


def stale_baseline_entries(findings: Sequence[Finding],
                           baseline: Set[str]) -> List[str]:
    """Baseline fingerprints matching no current finding — each one is a
    fixed (or vanished) legacy finding whose grandfather entry now only
    risks masking a future regression at the same source line.  Pass the
    PRE-filter findings; prune with ``tools/graftlint.py
    --prune-baseline``."""
    live = {fingerprint(f) for f in findings}
    return sorted(baseline - live)


def prune_baseline(findings: Sequence[Finding], path) -> List[str]:
    """Rewrite the baseline at ``path`` keeping only fingerprints that
    still match a (pre-filter) finding; returns the dropped stale
    entries.  No-op when the file does not exist."""
    baseline = load_baseline(path)
    if not baseline:
        return []
    stale = stale_baseline_entries(findings, baseline)
    if stale:
        live = {fingerprint(f) for f in findings}
        Path(path).write_text(json.dumps(
            {"comment": "graftlint baseline — known findings grandfathered "
                        "in; regenerate with tools/graftlint.py "
                        "--write-baseline",
             "suppressed": sorted(baseline & live)}, indent=2) + "\n")
    return stale


# --- machine-readable output ---------------------------------------------

# The contract CI consumes (tests/test_graftlint.py validates emitted
# documents against this schema): bump "version" on breaking changes.
FINDINGS_JSON_SCHEMA = {
    "$schema": "http://json-schema.org/draft-07/schema#",
    "type": "object",
    "required": ["tool", "version", "files_scanned", "counts", "findings"],
    "additionalProperties": False,
    "properties": {
        "tool": {"const": "graftlint"},
        "version": {"type": "integer", "minimum": 1},
        "files_scanned": {"type": "integer", "minimum": 0},
        "counts": {"type": "object",
                   "additionalProperties": {"type": "integer"}},
        "findings": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["path", "rule", "line", "col", "message",
                             "fingerprint"],
                "additionalProperties": False,
                "properties": {
                    "path": {"type": "string"},
                    "rule": {"type": "string", "pattern": "^[A-Z0-9_]+$"},
                    "line": {"type": "integer", "minimum": 1},
                    "col": {"type": "integer", "minimum": 0},
                    "message": {"type": "string"},
                    "fingerprint": {"type": "string"},
                },
            },
        },
    },
}


def findings_to_json(findings: Sequence[Finding],
                     files_scanned: int = 0) -> dict:
    """Findings as the JSON document FINDINGS_JSON_SCHEMA describes."""
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "tool": "graftlint",
        "version": 1,
        "files_scanned": files_scanned,
        "counts": counts,
        "findings": [
            {"path": f.path, "rule": f.rule, "line": f.line, "col": f.col,
             "message": f.message, "fingerprint": fingerprint(f)}
            for f in findings],
    }


def findings_to_sarif(findings: Sequence[Finding]) -> dict:
    """Findings as a minimal SARIF 2.1.0 log (the format code-scanning
    UIs ingest); fingerprints carry the baseline identity."""
    from .rules import RULES

    rule_ids = sorted({f.rule for f in findings} | set(RULES))
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "rules": [{"id": r} for r in rule_ids],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": f.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                }}],
                "partialFingerprints": {"graftlint/v1": fingerprint(f)},
            } for f in findings],
        }],
    }


# --- ENV001 mechanical fix ----------------------------------------------

_ENV_IMPORT = "from dalle_pytorch_tpu.utils.helpers import env_flag"


def _env001_call_rewrite(node: ast.Call) -> Optional[str]:
    """env_flag replacement text for a fixable ENV001 call, else None.
    Fixable: single string-literal name, optionally with a falsy-constant
    default (None/''/False) — exactly the cases where env_flag(name) is
    the drop-in truth-equivalent."""
    if not node.args or node.keywords:
        return None
    name = node.args[0]
    if not (isinstance(name, ast.Constant) and isinstance(name.value, str)):
        return None
    if len(node.args) == 2:
        default = node.args[1]
        if not (isinstance(default, ast.Constant) and not default.value):
            return None
    elif len(node.args) != 1:
        return None
    return f'env_flag("{name.value}")'


def fix_env001(src: str, path: str = "<string>") -> Tuple[str, int]:
    """Mechanically rewrite fixable ENV001 truth-test calls to
    ``env_flag(NAME)``, adding the helpers import if the file doesn't
    already bind ``env_flag``.  Returns (new_source, fix_count)."""
    findings = lint_source(src, path=path, select=("ENV001",))
    tree = ast.parse(src)
    flagged = {(f.line, f.col) for f in findings if f.rule == "ENV001"}
    edits = []  # (lineno, col, end_lineno, end_col, replacement)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and (node.lineno, node.col_offset) in flagged:
            new = _env001_call_rewrite(node)
            if new is not None:
                edits.append((node.lineno, node.col_offset,
                              node.end_lineno, node.end_col_offset, new))
    if not edits:
        return src, 0

    lines = src.splitlines(keepends=True)
    applied = 0
    for l0, c0, l1, c1, new in sorted(edits, reverse=True):
        if l0 != l1:
            continue  # multi-line call: leave for a human
        line = lines[l0 - 1]
        lines[l0 - 1] = line[:c0] + new + line[c1:]
        applied += 1
    if not applied:
        return src, 0

    has_import = any(
        isinstance(n, ast.ImportFrom)
        and any(a.name == "env_flag" or a.asname == "env_flag"
                for a in n.names)
        for n in ast.walk(tree)) or "def env_flag" in src
    if not has_import:
        insert_at = 0
        for i, stmt in enumerate(tree.body):
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                insert_at = stmt.end_lineno
            elif i == 0 and isinstance(stmt, ast.Expr) \
                    and isinstance(stmt.value, ast.Constant):
                insert_at = stmt.end_lineno  # module docstring
        lines.insert(insert_at, _ENV_IMPORT + "\n")
    return "".join(lines), applied
