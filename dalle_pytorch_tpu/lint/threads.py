"""graftrace static half — lock-discipline analysis over thread-bearing
modules (the graftspmd of concurrency).

Pure-AST, import-free: the target file is parsed, never executed, so the
sweep runs on any box in milliseconds (same contract as graftlint).  Four
analyses, each named after the incident class it exists to catch:

* **T1 guarded-field inference** — a field written under ``with
  self.<lock>`` in any method is *guarded*: every other write must hold a
  lock, and reads from multi-thread-reachable methods (public methods,
  properties, ``threading.Thread`` targets) must hold one too.  The lost
  counter increment / torn dict update class.
* **T2 blocking-call-under-lock** — ``jit``/``compile``/``prefill``/
  ``.result()``/``.join()``/file I/O inside a ``with lock:`` body: every
  other thread that touches that lock stalls for the full blocking call
  (latency cliff), and a join on a thread that needs the same lock is a
  guaranteed deadlock.
* **T3 lock-order graph** — nested ``with lock:`` acquisitions (plus
  one level of ``self.method()`` call propagation) build a static
  acquisition-order graph; a cycle is a potential AB/BA deadlock, and a
  self-edge on a non-reentrant lock is a guaranteed one.
* **T4 callback-under-lock** — resolving a Future (``set_result`` /
  ``set_exception`` / ``add_done_callback``) or invoking a caller-supplied
  callable while holding a lock: the callback can re-enter the very
  structure whose lock is held.  The classic re-entrancy deadlock (and
  the bug class behind the router's resolve-outside-the-lock comment).

Pragma grammar (suppressions carry their justification inline, like
``graftlint: disable``):

* ``# graftrace: unguarded (reason)`` — suppress T1 on that line.
* ``# graftrace: allow=T2 (reason)`` — suppress the named analyses
  (comma-separated) on that line.

A pragma without a parenthesized reason is itself a finding (``TP``),
mirroring PRAGMA001: silent baselines are exactly what this tool exists
to prevent.  Fixtures proving each analysis has teeth live in
``threads_fixtures.py``; ``tools/thread_check.py`` is the CLI.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["Finding", "analyze_source", "analyze_file", "ANALYSES"]

ANALYSES = ("T1", "T2", "T3", "T4")

# Constructors whose result is a lock-like object (both the raw threading
# primitives and the graftrace wrappers; Condition rides the same
# with-statement discipline).
_LOCK_CTORS = {
    "Lock", "RLock", "Condition",
    "TracedLock", "TracedRLock", "TracedCondition",
}
_REENTRANT_CTORS = {"RLock", "TracedRLock", "Condition", "TracedCondition"}

# T2: calls that block (or can block unboundedly) — holding a lock across
# them stalls every peer of that lock.
_BLOCKING_BARE = {"open", "sleep", "jit", "compile", "prefill", "urlopen",
                  "fsync"}
_BLOCKING_METHOD = {"result", "join"}
_BLOCKING_DOTTED = {("os", "write"), ("os", "read"), ("os", "fsync"),
                    ("os", "open"), ("time", "sleep")}

# T4: names that denote caller-supplied callables when invoked as
# ``self.<name>(...)`` / ``obj.<name>(...)``.
_CALLBACK_RE = re.compile(r"(^on_|_cb$|_callback$|^callback$|_hook$|hooks?$)")
_FUTURE_RESOLVE = {"set_result", "set_exception", "add_done_callback"}

# Containers mutated in place: ``self.F.append(x)`` is a write to F.
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "discard",
    "pop", "popleft", "popitem", "add", "clear", "update", "setdefault",
}

_PRAGMA_RE = re.compile(
    r"#\s*graftrace:\s*(unguarded|allow=(?P<codes>[A-Z0-9,\s]+))"
    r"(?P<reason>\s*\(.+\))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    code: str       # T1..T4 | TP
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


# ---------------------------------------------------------------------------
# pragma handling
# ---------------------------------------------------------------------------


def _parse_pragmas(source: str, path: str):
    """line -> set of suppressed codes; bare pragmas become TP findings."""
    suppress: Dict[int, Set[str]] = {}
    bare: List[Finding] = []
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(text)
        if not m:
            continue
        if not m.group("reason"):
            bare.append(Finding(
                "TP", path, lineno,
                "bare graftrace pragma — a suppression must carry its "
                "justification: `# graftrace: unguarded (why)`"))
            continue
        if m.group(1).startswith("unguarded"):
            suppress.setdefault(lineno, set()).add("T1")
        else:
            for code in m.group("codes").split(","):
                code = code.strip()
                if code:
                    suppress.setdefault(lineno, set()).add(code)
    return suppress, bare


# ---------------------------------------------------------------------------
# per-class event extraction
# ---------------------------------------------------------------------------


def _terminal_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _dotted(func: ast.AST) -> Optional[Tuple[str, str]]:
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return (func.value.id, func.attr)
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _ctor_name(value: ast.AST) -> Optional[str]:
    if isinstance(value, ast.Call):
        return _terminal_name(value.func)
    return None


@dataclasses.dataclass
class _Event:
    kind: str            # "write" | "read" | "call" | "acquire"
    name: str            # field name, call repr, or lock id
    line: int
    held: Tuple[str, ...]
    method: str
    extra: Optional[ast.Call] = None


class _MethodVisitor(ast.NodeVisitor):
    """Walks one method body tracking the held-lock stack and recording
    field writes/reads, calls, and lock acquisitions."""

    def __init__(self, cls: "_ClassModel", method: str,
                 params: Set[str]) -> None:
        self.cls = cls
        self.method = method
        self.params = params
        self.held: List[str] = []
        # the `_locked` suffix convention (telemetry._rotate_locked):
        # such helpers are documented as called only with the class lock
        # already held, so seed the held-stack with every lock attr —
        # right for all four analyses, since the convention asserts the
        # locks ARE held for the method's whole body.
        if method.endswith("_locked"):
            self.held = [f"{cls.name}.{attr}" for attr in cls.lock_attrs]
        self.events: List[_Event] = []
        self._write_targets: Set[int] = set()  # id()s of store-ctx nodes

    # --- lock identification ---

    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and attr in self.cls.lock_attrs:
            return f"{self.cls.name}.{attr}"
        if isinstance(expr, ast.Name) and expr.id in self.cls.module_locks:
            return f"<module>.{expr.id}"
        return None

    # --- with: acquisition regions ---

    def visit_With(self, node: ast.With) -> None:
        acquired: List[str] = []
        for item in node.items:
            lock = self._lock_id(item.context_expr)
            if lock is not None:
                self.events.append(_Event(
                    "acquire", lock, node.lineno, tuple(self.held),
                    self.method))
                self.held.append(lock)
                acquired.append(lock)
            else:
                # the context expr itself may contain reads/calls
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    # --- writes ---

    def _record_write(self, target: ast.AST, line: int) -> None:
        # unwrap subscript chains: self.F[k] = v / del self.F[k]
        while isinstance(target, ast.Subscript):
            target = target.value
        attr = _self_attr(target)
        if attr is not None:
            self._write_targets.add(id(target))
            self.events.append(_Event(
                "write", attr, line, tuple(self.held), self.method))
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_write(elt, line)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_write(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._record_write(t, node.lineno)
        self.generic_visit(node)

    # --- reads + calls ---

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if (attr is not None and isinstance(node.ctx, ast.Load)
                and id(node) not in self._write_targets):
            self.events.append(_Event(
                "read", attr, node.lineno, tuple(self.held), self.method))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        self.events.append(_Event(
            "call", _terminal_name(node.func) or "<expr>", node.lineno,
            tuple(self.held), self.method, extra=node))
        # in-place mutation counts as a write to the receiver field
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            while isinstance(recv, ast.Subscript):
                recv = recv.value
            attr = _self_attr(recv)
            if attr is not None and node.func.attr in _MUTATORS:
                self.events.append(_Event(
                    "write", attr, node.lineno, tuple(self.held),
                    self.method))
        dotted = _dotted(node.func)
        if dotted and dotted[0] == "heapq" and node.args:
            recv = node.args[0]
            while isinstance(recv, ast.Subscript):
                recv = recv.value
            attr = _self_attr(recv)
            if attr is not None:
                self.events.append(_Event(
                    "write", attr, node.lineno, tuple(self.held),
                    self.method))
        self.generic_visit(node)

    # nested defs get their own thread of control only when used as Thread
    # targets (handled at class level); don't fold their bodies into the
    # enclosing method's held-stack
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return


@dataclasses.dataclass
class _ClassModel:
    name: str
    lock_attrs: Dict[str, str]        # attr -> ctor name
    module_locks: Dict[str, str]      # module-level name -> ctor name
    methods: Dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict)
    properties: Set[str] = dataclasses.field(default_factory=set)
    thread_targets: Set[str] = dataclasses.field(default_factory=set)
    events: List[_Event] = dataclasses.field(default_factory=list)


def _scan_lock_attrs(cls_node: ast.ClassDef) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.walk(cls_node):
        if isinstance(node, ast.Assign):
            ctor = _ctor_name(node.value)
            if ctor in _LOCK_CTORS:
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        out[attr] = ctor
    return out


def _scan_module_locks(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            ctor = _ctor_name(node.value)
            if ctor in _LOCK_CTORS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = ctor
    return out


def _is_property(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        name = _terminal_name(dec) or (dec.id if isinstance(dec, ast.Name)
                                       else None)
        if name in ("property", "cached_property", "setter"):
            return True
    return False


def _scan_thread_targets(cls_node: ast.ClassDef) -> Set[str]:
    """Method names passed as ``target=self.X`` to a Thread ctor."""
    out: Set[str] = set()
    for node in ast.walk(cls_node):
        if isinstance(node, ast.Call):
            name = _terminal_name(node.func)
            if name != "Thread":
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    attr = _self_attr(kw.value)
                    if attr is not None:
                        out.add(attr)
    return out


def _build_class_model(cls_node: ast.ClassDef,
                       module_locks: Dict[str, str]) -> _ClassModel:
    model = _ClassModel(cls_node.name, _scan_lock_attrs(cls_node),
                        module_locks)
    model.thread_targets = _scan_thread_targets(cls_node)
    for item in cls_node.body:
        if isinstance(item, ast.FunctionDef):
            model.methods[item.name] = item
            if _is_property(item):
                model.properties.add(item.name)
            params = {a.arg for a in item.args.args if a.arg != "self"}
            params |= {a.arg for a in item.args.kwonlyargs}
            v = _MethodVisitor(model, item.name, params)
            for stmt in item.body:
                v.visit(stmt)
            model.events.extend(v.events)
    return model


# ---------------------------------------------------------------------------
# the four analyses
# ---------------------------------------------------------------------------


def _t1_guarded_fields(model: _ClassModel, path: str) -> Iterable[Finding]:
    writes = [e for e in model.events if e.kind == "write"
              and e.name not in model.lock_attrs]
    guarded: Dict[str, str] = {}  # field -> one lock it is written under
    for e in writes:
        if e.held and e.method != "__init__" and e.name not in guarded:
            guarded[e.name] = e.held[-1]
    if not guarded:
        return
    reachable = {m for m in model.methods
                 if not m.startswith("_")} | model.properties \
        | model.thread_targets
    for e in model.events:
        if e.name not in guarded or e.held or e.method == "__init__":
            continue
        lock = guarded[e.name]
        if e.kind == "write":
            yield Finding(
                "T1", path, e.line,
                f"{model.name}.{e.name} is written under {lock} elsewhere "
                f"but written without a lock in {e.method}() — torn update "
                f"(annotate `# graftrace: unguarded (reason)` if benign)")
        elif e.kind == "read" and e.method in reachable:
            yield Finding(
                "T1", path, e.line,
                f"{model.name}.{e.name} is guarded by {lock} but read "
                f"without it in multi-thread-reachable {e.method}() — "
                f"stale/torn read (annotate `# graftrace: unguarded "
                f"(reason)` if benign)")


def _is_blocking_call(call: ast.Call) -> Optional[str]:
    func = call.func
    dotted = _dotted(func)
    if dotted in _BLOCKING_DOTTED:
        return ".".join(dotted)
    name = _terminal_name(func)
    if name is None:
        return None
    if isinstance(func, ast.Name) and name in _BLOCKING_BARE:
        return name
    if isinstance(func, ast.Attribute):
        if name in _BLOCKING_BARE:
            return name
        if name in _BLOCKING_METHOD:
            # ``", ".join(parts)`` is not a thread join: skip constant-str
            # receivers and iterable-arg joins; flag no-arg / timeout forms
            if name == "join":
                if isinstance(func.value, ast.Constant):
                    return None
                if call.args and not isinstance(
                        call.args[0], ast.Constant):
                    return None
            return name
    return None


def _t2_blocking_under_lock(model: _ClassModel,
                            path: str) -> Iterable[Finding]:
    for e in model.events:
        if e.kind != "call" or not e.held or e.extra is None:
            continue
        blocked = _is_blocking_call(e.extra)
        if blocked is not None:
            yield Finding(
                "T2", path, e.line,
                f"blocking call {blocked}() while holding {e.held[-1]} in "
                f"{model.name}.{e.method}() — every thread needing that "
                f"lock stalls for the full call (deadlock if the callee "
                f"needs it too)")


def _t3_lock_order(models: List[_ClassModel],
                   path: str) -> Iterable[Finding]:
    # edges from direct nesting
    edges: Dict[Tuple[str, str], Tuple[int, str]] = {}
    ctor_of: Dict[str, str] = {}
    for model in models:
        for attr, ctor in model.lock_attrs.items():
            ctor_of[f"{model.name}.{attr}"] = ctor
        for name, ctor in model.module_locks.items():
            ctor_of[f"<module>.{name}"] = ctor
        # locks a method acquires while holding nothing (for propagation)
        top_acquires: Dict[str, Set[str]] = {}
        for e in model.events:
            if e.kind == "acquire" and not e.held:
                top_acquires.setdefault(e.method, set()).add(e.name)
        for e in model.events:
            if e.kind == "acquire":
                for held in e.held:
                    edges.setdefault(
                        (held, e.name),
                        (e.line, f"{model.name}.{e.method}"))
            elif (e.kind == "call" and e.held and e.extra is not None):
                # one level of self-call propagation
                attr = _self_attr(e.extra.func)
                if attr in top_acquires:
                    for inner in top_acquires[attr]:
                        for held in e.held:
                            edges.setdefault(
                                (held, inner),
                                (e.line, f"{model.name}.{e.method}"))
    # self-edge on a non-reentrant lock: guaranteed deadlock
    for (a, b), (line, where) in sorted(edges.items(),
                                        key=lambda kv: kv[1][0]):
        if a == b and ctor_of.get(a) not in _REENTRANT_CTORS:
            yield Finding(
                "T3", path, line,
                f"re-entrant acquisition of non-reentrant lock {a} in "
                f"{where} — guaranteed self-deadlock")
    # cycle over distinct locks: potential AB/BA deadlock
    adj: Dict[str, List[str]] = {}
    for (a, b) in edges:
        if a != b:
            adj.setdefault(a, []).append(b)
    cycle = _find_cycle(adj)
    if cycle is not None:
        first = edges.get((cycle[0], cycle[1])) or (0, "?")
        yield Finding(
            "T3", path, first[0],
            "lock acquisition order cycle "
            + " -> ".join(cycle + [cycle[0]])
            + " — two threads entering from opposite ends deadlock")


def _find_cycle(adj: Dict[str, List[str]]) -> Optional[List[str]]:
    WHITE, GREY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    parent: Dict[str, str] = {}
    for start in sorted(adj):
        if color.get(start, WHITE) != WHITE:
            continue
        stack = [(start, iter(sorted(adj.get(start, ()))))]
        color[start] = GREY
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                c = color.get(nxt, WHITE)
                if c == GREY:
                    cycle = [node]
                    cur = node
                    while cur != nxt:
                        cur = parent[cur]
                        cycle.append(cur)
                    cycle.reverse()
                    return cycle
                if c == WHITE:
                    color[nxt] = GREY
                    parent[nxt] = node
                    stack.append((nxt, iter(sorted(adj.get(nxt, ())))))
                    advanced = True
                    break
            if not advanced:
                color[node] = BLACK
                stack.pop()
    return None


def _t4_callback_under_lock(model: _ClassModel,
                            path: str) -> Iterable[Finding]:
    param_names: Dict[str, Set[str]] = {}
    for name, fn in model.methods.items():
        params = {a.arg for a in fn.args.args if a.arg != "self"}
        params |= {a.arg for a in fn.args.kwonlyargs}
        param_names[name] = params
    for e in model.events:
        if e.kind != "call" or not e.held or e.extra is None:
            continue
        func = e.extra.func
        name = _terminal_name(func)
        if name in _FUTURE_RESOLVE:
            yield Finding(
                "T4", path, e.line,
                f"{name}() while holding {e.held[-1]} in "
                f"{model.name}.{e.method}() — done-callbacks run inline "
                f"and can re-enter the locked structure (resolve futures "
                f"OUTSIDE the lock)")
        elif (isinstance(func, ast.Name)
              and func.id in param_names.get(e.method, ())):
            yield Finding(
                "T4", path, e.line,
                f"caller-supplied callable {func.id}() invoked while "
                f"holding {e.held[-1]} in {model.name}.{e.method}() — "
                f"re-entrancy deadlock if the callback touches this "
                f"structure")
        elif name is not None and _CALLBACK_RE.search(name):
            yield Finding(
                "T4", path, e.line,
                f"callback-like {name}() invoked while holding "
                f"{e.held[-1]} in {model.name}.{e.method}() — re-entrancy "
                f"hazard (invoke after release, or annotate "
                f"`# graftrace: allow=T4 (reason)`)")


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def analyze_source(source: str, path: str = "<source>",
                   select: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run T1–T4 over one module's source; returns surviving findings
    (pragma-suppressed ones dropped, bare pragmas reported as TP)."""
    tree = ast.parse(source, filename=path)
    suppress, findings = _parse_pragmas(source, path)
    module_locks = _scan_module_locks(tree)
    models = [_build_class_model(node, module_locks)
              for node in tree.body if isinstance(node, ast.ClassDef)]
    raw: List[Finding] = []
    for model in models:
        raw.extend(_t1_guarded_fields(model, path))
        raw.extend(_t2_blocking_under_lock(model, path))
        raw.extend(_t4_callback_under_lock(model, path))
    raw.extend(_t3_lock_order(models, path))
    wanted = set(select) if select is not None else set(ANALYSES)
    for f in raw:
        if f.code not in wanted:
            continue
        if f.code in suppress.get(f.line, ()):
            continue
        findings.append(f)
    findings.sort(key=lambda f: (f.line, f.code))
    return findings


def analyze_file(path, select: Optional[Iterable[str]] = None
                 ) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    return analyze_source(source, str(path), select=select)
