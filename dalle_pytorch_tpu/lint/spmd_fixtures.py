"""Deliberately-broken step programs — the teeth-proof for graftspmd.

One fixture per analysis, each reproducing the bug class its analysis
exists to catch (mirrors the broken-model pattern of
tests/test_contract_check.py): a data-dependent ``ppermute`` (S1 SPMD
deadlock), a train step built without donation (S2 doubled HBM), a step
whose static arg is a fresh object per call and one whose static arg is a
list (S3 recompile storm / cache defeat), and a plan gated against a chip
it cannot fit (S4).  Used by tests/test_spmd_check.py and by
``tools/spmd_check.py --selftest``; never imported by production code.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..parallel.mesh import shard_map


# --- S1: a collective dominated by data-dependent control flow ------------


def make_conditional_collective_step(mesh, axis: str = "dp"):
    """A shard_map'd step whose ``ppermute`` only runs when the local batch
    mean is positive — a data-dependent predicate that can disagree across
    shards, leaving part of the mesh blocked in a collective its peers
    never enter.  The canonical SPMD deadlock."""
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def local(x):
        def rotate(v):
            return jax.lax.ppermute(v, axis, perm)

        # divergent predicate: each shard sees its OWN slice's statistics
        return jax.lax.cond(jnp.mean(x) > 0.0, rotate, lambda v: v, x)

    # graftlint: disable=DON001 (stateless S1 toy step: nothing to donate)
    return jax.jit(shard_map(local, mesh=mesh, in_specs=(P(axis),),
                             out_specs=P(axis), check_vma=False))


def make_branch_matched_collective_step(mesh, axis: str = "dp"):
    """The clean twin: both branches issue the IDENTICAL collective
    sequence, so shards stay in lockstep whichever branch each takes
    (the parallel/pipeline.py drain-bubble pattern).  Must PASS S1."""
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def local(x):
        def fwd(v):
            return jax.lax.ppermute(v, axis, perm) * 2.0

        def bwd(v):
            return jax.lax.ppermute(v, axis, perm) * 0.5

        return jax.lax.cond(jnp.mean(x) > 0.0, fwd, bwd, x)

    # graftlint: disable=DON001 (stateless S1 toy step: nothing to donate)
    return jax.jit(shard_map(local, mesh=mesh, in_specs=(P(axis),),
                             out_specs=P(axis), check_vma=False))


# --- S2: a dropped donation -----------------------------------------------


def make_undonated_train_step(tx):
    """A params/opt_state update jitted WITHOUT ``donate_argnums`` — the
    forgotten-donation bug: params and opt_state are live twice across the
    step (inputs held by the caller, outputs fresh buffers)."""
    import optax

    def train_step(params, opt_state, batch):
        def loss_fn(p):
            pred = batch @ p["w"] + p["b"]
            return jnp.mean(pred ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # graftlint: disable=DON001 (the broken S2 fixture: the dropped donation IS the bug check_donation must catch)
    return jax.jit(train_step)


def fixture_params(dim: int = 64):
    params = {"w": jnp.zeros((dim, dim), jnp.float32),
              "b": jnp.zeros((dim,), jnp.float32)}
    return params


# --- S3: weak-hash / unhashable static args -------------------------------


@dataclasses.dataclass(eq=False)
class WeakHashSchedule:
    """Hashes by identity (eq=False): two equal-valued instances are
    different jit cache keys, so rebuilding it per step — the natural way
    to write a schedule — retraces every call."""

    lr: float


def make_retracing_step():
    """A step whose schedule rides in as a static arg and is rebuilt per
    call: every invocation is a cache miss (the recompile storm S3
    exists to catch).  Returns ``(jitted, make_args)``."""

    def step(x, sched):
        return x * sched.lr

    # graftlint: disable=DON001 (stateless S3 toy step: nothing to donate)
    jitted = jax.jit(step, static_argnums=(1,))

    def make_args(i):
        return (jnp.ones((4,), jnp.float32) * (i + 1),
                WeakHashSchedule(lr=1e-3))  # fresh object per step

    return jitted, make_args


def make_unhashable_static_step():
    """The list-keyed variant: a list static arg cannot hash at all, so
    the call never reaches the cache — jax raises instead.  Returns
    ``(jitted, make_args)``."""

    def step(x, dims):
        return x.reshape(dims)

    # graftlint: disable=DON001 (stateless S3 toy step: nothing to donate)
    jitted = jax.jit(step, static_argnums=(1,))

    def make_args(i):
        return jnp.ones((4,), jnp.float32), [2, 2]  # list: unhashable

    return jitted, make_args


def make_stable_step():
    """The clean twin: schedule values ride as traced scalars; N steps,
    one trace.  Must PASS S3."""

    def step(x, lr):
        return x * lr

    # graftlint: disable=DON001 (stateless S3 toy step: nothing to donate)
    jitted = jax.jit(step)

    def make_args(i):
        return (jnp.ones((4,), jnp.float32) * (i + 1),
                jnp.float32(1e-3 * (i + 1)))

    return jitted, make_args


# --- S4: an oversized plan ------------------------------------------------


def oversized_step_compiled(mib: int = 64):
    """Compile a step whose arguments alone exceed ``mib`` MiB — gate it
    against a toy capacity to prove the budget check fires.  (The real
    CLI gates production plans against real chip tables; the fixture
    keeps the compile tiny.)"""

    from . import spmd

    def step(a, b):
        return a @ b

    n = 1024
    a = jax.ShapeDtypeStruct((n, n * 16), jnp.float32)  # 64 MiB
    b = jax.ShapeDtypeStruct((n * 16, 8), jnp.float32)
    with spmd.fresh_stats_compile():  # cached executables report zero stats
        return jax.jit(step).lower(a, b).compile()


# --- S1 (scan schedule): microbatch-scan collective schedules -------------


def make_pipelined_collective_scan(mesh, axis: str = "dp",
                                   length: int = 4):
    """The clean microbatch-scan shape: every iteration issues the same
    one-hop ``ppermute`` (the GPipe stage handoff), so the schedule is a
    static ``length x [ppermute]`` fact.  Must PASS
    ``scan_collective_schedule`` and report exactly that schedule."""
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def local(x):
        def body(carry, _):
            return jax.lax.ppermute(carry, axis, perm), ()

        out, _ = jax.lax.scan(body, x, None, length=length)
        return out

    return jax.jit(shard_map(local, mesh=mesh, in_specs=(P(axis),),
                             out_specs=P(axis), check_vma=False))


def make_unbalanced_microbatch_scan(mesh, axis: str = "dp",
                                    length: int = 4):
    """The anti-pattern the scan-schedule analysis exists to refuse: an
    epilogue collective folded into the LAST scan iteration via a cond
    whose other branch issues nothing — the per-iteration collective
    sequence is no longer a static fact (it depends on the traced
    iteration index), so no ``iteration-count x per-iteration`` schedule
    exists and shards whose predicates disagree deadlock.  Must FAIL
    ``scan_collective_schedule``."""
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def local(x):
        def body(carry, t):
            def epilogue(v):
                return jax.lax.psum(
                    jax.lax.ppermute(v, axis, perm), axis)

            carry = jax.lax.cond(t == length - 1, epilogue,
                                 lambda v: v * n, carry)
            return carry, ()

        out, _ = jax.lax.scan(body, x, jnp.arange(length))
        return out

    return jax.jit(shard_map(local, mesh=mesh, in_specs=(P(axis),),
                             out_specs=P(axis), check_vma=False))


# --- S3 (serve): a shape-changing decode tick -----------------------------


def make_shape_changing_serve_tick(num_slots: int = 4):
    """The continuous-batching anti-pattern the serve arena exists to
    prevent: a decode tick whose cache tensors are CROPPED to the current
    occupancy ("why compute the idle slots?").  Every occupancy change is
    a new shape, so admitting or retiring one request recompiles the tick
    — on a real pod that is a recompile per arrival, the exact storm the
    S3 serve gate (tools/spmd_check.py serve-tick harness) pins the real
    arena against.  Returns ``(jitted, make_args)``: ``make_args(i)``
    cycles through occupancies 1..num_slots like an admit/retire churn.
    Must FAIL check_single_trace."""

    def tick(caches, codes):
        return caches + 1.0, codes + 1

    jitted = jax.jit(tick)

    def make_args(i):
        n = (i % num_slots) + 1  # occupancy churn: 1, 2, ..., S, 1, ...
        return (jnp.zeros((n, 8, 16), jnp.float32),
                jnp.zeros((n,), jnp.int32))

    return jitted, make_args
