"""graftplan — static ParallelPlan contract analyses (P1-P4).

graftspmd reads the traced programs and graftrace reads the lock graph;
this module reads the *sharding contract itself*: the regex rule table
(``parallel/plan.PARTITION_RULES``), the plan registry, and the preset
geometries, cross-checked chip-free against declared chip topologies.
Four pure analyses, each provable against a deliberately-broken fixture
twin (``plans_fixtures.py``, ``tools/plan_check.py --selftest``):

* **P1 rule coverage / ambiguity** — every shardable (ndim >= 2) param
  leaf of every preset matches a ``PARTITION_RULES`` entry or a declared
  replication pattern (:data:`P1_REPLICATED`).  An unmatched leaf
  silently replicates (the exact failure dalle-mini hand-audited its
  rule tables against); two *conflicting* non-terminal rules matching
  the same leaf make the table order load-bearing — first-hit-wins
  silently shadows the loser, so the overlap is a finding.
* **P2 axis divisibility** — ``mesh._prune_spec`` SILENTLY drops any
  rule axis that does not divide the param dim, and
  ``Partitioner.shard_batch`` silently replicates a batch the data axes
  don't divide.  P2 makes both degradations loud: for each (preset x
  plan x topology) cell it resolves the mesh axis sizes (``dp=None``
  absorption included) and flags every sharded dim the mesh would
  silently un-shard.
* **P3 analytic HBM fit** — per-leaf sharded state residency (params +
  optimizer moments, divided by exactly the axis products that survive
  P2's divisibility) folded through the graftmem phase model against
  ``CHIP_SPECS`` x0.9.  The hard gate covers the phases sharding alone
  controls — ``init`` (state resident) and ``ckpt`` (snapshot pins the
  state twice, no donation); the walker's global activation peak rides
  along as the advisory ``step_peak`` (the committed cub-512 memory row
  precedent: the no-remat f32 walker is deliberately pessimistic, and
  the compiled S4 proof under ``spmd_check --presets`` owns step-peak
  truth).
* **P4 collective placement** — for dcn hybrid plans: fsdp/tp axes must
  fit inside one ICI slice (a multi-slice topology without a matching
  ``dcn_dp`` axis leaves slice pinning undefined), and in the traced
  step only a ``psum`` over the dp axis (the grad all-reduce) may cross
  DCN — any other collective over a DCN-crossing axis is a finding.
  The jaxpr walk reuses graftspmd's collective taxonomy
  (``spmd.collective_trace``), so shard_map plans with explicit
  collectives are covered by the same sweep.

``tools/plan_check.py`` is the CLI (the graftrace shape: default sweep,
``--select``, ``--json``, ``--selftest``); ``tools/plan_search.py``
reuses the same analyses as hard feasibility gates and adds the
roofline score (:func:`score_cell`) to pick the committed
``PLAN_LEDGER.json`` winners.

Chip topologies are declared here (:data:`TOPOLOGIES`), separate from
``prof.CHIP_SPECS``: a chip spec is one device's peaks; a topology is
how many of them, in how many DCN-connected slices.  Waivers
(:data:`WAIVERS`) are the pragma equivalent for cell-anchored findings
— empty at HEAD; every entry needs a written reason.
"""
from __future__ import annotations

import dataclasses
import functools
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from dalle_pytorch_tpu.parallel.plan import (PARTITION_RULES, PLAN_REGISTRY,
                                             ParallelPlan)

ANALYSES = ("P1", "P2", "P3", "P4")

#: Mirror of obs/mem.HBM_MARGIN — allocator fragmentation eats the rest.
HBM_MARGIN = 0.9

#: Analytic DCN bandwidth per device (bytes/s) for the autotuner's
#: multi-slice penalty term: the grad all-reduce's ring streams ~2x the
#: per-device grad shard over the data-center network.  Held stable by
#: construction (the drift gate compares scores computed from it).
DCN_BW = 25e9

#: 2-D+ leaves that are replicated BY DESIGN, not by rule-table
#: fall-through: position embeddings (tiny, consumed whole every step)
#: and the per-layer layerscale vectors.  P1 flags any other >=2-D leaf
#: that matches no PARTITION_RULES entry — new param surfaces must either
#: get a rule or be declared here, with a reason, in review.
P1_REPLICATED = (
    r".*pos_emb/(embedding|row|col)$",
    r".*(attn|ff)/scale$",
)

#: Cell-anchored waivers, the pragma equivalent for findings that have no
#: source line to annotate: (code, cell regex, reason).  Empty at HEAD —
#: plan_check reports a waived finding as suppressed, and an entry that
#: matches nothing is itself an error (the PRAGMA002 discipline).
WAIVERS: Tuple[Tuple[str, str, str], ...] = ()


class PlanAnalysisError(Exception):
    """Harness errors (unknown preset/chip, malformed waiver) — distinct
    from findings, which are contract violations."""


def _fmt_bytes(n: int) -> str:
    for unit, div in (("GiB", 2**30), ("MiB", 2**20), ("KiB", 2**10)):
        if n >= div:
            return f"{n / div:.1f} {unit}"
    return f"{n} B"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One contract violation, anchored to its (preset x plan @ topology)
    cell rather than a source line."""

    code: str      # P1..P4
    cell: str      # e.g. "cub-1024 x fsdp4.tp2 @ v5e-8"
    message: str

    def render(self) -> str:
        return f"{self.cell}: {self.code} {self.message}"


# --- chip topologies ------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Topology:
    """A concrete device pool: ``chip`` names the per-device
    ``prof.CHIP_SPECS`` entry, ``devices`` how many, ``slices`` how many
    DCN-connected ICI islands they form (1 = single slice, everything on
    ICI)."""

    name: str
    chip: str
    devices: int
    slices: int = 1

    def __post_init__(self):
        if self.devices % self.slices:
            raise PlanAnalysisError(
                f"topology {self.name!r}: {self.devices} devices not "
                f"divisible into {self.slices} slices")

    @property
    def per_slice(self) -> int:
        return self.devices // self.slices


#: The topology ladder the analyzer and autotuner sweep.  Single-slice
#: pods first, then the multi-slice rung where dcn plans earn their keep.
TOPOLOGIES: Tuple[Topology, ...] = (
    Topology("v4-8", "v4-8", 4),
    Topology("v5e-4", "v5e-4", 4),
    Topology("v4-16", "v4-8", 8),
    Topology("v5e-8", "v5e-4", 8),
    Topology("2x-v5e-8", "v5e-4", 16, slices=2),
)


def topology(name: str) -> Topology:
    for t in TOPOLOGIES:
        if t.name == name:
            return t
    raise PlanAnalysisError(f"unknown topology {name!r}; known: "
                            f"{[t.name for t in TOPOLOGIES]}")


# --- plan candidates ------------------------------------------------------

#: The autotuner's candidate grid, as plan specs.  Covers every dense
#: (rule-table) registry plan's spec — dp, fsdp (fsdp4), tp (tp2),
#: cub-512 (fsdp4), cub-1024 (fsdp4.tp2) — plus the hybrids the registry
#: doesn't name and the dcn variants for multi-slice topologies.
#: sp/pp/ep plans are out of scope here: they own the inner mesh axis,
#: the partition rules prune to replicated under their meshes, and their
#: shard_map steps are scored by graftprof's per-shard walk instead.
CANDIDATE_SPECS: Tuple[str, ...] = (
    "dp",
    "fsdp4",
    "fsdp8",
    "tp2",
    "fsdp2.tp2",
    "fsdp4.tp2",
    "dcn2.fsdp2",
    "dcn2.fsdp2.tp2",
    "dcn2.fsdp4.tp2",
)


@functools.lru_cache(maxsize=None)
def candidate_plans() -> Tuple[ParallelPlan, ...]:
    return tuple(ParallelPlan.parse(s) for s in CANDIDATE_SPECS)


# --- mesh-axis resolution (the dp=None absorption, chip-free) -------------


def resolve_axis_sizes(plan: ParallelPlan, topo: Topology
                       ) -> Tuple[Optional[Dict[str, int]], Optional[str]]:
    """Resolve the plan's mesh axis sizes on a topology — the same
    arithmetic ``mesh.make_mesh`` performs, without devices.  Returns
    ``(sizes, None)`` with sizes keyed by mesh axis name, or
    ``(None, reason)`` when the plan cannot build on this topology at
    all (an infeasibility, not a finding: the autotuner records the
    reason, the analyzer skips the cell)."""
    n = topo.devices
    if plan.sp > 1 or plan.pp > 1 or plan.ep > 1:
        axis = "sp" if plan.sp > 1 else "pp" if plan.pp > 1 else "ep"
        inner = getattr(plan, axis)
        if n % inner:
            return None, (f"{n} devices not divisible by {axis}={inner}")
        dp = plan.dp if plan.dp is not None else n // inner
        if dp * inner != n:
            return None, (f"dp{dp} x {axis}{inner} != {n} devices")
        return {"dp": dp, axis: inner}, None
    inner = plan.fsdp * plan.tp
    if plan.dp is None:
        if n % inner:
            return None, (f"{n} devices not divisible by "
                          f"fsdp{plan.fsdp} x tp{plan.tp} = {inner}")
        dp = n // inner
    else:
        dp = plan.dp
        if dp * inner != n:
            return None, (f"dp{dp} x fsdp{plan.fsdp} x tp{plan.tp} "
                          f"= {dp * inner} != {n} devices")
    if dp == 0:
        return None, (f"fsdp{plan.fsdp} x tp{plan.tp} = {inner} ways "
                      f"exceed {n} devices")
    if plan.dcn_dp > 1 and dp % plan.dcn_dp:
        return None, f"dp={dp} not divisible by dcn_dp={plan.dcn_dp}"
    return {"dp": dp, "fsdp": plan.fsdp, "tp": plan.tp}, None


# --- rule matching (P1/P2 share it) ---------------------------------------


@functools.lru_cache(maxsize=8)
def _compiled(rules) -> Tuple:
    return tuple((re.compile(pat), spec) for pat, spec in rules)


def matching_rules(path: str, rules=PARTITION_RULES) -> List[int]:
    """Indices of every rule whose pattern matches the '/'-joined param
    path (the Partitioner takes index 0 — first hit wins)."""
    return [i for i, (pat, _) in enumerate(_compiled(rules))
            if pat.match(path)]


def winning_spec(path: str, rules=PARTITION_RULES):
    """The spec the Partitioner would pick, before divisibility pruning
    (None = no rule matches: replicated by fall-through)."""
    hits = matching_rules(path, rules)
    return rules[hits[0]][1] if hits else None


def _spec_axes(spec) -> Tuple[Tuple[Tuple[str, ...], ...], ...]:
    """Per-dim tuples of axis names (empty tuple = unsharded dim)."""
    out = []
    for names in spec:
        if names is None:
            out.append(())
        else:
            out.append((names,) if isinstance(names, str) else tuple(names))
    return tuple(out)


def leaf_shard_factor(shape: Tuple[int, ...], spec,
                      sizes: Dict[str, int]) -> int:
    """The divisor ``_prune_spec`` would actually realize for this leaf:
    the product of axis sizes over dims where every named axis exists in
    the mesh and the product divides the dim.  1 = fully replicated."""
    if spec is None:
        return 1
    factor = 1
    for dim, names in enumerate(_spec_axes(spec)):
        if not names or dim >= len(shape):
            continue
        size = 1
        for nm in names:
            size *= sizes.get(nm, 1)
        if size > 1 and all(nm in sizes for nm in names) \
                and shape[dim] % size == 0:
            factor *= size
    return factor


# --- P1: rule-table coverage / ambiguity ----------------------------------


def check_rule_coverage(param_shapes: Dict[str, Tuple[Tuple[int, ...], int]],
                        rules=PARTITION_RULES, *,
                        preset: str = "?") -> List[Finding]:
    """P1.  ``param_shapes`` maps '/'-joined leaf paths to (shape,
    itemsize) — :func:`preset_cost` builds it from ``jax.eval_shape``,
    fixtures hand-craft it."""
    findings: List[Finding] = []
    cell = f"{preset} x PARTITION_RULES"
    replicated_ok = tuple(re.compile(p) for p in P1_REPLICATED)
    terminal = len(rules) - 1
    for path, (shape, _item) in sorted(param_shapes.items()):
        hits = matching_rules(path, rules)
        if not hits:
            if len(shape) >= 2 and not any(p.match(path)
                                           for p in replicated_ok):
                findings.append(Finding(
                    "P1", cell,
                    f"param leaf {path} {tuple(shape)} matches no "
                    "PARTITION_RULES entry — it silently replicates on "
                    "every mesh; add a rule (or declare it in "
                    "plans.P1_REPLICATED with a reason)"))
            continue
        winner = rules[hits[0]][1]
        for i in hits[1:]:
            if i == terminal:
                continue  # the declared catch-all default may overlap
            if tuple(rules[i][1]) != tuple(winner):
                findings.append(Finding(
                    "P1", cell,
                    f"param leaf {path} matches rule #{hits[0]} "
                    f"({rules[hits[0]][0]!r} -> {winner}) AND rule #{i} "
                    f"({rules[i][0]!r} -> {rules[i][1]}) with conflicting "
                    "specs — first-hit-wins silently shadows the loser; "
                    "tighten one pattern so the table order is not "
                    "load-bearing"))
    return findings


# --- P2: axis divisibility -------------------------------------------------


def check_divisibility(param_shapes: Dict[str, Tuple[Tuple[int, ...], int]],
                       plan: ParallelPlan, topo: Topology, *,
                       preset: str = "?", batch: Optional[int] = None,
                       rules=None) -> List[Finding]:
    """P2.  Every axis a rule shards by must divide its dim on this
    topology's resolved mesh — otherwise ``_prune_spec`` silently drops
    the axis and the leaf replicates (the memory the plan promised to
    shard quietly comes back).  ``batch`` additionally gates
    ``shard_batch``'s silent replicated fallback."""
    rules = plan.rules if rules is None else rules
    sizes, why = resolve_axis_sizes(plan, topo)
    if sizes is None:
        return []  # infeasible cell: the autotuner records `why`
    findings: List[Finding] = []
    cell = f"{preset} x {plan.spec()} @ {topo.name}"
    for path, (shape, item) in sorted(param_shapes.items()):
        spec = winning_spec(path, rules)
        if spec is None:
            continue
        for dim, names in enumerate(_spec_axes(spec)):
            if not names or dim >= len(shape):
                continue
            size = 1
            for nm in names:
                size *= sizes.get(nm, 1)
            if size > 1 and all(nm in sizes for nm in names) \
                    and shape[dim] % size != 0:
                leaf_bytes = item
                for s in shape:
                    leaf_bytes *= s
                findings.append(Finding(
                    "P2", cell,
                    f"{path} dim {dim} ({shape[dim]}) is not divisible by "
                    f"{'x'.join(names)}={size} — mesh._prune_spec will "
                    f"silently drop the axis and keep all "
                    f"{_fmt_bytes(leaf_bytes)} resident per device "
                    f"instead of 1/{size}"))
    if batch is not None:
        data_ways = sizes.get("dp", 1) * sizes.get("fsdp", 1)
        # data_ways > batch is a capacity infeasibility (the cell cannot
        # even give one row per group — plan_search records the reason
        # via batch_infeasible); only the silent-degradation case where
        # the batch COULD shard but doesn't divide is a finding.
        if 1 < data_ways <= batch and batch % data_ways:
            findings.append(Finding(
                "P2", cell,
                f"batch {batch} is not divisible by the data axes "
                f"dp x fsdp = {data_ways} — Partitioner.shard_batch "
                "silently falls back to a replicated batch (every device "
                "computes every row)"))
    return findings


def batch_infeasible(plan: ParallelPlan, topo: Topology,
                     batch: int) -> Optional[str]:
    """The autotuner's capacity check: more data-parallel groups than
    batch rows means the cell cannot run as intended at all (reason
    string), as opposed to P2's silent-replication finding."""
    sizes, why = resolve_axis_sizes(plan, topo)
    if sizes is None:
        return why
    data_ways = sizes.get("dp", 1) * sizes.get("fsdp", 1)
    if data_ways > batch:
        return (f"data axes dp x fsdp = {data_ways} exceed batch {batch} "
                "— fewer than one row per data-parallel group")
    return None


# --- per-preset cost model (the one expensive walk, cached) ----------------


@dataclasses.dataclass(frozen=True)
class PresetCost:
    """Everything the per-cell analyses need about one preset geometry,
    computed once: the param tree's paths/shapes, global state bytes,
    the graftmem liveness walk, and the graftprof flop/byte attribution.
    ``jaxpr`` rides along for P4's collective walk."""

    preset: str
    batch: int
    param_shapes: Dict[str, Tuple[Tuple[int, ...], int]]
    params_bytes: int
    opt_bytes: int
    flops: int
    walker_bytes: int
    walker_peak_bytes: int
    resident_bytes: int
    jaxpr: object = dataclasses.field(repr=False, hash=False, compare=False)
    config: object = dataclasses.field(repr=False, hash=False, compare=False)


@functools.lru_cache(maxsize=None)
def preset_cost(preset: str, batch: int = 8) -> PresetCost:
    """Trace the preset's real train step (health-enabled, the graftprof
    convention) once and distill the analysis inputs.  Chip-free:
    eval_shape + make_jaxpr, nothing executes or compiles — ~20 s at
    cub-1024, milliseconds at tiny."""
    import jax
    import jax.numpy as jnp

    from dalle_pytorch_tpu.models.dalle import DALLE
    from dalle_pytorch_tpu.obs import mem, prof
    from dalle_pytorch_tpu.parallel.mesh import _path_str
    from dalle_pytorch_tpu.presets import preset_config
    from dalle_pytorch_tpu.training import (make_dalle_train_step,
                                            make_optimizer)

    cfg = preset_config(preset)
    dalle = DALLE(cfg)
    tx = make_optimizer(1e-3)
    sds = jax.ShapeDtypeStruct
    text = sds((batch, cfg.text_seq_len), jnp.int32)
    codes = sds((batch, cfg.image_seq_len), jnp.int32)
    rng = sds((2,), jnp.uint32)
    fs = sds((), jnp.float32)
    params = jax.eval_shape(dalle.init, jax.random.PRNGKey(0), text,
                            codes)["params"]
    opt = jax.eval_shape(tx.init, params)
    step = make_dalle_train_step(dalle, tx, health=True)
    jaxpr = jax.make_jaxpr(step)(params, opt, None, text, codes, rng, fs)
    attr = prof.attribute(jaxpr)
    prof.check_coverage(attr, label=f"graftplan/{preset}")
    walk = mem.peak_live(
        jaxpr,
        planes=mem.arg_planes(("params", params), ("opt-state", opt),
                              ("args", (None, text, codes, rng, fs))))
    shapes = {
        _path_str(path): (tuple(leaf.shape), int(leaf.dtype.itemsize))
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]}
    return PresetCost(
        preset=preset, batch=batch, param_shapes=shapes,
        params_bytes=int(mem.tree_bytes(params)),
        opt_bytes=int(mem.tree_bytes(opt)),
        flops=int(attr["total"]["flops"]),
        walker_bytes=int(attr["total"]["bytes"]),
        walker_peak_bytes=int(walk["peak_bytes"]),
        resident_bytes=int(walk["resident_bytes"]),
        jaxpr=jaxpr, config=cfg)


def sharded_state_bytes(cost: PresetCost, plan: ParallelPlan,
                        sizes: Dict[str, int]) -> Tuple[int, int]:
    """Per-device (params, opt) residency under exactly the sharding the
    mesh would realize: each leaf divided by its :func:`leaf_shard_factor`
    (the P2-surviving axis product).  The Adam moments shard like their
    params (Partitioner.init_opt_state pins them so), so the optimizer
    side is 2x the sharded params plus the tree's scalar remainder,
    replicated."""
    params_sh = 0
    for path, (shape, item) in cost.param_shapes.items():
        leaf = item
        for s in shape:
            leaf *= s
        params_sh += leaf // leaf_shard_factor(
            shape, winning_spec(path, plan.rules), sizes)
    moments = 2 * cost.params_bytes
    remainder = max(0, cost.opt_bytes - moments)
    opt_sh = 2 * params_sh + remainder
    return params_sh, opt_sh


# --- P3: analytic HBM fit --------------------------------------------------


def state_phases(cost: PresetCost, plan: ParallelPlan, topo: Topology
                 ) -> Optional[Dict[str, int]]:
    """The graftmem phase timeline for one cell, per device: ``init``
    (sharded state resident) and ``ckpt`` (the between-steps snapshot
    pins the state twice — unlike ``mem.analytic_train_phases`` this
    chip-free gate models checkpointing between steps, not mid-step)
    from per-leaf sharded state, exact; ``step_peak`` adds the walker's
    global activation peak divided across devices (advisory — no-remat
    f32, see module docstring)."""
    sizes, _ = resolve_axis_sizes(plan, topo)
    if sizes is None:
        return None
    params_sh, opt_sh = sharded_state_bytes(cost, plan, sizes)
    state = params_sh + opt_sh
    act = max(0, cost.walker_peak_bytes
              - cost.resident_bytes) // max(topo.devices, 1)
    return {"init": state, "step_peak": state + act, "ckpt": 2 * state}


def check_hbm_fit(cost: PresetCost, plan: ParallelPlan, topo: Topology, *,
                  margin: float = HBM_MARGIN) -> List[Finding]:
    """P3.  Gate ``init`` and ``ckpt`` (state residency — what sharding
    alone controls) against the topology's per-device HBM at the S4
    margin."""
    from dalle_pytorch_tpu.obs import mem

    phases = state_phases(cost, plan, topo)
    if phases is None:
        return []
    gated = {k: phases[k] for k in ("init", "ckpt")}
    verdict = mem.headroom_verdict(gated, topo.chip, margin)
    if verdict["fits"]:
        return []
    cell = f"{cost.preset} x {plan.spec()} @ {topo.name}"
    return [Finding(
        "P3", cell,
        f"sharded state residency {verdict['peak_bytes'] / 2**30:.2f} GiB "
        f"in phase {verdict['peak_phase']!r} exceeds {margin:.0%} of "
        f"{topo.chip}'s {verdict['hbm_bytes'] / 2**30:.1f} GiB HBM — the "
        "plan's shard factors cannot hold this preset's params + "
        "optimizer moments; more fsdp/tp ways (or a bigger chip) needed")]


# --- P4: collective placement (dcn hybrids) --------------------------------


def crossing_axes(plan: ParallelPlan, topo: Topology
                  ) -> Tuple[set, List[str]]:
    """The mesh axes whose collectives traverse DCN on this topology,
    plus structural violations (reasons) that make placement undefined
    or force inner axes across slices."""
    problems: List[str] = []
    if topo.slices == 1:
        if plan.dcn_dp > 1:
            problems.append(
                f"plan declares dcn_dp={plan.dcn_dp} on single-slice "
                f"{topo.name} — there is no DCN boundary to pin")
        return set(), problems
    cross = {"dp"}  # dp's outer groups span the slice boundary
    if plan.dcn_dp != topo.slices:
        problems.append(
            f"multi-slice topology ({topo.slices} slices) but plan "
            f"dcn_dp={plan.dcn_dp}: mesh construction cannot pin the "
            "slice boundary, so fsdp/tp collective placement is "
            "undefined — declare dcn_dp equal to the slice count")
    inner = plan.fsdp * plan.tp * plan.sp * plan.pp * plan.ep
    if inner > topo.per_slice:
        problems.append(
            f"fsdp/tp ways ({inner}) exceed the {topo.per_slice} devices "
            "of one ICI slice — their all-gathers would cross DCN")
        for axis in ("fsdp", "tp", "sp", "pp", "ep"):
            if getattr(plan, axis) > 1:
                cross.add(axis)
    return cross, problems


def check_collective_placement(plan: ParallelPlan, topo: Topology, *,
                               preset: str = "?",
                               jaxpr=None) -> List[Finding]:
    """P4.  Structural slice-pinning checks plus the graftspmd-taxonomy
    jaxpr walk: only a ``psum`` over the dp axis (the grad all-reduce)
    may cross DCN."""
    cross, problems = crossing_axes(plan, topo)
    cell = f"{preset} x {plan.spec()} @ {topo.name}"
    findings = [Finding("P4", cell, p) for p in problems]
    if jaxpr is not None and cross:
        from dalle_pytorch_tpu.lint import spmd

        sites, _ = spmd.collective_trace(jaxpr)
        for site in sites:
            hit = set(site.axes) & cross
            if not hit:
                continue
            if site.prim == "psum" and set(site.axes) <= {"dp"}:
                continue  # the one collective allowed to cross DCN
            findings.append(Finding(
                "P4", cell,
                f"{site.prim} over axes {tuple(site.axes)} crosses DCN "
                f"(crossing axes here: {sorted(cross)}) — only the dp "
                "grad all-reduce may; pin this collective to ICI axes "
                "or restructure the plan"))
    return findings


# --- the autotuner's score model ------------------------------------------

#: Bump when the score arithmetic changes — part of every ledger row's
#: fingerprint, so a model change reads as "update the ledger", never as
#: silent drift.
SCORE_MODEL = 1


def score_cell(cost: PresetCost, plan: ParallelPlan, topo: Topology
               ) -> Optional[dict]:
    """The chip-free roofline score for one feasible cell: predicted
    step time = max(flop time, per-device byte stream) + the DCN
    all-reduce penalty on multi-slice topologies.  The byte stream is
    the per-device sharded state plus the walker's activation share —
    plan-sensitive through exactly the per-leaf shard factors P2
    validates.  Deterministic pure arithmetic: the drift gate compares
    it exactly."""
    from dalle_pytorch_tpu.obs import mem, prof

    sizes, _ = resolve_axis_sizes(plan, topo)
    if sizes is None:
        return None
    spec = prof.CHIP_SPECS[topo.chip]
    params_sh, opt_sh = sharded_state_bytes(cost, plan, sizes)
    state = params_sh + opt_sh
    act = max(0, cost.walker_peak_bytes
              - cost.resident_bytes) // max(topo.devices, 1)
    traffic = state + act
    flop_time = cost.flops / (spec.peak_flops * topo.devices)
    byte_time = traffic / spec.hbm_bw
    dcn_time = (2 * params_sh / DCN_BW) if topo.slices > 1 else 0.0
    pred = max(flop_time, byte_time) + dcn_time
    phases = state_phases(cost, plan, topo)
    verdict = mem.headroom_verdict(
        {k: phases[k] for k in ("init", "ckpt")}, topo.chip)
    return {
        "pred_step_time_s": pred,
        "predicted_mfu": (flop_time / pred) if pred else 0.0,
        "bound": "byte" if byte_time > flop_time else "flop",
        "flop_time_s": flop_time,
        "byte_time_s": byte_time,
        "dcn_time_s": dcn_time,
        "state_bytes": int(state),
        "act_bytes": int(act),
        "traffic_bytes": int(traffic),
        "headroom_frac": verdict["headroom_frac"],
        "walker_step_peak_bytes": int(phases["step_peak"]),
    }


# --- the sweep -------------------------------------------------------------

#: The presets the default contract sweep covers — the geometries the
#: ISSUE gates (tiny is test-only: its deliberately-awkward 58-row text
#: vocab exercises _prune_spec fallbacks in tests, not the repo gate).
SWEEP_PRESETS = ("cub", "cub-512", "cub-1024")


def analyze_cell(cost: PresetCost, plan: ParallelPlan, topo: Topology, *,
                 select: Sequence[str] = ANALYSES) -> List[Finding]:
    """P2-P4 for one (preset x plan @ topology) cell (P1 is rules x
    preset, plan-independent — see :func:`analyze`).  Infeasible cells
    return no findings: infeasibility is the autotuner's concern."""
    sizes, _ = resolve_axis_sizes(plan, topo)
    if sizes is None:
        return []
    out: List[Finding] = []
    if "P2" in select:
        out.extend(check_divisibility(cost.param_shapes, plan, topo,
                                      preset=cost.preset, batch=cost.batch))
    if "P3" in select:
        out.extend(check_hbm_fit(cost, plan, topo))
    if "P4" in select and (topo.slices > 1 or plan.dcn_dp > 1):
        out.extend(check_collective_placement(plan, topo,
                                              preset=cost.preset,
                                              jaxpr=cost.jaxpr))
    return out


def _feasible_pairing(plan: ParallelPlan, topo: Topology) -> bool:
    """The analyzer's cell filter: dcn plans pair with multi-slice
    topologies (and vice versa) — the mismatched pairings are
    infeasibilities P4 would flag structurally, which the autotuner
    records as reasons rather than failures."""
    return (plan.dcn_dp > 1) == (topo.slices > 1)


def plans_for(preset: str) -> List[ParallelPlan]:
    """The contract sweep's plan set for one preset.  A scale rung is
    pinned to its own registry plan — the committed (geometry, plan)
    pairing is the contract; whether OTHER plans could hold it is the
    autotuner's question, answered in PLAN_LEDGER.json, not a repo
    defect.  The production geometries sweep the dense registry plans
    plus the whole candidate grid (dcn hybrids included, which is what
    gives P4 live cells at HEAD)."""
    from dalle_pytorch_tpu.presets import SCALE_PRESETS

    if preset in SCALE_PRESETS:
        return [PLAN_REGISTRY[preset]]
    dense = [p for p in PLAN_REGISTRY.values()
             if p.sp == 1 and p.pp == 1 and p.ep == 1]
    by_spec = {p.spec(): p for p in list(candidate_plans()) + dense}
    return [by_spec[s] for s in sorted(by_spec)]


def analyze(presets: Sequence[str] = SWEEP_PRESETS, *,
            select: Sequence[str] = ANALYSES,
            topologies: Sequence[Topology] = TOPOLOGIES,
            plans: Optional[Sequence[ParallelPlan]] = None,
            batch: int = 8) -> List[Finding]:
    """The full contract sweep: P1 per preset, P2-P4 per feasible cell
    (:func:`plans_for` x :data:`TOPOLOGIES`, capacity-infeasible cells
    skipped)."""
    findings: List[Finding] = []
    for preset in presets:
        cost = preset_cost(preset, batch)
        if "P1" in select:
            findings.extend(check_rule_coverage(cost.param_shapes,
                                                preset=preset))
        for topo in topologies:
            for plan in (plans_for(preset) if plans is None else plans):
                if not _feasible_pairing(plan, topo):
                    continue
                if batch_infeasible(plan, topo, batch) is not None:
                    continue
                findings.extend(analyze_cell(cost, plan, topo,
                                             select=select))
    return findings


def apply_waivers(findings: Iterable[Finding],
                  waivers: Sequence[Tuple[str, str, str]] = WAIVERS
                  ) -> Tuple[List[Finding], List[Tuple[Finding, str]],
                             List[str]]:
    """Split findings into (kept, waived-with-reason, unused-waiver
    errors) — the PRAGMA001/002 discipline for cell-anchored findings:
    every waiver carries a reason, and a waiver matching nothing is
    itself reported."""
    waivers = tuple(waivers)
    used = [False] * len(waivers)
    kept: List[Finding] = []
    waived: List[Tuple[Finding, str]] = []
    for f in findings:
        reason = None
        for i, (code, cell_pat, why) in enumerate(waivers):
            if f.code == code and re.search(cell_pat, f.cell):
                reason, used[i] = why, True
                break
        if reason is None:
            kept.append(f)
        else:
            waived.append((f, reason))
    unused = [f"waiver ({waivers[i][0]!r}, {waivers[i][1]!r}) matched no "
              "finding — stale suppression, remove it"
              for i, u in enumerate(used) if not u]
    return kept, waived, unused
