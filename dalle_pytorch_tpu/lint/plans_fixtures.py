"""Deliberately-broken plan contracts — the teeth-proof for graftplan.

One fixture twin per analysis, each reproducing the bug class its
analysis exists to catch (the spmd_fixtures/threads_fixtures pattern):
a param tree with a leaf no rule covers (P1 orphan), a rule table whose
order is load-bearing (P1 ambiguity), a head count the tp axis cannot
divide (P2), a param tree whose sharded state cannot fit the chip (P3),
and a step whose ``all_gather`` crosses the DCN boundary (P4).  Used by
``tests/test_plan_check.py`` and ``tools/plan_check.py --selftest``;
never imported by production code.

This file hand-builds meshes and specs on purpose — it is exempt from
PLAN001 (the ``_fixtures.py`` suffix), like every fixture module that
must construct the pathology the rule bans.
"""
from __future__ import annotations

# --- P1: an orphan leaf ----------------------------------------------------

#: A plausible new param surface (a perceiver-style bank of learned
#: latents) added without touching PARTITION_RULES: the '/'-joined path
#: matches neither a rule (not a ``kernel``/``embedding`` leaf, so even
#: the terminal catch-all misses it) nor plans.P1_REPLICATED, so every
#: mesh silently replicates its 2-D weight.  Must FAIL
#: check_rule_coverage.
ORPHAN_SHAPES = {
    "transformer/layers_0_attn/to_qkv/kernel": ((256, 3, 8, 64), 4),
    "resampler/latents": ((256, 2048), 4),
}

#: The clean twin: the same tree without the uncovered surface.  Must
#: PASS check_rule_coverage.
COVERED_SHAPES = {
    "transformer/layers_0_attn/to_qkv/kernel": ((256, 3, 8, 64), 4),
    "transformer/layers_0_ff/dense_in/kernel": ((256, 2048), 4),
}


# --- P1: a load-bearing rule order -----------------------------------------


def ambiguous_rules():
    """A rule table where a second, CONFLICTING pattern also matches the
    fused-qkv kernel — first-hit-wins silently shadows it, so whether the
    heads dim shards over tp depends on table order.  Must FAIL
    check_rule_coverage (ambiguity arm) against AMBIGUOUS_SHAPES."""
    from jax.sharding import PartitionSpec as P

    return (
        (r".*/to_qkv/kernel$", P("fsdp", None, "tp", None)),
        (r".*qkv/kernel$", P("tp", None, "fsdp", None)),  # the shadowed rival
        (r".*/kernel$", P(None, None)),                    # terminal default
    )


def benign_overlap_rules():
    """The clean twin: the second match is the TERMINAL catch-all — the
    declared default every kernel falls through to, so the overlap is the
    design, not an ambiguity.  Must PASS check_rule_coverage."""
    from jax.sharding import PartitionSpec as P

    return (
        (r".*/to_qkv/kernel$", P("fsdp", None, "tp", None)),
        (r".*/kernel$", P(None, None)),
    )


AMBIGUOUS_SHAPES = {
    "transformer/layers_0_attn/to_qkv/kernel": ((256, 3, 8, 64), 4),
}


# --- P2: an indivisible axis -----------------------------------------------

#: A to_qkv kernel with SIX heads: rule #0 shards the heads dim over tp,
#: and tp=4 does not divide 6 — mesh._prune_spec silently drops the axis
#: and the leaf replicates.  Must FAIL check_divisibility under a tp-4
#: plan (plans_fixture_plan_tp4) on an 8-device topology.
INDIVISIBLE_SHAPES = {
    "transformer/layers_0_attn/to_qkv/kernel": ((256, 3, 6, 64), 4),
}

#: The clean twin: eight heads, every sharded dim divides.  Must PASS.
DIVISIBLE_SHAPES = {
    "transformer/layers_0_attn/to_qkv/kernel": ((256, 3, 8, 64), 4),
}


# --- P3: state that cannot fit ---------------------------------------------


def overweight_cost(plans_module):
    """A synthetic PresetCost whose params alone are 4 GiB (12 GiB with
    Adam moments): under a pure-dp plan the full state is resident per
    device and the ckpt phase (2x) busts v5e-4's 0.9 x 16 GiB budget.
    Must FAIL check_hbm_fit under dp @ v5e-4 and PASS under fsdp4 (the
    leaf shards 4-way through rule #2).  ``plans_module`` is lint.plans
    (passed in to keep this module import-light)."""
    shapes = {"transformer/layers_0_ff/dense_in/kernel": ((131072, 8192), 4)}
    params = 131072 * 8192 * 4
    return plans_module.PresetCost(
        preset="fixture-overweight", batch=8, param_shapes=shapes,
        params_bytes=params, opt_bytes=2 * params,
        flops=10**12, walker_bytes=4 * params,
        walker_peak_bytes=params, resident_bytes=params,  # act term zero
        jaxpr=None, config=None)


# --- P4: a collective that crosses DCN -------------------------------------


def _dp_mesh():
    import jax
    import numpy as np

    devs = jax.devices()
    if len(devs) < 2:
        raise RuntimeError("P4 fixtures need >= 2 devices "
                           "(set --xla_force_host_platform_device_count)")
    return jax.sharding.Mesh(np.asarray(devs[:2]).reshape(2), ("dp",))


def dcn_crossing_jaxpr():
    """A step that ``all_gather``s activations over the dp axis — on a
    multi-slice topology dp is the DCN-crossing axis, and an all-gather
    there streams the whole tensor over the data-center network every
    step (the exact mistake of sharding fsdp across slices).  Must FAIL
    check_collective_placement for a dcn plan."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import shard_map

    def local(x):
        return jax.lax.all_gather(x, "dp").sum(axis=0)

    fn = shard_map(local, mesh=_dp_mesh(), in_specs=(P("dp"),),
                   out_specs=P("dp"), check_vma=False)
    return jax.make_jaxpr(fn)(jnp.zeros((8, 16), jnp.float32))


def dcn_clean_jaxpr():
    """The clean twin: the only dp-axis collective is the ``psum`` grad
    all-reduce — the one collective allowed to cross DCN.  Must PASS."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import shard_map

    def local(x):
        return jax.lax.psum(x * 2.0, "dp")

    fn = shard_map(local, mesh=_dp_mesh(), in_specs=(P("dp"),),
                   out_specs=P(), check_vma=False)
    return jax.make_jaxpr(fn)(jnp.zeros((8, 16), jnp.float32))
