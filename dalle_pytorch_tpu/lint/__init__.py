"""graftlint — TPU/JAX static analysis distilled from this repo's bug history.

Five review rounds each burned scarce TPU-tunnel windows rediscovering bug
classes that are statically detectable on CPU in seconds (ISSUE 2 / ADVICE
rounds 3-5): raw env-var truthiness treating ``FLAG=0`` as ON, ``hash()``
seeds that don't reproduce across processes, module-level backend queries
that hang when the axon tunnel is pinned-but-down, mixed-dtype dots whose
f32-accumulation contract held only by convention, host syncs inside traced
code, and broad excepts swallowing XLA errors.  This package is the rule
engine; ``tools/graftlint.py`` is the CLI and ``tools/contract_check.py``
is the companion dynamic-contract checker (``jax.eval_shape``, zero FLOPs).

Every rule supports an inline suppression pragma **with a mandatory
justification**::

    if os.environ.get("ADDR"):  # graftlint: disable=ENV001 (address-valued)

A pragma without a parenthesized reason is itself an error (PRAGMA001) —
suppressions document *why* the rule does not apply, or they don't count.
"""
from .engine import (FINDINGS_JSON_SCHEMA, Finding, filter_baseline,
                     findings_to_json, findings_to_sarif, fingerprint,
                     fix_env001, iter_python_files, lint_paths, lint_source,
                     load_baseline, prune_baseline, stale_baseline_entries,
                     write_baseline)
from .rules import RULES

__all__ = [
    "Finding", "RULES", "lint_source", "lint_paths", "fingerprint",
    "iter_python_files",
    "load_baseline", "write_baseline", "filter_baseline", "fix_env001",
    "stale_baseline_entries", "prune_baseline",
    "findings_to_json", "findings_to_sarif", "FINDINGS_JSON_SCHEMA",
]
