"""Deliberately-broken concurrent classes — the teeth-proof for graftrace.

One fixture class per analysis, each reproducing the bug class its
analysis exists to catch (mirrors ``spmd_fixtures.py``): an unguarded
counter write racing a locked writer (T1), a compile inside a ``with
lock:`` body (T2), an AB/BA acquisition inversion (T3), and a Future
resolved while holding the lock (T4) — plus a clean twin for each that
must pass.  Used by tests/test_thread_check.py and ``tools/
thread_check.py --selftest``; never imported by production code, and the
classes are never instantiated by the checker (pure AST analysis).
"""
from __future__ import annotations

import threading


def _compile_fn(fn):  # stands in for jax.jit et al. in the T2 fixtures
    return fn


# --- T1: unguarded write to a lock-guarded field ---------------------------


class BrokenUnguardedCounter:
    """``served`` is written under the lock in ``retire`` but bumped
    lock-free in ``record_error`` — two driver threads lose increments.
    Must be CAUGHT by T1."""

    def __init__(self):
        self._lock = threading.Lock()
        self.served = 0

    def retire(self):
        with self._lock:
            self.served += 1

    def record_error(self):
        self.served += 1  # racing write, no lock

    def snapshot(self):
        return self.served  # racing read from a public method


class CleanGuardedCounter:
    """The clean twin: every touch of ``served`` holds the lock.
    Must PASS T1."""

    def __init__(self):
        self._lock = threading.Lock()
        self.served = 0

    def retire(self):
        with self._lock:
            self.served += 1

    def record_error(self):
        with self._lock:
            self.served += 1

    def snapshot(self):
        with self._lock:
            return self.served


# --- T2: blocking call while holding a lock --------------------------------


class BrokenCompileUnderLock:
    """Compiles (seconds) inside the admission lock — every submitter
    stalls behind the trace.  Must be CAUGHT by T2."""

    def __init__(self):
        self._lock = threading.Lock()
        self._step = None

    def admit(self, fn):
        with self._lock:
            self._step = _compile_fn(fn)  # pretend this is jax.jit

    def admit_traced(self, fn):
        with self._lock:
            self._step = compile(fn, "<fixture>", "eval")


class CleanCompileOutsideLock:
    """The clean twin: compile first, publish the result under the lock.
    Must PASS T2."""

    def __init__(self):
        self._lock = threading.Lock()
        self._step = None

    def admit(self, fn):
        step = compile(fn, "<fixture>", "eval")
        with self._lock:
            self._step = step


# --- T3: AB/BA lock-order inversion ----------------------------------------


class BrokenOrderInversion:
    """``transfer`` takes A then B, ``refund`` takes B then A — two
    threads entering from opposite ends deadlock.  Must be CAUGHT by
    T3."""

    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self.balance = 0

    def transfer(self):
        with self._lock_a:
            with self._lock_b:
                self.balance += 1

    def refund(self):
        with self._lock_b:
            with self._lock_a:
                self.balance -= 1


class CleanOrderedPair:
    """The clean twin: both paths acquire A before B.  Must PASS T3."""

    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self.balance = 0

    def transfer(self):
        with self._lock_a:
            with self._lock_b:
                self.balance += 1

    def refund(self):
        with self._lock_a:
            with self._lock_b:
                self.balance -= 1


# --- T4: resolving a Future / firing a callback under the lock -------------


class BrokenResolveUnderLock:
    """Resolves the request future while still holding the table lock —
    a done-callback that re-submits re-enters ``resolve`` and deadlocks.
    Must be CAUGHT by T4."""

    def __init__(self):
        self._lock = threading.Lock()
        self._futures = {}

    def resolve(self, rid, value):
        with self._lock:
            fut = self._futures.pop(rid)
            fut.set_result(value)  # inline done-callbacks under the lock

    def notify(self, on_done):
        with self._lock:
            on_done(len(self._futures))  # caller-supplied callable


class CleanResolveOutsideLock:
    """The clean twin: pop under the lock, resolve after release (the
    router's resolve-outside-the-lock discipline).  Must PASS T4."""

    def __init__(self):
        self._lock = threading.Lock()
        self._futures = {}

    def resolve(self, rid, value):
        with self._lock:
            fut = self._futures.pop(rid)
        fut.set_result(value)

    def notify(self, on_done):
        with self._lock:
            n = len(self._futures)
        on_done(n)
