"""graftspmd: jaxpr-level SPMD analyses for jitted step programs (S1-S4).

graftlint (engine.py/rules.py) sees source; ``tools/contract_check.py``
sees shapes and dtypes.  Between them sits the class of bugs that only the
*traced program* exposes, and that are the three most expensive ways to
waste a TPU pod:

* **S1 collective order** — under ``shard_map`` every shard runs the same
  traced jaxpr, so the only way shards can issue *different* collective
  sequences (the classic SPMD deadlock: half the mesh waits in a
  ``ppermute`` the other half never enters) is a collective dominated by
  data-dependent control flow.  :func:`collective_trace` walks the jaxpr
  (recursing through ``pjit``/``shard_map``/``scan``/``remat`` bodies),
  records the unconditional collective sequence, and flags any collective
  under a ``while`` (data-dependent trip count) or inside ``cond``
  branches whose collective signatures differ (shards taking different
  branches would desynchronize).  ``cond`` branches whose collective
  sequences are *identical* are allowed — every shard issues the same ops
  in the same order whichever branch it takes (parallel/pipeline.py's
  drain-bubble ``cond`` is the motivating clean case).
* **S2 donation audit** — a forgotten ``donate_argnums`` silently doubles
  params+opt_state HBM (the buffers live twice across the update).
  :func:`audit_donation` reads the AOT ``lowered.args_info`` donation
  flags per pytree leaf and, when a compiled executable is given, parses
  the optimized HLO's ``input_output_alias`` config to verify every
  donated leaf is *actually aliased* to an output — jax drops donation
  silently when a donated input matches no output (the
  refactor-changed-the-return-structure bug), which is exactly when you
  want to hear about it.  (``memory_analysis().alias_size_in_bytes`` is
  NOT used: XLA:CPU zeroes it at backend opt level 0 and on
  cache-deserialized executables even when the aliases are honored.)
* **S3 retrace sentinel** — a weak-hash or unhashable static arg retraces
  the step every call (the recompile storm that reads as "TPU is slow").
  :func:`count_traces` drives a jitted fn through N simulated steps with
  fresh inputs and fails if the executable cache grew past one entry.
* **S4 static HBM budget** — :func:`hbm_estimate` sums the per-device live
  bytes of a compiled step (arguments + outputs − donated aliases + peak
  XLA temporaries); :func:`check_hbm_budget` gates the sum against a
  per-chip capacity table so an oversized plan fails on CPU in seconds,
  not on the pod at step 0.

Everything here is chip-free by the same construction as contract_check:
AOT tracing/lowering on a virtual 8-device CPU mesh, zero FLOPs (S3 runs
tiny concrete steps — the one analysis that needs execution, at toy
geometry).  ``tools/spmd_check.py`` is the CLI that applies these to every
train-step factory in ``training.py`` (STEP_FACTORIES) under every
parallelism plan; ``lint/spmd_fixtures.py`` holds the deliberately-broken
models that prove each analysis has teeth.
"""
from __future__ import annotations

import contextlib
import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple


class SPMDViolation(AssertionError):
    """A statically-decidable SPMD property of a traced program is broken."""


@contextlib.contextmanager
def fresh_stats_compile():
    """Compile with the persistent XLA compilation cache fully bypassed:
    a cache-deserialized executable can report zeroed or stale
    ``memory_analysis()`` stats (jax 0.4.37 serializes the executable,
    not all of its analyses), which would corrupt the S4 budget.
    Toggling ``jax_enable_compilation_cache`` alone does NOT stop
    disk-cache reads on the AOT ``lowered.compile()`` path — the cache
    directory itself must be unset for the duration.  The analyzed
    executables are always compiled fresh; everything else (the S3 tiny
    steps, test suites) keeps the cache."""
    import jax

    prev_enabled = jax.config.jax_enable_compilation_cache
    prev_dir = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_enable_compilation_cache", False)
        jax.config.update("jax_compilation_cache_dir", None)
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_enable_compilation_cache", prev_enabled)


# --- S1: collective order -------------------------------------------------

# cross-shard primitives in jax 0.4.x jaxprs: a shard blocking in any of
# these waits for every peer on the named axes.  axis_index is deliberately
# absent (it is shard-local — no synchronization).
COLLECTIVE_PRIMS = frozenset((
    "psum", "pmin", "pmax", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter", "pgather",
    "all_gather_invariant",
))


@dataclasses.dataclass(frozen=True)
class CollectiveSite:
    """One collective equation, located by its structural context."""

    prim: str
    axes: Tuple[str, ...]
    shapes: Tuple[str, ...]          # "f32[2,8]"-style operand avals
    context: Tuple[str, ...]         # enclosing HOP chain, outermost first

    @property
    def signature(self) -> Tuple:
        """Deadlock-relevant identity: two shards match a collective by
        primitive, mesh axes, and operand shapes — context excluded, so
        identical sequences reached through different branches compare
        equal."""
        return (self.prim, self.axes, self.shapes)

    def format(self) -> str:
        ctx = ">".join(self.context) or "top"
        return f"{self.prim}[{','.join(self.axes)}]({','.join(self.shapes)}) @ {ctx}"


def _aval_str(var) -> str:
    aval = getattr(var, "aval", None)
    if aval is None:
        return "?"
    return f"{getattr(aval.dtype, 'name', aval.dtype)}{list(aval.shape)}"


def _collective_axes(params: dict) -> Tuple[str, ...]:
    axes = params.get("axes") or params.get("axis_name") or ()
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def _sub_jaxprs(params: dict):
    """Every nested jaxpr in an equation's params (pjit/scan/shard_map/
    remat/custom_* all carry theirs under different keys — match by
    structure, like contract_check._iter_eqns)."""
    for val in params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            inner = getattr(v, "jaxpr", None)
            if inner is not None:
                yield inner
            elif hasattr(v, "eqns"):
                yield v


def _walk_collectives(jaxpr, context: Tuple[str, ...],
                      sites: List[CollectiveSite],
                      violations: List[str]) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            sites.append(CollectiveSite(
                prim=name, axes=_collective_axes(eqn.params),
                shapes=tuple(_aval_str(v) for v in eqn.invars),
                context=context))
        elif name == "cond":
            # branches: executed under a traced predicate — shards may take
            # different branches, so a collective here only stays in lockstep
            # if EVERY branch issues the identical collective sequence
            branch_sites: List[List[CollectiveSite]] = []
            for i, br in enumerate(eqn.params["branches"]):
                bs: List[CollectiveSite] = []
                _walk_collectives(br.jaxpr, context + (f"cond#b{i}",), bs,
                                  violations)
                branch_sites.append(bs)
            sigs = [tuple(s.signature for s in bs) for bs in branch_sites]
            if any(s != sigs[0] for s in sigs[1:]):
                seqs = "; ".join(
                    f"branch {i}: [{', '.join(s.format() for s in bs) or 'none'}]"
                    for i, bs in enumerate(branch_sites))
                violations.append(
                    "collective under data-dependent control flow: cond "
                    f"branches at {'>'.join(context) or 'top'} issue "
                    f"DIFFERENT collective sequences ({seqs}) — shards "
                    "taking different branches deadlock the mesh")
            elif sigs[0]:
                # identical on every branch: unconditional in effect
                sites.extend(branch_sites[0])
        elif name == "while":
            for key in ("cond_jaxpr", "body_jaxpr"):
                ws: List[CollectiveSite] = []
                _walk_collectives(eqn.params[key].jaxpr,
                                  context + (f"while.{key[:4]}",), ws,
                                  violations)
                for s in ws:
                    violations.append(
                        f"collective {s.format()} inside a while loop's "
                        f"{key} — the trip count is data-dependent, so "
                        "shards can disagree on how many times the "
                        "collective runs (SPMD deadlock)")
        else:
            # scan (static trip count), pjit, shard_map, remat, custom_jvp/
            # vjp, ...: uniform across shards — recurse transparently
            for sub in _sub_jaxprs(eqn.params):
                _walk_collectives(sub, context + (name,), sites, violations)


def collective_trace(closed_jaxpr) -> Tuple[List[CollectiveSite], List[str]]:
    """Walk a (Closed)Jaxpr; return the unconditionally-executed collective
    sequence and the S1 violations (collectives whose execution a shard
    could skip or repeat differently from its peers)."""
    sites: List[CollectiveSite] = []
    violations: List[str] = []
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    _walk_collectives(jaxpr, (), sites, violations)
    return sites, violations


def check_collective_order(closed_jaxpr, label: str = "step") -> List[CollectiveSite]:
    """S1 gate: raise :class:`SPMDViolation` on any conditionally-executed
    collective; return the (safe) unconditional sequence for reporting."""
    sites, violations = collective_trace(closed_jaxpr)
    if violations:
        raise SPMDViolation(
            f"S1 collective order [{label}]: " + " | ".join(violations))
    return sites


# --- S1 extension: scan collective schedules ------------------------------
#
# Per-body uniformity (above) proves every shard issues the same sequence
# *per scan iteration*; a pipelined step additionally needs the TOTAL
# schedule — iteration count x per-iteration sequence — to be a static
# fact, because the microbatch scan is where the stage-to-stage ppermutes
# live and a count mismatch between stages is a deadlock the per-body view
# cannot see.  scan's trip count is static by construction, so the
# schedule is decidable: extract it, and let the caller pin the
# per-iteration sequence invariant across schedule-shaping knobs
# (tools/spmd_check.py compares num_microbatches=2 vs 4 — the sequence
# must be identical, only the length may change).


@dataclasses.dataclass(frozen=True)
class ScanSchedule:
    """The collective schedule of one collective-bearing scan: ``length``
    iterations, each issuing ``per_iteration`` in order (branch-matched
    conds already flattened; a branch-DIVERGENT cond inside the body is an
    S1 violation raised during extraction, not a schedule)."""

    context: Tuple[str, ...]             # enclosing HOP chain of the scan
    length: int                          # static trip count
    per_iteration: Tuple[Tuple, ...]     # CollectiveSite.signature sequence

    @property
    def total(self) -> int:
        return self.length * len(self.per_iteration)

    def format(self) -> str:
        prims = ",".join(sig[0] for sig in self.per_iteration)
        ctx = ">".join(self.context) or "top"
        return (f"{self.length} iterations x [{prims}] = {self.total} "
                f"collectives @ {ctx}")


def scan_collective_schedule(closed_jaxpr,
                             label: str = "step") -> List[ScanSchedule]:
    """Every collective-bearing ``scan`` in the program, outermost first,
    as a static schedule.  Raises :class:`SPMDViolation` if a scan body
    hides a collective under data-dependent control flow (the conditions
    under which no static schedule exists)."""
    out: List[ScanSchedule] = []

    def walk(jaxpr, context: Tuple[str, ...]) -> None:
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "scan":
                body = eqn.params["jaxpr"].jaxpr
                sites: List[CollectiveSite] = []
                violations: List[str] = []
                _walk_collectives(body, context + ("scan",), sites,
                                  violations)
                if violations:
                    raise SPMDViolation(
                        f"S1 scan schedule [{label}]: "
                        + " | ".join(violations))
                if sites:
                    out.append(ScanSchedule(
                        context=context, length=int(eqn.params["length"]),
                        per_iteration=tuple(s.signature for s in sites)))
                # the body was fully analyzed above — no double recursion
            else:
                for sub in _sub_jaxprs(eqn.params):
                    walk(sub, context + (name,))

    walk(getattr(closed_jaxpr, "jaxpr", closed_jaxpr), ())
    return out


# --- S2: donation audit ---------------------------------------------------


@dataclasses.dataclass
class DonationAudit:
    """Per-leaf donation facts of one AOT-lowered program."""

    donated_bytes: int
    undonated_bytes: int
    # (arg label, pytree path, bytes) for undonated leaves over the
    # reporting threshold — informational unless the label was expected
    # to donate
    undonated_big: List[Tuple[str, str, int]]
    # pytree paths of leaves under expected-donated labels that the jit
    # did NOT mark donated
    missing: List[str]
    donated_leaves: int = 0              # array leaves marked donated
    aliased_params: Optional[int] = None  # compiled executable's aliases

    @property
    def donated_fraction(self) -> float:
        """Requested-donated share of the total argument bytes (global,
        pre-sharding).  Donated and undonated args shard across the same
        mesh, so the share survives partitioning — S4 uses it to convert
        per-device argument bytes into per-device aliased bytes."""
        total = self.donated_bytes + self.undonated_bytes
        return self.donated_bytes / total if total else 0.0

    def ok(self) -> bool:
        if self.missing:
            return False
        if self.aliased_params is None:
            return True
        return self.aliased_params >= self.donated_leaves


def _leaf_bytes(aval) -> int:
    n = 1
    for d in aval.shape:
        n *= int(d)
    return n * aval.dtype.itemsize


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        if key is None:
            key = getattr(p, "name", p)
        parts.append(str(key))
    return "/".join(parts)


def compiled_alias_count(compiled) -> int:
    """Count the distinct aliased input parameters in a compiled
    executable's optimized-HLO ``input_output_alias`` config — the
    compiler's ACTUAL aliasing decision, read from ``compiled.as_text()``
    (``memory_analysis().alias_size_in_bytes`` is zeroed at backend opt
    level 0 and on cache-deserialized executables even when the aliases
    are honored, so it cannot carry this check).  Entries look like
    ``{output_index}: (param_number, {param_tuple_index}, may-alias)``;
    distinct (param_number, tuple_index) pairs are counted so tupled
    parameters audit correctly."""
    import re

    txt = compiled.as_text()
    key = "input_output_alias={"
    start = txt.find(key)
    if start < 0:
        return 0
    i = start + len(key) - 1
    depth = 0
    end = i
    for end in range(i, len(txt)):
        if txt[end] == "{":
            depth += 1
        elif txt[end] == "}":
            depth -= 1
            if depth == 0:
                break
    body = txt[i:end + 1]
    pairs = set(re.findall(r"\(\s*(\d+)\s*,\s*\{([^}]*)\}", body))
    return len(pairs)


def audit_donation(lowered, arg_labels: Sequence[str],
                   expect_donated: Sequence[int] = (0, 1),
                   compiled=None, big: int = 1 << 20) -> DonationAudit:
    """S2: read per-leaf donation off ``lowered.args_info``.

    ``arg_labels`` names the positional args (for reporting);
    ``expect_donated`` are the positional indices whose every array leaf
    must be donated (params/opt_state for a train step).  ``compiled``
    (optional) adds the did-the-compiler-actually-alias check via
    :func:`compiled_alias_count`.
    """
    import jax

    info = lowered.args_info
    donated = 0
    undonated = 0
    donated_leaves = 0
    undonated_big: List[Tuple[str, str, int]] = []
    missing: List[str] = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(info):
        # args_info paths start ((args, kwargs) idx, arg idx, per-arg path)
        arg_idx = getattr(path[1], "idx", None) if len(path) > 1 else None
        label = (arg_labels[arg_idx]
                 if arg_idx is not None and arg_idx < len(arg_labels)
                 else f"arg{arg_idx}")
        size = _leaf_bytes(getattr(leaf, "aval", None) or leaf._aval)
        if getattr(leaf, "donated", False):
            donated += size
            donated_leaves += 1
        else:
            undonated += size
            if arg_idx in tuple(expect_donated):
                missing.append(f"{label}/{_path_str(path[2:])}")
            elif size >= big:
                undonated_big.append(
                    (label, _path_str(path[2:]), size))
    aliased = None
    if compiled is not None:
        aliased = compiled_alias_count(compiled)
    return DonationAudit(donated_bytes=donated, undonated_bytes=undonated,
                         undonated_big=sorted(undonated_big,
                                              key=lambda t: -t[2]),
                         missing=missing, donated_leaves=donated_leaves,
                         aliased_params=aliased)


def check_donation(lowered, arg_labels: Sequence[str],
                   expect_donated: Sequence[int] = (0, 1),
                   compiled=None, label: str = "step") -> DonationAudit:
    """S2 gate: raise when an expected-donated leaf is undonated, or when
    the compiler silently dropped the requested aliasing."""
    audit = audit_donation(lowered, arg_labels, expect_donated, compiled)
    if audit.missing:
        head = ", ".join(audit.missing[:5])
        more = f" (+{len(audit.missing) - 5} more)" if len(audit.missing) > 5 else ""
        raise SPMDViolation(
            f"S2 donation [{label}]: {len(audit.missing)} leaves of the "
            f"donated args are NOT donated ({head}{more}) — the step holds "
            "these buffers twice across the update; pass donate_argnums")
    if not audit.ok():
        raise SPMDViolation(
            f"S2 donation [{label}]: {audit.donated_leaves} leaves were "
            f"requested donated but the compiled executable aliases only "
            f"{audit.aliased_params} input parameters to outputs — jax "
            "dropped donation silently (a donated input matches no "
            "output's shape/dtype/sharding, e.g. a refactored return "
            "structure)")
    return audit


# --- S3: retrace sentinel -------------------------------------------------


def count_traces(jitted, make_args: Callable[[int], tuple],
                 steps: int = 3, label: str = "step") -> int:
    """S3: run ``jitted(*make_args(i))`` for ``steps`` simulated steps and
    return the executable-cache population.  A healthy step traces ONCE;
    every additional entry is a recompile that will repeat per epoch on
    the pod.  Unhashable static args (the list-keyed footgun) surface as a
    violation instead of an opaque jax error."""
    cache_size = getattr(jitted, "_cache_size", None)
    if cache_size is None:
        raise SPMDViolation(
            f"S3 retrace [{label}]: jitted function exposes no _cache_size "
            "— jax upgraded past the sentinel; re-pin the trace-count API")
    for i in range(steps):
        try:
            jitted(*make_args(i))
        except (TypeError, ValueError) as e:
            raise SPMDViolation(
                f"S3 retrace [{label}]: step {i} failed to hash its static "
                f"args ({type(e).__name__}: {e}) — an unhashable static "
                "arg (list/dict/ndarray) defeats the jit cache entirely")
    return int(cache_size())


def check_single_trace(jitted, make_args: Callable[[int], tuple],
                       steps: int = 3, label: str = "step") -> None:
    n = count_traces(jitted, make_args, steps=steps, label=label)
    if n > 1:
        raise SPMDViolation(
            f"S3 retrace [{label}]: {steps} simulated steps produced {n} "
            "traces — a static arg with value-unstable hashing (fresh "
            "object per call, float jitter, changing shape) recompiles "
            "the step; hoist it to a traced arg or intern the static")


# --- S4: static HBM budget ------------------------------------------------

# Usable per-chip HBM.  None = unbounded (the virtual CPU mesh).  v4 chips
# carry 32 GiB HBM2, v5e 16 GiB HBM2 (public TPU system specs); the
# margin in check_hbm_budget leaves headroom for XLA's runtime scratch
# and fragmentation, which the static sum cannot see.
CHIP_HBM_BYTES: Dict[str, Optional[int]] = {
    "cpu-virtual": None,
    "v4-8": 32 * 1024 ** 3,
    "v5e-4": 16 * 1024 ** 3,
}


@dataclasses.dataclass(frozen=True)
class HBMEstimate:
    """Per-device live bytes of one compiled step program."""

    argument_bytes: int
    output_bytes: int
    alias_bytes: int
    temp_bytes: int

    @property
    def total_bytes(self) -> int:
        """Peak live estimate: inputs resident + non-aliased outputs +
        XLA temporaries.  Donated aliases are subtracted once — a donated
        output lands in its input's buffer."""
        return (self.argument_bytes + self.output_bytes
                - self.alias_bytes + self.temp_bytes)

    def format(self) -> str:
        mib = 1024 ** 2
        return (f"args {self.argument_bytes / mib:.0f} MiB + out "
                f"{self.output_bytes / mib:.0f} - alias "
                f"{self.alias_bytes / mib:.0f} + temp "
                f"{self.temp_bytes / mib:.0f} = "
                f"{self.total_bytes / mib:.0f} MiB/device")


def hbm_estimate(compiled) -> HBMEstimate:
    """S4: static per-device memory of a compiled (SPMD-partitioned)
    program.  On the virtual mesh the compiled module IS the per-device
    program, so these sizes are already per-chip."""
    ma = compiled.memory_analysis()
    return HBMEstimate(
        argument_bytes=int(ma.argument_size_in_bytes),
        output_bytes=int(ma.output_size_in_bytes),
        alias_bytes=int(ma.alias_size_in_bytes),
        temp_bytes=int(ma.temp_size_in_bytes))


def check_hbm_budget(estimate: HBMEstimate, chip: str,
                     margin: float = 0.9, label: str = "step") -> None:
    """S4 gate: the static live sum must fit ``margin`` of the chip's HBM.
    Unknown chips are a configuration error, not a pass."""
    if chip not in CHIP_HBM_BYTES:
        raise SPMDViolation(
            f"S4 hbm [{label}]: unknown chip {chip!r}; known: "
            f"{sorted(CHIP_HBM_BYTES)}")
    if not estimate.argument_bytes:
        raise SPMDViolation(
            f"S4 hbm [{label}]: the compiled executable reports zero "
            "argument bytes — cache-deserialized executables carry no "
            "memory stats, so this budget would gate nothing; re-compile "
            "under spmd.fresh_stats_compile()")
    cap = CHIP_HBM_BYTES[chip]
    if cap is None:
        return
    budget = int(cap * margin)
    if estimate.total_bytes > budget:
        raise SPMDViolation(
            f"S4 hbm [{label}]: static live bytes {estimate.format()} "
            f"exceed {margin:.0%} of {chip} HBM "
            f"({budget / 1024 ** 2:.0f} MiB) — this plan OOMs at step 0; "
            "shard further (fsdp/sp), cut the batch, or enable remat")
