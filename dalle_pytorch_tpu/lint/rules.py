"""The graftlint rule catalog — each rule is one bug class this repo has
actually shipped (or nearly shipped) and then paid chip time to find.

A rule is a function ``(FileCtx) -> Iterator[(node, message)]``; the engine
owns pragma handling, baselines and reporting.  Rules are deliberately
syntactic (no type inference): they over-approximate, and the pragma's
mandatory justification is the escape hatch where the human knows better.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Tuple

RuleHit = Tuple[ast.AST, str]


@dataclasses.dataclass
class FileCtx:
    """Parsed source handed to each rule."""

    path: str
    tree: ast.Module
    lines: List[str]


# --- helpers -------------------------------------------------------------


def _attr_chain(node: ast.AST) -> str:
    """Dotted name of a Name/Attribute chain ('jax.lax.scan'), '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_env_get(call: ast.Call) -> bool:
    """``os.environ.get(...)`` / ``environ.get(...)`` / ``os.getenv(...)``."""
    chain = _attr_chain(call.func)
    return chain.endswith("environ.get") or chain.endswith("os.getenv") \
        or chain == "getenv"


def _walk_skip_defs(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that does not descend into nested function/class bodies."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


# --- ENV001: raw truthiness on os.environ.get ----------------------------


def rule_env001(ctx: FileCtx) -> Iterator[RuleHit]:
    """``bool(os.environ.get(X))`` treats ``X=0`` as ON — an operator
    disabling a flag with 0 silently enables it (the BENCH_PALLAS /
    GRAFT_DRYRUN_FULL footgun, hit twice).  Boolean env knobs must parse
    through ``utils.helpers.env_flag``; value-valued vars where truthiness
    is genuinely presence-of-value (addresses, paths) carry a pragma."""
    msg = ("raw truthiness on an environment read ('VAR=0' counts as ON); "
           "use dalle_pytorch_tpu.utils.helpers.env_flag for boolean flags")
    truth_exprs: list = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            truth_exprs.append(node.test)
        elif isinstance(node, ast.Assert):
            truth_exprs.append(node.test)
        elif isinstance(node, ast.BoolOp):
            truth_exprs.extend(node.values)
        elif isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            truth_exprs.append(node.operand)
        elif isinstance(node, ast.comprehension):
            truth_exprs.extend(node.ifs)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "bool":
            truth_exprs.extend(node.args)
    for expr in truth_exprs:
        if isinstance(expr, ast.Call) and _is_env_get(expr):
            yield expr, msg


# --- SEED001: hash()-derived seeds ---------------------------------------


def rule_seed001(ctx: FileCtx) -> Iterator[RuleHit]:
    """Python string hashes are per-process randomized (PYTHONHASHSEED), so
    a ``hash()``-derived seed draws different data on every rerun — an
    on-chip FAIL that doesn't reproduce (the round-5 ``chip_equiv`` bug).
    Use ``zlib.crc32`` for stable content-derived seeds."""
    msg = ("hash() is per-process randomized (PYTHONHASHSEED) — a seed or "
           "PRNGKey derived from it will not reproduce across reruns; use "
           "zlib.crc32 for stable content-derived seeds")
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "hash":
            yield node, msg


# --- BACKEND001: module-level backend queries ----------------------------

_BACKEND_QUERIES = frozenset((
    "devices", "local_devices", "default_backend", "device_count",
    "local_device_count", "process_count", "process_index",
))


def rule_backend001(ctx: FileCtx) -> Iterator[RuleHit]:
    """A module-level ``jax.devices()`` / ``jax.default_backend()`` runs at
    import time — and with the axon tunnel's sitecustomize plugin pinned
    but the tunnel down, backend init hangs >9 min inside the query with no
    exception (ADVICE round 5).  ``cli.apply_platform_env()`` must run
    first (module-level, earlier in the file) so ``JAX_PLATFORMS=cpu``
    actually takes effect before the backend initializes."""
    msg = ("module-level {} initializes the JAX backend at import time; "
           "call cli.apply_platform_env() first (earlier at module level) "
           "so JAX_PLATFORMS=cpu is honored before any backend query")
    platform_line = None
    queries = []
    for node in _walk_skip_defs(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain.endswith("apply_platform_env"):
            if platform_line is None or node.lineno < platform_line:
                platform_line = node.lineno
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in _BACKEND_QUERIES \
                and _attr_chain(node.func.value) == "jax":
            queries.append((node, chain))
    for node, chain in queries:
        if platform_line is None or node.lineno < platform_line:
            yield node, msg.format(f"{chain}()")


# --- DOT001: dot-family calls without an accumulation contract -----------

_DOT_FUNCS = frozenset(("einsum", "dot", "matmul", "tensordot"))
_JAX_NUMPY_RECEIVERS = frozenset(("jnp", "jax.numpy", "jaxnp"))
_LAX_RECEIVERS = frozenset(("lax", "jax.lax"))


def rule_dot001(ctx: FileCtx) -> Iterator[RuleHit]:
    """A jnp dot/einsum with no ``preferred_element_type`` leaves the
    accumulation dtype to inference from the (possibly mixed) operand
    dtypes — and lets XLA satisfy a mixed-dtype dot by hoisting a full
    f32 convert of the wider operand (the bf16-KV-cache defeat PR 1
    measured: it more than doubled decode cache bytes).  Every jnp-level
    dot states ``preferred_element_type`` explicitly, or carries a pragma
    proving the operand dtypes are uniform by construction."""
    msg = ("{} without preferred_element_type: the accumulation/output "
           "dtype is inferred from operand dtypes, and a mixed-dtype dot "
           "lets XLA materialize a full f32 convert of the wider operand; "
           "pass preferred_element_type (usually jnp.float32) or pragma "
           "with a proof the operands are dtype-uniform")
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        recv = _attr_chain(node.func.value)
        is_dot = (node.func.attr in _DOT_FUNCS
                  and recv in _JAX_NUMPY_RECEIVERS) \
            or (node.func.attr == "dot_general" and recv in _LAX_RECEIVERS)
        if not is_dot:
            continue
        if any(kw.arg == "preferred_element_type" for kw in node.keywords):
            continue
        yield node, msg.format(f"{recv}.{node.func.attr}")


# --- TRACE001: host syncs inside traced code -----------------------------

_SCAN_BODY_ARGS = {  # callable-position args of the structured control flow
    "scan": (0,), "map": (0,), "while_loop": (0, 1), "fori_loop": (2,),
    "cond": (1, 2), "switch": ()  # switch takes a list — handled below
}
_HOST_SYNC_RECEIVERS = frozenset(("np", "numpy", "onp"))


def _is_jit_decorator(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        # @partial(jax.jit, ...) / @jax.jit(...) / @nn.jit(...)
        chain = _attr_chain(dec.func)
        if chain.endswith("partial") and dec.args:
            return _attr_chain(dec.args[0]).endswith("jit")
        return chain.endswith("jit") or chain.endswith("pjit")
    return _attr_chain(dec).endswith("jit") or _attr_chain(dec).endswith("pjit")


def rule_trace001(ctx: FileCtx) -> Iterator[RuleHit]:
    """``.item()`` / ``np.asarray`` / ``float()`` on a traced value inside a
    ``@jax.jit`` or ``lax.scan`` body either fails at trace time on a path
    nobody ran, or (worse, via callbacks/weak types) forces a device sync
    per step.  Host fetches belong outside the traced program."""
    msg = ("host-sync call {} inside a traced ({}) body: this blocks on "
           "device transfer per trace or fails on untested paths; hoist "
           "the host fetch out of the traced program")
    traced: list = []  # (body_root, why)
    defs_by_name: dict = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, node)
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                traced.append((node, "@jit"))
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        if _attr_chain(node.func.value) not in _LAX_RECEIVERS:
            continue
        for pos in _SCAN_BODY_ARGS.get(node.func.attr, ()):
            if pos >= len(node.args):
                continue
            arg = node.args[pos]
            if isinstance(arg, ast.Lambda):
                traced.append((arg, f"lax.{node.func.attr}"))
            elif isinstance(arg, ast.Name) and arg.id in defs_by_name:
                traced.append((defs_by_name[arg.id],
                               f"lax.{node.func.attr}"))

    seen = set()
    for body_root, why in traced:
        for node in ast.walk(body_root):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            bad = None
            if isinstance(node.func, ast.Attribute):
                recv = _attr_chain(node.func.value)
                if node.func.attr == "item" and not node.args:
                    bad = ".item()"
                elif node.func.attr in ("asarray", "array") \
                        and recv in _HOST_SYNC_RECEIVERS:
                    bad = f"{recv}.{node.func.attr}()"
                elif node.func.attr == "device_get" and recv == "jax":
                    bad = "jax.device_get()"
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int") \
                    and len(node.args) == 1 \
                    and isinstance(node.args[0],
                                   (ast.Attribute, ast.Subscript)):
                bad = f"{node.func.id}()"
            if bad:
                seen.add(id(node))
                yield node, msg.format(bad, why)


# --- EXC001: broad excepts that swallow XLA errors -----------------------


def rule_exc001(ctx: FileCtx) -> Iterator[RuleHit]:
    """``except:`` / ``except Exception:`` with no re-raise swallows
    ``XlaRuntimeError`` — which is how a wedged tunnel, an OOM, or a
    cross-host desync presents.  A swallowed one turns a loud failure into
    silent corruption.  Narrow the class, re-raise, or pragma with the
    reason this specific handler may eat everything."""
    msg = ("{} swallows XlaRuntimeError (wedged tunnel / OOM / desync "
           "present as generic exceptions); catch a narrower class, "
           "re-raise, or pragma with why swallowing is safe here")
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            label = "bare 'except:'"
        else:
            names = [node.type] if not isinstance(node.type, ast.Tuple) \
                else list(node.type.elts)
            broad = [n for n in names
                     if _attr_chain(n).split(".")[-1] in ("Exception",
                                                          "BaseException")]
            if not broad:
                continue
            label = f"'except {_attr_chain(broad[0])}:'"
        if any(isinstance(n, ast.Raise) for n in ast.walk(node)):
            continue  # the handler re-raises — errors still propagate
        yield node, msg.format(label)


# --- CKPT001: raw durable-state writes outside the atomic helpers --------

# "shard"/"index" cover the streaming shard sets (data/stream.py): the
# shard index IS a manifest — a torn index.json makes the whole corpus
# unreadable — so raw writes to shard-ish targets route through the same
# atomic helpers (helpers.atomic_write_json / temp + os.replace).
_CKPT_TOKENS = ("ckpt", "checkpoint", "heartbeat", "manifest", "shard")
_WRITE_MODE_CHARS = "wax"


def _literal_mode(call: ast.Call, pos: int) -> str:
    """The mode string of an open()-style call, '' if absent/non-literal.
    ``pos`` is the mode's positional index: 1 for builtin ``open(file,
    mode)``, 0 for ``Path.open(mode)``."""
    mode = None
    if len(call.args) > pos:
        mode = call.args[pos]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return ""


def rule_ckpt001(ctx: FileCtx) -> Iterator[RuleHit]:
    """Durable run state (checkpoints, heartbeats, manifests) written with
    a raw ``open(..., "wb")`` / ``write_text`` can be torn by a crash or
    preemption mid-write — and a torn checkpoint is exactly the failure
    the crash-consistency layer exists to survive.  Every durable-state
    write must go through the atomic-rename helpers in ``utils/``
    (``save_checkpoint``, ``CheckpointManager``, ``Heartbeat._write``:
    temp file + fsync + ``os.replace``), which are themselves exempt.
    Syntactic over-approximation: any write-mode open / ``write_text`` /
    ``write_bytes`` whose target expression mentions a checkpoint-ish
    token; pragma with a justification where the write is provably not
    durable state (or already renamed into place)."""
    msg = ("raw {} to a checkpoint/heartbeat/manifest path can be torn by "
           "a crash mid-write; route durable-state writes through the "
           "atomic-rename helpers in dalle_pytorch_tpu/utils "
           "(save_checkpoint / CheckpointManager / Heartbeat), or pragma "
           "with why this write is not durable state")
    parts = ctx.path.replace("\\", "/").split("/")
    if "utils" in parts:  # the atomic helpers live here
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        target = None
        label = None
        if isinstance(node.func, ast.Name) and node.func.id == "open" \
                and node.args:
            mode = _literal_mode(node, 1)
            if any(c in mode for c in _WRITE_MODE_CHARS):
                target = ast.unparse(node.args[0])
                label = f'open(..., "{mode}")'
        elif isinstance(node.func, ast.Attribute):
            if node.func.attr == "open":
                mode = _literal_mode(node, 0)
                if any(c in mode for c in _WRITE_MODE_CHARS):
                    target = ast.unparse(node.func.value)
                    label = f'.open("{mode}")'
            elif node.func.attr in ("write_text", "write_bytes"):
                target = ast.unparse(node.func.value)
                label = f".{node.func.attr}()"
        if target and any(tok in target.lower() for tok in _CKPT_TOKENS):
            yield node, msg.format(label)


# --- OBS001: bare print() in step/serve/ckpt hot paths --------------------

# package subtrees whose narration must reach the telemetry stream: the
# step/serve/ckpt/data hot paths every post-mortem replays.  models/ops/
# parallel are pure computation (no narration), lint is host tooling, and
# the sinks themselves (obs/, utils/logging.py's TrainLogger) are exempt —
# a sink printing is the sink working.
_OBS_HOT_SUBTREES = ("serve", "data", "utils")
_OBS_HOT_FILES = ("training.py",)
_OBS_EXEMPT = (("utils", "logging.py"),)


def rule_obs001(ctx: FileCtx) -> Iterator[RuleHit]:
    """A bare ``print()`` in a hot path (step loop, serve scheduler,
    checkpoint manager, data pipeline) narrates to a terminal nobody is
    watching and to no one else: the BENCH rounds that died on a wedged
    tunnel left NO attributable timeline because every layer logged this
    way.  Operator messages in ``dalle_pytorch_tpu/``'s serve/data/utils
    subtrees (and training.py) must go through ``obs.telemetry.note`` —
    the stderr line AND the stream event in one call — or TrainLogger;
    pragma with a reason where a raw print is genuinely correct (e.g. a
    CLI-only surface)."""
    msg = ("bare print() in a step/serve/ckpt hot path leaves no record in "
           "the run's telemetry stream; use dalle_pytorch_tpu.obs."
           "telemetry.note (stderr line + stream event) or TrainLogger, or "
           "pragma with why a raw print is correct here")
    parts = tuple(ctx.path.replace("\\", "/").split("/"))
    if "dalle_pytorch_tpu" not in parts:
        return
    sub = parts[parts.index("dalle_pytorch_tpu") + 1:]
    if not sub or any(sub[-len(ex):] == ex for ex in _OBS_EXEMPT):
        return
    if sub[0] not in _OBS_HOT_SUBTREES and sub[-1] not in _OBS_HOT_FILES:
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "print":
            yield node, msg


# --- OBS002: wall-clock duration math -------------------------------------


def _is_time_time(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) \
        and _attr_chain(node.func) == "time.time"


def rule_obs002(ctx: FileCtx) -> Iterator[RuleHit]:
    """``time.time() - t0`` measures a duration with a clock that NTP can
    step backwards mid-run and that skews by seconds across a fleet — the
    exact wobble obs/align.py exists to undo.  Inside
    ``dalle_pytorch_tpu/``, durations must come from ``time.monotonic()``
    (or ``perf_counter``); wall clock is reserved for envelope timestamps
    (telemetry ``t``, heartbeat ``time``) that cross processes.  Flags a
    subtraction whose operand is a direct ``time.time()`` call or a name
    assigned from one in the same scope; genuinely cross-clock math
    (wall vs a file mtime) carries a pragma saying so.  Aliased imports
    escape — the usual syntactic over-approximation contract."""
    msg = ("duration math on a time.time() delta: wall clocks skew across "
           "hosts and NTP can step them mid-run; use time.monotonic() for "
           "durations (wall clock is for envelope timestamps only), or "
           "pragma with why this subtraction is genuinely cross-clock")
    parts = tuple(ctx.path.replace("\\", "/").split("/"))
    if "dalle_pytorch_tpu" not in parts:
        return
    scopes = [ctx.tree] + [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for scope in scopes:
        wall_names = {
            node.targets[0].id
            for node in _walk_skip_defs(scope)
            if isinstance(node, ast.Assign) and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and _is_time_time(node.value)}
        for node in _walk_skip_defs(scope):
            if not isinstance(node, ast.BinOp) \
                    or not isinstance(node.op, ast.Sub):
                continue
            if any(_is_time_time(side)
                   or (isinstance(side, ast.Name) and side.id in wall_names)
                   for side in (node.left, node.right)):
                yield node, msg


# --- OBS003: unmanaged jax.profiler entry points ---------------------------

_OBS3_PROFILER_CALLS = frozenset(("profiler.start_trace",
                                  "profiler.stop_trace", "profiler.trace"))
_OBS3_EXEMPT = (("obs", "prof.py"),)


def rule_obs003(ctx: FileCtx) -> Iterator[RuleHit]:
    """A direct ``jax.profiler.start_trace/stop_trace/trace`` call outside
    ``obs/prof.py``'s managed ``capture()`` helper produces an on-chip
    trace window the telemetry stream never hears about: the Perfetto
    fleet merge can't correlate it, a death inside it leaves the profiler
    wedged with no torn-span record, and graftscope's run report shows a
    step-time crater with no cause.  Route captures through
    ``obs.prof.capture(logdir)`` (or ``prof.XprofWindow`` for step-window
    arming) — one entry point that opens the trace inside a ``prof.xprof``
    span; pragma with a reason where a raw call is genuinely correct
    (e.g. a debugging scratch script)."""
    msg = ("direct jax.profiler trace call outside obs/prof.py: the "
           "on-chip capture window never lands in the telemetry stream "
           "(no prof.xprof span, no fleet correlation, no torn-span "
           "record on death); use dalle_pytorch_tpu.obs.prof.capture / "
           "XprofWindow, or pragma with why an unmanaged trace is "
           "correct here")
    parts = tuple(ctx.path.replace("\\", "/").split("/"))
    if any(parts[-len(ex):] == ex for ex in _OBS3_EXEMPT):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if any(chain == c or chain.endswith("." + c)
               for c in _OBS3_PROFILER_CALLS):
            yield node, msg


# --- MEM001: unmanaged device-memory polling entry points ------------------

_MEM1_POLL_CALLS = frozenset(("device_memory_profile",
                              "profiler.device_memory_profile",
                              "live_arrays"))
_MEM1_EXEMPT = (("obs", "mem.py"),)


def rule_mem001(ctx: FileCtx) -> Iterator[RuleHit]:
    """A direct ``jax.profiler.device_memory_profile`` /
    ``jax.live_arrays`` call outside ``obs/mem.py`` produces a memory
    sample the observability stack never hears about: no
    ``mem.watermark`` telemetry record, no ``graft_hbm_*`` gauges, no
    ``hbm_headroom`` alert input, and the serve leak gate's baseline
    census can't account for it (a stray ``live_arrays()`` in a hot loop
    is itself a way to pin buffers).  Route polling through
    ``obs.mem.MemTracker`` / ``mem.live_buffer_stats`` /
    ``mem.device_memory_stats`` / ``mem.write_device_memory_profile`` —
    the OBS003 one-managed-entry-point discipline, applied to the
    memory APIs; pragma with a reason where a raw call is genuinely
    correct (e.g. a debugging scratch script)."""
    msg = ("direct jax device-memory poll outside obs/mem.py: the sample "
           "never lands in the telemetry stream (no mem.watermark record, "
           "no graft_hbm_* gauges, no hbm_headroom alert input, invisible "
           "to the serve leak-gate baseline); use dalle_pytorch_tpu.obs."
           "mem.MemTracker / live_buffer_stats / device_memory_stats / "
           "write_device_memory_profile, or pragma with why an unmanaged "
           "poll is correct here")
    parts = tuple(ctx.path.replace("\\", "/").split("/"))
    if any(parts[-len(ex):] == ex for ex in _MEM1_EXEMPT):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if any(chain == c or chain.endswith("." + c)
               for c in _MEM1_POLL_CALLS):
            yield node, msg


# --- SRV001: unbounded blocking waits in serve/ ---------------------------

_SRV_BLOCKING = frozenset(("result", "get", "acquire"))


def rule_srv001(ctx: FileCtx) -> Iterator[RuleHit]:
    """A blocking wait without a timeout inside ``dalle_pytorch_tpu/serve/``
    turns a dead replica into a hung router: the whole fleet tier exists
    to convert losses into typed errors, and one ``future.result()`` with
    no deadline quietly reintroduces the infinite hang the SLO layer can
    never shed.  Flags ``.result()`` / ``.get()`` / ``.acquire()`` calls
    that pass neither a positional argument nor a ``timeout=`` keyword
    (a zero-arg ``.get()`` is the blocking queue form — dict ``.get``
    always takes a key).  ``with lock:`` blocks are fine (bounded by the
    holder, not a wait-for-event); pragma with why a wait is provably
    bounded where the rule over-approximates."""
    msg = ("blocking {}() without a timeout in serve/: a dead replica or a "
           "lost wakeup turns this wait into a hang no SLO policy can "
           "shed; pass an explicit timeout (and handle expiry) or pragma "
           "with why this wait is bounded")
    parts = tuple(ctx.path.replace("\\", "/").split("/"))
    if "dalle_pytorch_tpu" not in parts:
        return
    sub = parts[parts.index("dalle_pytorch_tpu") + 1:]
    if not sub or sub[0] != "serve":
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute) \
                or node.func.attr not in _SRV_BLOCKING:
            continue
        if node.args:
            continue  # positional timeout (result(t), get(block, t))
        if any(kw.arg == "timeout" for kw in node.keywords):
            continue
        yield node, msg.format(node.func.attr)


# --- DON001/DON002: buffer donation (the AST side of graftspmd S2) --------

_STEP_FACTORY_RE = re.compile(r"^make_\w*step\w*$")
_TRAIN_STEP_FACTORY_RE = re.compile(r"^make_\w*train_step$")


def _jit_call_keywords(call: ast.Call) -> Optional[List[ast.keyword]]:
    """The keyword list of a jit/pjit wrapping call (including the
    ``partial(jax.jit, ...)`` form), or None if ``call`` is not one."""
    chain = _attr_chain(call.func)
    if chain.endswith("partial") and call.args \
            and _attr_chain(call.args[0]).split(".")[-1] in ("jit", "pjit"):
        return list(call.keywords)
    if chain.split(".")[-1] in ("jit", "pjit"):
        return list(call.keywords)
    return None


def rule_don001(ctx: FileCtx) -> Iterator[RuleHit]:
    """A train-step factory that jits without ``donate_argnums`` ships a
    step holding params+opt_state alive TWICE across the update (inputs
    kept by the caller, outputs fresh buffers) — at CUB geometry that is
    ~350 MiB of silent HBM overhead per chip, and the optimizer-state
    double is exactly how plans that "should fit" OOM.  Every jit inside
    a ``make_*step*`` factory must state its donation (an explicit empty
    ``donate_argnums=()`` is a statement, and the dynamic half — whether
    the donation survives compilation — is graftspmd S2's job)."""
    msg = ("jit inside step factory {!r} without donate_argnums: the "
           "returned step keeps params/opt_state buffers alive twice "
           "across the update; state the donation explicitly "
           "(donate_argnums=(0, 1), or =() with a pragma-level reason)")
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or not _STEP_FACTORY_RE.match(fn.name):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            kws = _jit_call_keywords(node)
            if kws is None:
                continue
            if not any(kw.arg in ("donate_argnums", "donate_argnames")
                       for kw in kws):
                yield node, msg.format(fn.name)


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Positional indices a call's assignee will donate, if statically
    knowable: ``jax.jit(..., donate_argnums=<literal>)`` or a
    ``make_*_train_step(...)`` factory call (donates (0, 1) unless built
    with ``donate=False`` or ``jit=False``)."""
    kws = {kw.arg: kw.value for kw in call.keywords}
    jit_kws = _jit_call_keywords(call)
    if jit_kws is not None:
        da = kws.get("donate_argnums")
        if isinstance(da, ast.Constant) and isinstance(da.value, int):
            return (da.value,)
        if isinstance(da, ast.Tuple) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in da.elts):
            return tuple(e.value for e in da.elts)
        return None
    if isinstance(call.func, ast.Name) \
            and _TRAIN_STEP_FACTORY_RE.match(call.func.id):
        for off in ("donate", "jit"):
            v = kws.get(off)
            if isinstance(v, ast.Constant) and v.value is False:
                return None
        return (0, 1)
    return None


def _target_names(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in node.elts:
            out.extend(_target_names(e.value if isinstance(e, ast.Starred)
                                     else e))
        return out
    return []


def _helper_donation_signatures(tree) -> Dict[str, Tuple[int, ...]]:
    """Per-function donated-PARAMETER positions: the cross-function half
    of DON002.  A helper that forwards its own parameter to a donated
    position of a tracked donating call (a donating jit/factory
    assignment visible anywhere in the file, or another already-resolved
    helper — fixed point, so helper-of-helper chains resolve) effectively
    donates that parameter: the CALLER's variable is dead after the
    helper returns, exactly as if it had called the jit directly.  Name
    resolution is file-global and syntactic (no scope analysis) — the
    over-approximation a pragma can override, same contract as the rest
    of the rule."""
    # every single-name donating assignment anywhere in the file (module
    # scope, function bodies, nested defs): the closure-captured
    # `_codes_step = make_*_train_step(...)` idiom must resolve inside
    # the sibling nested def that forwards to it
    assigned: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            pos = _donated_positions(node.value)
            if pos:
                assigned[node.targets[0].id] = pos
    fns = [n for n in ast.walk(tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    signatures: Dict[str, Tuple[int, ...]] = {}
    changed = True
    while changed:
        changed = False
        for fn in fns:
            param_idx = {a.arg: i for i, a in enumerate(fn.args.args)}
            donated: set = set(signatures.get(fn.name, ()))
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Name):
                    continue
                callee = node.func.id
                positions = assigned.get(callee) or signatures.get(callee)
                if not positions:
                    continue
                for pos in positions:
                    if pos < len(node.args) \
                            and isinstance(node.args[pos], ast.Name) \
                            and node.args[pos].id in param_idx:
                        donated.add(param_idx[node.args[pos].id])
            if donated and tuple(sorted(donated)) \
                    != signatures.get(fn.name):
                signatures[fn.name] = tuple(sorted(donated))
                changed = True
    return signatures


def rule_don002(ctx: FileCtx) -> Iterator[RuleHit]:
    """A variable passed at a donated position is DEAD after the call —
    jax invalidates the buffer — yet a read after the call is only caught
    at runtime ("array has been deleted"), typically on the untested
    resume/periodic-save path.  Flags donated args that are read again
    later in the same scope without the call statement rebinding them
    (the ``params, opt_state, ... = step(params, opt_state, ...)`` idiom
    is the clean shape).  Tracks single-name assignments from
    ``jax.jit(..., donate_argnums=...)`` and ``make_*_train_step(...)``
    calls, AND — the cross-function escape — helpers that forward their
    own parameters to such a call (:func:`_helper_donation_signatures`):
    a caller's variable handed to ``run_step(params, ...)`` is just as
    dead as one handed to the jit directly, and reading it afterwards is
    the same use-after-donation.  Syntactic over-approximation — a read
    on a disjoint branch needs a pragma with the reason."""
    msg = ("{!r} is donated by this call (position {}) and its buffer is "
           "deleted, but it is read again at line {} in the same scope; "
           "rebind it from the call's outputs or drop the later read")
    helper_sigs = _helper_donation_signatures(ctx.tree)
    scopes = [ctx.tree] + [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for scope in scopes:
        body = scope.body if hasattr(scope, "body") else []
        wrapped = ast.Module(body=body, type_ignores=[])
        # per-scope tracking: a name is donating only while its latest
        # single-name assignment in THIS scope is a donating jit/factory
        # call (a donate=False or unrelated reassignment drops it).
        # Helpers with donation signatures seed the map — a nested `def
        # run_step(...)` binding in this scope, or a module-level helper.
        donating: Dict[str, Tuple[int, ...]] = dict(helper_sigs)
        for node in _walk_skip_defs(wrapped):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                pos = _donated_positions(node.value) \
                    if isinstance(node.value, ast.Call) else None
                if pos:
                    donating[node.targets[0].id] = pos
                else:
                    donating.pop(node.targets[0].id, None)
        if not donating:
            continue
        loads = [(n.lineno, n.id) for n in _walk_skip_defs(wrapped)
                 if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)]
        for stmt in body:
            yield from _don002_stmt(stmt, donating, loads, msg)


_STMT_CONTAINERS = (ast.ExceptHandler,) + (
    (ast.match_case,) if hasattr(ast, "match_case") else ())


def _own_exprs(stmt: ast.AST) -> Iterator[ast.AST]:
    """The expressions belonging to this statement itself — its header and
    inline values, but not its sub-statements (each gets its own
    rebinding context) and not nested def/lambda bodies (their params
    shadow outer names)."""
    skip = (ast.stmt, ast.Lambda) + _STMT_CONTAINERS
    stack = [c for c in ast.iter_child_nodes(stmt)
             if not isinstance(c, skip)]
    while stack:
        n = stack.pop()
        yield n
        stack.extend(c for c in ast.iter_child_nodes(n)
                     if not isinstance(c, skip))


def _don002_stmt(stmt: ast.AST, donating, loads, msg) -> Iterator[RuleHit]:
    """Check one statement's own expressions for tracked donating calls,
    recursing into compound-statement bodies (each inner statement carries
    its own rebinding context) but not nested defs."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, (ast.stmt,) + _STMT_CONTAINERS):
            yield from _don002_stmt(child, donating, loads, msg)
    rebound = [n for t in stmt.targets for n in _target_names(t)] \
        if isinstance(stmt, ast.Assign) else []
    end = stmt.end_lineno or stmt.lineno
    for node in _own_exprs(stmt):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Name):
            continue
        positions = donating.get(node.func.id)
        if not positions:
            continue
        for pos in positions:
            if pos >= len(node.args) or not isinstance(node.args[pos],
                                                       ast.Name):
                continue
            name = node.args[pos].id
            if name in rebound:
                continue
            later = [ln for ln, nid in loads if nid == name and ln > end]
            if later:
                yield node, msg.format(name, pos, min(later))


# --- THR001/THR002: thread discipline (the AST side of graftrace) ---------

_THR_LOCK_CTORS = frozenset(("Lock", "RLock", "Condition"))


def rule_thr001(ctx: FileCtx) -> Iterator[RuleHit]:
    """Raw ``threading.Lock/RLock/Condition`` construction outside
    ``utils/locks.py`` bypasses the graftrace witness: that lock's
    acquisitions never land in the order graph or the contention stats,
    so the chaos suites can no longer prove the fleet deadlock-free.
    Construct through ``locks.TracedLock/TracedRLock/TracedCondition``
    (drop-in, free when the witness is disarmed).  ``threading.Event`` is
    fine — events carry no ordering.  Fixture files (``*_fixtures.py``)
    are exempt: their raw locks are the analyzer's test subjects."""
    msg = ("raw threading.{}() bypasses the graftrace lock-order witness; "
           "construct via utils.locks.Traced{} (same semantics, witness "
           "sees it) or pragma with why this lock must stay untraced")
    norm = ctx.path.replace("\\", "/")
    if norm.endswith("utils/locks.py") or norm.endswith("_fixtures.py"):
        return
    from_imports = set()
    for node in ctx.tree.body:
        if isinstance(node, ast.ImportFrom) and node.module == "threading":
            from_imports.update(a.asname or a.name for a in node.names)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        name = chain.split(".")[-1]
        if name not in _THR_LOCK_CTORS:
            continue
        if chain == f"threading.{name}" or (chain == name
                                            and name in from_imports):
            yield node, msg.format(name, name)


def rule_thr002(ctx: FileCtx) -> Iterator[RuleHit]:
    """A ``while`` loop that polls shared state with ``time.sleep`` under
    ``dalle_pytorch_tpu/serve/`` burns its poll interval on every state
    change it is waiting for — and worse, never wakes early for shutdown,
    so a close() racing the loop waits out the full interval (or hangs,
    if the condition can no longer become true).  Wait on a
    ``threading.Event``/``Condition`` instead (``stop_evt.wait(dt)`` is
    the drop-in form: same pacing, immediate wakeup on close).  Pragma
    the open-loop cases that pace against a local clock rather than
    shared state."""
    msg = ("while-loop polls with sleep() in serve/: sleeps never wake "
           "early for close/stop and add a full interval of latency per "
           "state change; wait on an Event/Condition "
           "(e.g. stop_evt.wait(dt)) or pragma with why this loop paces "
           "a local clock, not shared state")
    parts = tuple(ctx.path.replace("\\", "/").split("/"))
    if "dalle_pytorch_tpu" not in parts:
        return
    sub = parts[parts.index("dalle_pytorch_tpu") + 1:]
    if not sub or sub[0] != "serve":
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.While):
            continue
        for inner in _walk_skip_defs(node):
            if isinstance(inner, ast.Call) \
                    and _attr_chain(inner.func).split(".")[-1] == "sleep" \
                    and _attr_chain(inner.func) in ("time.sleep", "sleep"):
                yield inner, msg
                break


_PLAN_SHARDING_CTORS = frozenset(("Mesh", "NamedSharding", "PartitionSpec"))


def rule_plan001(ctx: FileCtx) -> Iterator[RuleHit]:
    """Hand-constructed ``Mesh``/``NamedSharding``/``PartitionSpec``
    outside ``parallel/`` bypasses the ParallelPlan contract: the sharding
    never flows through PARTITION_RULES, so graftplan's P1-P4 analyses
    (rule coverage, axis divisibility, HBM fit, collective placement —
    lint/plans.py) cannot see it, and spec strings drift from the plan the
    run declared.  Go through the plan registry and ``Partitioner``
    (``plan.partitioner().param_specs/shard_batch``) instead, or pragma
    with why this sharding is genuinely outside the plan's rule table.
    The ``parallel/`` package itself and fixture files are exempt: they
    are where the contract is implemented and tested."""
    msg = ("hand-constructed {}() bypasses the ParallelPlan rule table — "
           "graftplan's static analyses can't see this sharding; build it "
           "through parallel.plan/Partitioner or pragma with why it lives "
           "outside the plan contract")
    norm = ctx.path.replace("\\", "/")
    if "/parallel/" in norm or norm.startswith("parallel/") \
            or norm.endswith("_fixtures.py"):
        return
    # local aliases of the ctors: `from jax.sharding import
    # PartitionSpec as P` must still match — walk the WHOLE tree, since
    # this repo imports jax lazily inside functions (ENV001 discipline)
    aliases = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) \
                and node.module in ("jax.sharding", "jax.experimental.pjit"):
            for a in node.names:
                if a.name in _PLAN_SHARDING_CTORS:
                    aliases[a.asname or a.name] = a.name
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        name = chain.split(".")[-1]
        if name in _PLAN_SHARDING_CTORS and (
                chain == f"jax.sharding.{name}"
                or chain == f"sharding.{name}"):
            yield node, msg.format(name)
        elif chain in aliases:
            yield node, msg.format(aliases[chain])


RULES = {
    "ENV001": rule_env001,
    "SEED001": rule_seed001,
    "BACKEND001": rule_backend001,
    "DOT001": rule_dot001,
    "TRACE001": rule_trace001,
    "EXC001": rule_exc001,
    "CKPT001": rule_ckpt001,
    "OBS001": rule_obs001,
    "OBS002": rule_obs002,
    "OBS003": rule_obs003,
    "MEM001": rule_mem001,
    "SRV001": rule_srv001,
    "THR001": rule_thr001,
    "THR002": rule_thr002,
    "DON001": rule_don001,
    "DON002": rule_don002,
    "PLAN001": rule_plan001,
}
