"""Fault injection: a faultpoint registry driven by the ``GRAFT_FAULTS`` env.

The recovery paths this repo grew for preemptible pods (graceful shutdown,
manifest-validated checkpoints, quarantined samples) are exactly the code
nobody runs until a 3am preemption does — the untested-recovery failure
mode production checkpoint managers are built to close.  This module makes
the failures injectable so tests and the CI ``crash-resume`` job can rehearse
them deterministically on CPU:

    GRAFT_FAULTS="ckpt_write:fail_after=2,ckpt_write:truncate=3,\
sigterm:at_step=7,sample_read:every=50"

Grammar: comma-separated ``site:action=value`` entries.  Sites are named
call-points threaded through the real code (``ckpt_write`` in
``CheckpointManager.save``, ``sample_read`` in the dataset image/caption
reads, ``sigterm`` in the trainers' step loops).  Actions:

* ``fail_after=N`` — the (N+1)-th hit of the site raises
  :class:`InjectedFault` (an ``OSError``), once.  Exercises retry paths:
  the first N calls succeed, one fails, the retry lands.
* ``every=K`` — every K-th hit raises :class:`InjectedFault`.  Exercises
  degradation paths (sample quarantine) and retry exhaustion (``every=1``).
* ``truncate=N`` — the N-th hit returns the ``"truncate"`` action to the
  caller, once; the caller tears its own write (``CheckpointManager``
  halves the payload file *after* the manifest CRCs were computed —
  modeling a crash or bit-rot between the data landing and the next read).
* ``at_step=N`` — fires once when the caller passes ``step == N``;
  :func:`maybe_kill` turns it into a real ``SIGTERM`` to this process
  (the preemption notice, mid-training).
* ``at_tick=N`` — ``at_step`` for callers whose progress coordinate is a
  *tick counter*, not a training step (the serve fleet's replica driver
  loops).  Same one-shot semantics, distinct spelling so a chaos spec
  reads unambiguously: ``replica_down:at_tick=40`` kills a replica at
  its 40th driver tick, whatever training step anything else is on.
* ``grace_ms=N`` — configuration, not a trigger: the grace window (in
  milliseconds) the ``preempt`` site pairs with its ``at_step``.
* ``drop=N`` / ``conn_reset=N`` — network actions for the RPC transport
  sites (below): the N-th hit returns the action name to the caller,
  once — ``serve/wire.py`` turns ``drop`` into a vanished frame (the
  peer never sees it; the caller's deadline is what notices) and
  ``conn_reset`` into a torn TCP connection.  Same one-shot return
  semantics as ``truncate``.
* ``delay_ms=N`` — configuration like ``grace_ms``: the transport sleeps
  N milliseconds on every hit of the site (tail-latency injection, the
  slow-network shape that must surface as deadline misses, not hangs).

Preemption site (both trainers' step loops): ``preempt:at_step=N`` is the
full preemption drill — :func:`maybe_preempt` delivers a real SIGTERM
*and* arms a bounded grace window (``preempt:grace_ms=M``, default 30 s —
the shape of every real scheduler's notice-then-kill contract).  The
trainer's GracefulShutdown path gets exactly the window to write its
final checkpoint and exit cleanly; if the window expires first, the
process hard-exits ``ExitCode.PREEMPT_EXPIRED`` (74) mid-save, leaving
whatever the manifest commit protocol made durable — the supervisor
relaunches with ``--resume auto`` (possibly under a different
``--plan``).  Trainers cancel the window via
:func:`cancel_preempt_grace` once their final save has committed.

Training-health sites (utils/guardrails.py): ``grad_nan:at_step=N`` and
``loss_spike:at_step=N`` drive :func:`guardrails.fault_scale_for`, the
traced loss-scale port of the health-enabled train steps (NaN poisons the
real on-device gradients; a large finite factor lands a genuine spike);
``step_hang:at_step=N`` (:func:`maybe_hang`) wedges the step loop inside
the hung-step watchdog's armed window so its kill-and-relaunch path is
rehearsed end to end.

Streaming-ingestion site (data/stream.py): ``shard_read`` is hit once per
shard sample-read attempt.  ``fail_after``/``every`` model transient shard
I/O (retried once, then the SHARD is quarantined — logged, capped);
``truncate=N`` hands the reader a half-read image member (torn shard
bytes), which must end in the same retry/quarantine path.

Async-checkpoint site (utils/ckpt_manager.py): ``ckpt_async`` fires
between the checkpoint's data write and its manifest publish, with
``step`` = the checkpoint step.  ``at_step=N`` raises
:class:`InjectedKill` there — the background writer dies with the data on
disk and the commit record absent, the exact crash window invariant I1
exists for (`latest_valid()` must fall back to the previous checkpoint).

Serving site (serve/scheduler.py): ``serve_request`` is hit once per
occupied slot per decode tick (slot order; ``step`` carries the request's
decoded-token count, so ``at_step`` can target a progress milestone).  An
injected failure mid-decode fails THAT request — its future carries the
fault, its slot frees the same scheduler iteration — while co-batched
requests keep decoding (tests/test_serve.py pins the isolation).

Fleet-serving sites (serve/replica.py + serve/router.py):
``replica_down`` is hit once per replica driver-loop pass (``step`` =
that replica's completed DECODE-tick count, so ``at_tick=N`` lands
mid-stream after the Nth decode tick — an idle loop spins far faster
than it decodes); ``at_tick=N`` makes the driver thread
*vanish* mid-decode — no cleanup, no future resolution — so the router's
failure detectors (heartbeat staleness, ``/healthz``) are what find the
corpse, exactly like a killed pod; ``every=K`` models a crashy driver
loop instead.  ``router_submit`` is hit once per dispatch attempt inside
``FleetRouter``; ``every=K`` makes dispatches fail transiently, driving
the bounded-retry/backoff path (``every=1`` = retry exhaustion).
``replica_health`` is hit once per ``Replica.healthz()`` probe; ``every``
makes the probe fail while the driver keeps beating — the
probe-signal-without-heartbeat-signal case the router must treat as a
graceful quarantine, not an instant death.

Network sites (serve/wire.py): ``rpc_send`` fires once per frame a
``WireClient`` writes, ``rpc_recv`` once per response frame it reads —
CLIENT-side only, so one in-process fault registry shared by a test's
client and server injects deterministically at the caller's edge of the
wire.  ``drop``/``conn_reset``/``truncate`` are one-shot Nth-hit
actions; ``delay_ms`` is per-hit configuration.  A dropped *send*
models a lost request (the peer never executed); a dropped *recv*
models a lost response (the peer DID execute — the ambiguous timeout
the idempotent-retry contract exists for).

Counters are per-site and thread-safe (dataset reads run under the
prefetching DataLoader's thread pool).  The registry is parsed lazily from
the environment; trainers call :func:`install_from_env` at startup so
in-process reruns (tests call ``main()`` repeatedly) see the *current*
environment, not a cached one.
"""
from __future__ import annotations

import dataclasses
import os
import signal
import threading
from typing import Dict, FrozenSet, List, Optional

from ..obs import telemetry
from . import locks

_ACTIONS = ("fail_after", "every", "truncate", "at_step", "at_tick",
            "grace_ms", "drop", "delay_ms", "conn_reset")


class InjectedFault(OSError):
    """A deliberately injected transient I/O failure (``GRAFT_FAULTS``)."""


class InjectedKill(RuntimeError):
    """A deliberately injected *process death* at a faultpoint — unlike
    :class:`InjectedFault` it is NOT an ``OSError``, so retry loops that
    model transient I/O (``CheckpointManager.save``) let it escape: the
    code after the faultpoint never runs, exactly as if the scheduler had
    killed the process there.  The ``ckpt_async`` site uses it to abandon
    an async checkpoint between its data write and its manifest publish
    (the I1 crash window: data on disk, commit record absent)."""


@dataclasses.dataclass
class _Trigger:
    action: str
    value: int
    fired: bool = False


class FaultRegistry:
    """Parsed ``GRAFT_FAULTS`` spec + per-site hit counters."""

    def __init__(self, spec: str = ""):
        self._lock = locks.TracedLock("faults.registry")
        self._triggers: Dict[str, List[_Trigger]] = {}
        self._hits: Dict[str, int] = {}
        for entry in (e.strip() for e in (spec or "").split(",")):
            if not entry:
                continue
            site, sep, act = entry.partition(":")
            action, sep2, value = act.partition("=")
            if not sep or not sep2 or not site or action not in _ACTIONS:
                raise ValueError(
                    f"bad GRAFT_FAULTS entry {entry!r}: expected "
                    f"'site:action=value' with action in {_ACTIONS}")
            try:
                ivalue = int(value)
            except ValueError as e:
                raise ValueError(
                    f"bad GRAFT_FAULTS value in {entry!r}: {value!r} is not "
                    "an integer") from e
            if ivalue < 0:
                raise ValueError(f"bad GRAFT_FAULTS value in {entry!r}: "
                                 "must be >= 0")
            self._triggers.setdefault(site, []).append(
                _Trigger(action, ivalue))

    @property
    def empty(self) -> bool:
        return not self._triggers

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def config(self, site: str, action: str) -> Optional[int]:
        """Value of a configuration action (``grace_ms``/``delay_ms``)
        on ``site``, or None when the spec doesn't carry one."""
        with self._lock:
            for t in self._triggers.get(site, ()):
                if t.action == action:
                    return t.value
        return None

    def fire(self, site: str, step: Optional[int] = None) -> FrozenSet[str]:
        """Register one hit of ``site``; raise or return triggered actions.

        ``fail_after``/``every`` raise :class:`InjectedFault`;
        ``truncate``/``at_step`` are returned for the caller to act on.
        """
        with self._lock:
            hits = self._hits[site] = self._hits.get(site, 0) + 1
            actions = set()
            for t in self._triggers.get(site, ()):
                if t.action in ("grace_ms", "delay_ms"):
                    continue  # configuration, read via config(), never fires
                if t.action == "fail_after":
                    if not t.fired and hits == t.value + 1:
                        t.fired = True
                        _record(site, "fail_after", hits, step)
                        raise InjectedFault(
                            f"injected fault: {site} hit {hits} "
                            f"(fail_after={t.value})")
                elif t.action == "every":
                    if t.value > 0 and hits % t.value == 0:
                        _record(site, "every", hits, step)
                        raise InjectedFault(
                            f"injected fault: {site} hit {hits} "
                            f"(every={t.value})")
                elif t.action in ("truncate", "drop", "conn_reset"):
                    # one-shot Nth-hit actions returned to the caller:
                    # the transport (or checkpoint writer) tears its own
                    # frame/connection so the failure is a REAL one
                    if not t.fired and hits == t.value:
                        t.fired = True
                        actions.add(t.action)
                elif t.action in ("at_step", "at_tick"):
                    # same one-shot progress trigger; at_tick is the
                    # spelling for tick-counter callers (replica drivers)
                    if not t.fired and step is not None and step == t.value:
                        t.fired = True
                        actions.add(t.action)
            for action in actions:
                _record(site, action, hits, step)
            return frozenset(actions)


def _record(site: str, action: str, hits: int, step: Optional[int]) -> None:
    """A TRIGGERED injection becomes a telemetry event, so chaos suites can
    assert cause→recovery ordering from the stream alone (the untriggered
    per-hit path emits nothing — ``fire`` runs per sample read and per
    serve slot per tick)."""
    telemetry.emit("fault", site, action=action, hits=hits, step=step)


_registry: Optional[FaultRegistry] = None
_registry_lock = locks.TracedLock("faults.active")


def install(spec: str) -> FaultRegistry:
    """Install an explicit spec (tests); returns the registry.  Any grace
    timer armed by a previous run's preemption drill is cancelled — an
    in-process rerun must never be hard-killed by its predecessor."""
    global _registry
    cancel_preempt_grace()
    with _registry_lock:
        _registry = FaultRegistry(spec)
        return _registry


def install_from_env() -> FaultRegistry:
    """(Re-)parse ``GRAFT_FAULTS``.  Trainers call this at startup so
    in-process reruns pick up the current environment, not a stale cache."""
    return install(os.environ.get("GRAFT_FAULTS", ""))


def reset() -> None:
    """Drop the registry (and cancel any armed preemption grace timer);
    the next :func:`fire` re-reads the environment."""
    global _registry
    cancel_preempt_grace()
    with _registry_lock:
        _registry = None


def get_registry() -> FaultRegistry:
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = FaultRegistry(os.environ.get("GRAFT_FAULTS", ""))
        return _registry


def fire(site: str, step: Optional[int] = None) -> FrozenSet[str]:
    """Hit a faultpoint.  No-op (empty set) when no faults are configured —
    cheap enough to leave in hot-ish paths like the dataset read."""
    reg = get_registry()
    if reg.empty:
        return frozenset()
    return reg.fire(site, step=step)


def maybe_kill(step: int) -> None:
    """The ``sigterm:at_step=N`` site: deliver a real SIGTERM to this
    process at step N — the preemption notice, so GracefulShutdown's
    checkpoint-and-stop path is rehearsed end to end."""
    if "at_step" in fire("sigterm", step=step):
        signal.raise_signal(signal.SIGTERM)


_PREEMPT_DEFAULT_GRACE_S = 30.0
_preempt_timers: List[threading.Timer] = []


def _grace_expired(step: int, grace_s: float) -> None:
    """The scheduler's hard kill: the grace window closed with the process
    still running.  ``os._exit`` (not sys.exit) — a real kill runs no
    finalizers, and the whole point is proving the manifest commit
    protocol needs none."""
    import os as _os

    from .failure import ExitCode

    telemetry.note(
        "fault", "preempt_expired",
        f"preemption grace window ({grace_s:.1f}s) expired before the "
        f"final checkpoint committed (step {step}); hard exit "
        f"{int(ExitCode.PREEMPT_EXPIRED)}", prefix="[faults]", step=step,
        grace_s=grace_s)
    _os._exit(int(ExitCode.PREEMPT_EXPIRED))


def maybe_preempt(step: int) -> None:
    """The ``preempt:at_step=N`` site: the full preemption drill.

    Delivers a real SIGTERM (the notice) AND arms a bounded grace window
    (``preempt:grace_ms=M`` on the same site, default 30 s) on a daemon
    timer: if the process is still alive when it expires — the final save
    stalled, a collective wedged — the timer hard-exits
    ``ExitCode.PREEMPT_EXPIRED`` exactly as the scheduler's follow-up
    SIGKILL would, mid-write, with no finalizers.  The graceful path
    (GracefulShutdown → final save → clean exit) must call
    :func:`cancel_preempt_grace` once its save has committed."""
    if "at_step" not in fire("preempt", step=step):
        return
    grace_ms = get_registry().config("preempt", "grace_ms")
    grace_s = (_PREEMPT_DEFAULT_GRACE_S if grace_ms is None
               else grace_ms / 1000.0)
    telemetry.note(
        "fault", "preempt",
        f"preemption notice at step {step}: SIGTERM delivered, "
        f"{grace_s:.1f}s grace window armed", prefix="[faults]",
        step=step, grace_s=grace_s)
    timer = threading.Timer(grace_s, _grace_expired, args=(step, grace_s))
    timer.daemon = True
    timer.name = f"preempt-grace-{step}"
    with _registry_lock:
        _preempt_timers.append(timer)
    timer.start()
    signal.raise_signal(signal.SIGTERM)


def cancel_preempt_grace() -> None:
    """Disarm any armed preemption grace timer: the final checkpoint
    committed inside the window (or an in-process rerun is starting).
    Trainers call this on their exit path; without it, a graceful stop
    that finished in time could still be hard-killed moments later."""
    with _registry_lock:
        timers, _preempt_timers[:] = list(_preempt_timers), []
    for t in timers:
        t.cancel()


def maybe_hang(step: int, cap: float = 3600.0) -> None:
    """The ``step_hang:at_step=N`` site: wedge the step loop at step N —
    a device call that never returns (the DESIGN.md §6 tunnel-wedge class,
    which raises no exception).  Sleeps inside the StepWatchdog's armed
    window so the watchdog's stack-dump + ``ExitCode.WEDGED`` exit is what
    ends it; ``cap`` bounds the sleep so a test that forgot to arm a
    watchdog still terminates eventually."""
    if "at_step" in fire("step_hang", step=step):
        import time

        telemetry.note(
            "fault", "step_hang_wedged",
            f"step_hang: wedging the step loop at step {step}",
            prefix="[faults]", step=step)
        deadline = time.monotonic() + cap
        while time.monotonic() < deadline:
            time.sleep(0.5)
