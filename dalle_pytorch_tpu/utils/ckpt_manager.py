"""Crash-consistent checkpoint management: manifests, fallback, retention.

The single-file atomic saves (``utils/checkpoint.py``) make one checkpoint
crash-consistent; this layer makes a *run directory* of them
crash-consistent.  A preemptible-pod run dies mid-write, resumes from
storage that bit-rots, and must never lose more than one checkpoint
interval — so every checkpoint gets an integrity manifest, resume scans for
the newest checkpoint that *verifies* (falling back past torn or corrupt
ones), and saves retry transient I/O errors with exponential backoff.

Layout (one directory per checkpoint)::

    run_dir/
      ckpt-00000004/
        data.msgpack            # or data.orbax/ (sharded saves)
        manifest.json           # published LAST, by atomic rename
      ckpt-00000007/ ...

The manifest is the commit record: it is written (atomically) only after
the payload bytes are on disk, so a directory without a valid manifest is
by definition a torn write and :meth:`CheckpointManager.latest_valid`
skips it.  Manifest fields: ``schema`` (payload schema version), ``step``,
``config_fingerprint`` (crc32 of the canonical config JSON — resuming a
*different* model silently is its own bug class), ``payload`` (the data
file/dir name), ``files`` (per-file size + crc32, verified on scan),
``time``, and the elastic-resume provenance pair ``plan`` (the writing
run's declarative ParallelPlan record, ``parallel/plan.py``) +
``topology`` (device/process count, platform) — so a resume on different
hardware can report exactly what it is resharding from.

Fault injection (``GRAFT_FAULTS``, see ``utils/faults.py``) threads through
``save`` at the ``ckpt_write`` site so the retry and fallback paths are
rehearsed by tests instead of discovered by the first real preemption, and
at the ``ckpt_async`` site (between data write and manifest publish) so the
async writer's crash window is rehearsed too.

**Async saves** (``async_save=True``, the trainers' default for msgpack
payloads): the caller snapshots device arrays to host synchronously
(``host_fetch``), then ``save`` hands the host payload to a background
writer thread and returns immediately — the step loop no longer stalls on
serialization + disk + crc for the whole checkpoint.  Nothing about the
commit protocol changes: the SAME ``_save_once`` runs on the worker, the
manifest publish remains the single commit point, and a crash anywhere
before it leaves a manifest-less directory that ``latest_valid`` skips
(invariants I1–I3, DESIGN.md §8, hold unchanged — proven by pointing the
existing GRAFT_FAULTS torn-write/SIGTERM harness at the async path).  One
save in flight at a time; Orbax sharded saves stay synchronous (they are
collective across processes).
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
import shutil
import tempfile
import threading
import time
import zlib
from pathlib import Path
from typing import Optional

from . import faults
from . import locks
from ..obs import telemetry
from .checkpoint import (is_process_zero, save_checkpoint,
                         save_checkpoint_sharded)

SCHEMA_VERSION = 1
MANIFEST = "manifest.json"
_DIR_RE = re.compile(r"^(?P<prefix>.+)-(?P<step>\d{8})$")


def config_fingerprint(cfg: Optional[dict]) -> Optional[str]:
    """crc32 of the canonical JSON of a config dict — cheap identity check
    so ``latest_valid`` can refuse checkpoints of a different model."""
    if cfg is None:
        return None
    blob = json.dumps(cfg, sort_keys=True, default=str).encode()
    return f"{zlib.crc32(blob):08x}"


def file_crc32(path: Path, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


def _tree_crc(root: Path) -> dict:
    """relpath -> {size, crc32} for every file under ``root`` except the
    manifest itself (orbax payloads are directories of shard files)."""
    out = {}
    for p in sorted(root.rglob("*")):
        if p.is_file() and p.name != MANIFEST:
            rel = str(p.relative_to(root))
            out[rel] = {"size": p.stat().st_size,
                        "crc32": f"{file_crc32(p):08x}"}
    return out


@dataclasses.dataclass(frozen=True)
class CheckpointInfo:
    """One verified checkpoint: pass ``payload`` to ``load_checkpoint``
    (a msgpack file or an Orbax directory — load sites accept both)."""

    directory: Path
    payload: Path
    step: int
    manifest: dict


def verify(directory: Path,
           fingerprint: Optional[str] = None) -> Optional[CheckpointInfo]:
    """Integrity-check one checkpoint directory: manifest present and
    parseable, schema known, payload present, every listed file matching
    its recorded size and crc32.  Returns None (with a stderr note saying
    why) instead of raising — a corrupt checkpoint is a *skip*, not a
    crash, on the resume path."""
    directory = Path(directory)
    if not directory.is_dir():
        return None  # nothing there at all — silent (save()'s pre-check)
    mpath = directory / MANIFEST

    def bad(why: str) -> None:
        telemetry.note("ckpt", "fallback_skip",
                       f"skipping {directory.name}: {why}", prefix="[ckpt]",
                       directory=directory.name)

    if not mpath.is_file():
        bad("no manifest (torn write — the save died before publishing)")
        return None
    try:
        manifest = json.loads(mpath.read_text())
    except (json.JSONDecodeError, OSError) as e:
        bad(f"unreadable manifest ({e})")
        return None
    if manifest.get("schema", 0) > SCHEMA_VERSION:
        bad(f"manifest schema {manifest.get('schema')} is newer than this "
            f"build's {SCHEMA_VERSION}")
        return None
    if fingerprint is not None and manifest.get("config_fingerprint") \
            not in (None, fingerprint):
        bad(f"config fingerprint {manifest.get('config_fingerprint')} != "
            f"this run's {fingerprint} (a different model)")
        return None
    payload = directory / manifest.get("payload", "")
    if not payload.exists():
        bad(f"payload {manifest.get('payload')!r} missing")
        return None
    for rel, meta in manifest.get("files", {}).items():
        f = directory / rel
        if not f.is_file():
            bad(f"listed file {rel} missing")
            return None
        size = f.stat().st_size
        if size != meta.get("size"):
            bad(f"{rel} is {size} bytes, manifest says {meta.get('size')} "
                "(truncated?)")
            return None
        if f"{file_crc32(f):08x}" != meta.get("crc32"):
            bad(f"{rel} fails its crc32 (corrupt)")
            return None
    return CheckpointInfo(directory=directory, payload=payload,
                          step=int(manifest.get("step", 0)),
                          manifest=manifest)


class CheckpointManager:
    """Manifest-publishing writer + validity-scanning reader over the
    existing msgpack/Orbax checkpoint formats.

    Single-writer semantics for msgpack payloads (call ``save`` on process
    0 only, with host arrays — same contract as ``save_checkpoint``);
    sharded saves are collective (every process calls ``save``, only
    process 0 publishes the manifest and applies retention).
    """

    def __init__(self, run_dir, prefix: str = "ckpt", keep_last: int = 3,
                 keep_every: int = 0, retries: int = 3,
                 backoff: float = 0.25, sharded: bool = False,
                 fingerprint: Optional[str] = None,
                 async_save: bool = False, plan: Optional[dict] = None,
                 topology: Optional[dict] = None):
        self.run_dir = Path(run_dir)
        self.prefix = prefix
        self.keep_last = int(keep_last)
        self.keep_every = int(keep_every)
        self.retries = int(retries)
        self.backoff = float(backoff)
        self.sharded = bool(sharded)
        self.fingerprint = fingerprint
        # elastic-resume provenance: the writing run's ParallelPlan record
        # (plan.to_manifest()) and topology (plan.current_topology()) ride
        # every manifest, so a resume on different hardware knows exactly
        # what it is resharding from — never a verification gate (restores
        # reshard by construction), purely the operator's provenance trail
        self.plan = dict(plan) if plan else None
        self.topology = dict(topology) if topology else None
        # async saves write from a background thread (one in flight; the
        # manifest publish stays the sole commit point).  Orbax sharded
        # saves are COLLECTIVE — every process joins them — and collectives
        # from an unsynchronized background thread can interleave across
        # hosts, so sharded saves stay synchronous by construction.
        self.async_save = bool(async_save) and not self.sharded
        # _worker/last_error are the caller-thread <-> ckpt-async-N
        # handoff: both sides go through _async_lock (the join itself runs
        # outside it, so a slow write never blocks in_flight probes).
        self._async_lock = locks.TracedLock("ckpt.async")
        self._worker: Optional["threading.Thread"] = None
        self.last_error: Optional[BaseException] = None

    # --- paths ---

    def _dir_for(self, step: int) -> Path:
        return self.run_dir / f"{self.prefix}-{int(step):08d}"

    def _all_dirs(self):
        """(step, path) for every checkpoint-shaped dir, newest first."""
        if not self.run_dir.is_dir():
            return []
        out = []
        for p in self.run_dir.iterdir():
            m = _DIR_RE.match(p.name)
            if p.is_dir() and m and m.group("prefix") == self.prefix:
                out.append((int(m.group("step")), p))
        return sorted(out, reverse=True)

    # --- write side ---

    def save(self, step: int, payload: dict) -> Optional[Path]:
        """Write checkpoint ``step``.  Transient ``OSError``s (including
        injected ones) retry with exponential backoff; a step that already
        has a *valid* manifest is a no-op (the interrupt path may land on a
        step the cadence just saved).

        Synchronous mode returns the payload path.  With ``async_save``
        the caller must hand in a payload that is already HOST data (the
        trainers' ``host_fetch`` is the synchronous device→host snapshot);
        serialization, file writes, the crc pass, the manifest publish and
        retention all run on a background thread and ``save`` returns
        ``None`` immediately — the step loop's stall per checkpoint is the
        snapshot, not the write.  At most ONE save is in flight: a second
        ``save`` first joins the previous one, so checkpoints can never
        commit out of order and a cadence that outpaces the disk degrades
        to the blocking behavior instead of queueing unboundedly.  A
        background failure is recorded in ``last_error`` and logged —
        same log-not-fatal contract as the trainers' managed saves — and
        the NEXT checkpoint cadence writes the next one."""
        if self.async_save:
            self.wait()
            worker = threading.Thread(
                target=self._save_bg, args=(step, payload),
                name=f"ckpt-async-{step}", daemon=True)
            with self._async_lock:
                self._worker = worker
            worker.start()
            return None
        return self._save_blocking(step, payload)

    def _save_bg(self, step: int, payload: dict) -> None:
        try:
            self._save_blocking(step, payload)
        # graftlint: disable=EXC001 (background writer: the error is recorded in last_error, logged loudly, and the next cadence save proceeds — the log-not-fatal managed-save contract)
        except BaseException as e:  # noqa: BLE001
            with self._async_lock:
                self.last_error = e
            telemetry.note("ckpt", "save_failed",
                           f"async save step {step} failed: {e}",
                           prefix="[ckpt]", step=int(step))

    def wait(self) -> None:
        """Join the in-flight async save, if any.  Callers that must see a
        committed checkpoint before proceeding (the trainers' interrupt
        path, process exit) call this; a recorded background failure stays
        in ``last_error`` for inspection."""
        with self._async_lock:
            worker, self._worker = self._worker, None
        if worker is not None:  # join OUTSIDE the lock: it can block for
            worker.join()       # the whole write (T2 otherwise)

    @property
    def in_flight(self) -> bool:
        with self._async_lock:
            worker = self._worker
        return worker is not None and worker.is_alive()

    def finish(self) -> None:
        """End-of-run barrier: join the writer and surface (log) any
        recorded background failure.  Never raises — by the time a trainer
        calls this it is exiting, and the on-disk state is whatever the
        commit protocol made durable."""
        self.wait()
        with self._async_lock:
            err = self.last_error
        if err is not None:
            telemetry.note("ckpt", "save_failed_earlier",
                           f"note: an async save failed earlier: "
                           f"{err}", prefix="[ckpt]")

    def _save_blocking(self, step: int, payload: dict) -> Path:
        existing = verify(self._dir_for(step))
        if existing is not None:
            return existing.payload
        # the span runs on whichever thread executes the save — the step
        # loop for blocking saves, the ckpt-async-N worker for async ones —
        # so the Perfetto timeline shows where the write time actually went
        with telemetry.span("ckpt", "save", step=int(step),
                            sharded=self.sharded,
                            mode="async" if threading.current_thread().name
                            .startswith("ckpt-async") else "blocking"):
            for attempt in range(self.retries + 1):
                try:
                    return self._save_once(step, payload)
                except OSError as e:
                    if attempt >= self.retries:
                        raise
                    delay = self.backoff * (2 ** attempt)
                    telemetry.note(
                        "ckpt", "save_retry",
                        f"save step {step} attempt {attempt + 1} "
                        f"failed ({e}); retrying in {delay:.2f}s",
                        prefix="[ckpt]", step=int(step), attempt=attempt + 1)
                    time.sleep(delay)
        raise AssertionError("unreachable")

    def _save_once(self, step: int, payload: dict) -> Path:
        actions = faults.fire("ckpt_write")
        cdir = self._dir_for(step)
        if cdir.exists() and not (cdir / MANIFEST).exists():
            # partial leftovers of a previous failed attempt: start clean
            shutil.rmtree(cdir, ignore_errors=True)
        cdir.mkdir(parents=True, exist_ok=True)
        if self.sharded:
            data = cdir / "data.orbax"
            save_checkpoint_sharded(data, payload)
        else:
            data = cdir / "data.msgpack"
            save_checkpoint(data, payload)
        if self.sharded and not is_process_zero():
            return data  # manifest + retention are single-writer
        files = _tree_crc(cdir)
        if "truncate" in actions:
            # chaos: tear the payload AFTER the CRCs were computed — models
            # a crash/bit-rot between the data landing and the next read.
            # The manifest still publishes, so only CRC verification can
            # catch it (exactly what latest_valid must survive).
            victim = data if data.is_file() else next(
                p for p in sorted(data.rglob("*")) if p.is_file())
            os.truncate(victim, max(victim.stat().st_size // 2, 1))
        manifest = {"schema": SCHEMA_VERSION, "step": int(step),
                    "config_fingerprint": self.fingerprint,
                    "payload": data.name, "files": files,
                    "time": time.time()}
        if self.plan is not None:
            manifest["plan"] = self.plan
        if self.topology is not None:
            manifest["topology"] = self.topology
        # faultpoint: GRAFT_FAULTS="ckpt_async:at_step=N" kills the writer
        # HERE — data fully on disk, manifest never published.  This is the
        # I1 crash window the commit protocol exists for: the directory is
        # a torn write by definition and latest_valid() must fall back to
        # the previous checkpoint.  InjectedKill is not an OSError, so the
        # retry loop does NOT heal it — the save dies, as a real kill would.
        if "at_step" in faults.fire("ckpt_async", step=step):
            raise faults.InjectedKill(
                f"injected kill between data write and manifest publish "
                f"of step {step}")
        self._publish_manifest(cdir, manifest)
        telemetry.emit("ckpt", "publish", step=int(step),
                       files=len(files),
                       bytes=sum(m["size"] for m in files.values()))
        self._apply_retention()
        return data

    @staticmethod
    def _publish_manifest(cdir: Path, manifest: dict) -> None:
        """Atomic-rename publish, fsynced: the manifest IS the commit
        record, so it must never itself be readable half-written."""
        fd, tmp = tempfile.mkstemp(dir=str(cdir), prefix=".manifest-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(manifest, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, cdir / MANIFEST)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def _apply_retention(self) -> None:
        """keep-last-N + keep-every-M: after a successful save, delete
        checkpoints that are neither among the ``keep_last`` newest steps
        nor multiples of ``keep_every``.  ``keep_last <= 0`` keeps
        everything."""
        if self.keep_last <= 0:
            return
        dirs = self._all_dirs()  # newest first
        keep = {step for step, _ in dirs[:self.keep_last]}
        if self.keep_every > 0:
            keep |= {step for step, _ in dirs
                     if step % self.keep_every == 0}
        for step, path in dirs:
            if step not in keep:
                shutil.rmtree(path, ignore_errors=True)

    # --- read side ---

    def latest_valid(self) -> Optional[CheckpointInfo]:
        """The newest checkpoint that passes integrity verification,
        scanning past (and reporting) torn or corrupt ones."""
        for _step, path in self._all_dirs():
            info = verify(path, fingerprint=self.fingerprint)
            if info is not None:
                return info
        return None


def latest_valid(run_dir, prefix: str = "ckpt",
                 fingerprint: Optional[str] = None) -> Optional[CheckpointInfo]:
    """Module-level convenience for external monitors (tools/monitor.py)."""
    return CheckpointManager(run_dir, prefix=prefix,
                             fingerprint=fingerprint).latest_valid()
