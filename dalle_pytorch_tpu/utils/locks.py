"""graftrace runtime half — traced lock wrappers + lock-order witness.

Every lock in the serving/obs stack is constructed through this module
(``TracedLock`` / ``TracedRLock`` / ``TracedCondition``); graftlint THR001
flags raw ``threading.Lock()`` construction anywhere else.  The wrappers are
drop-in: with the witness disarmed they delegate to the underlying primitive
after a single module-global bool check (the telemetry free-when-off
contract — test_locks pins the disabled path at a few µs).

Armed (``GRAFT_LOCK_WITNESS=1`` or :func:`arm`), every acquisition records:

* **order edges** — for each lock already held by the acquiring thread, an
  ``held_name -> new_name`` edge with a count.  :func:`order_report` runs
  cycle detection over the edge graph; :func:`assert_acyclic` raises
  :class:`LockOrderError` naming the cycle.  An AB/BA inversion between two
  threads therefore fails the chaos suites even when the interleaving never
  actually deadlocked in that run.
* **contention stats** — per lock name: acquisitions, contended
  acquisitions (a non-blocking probe failed first), cumulative wait time,
  cumulative/max held time.  Exported as ``graft_lock_*`` metrics via
  :func:`publish_metrics` and as ``kind="lock"`` telemetry events via
  :func:`emit_telemetry`.

Witness internals are guarded by a raw ``threading.Lock`` — the one
justified THR001 exemption (the witness cannot trace itself).  Re-entrant
acquisitions of a ``TracedRLock`` record neither self-edges nor nested
held-time; only the outermost hold is timed.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple


def _env_flag(name: str, default: bool = False) -> bool:
    # helpers.env_flag semantics (OFF-able: "0"/"false"/"no"/"off"/"" are
    # False), restated locally: helpers imports jax at module scope and
    # locks must stay stdlib-only — obs/ and data/ import it at their own
    # module scope.
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("", "0", "false", "no", "off")

__all__ = [
    "TracedLock",
    "TracedRLock",
    "TracedCondition",
    "LockOrderError",
    "arm",
    "disarm",
    "armed",
    "reset",
    "stats",
    "order_report",
    "assert_acyclic",
    "publish_metrics",
    "emit_telemetry",
]


class LockOrderError(AssertionError):
    """Raised by :func:`assert_acyclic` when the acquisition graph has a
    cycle (a potential AB/BA deadlock observed at runtime)."""


# ---------------------------------------------------------------------------
# witness state (process-global)
# ---------------------------------------------------------------------------

_armed: bool = _env_flag("GRAFT_LOCK_WITNESS", default=False)

# The witness cannot trace itself: this is the one deliberate raw-lock
# construction site outside the wrappers.  graftlint THR001 exempts this
# module by path.
_state_lock = threading.Lock()
# (held_name, acquired_name) -> count
_edges: Dict[Tuple[str, str], int] = {}
# name -> [acquires, contended, wait_s, held_s, held_max_s]
_stats: Dict[str, List[float]] = {}

_tls = threading.local()


def _held_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = []
        _tls.stack = stack
    return stack


def arm() -> None:
    """Enable the witness for this process (tests/CI)."""
    global _armed
    _armed = True


def disarm() -> None:
    global _armed
    _armed = False


def armed() -> bool:
    return _armed


def reset() -> None:
    """Drop all recorded edges and stats (per-test isolation)."""
    with _state_lock:
        _edges.clear()
        _stats.clear()


def _record_acquire(name: str, waited_s: float, contended: bool) -> None:
    stack = _held_stack()
    with _state_lock:
        st = _stats.get(name)
        if st is None:
            st = [0, 0, 0.0, 0.0, 0.0]
            _stats[name] = st
        st[0] += 1
        if contended:
            st[1] += 1
        st[2] += waited_s
        for held, _t0 in stack:
            if held == name:  # RLock re-entry: no self-edge
                continue
            key = (held, name)
            _edges[key] = _edges.get(key, 0) + 1
    stack.append((name, time.perf_counter()))


def _record_release(name: str) -> None:
    stack = _held_stack()
    # release the most recent hold of this name (LIFO discipline is the
    # overwhelmingly common case; out-of-order release still accounts the
    # right entry)
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][0] == name:
            _name, t0 = stack.pop(i)
            held = time.perf_counter() - t0
            with _state_lock:
                st = _stats.get(name)
                if st is not None:
                    st[3] += held
                    if held > st[4]:
                        st[4] = held
            return


# ---------------------------------------------------------------------------
# wrappers
# ---------------------------------------------------------------------------


class _TracedBase:
    """Shared acquire/release plumbing over a ``threading`` primitive."""

    __slots__ = ("name", "_inner", "_depth")

    def __init__(self, name: str, inner) -> None:
        self.name = name
        self._inner = inner
        # per-wrapper nesting depth (RLock re-entry): witness records only
        # the outermost hold so held-time is wall time, not a nested sum.
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _armed:
            if timeout == -1:
                return self._inner.acquire(blocking)
            return self._inner.acquire(blocking, timeout)
        contended = False
        waited = 0.0
        got = self._inner.acquire(False)
        if not got:
            contended = True
            if not blocking:
                return False
            t0 = time.perf_counter()
            if timeout == -1:
                got = self._inner.acquire(True)
            else:
                got = self._inner.acquire(True, timeout)
            waited = time.perf_counter() - t0
            if not got:
                return False
        self._depth += 1
        if self._depth == 1:
            _record_acquire(self.name, waited, contended)
        return True

    def release(self) -> None:
        if not _armed:
            self._inner.release()
            return
        if self._depth > 0:
            self._depth -= 1
            if self._depth == 0:
                _record_release(self.name)
        self._inner.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        if locked is not None:
            return locked()
        # RLock has no locked() before 3.12; probe non-blocking
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # --- Condition protocol -------------------------------------------
    # threading.Condition probes its lock for these; without them its
    # fallbacks misbehave on a re-entrant inner (the owner's non-blocking
    # probe *succeeds* on an RLock, so the fallback _is_owned reports
    # "not owned" to the owner).  Delegate to the primitive and keep the
    # witness's depth/held-stack consistent across wait()'s full
    # release/re-acquire.

    def _is_owned(self) -> bool:
        fn = getattr(self._inner, "_is_owned", None)
        if fn is not None:
            return fn()
        if self._inner.acquire(False):  # plain Lock: same as Condition's
            self._inner.release()
            return False
        return True

    def _release_save(self):
        depth = self._depth
        if _armed and depth > 0:
            _record_release(self.name)
        self._depth = 0
        fn = getattr(self._inner, "_release_save", None)
        if fn is not None:
            return (depth, fn())
        self._inner.release()
        return (depth, None)

    def _acquire_restore(self, state) -> None:
        depth, inner_state = state
        fn = getattr(self._inner, "_acquire_restore", None)
        if fn is not None:
            fn(inner_state)
        else:
            self._inner.acquire()
        self._depth = depth
        if _armed and depth > 0:
            _record_acquire(self.name, 0.0, False)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<{type(self).__name__} {self.name!r}>"


class TracedLock(_TracedBase):
    """``threading.Lock`` with optional order/contention witness."""

    __slots__ = ()

    def __init__(self, name: str) -> None:
        super().__init__(name, threading.Lock())


class TracedRLock(_TracedBase):
    """``threading.RLock`` with optional order/contention witness."""

    __slots__ = ()

    def __init__(self, name: str) -> None:
        super().__init__(name, threading.RLock())


def TracedCondition(lock: Optional[_TracedBase] = None,
                    name: str = "cond") -> threading.Condition:
    """``threading.Condition`` over a traced lock.

    ``Condition`` only needs ``acquire``/``release``/``__enter__``/
    ``__exit__`` from its lock (``wait()`` falls back to a full
    release/re-acquire when the lock lacks ``_release_save``), so handing
    it a wrapper keeps every acquisition on the witness.
    """
    if lock is None:
        lock = TracedRLock(name)
    return threading.Condition(lock)


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------


def stats() -> Dict[str, Dict[str, float]]:
    """Per-lock contention stats: acquires, contended, wait_s, held_s,
    held_max_s."""
    with _state_lock:
        return {
            name: {
                "acquires": int(st[0]),
                "contended": int(st[1]),
                "wait_s": st[2],
                "held_s": st[3],
                "held_max_s": st[4],
            }
            for name, st in _stats.items()
        }


def _find_cycle(edges: Dict[Tuple[str, str], int]) -> Optional[List[str]]:
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    WHITE, GREY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    parent: Dict[str, str] = {}

    def visit(start: str) -> Optional[List[str]]:
        stack = [(start, iter(adj.get(start, ())))]
        color[start] = GREY
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                c = color.get(nxt, WHITE)
                if c == GREY:
                    cycle = [nxt, node]
                    cur = node
                    while cur != nxt:
                        cur = parent[cur]
                        cycle.append(cur)
                    cycle.reverse()
                    return cycle
                if c == WHITE:
                    color[nxt] = GREY
                    parent[nxt] = node
                    stack.append((nxt, iter(adj.get(nxt, ()))))
                    advanced = True
                    break
            if not advanced:
                color[node] = BLACK
                stack.pop()
        return None

    for start in adj:
        if color.get(start, WHITE) == WHITE:
            cycle = visit(start)
            if cycle is not None:
                return cycle
    return None


def order_report() -> Dict[str, object]:
    """Acquisition-order graph + cycle verdict.

    Returns ``{"edges": [(a, b, count), ...], "cycle": [names...] | None,
    "acyclic": bool}``.
    """
    with _state_lock:
        edges = dict(_edges)
    cycle = _find_cycle(edges)
    return {
        "edges": sorted((a, b, n) for (a, b), n in edges.items()),
        "cycle": cycle,
        "acyclic": cycle is None,
    }


def assert_acyclic() -> None:
    """Raise :class:`LockOrderError` if the observed acquisition-order graph
    has a cycle.  Standing gate in the chaos suites and fleet_smoke."""
    rep = order_report()
    if not rep["acyclic"]:
        cycle = rep["cycle"]
        raise LockOrderError(
            "lock acquisition order cycle (potential deadlock): "
            + " -> ".join(cycle))  # type: ignore[arg-type]


def publish_metrics() -> None:
    """Export per-lock stats as ``graft_lock_*`` gauges on the active
    metrics registry (no-op when none is active)."""
    from dalle_pytorch_tpu.obs import metrics as obs_metrics
    reg = obs_metrics.active()
    if reg is None:
        return
    for name, st in stats().items():
        reg.gauge("graft_lock_acquires_total",
                  "lock acquisitions", lock=name).set(st["acquires"])
        reg.gauge("graft_lock_contended_total",
                  "acquisitions that waited", lock=name).set(st["contended"])
        reg.gauge("graft_lock_wait_seconds_total",
                  "cumulative acquire wait", lock=name).set(st["wait_s"])
        reg.gauge("graft_lock_held_seconds_total",
                  "cumulative held time", lock=name).set(st["held_s"])
        reg.gauge("graft_lock_held_seconds_max",
                  "longest single hold", lock=name).set(st["held_max_s"])


def emit_telemetry() -> None:
    """Emit one ``kind="lock"`` telemetry event per lock plus one order-graph
    event (no-op when telemetry is inactive)."""
    from dalle_pytorch_tpu.obs import telemetry as obs_telemetry
    tel = obs_telemetry.get()
    if tel is None:
        return
    for name, st in stats().items():
        tel.event("lock", name, **st)
    rep = order_report()
    cycle = rep["cycle"]
    tel.event("lock", "order_graph", edges=len(rep["edges"]),
              acyclic=rep["acyclic"],
              cycle=" -> ".join(cycle) if cycle else None)
