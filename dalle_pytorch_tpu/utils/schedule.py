"""Host-side learning-rate schedules.

The reference relies on two stateful torch schedulers:
* ``ExponentialLR`` gamma=0.98 for the VAE (`train_vae.py:124`), stepped every
  100 iters alongside the gumbel temperature anneal (`train_vae.py:211-217`).
* ``ReduceLROnPlateau`` (factor 0.5, patience 5, cooldown 0, min 1e-7) for
  DALLE (`train_dalle.py:286-295`), stepped on the epoch-end loss.

Both are inherently host-side, loss-driven state machines; in JAX the jitted
train step takes the current lr as a scalar input (via
``optax.inject_hyperparams``), and these classes own the state on the host.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class ExponentialDecay:
    lr: float
    gamma: float = 0.98

    def step(self) -> float:
        self.lr *= self.gamma
        return self.lr


@dataclasses.dataclass
class ReduceLROnPlateau:
    """min-mode plateau scheduler, semantics of torch.optim.lr_scheduler's
    (threshold 1e-4 rel, as torch defaults; ref train_dalle.py:286-295)."""

    lr: float
    factor: float = 0.5
    patience: int = 5
    cooldown: int = 0
    min_lr: float = 1e-7
    threshold: float = 1e-4

    best: float = float("inf")
    num_bad_epochs: int = 0
    cooldown_counter: int = 0

    def step(self, metric: float) -> float:
        if metric < self.best * (1.0 - self.threshold):
            self.best = metric
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1

        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0

        if self.num_bad_epochs > self.patience:
            self.lr = max(self.lr * self.factor, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0
        return self.lr

    def state_dict(self) -> dict:
        return dataclasses.asdict(self)

    def load_state_dict(self, d: dict) -> None:
        for k, v in d.items():
            setattr(self, k, v)


@dataclasses.dataclass
class GumbelTemperature:
    """VAE gumbel temperature anneal: ``temp * exp(-anneal_rate * step)``
    floored at `min_temp`, updated every 100 steps (ref train_vae.py:55-57,
    :211-217)."""

    start: float = 1.0
    min_temp: float = 0.5
    anneal_rate: float = 1e-6
    value: float = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.value is None:
            self.value = self.start

    def update(self, global_step: int) -> float:
        import math

        # compounding, as the reference applies it repeatedly
        # (temp = max(temp * exp(-rate * global_step), min); train_vae.py:213)
        self.value = max(self.value * math.exp(-self.anneal_rate * global_step),
                         self.min_temp)
        return self.value
