"""Training logging: plain-text step log + optional wandb.

Reproduces the reference's observability surface (SURVEY.md §5.5):
* a text log file with one ``epoch iter loss lr`` line per step
  (`train_dalle.py:351-353, :378`) — these are the ``all-logs/*.txt``
  artifacts the fork's analysis notebook consumes;
* wandb scalars/images when wandb is installed (root process only);
* stdout prints every `print_every` iters (`train_dalle.py:383`).
"""
from __future__ import annotations

import sys
from pathlib import Path
from typing import Optional

import jax

try:
    import wandb as _wandb
except ImportError:  # environment without wandb — log to text/stdout only
    _wandb = None


class TrainLogger:
    def __init__(self, log_filename: Optional[str] = None, project: Optional[str] = None,
                 config: Optional[dict] = None, print_every: int = 10,
                 use_wandb: bool = True):
        self.is_root = jax.process_index() == 0
        self.print_every = print_every
        self.run = None
        self._f = None
        self._local_name = None
        if self.is_root and use_wandb and _wandb is not None and project is not None:
            self.run = _wandb.init(project=project, config=config or {})
            log_filename = log_filename or f"{self.run.name}.txt"
        elif self.is_root and project is not None and log_filename is None:
            # no wandb: synthesize a run name so the `{run}.txt` step log (the
            # reference's all-logs/*.txt artifact, train_dalle.py:351-353)
            # still exists
            import time as _time
            self._local_name = f"{project}-{_time.strftime('%Y%m%d-%H%M%S')}"
            log_filename = f"{self._local_name}.txt"
        if log_filename is not None and self.is_root:
            Path(log_filename).parent.mkdir(parents=True, exist_ok=True)
            self._f = open(log_filename, "a+")
        self.log_filename = log_filename
        self._shared_name = None
        if jax.process_count() > 1:
            # run_name feeds collective checkpoint paths (the sweep saves) —
            # every process must agree, but only root knows the wandb name:
            # broadcast it (fixed-size so the collective is shape-static)
            import numpy as np
            from jax.experimental import multihost_utils

            name = ((self.run.name if self.run is not None
                     else self._local_name) or "").encode()[:128]
            buf = np.zeros(128, np.uint8)
            buf[: len(name)] = np.frombuffer(name, np.uint8)
            out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
            shared = bytes(out).rstrip(b"\x00").decode(errors="replace")
            self._shared_name = shared or None

    @property
    def run_name(self) -> str:
        if self._shared_name is not None:
            return self._shared_name
        if self.run is not None:
            return self.run.name
        return self._local_name or "local-run"

    def step(self, epoch: int, it: int, loss: float, lr: float, extra: Optional[dict] = None):
        if not self.is_root:
            return
        if self._f is not None:
            self._f.write(f"{epoch} {it} {loss} {lr}\n")
        if self.run is not None:
            # the wandb stream logs EVERY step, extras included — `extra`
            # carries the perf/health metrics (mfu, stall, health_state),
            # and decimating them to the print cadence silently dropped
            # 9/10 of the mfu/stall trajectory from the dashboard.  Only
            # the stdout print and the file flush keep the print_every
            # cadence (the reference's surface).
            payload = {"epoch": epoch, "iter": it, "loss": loss, "lr": lr}
            payload.update(extra or {})
            self.run.log(payload)
        if it % self.print_every == 0:
            print(epoch, it, f"loss - {loss}")
            sys.stdout.flush()
            if self._f is not None:  # flush cadence of the reference (:393-394)
                self._f.flush()

    def log(self, payload: dict):
        if self.is_root and self.run is not None:
            self.run.log(payload)

    def save_file(self, path: str):
        """wandb.save parity (ref train_dalle.py:409, train_vae.py:221).
        Directory checkpoints (Orbax) are skipped — wandb.save wants files;
        they go up via log_artifact instead."""
        if self.is_root and self.run is not None and Path(path).is_file():
            _wandb.save(path)

    def log_artifact(self, path: str, name: str, type_: str = "model"):
        """wandb.Artifact upload parity (ref train_vae.py:241-253); handles
        both file (msgpack) and directory (Orbax) checkpoints."""
        if self.is_root and self.run is not None:
            art = _wandb.Artifact(name, type=type_)
            if Path(path).is_dir():
                art.add_dir(path)
            else:
                art.add_file(path)
            self.run.log_artifact(art)

    def finish(self):
        if self._f is not None:
            self._f.close()
        if self.run is not None:
            self.run.finish()
