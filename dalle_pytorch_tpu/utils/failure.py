"""Failure detection & preemption-safe training.

The reference has no failure handling (SURVEY.md §5.3): recovery is "rerun
``train_dalle.py --dalle_path ./dalle.pt``" and a preempted run silently
loses everything since the last 100-iter checkpoint, while a hung
collective or dead host is invisible until the scheduler kills the job.
TPU pods make both failure modes routine (preemptible capacity, multi-host
collectives), so this framework makes them first-class:

* ``GracefulShutdown`` converts SIGTERM/SIGINT — the preemption notice every
  scheduler sends before the hard kill — into a cooperative stop flag the
  training loop polls at step boundaries, so the loop can write a final
  resume checkpoint and exit cleanly.  In multi-host runs the flag is made
  *collective* (any-process OR via the backend's ``average_all``) so every
  process leaves the loop at the same step — required because the
  checkpoint save paths (``host_fetch`` gathers, Orbax sharded writes) are
  collective operations that deadlock if only one process calls them.
* ``Heartbeat`` writes a small per-process progress file (atomic
  rename) at most once per ``beat_interval`` seconds and optionally runs an
  in-process
  watchdog thread that warns on stderr when no step has completed for
  ``stall_timeout`` seconds — catching hung device steps / collectives from
  *inside* the process, while the files let an external monitor detect a
  dead or wedged host by mtime age (``Heartbeat.is_stalled``).
"""
from __future__ import annotations

import enum
import json
import os
import signal
import sys
import tempfile
import threading
import time
from pathlib import Path
from typing import Optional

import jax
import numpy as np

from ..obs import telemetry


class ExitCode(enum.IntEnum):
    """The process exit-code taxonomy — THE one place these numbers live.

    Supervisors key restart decisions off these values (``tools/monitor.py``,
    ``chip_babysitter.sh``'s ``BABYSIT_TRAIN_CMD`` loop, any external
    scheduler), so they are a frozen contract: never renumber, only add
    (``tests/test_failure.py`` pins them).

    Trainer processes (train_dalle.py / train_vae.py):

    * ``CLEAN`` (0) — the run completed.  ``PREEMPTED`` is deliberately an
      alias: a graceful SIGTERM stop writes its resume checkpoint and exits
      *cleanly*; supervisors distinguish "finished" from "preempted" by the
      heartbeat done-marker (``Heartbeat.close(done=True)``), never by exit
      code, so an impatient scheduler reading 0 does not re-kill the pod.
    * ``ROLLBACK_BUDGET`` (70, EX_SOFTWARE) — the anomaly-recovery ladder
      exhausted its ``--max_rollbacks``: the run will NOT converge by
      relaunching; a human must read the anomaly bundles.  Terminal —
      supervisors must not restart it.
    * ``WEDGED`` (75, EX_TEMPFAIL) — the hung-step watchdog fired: a device
      call or collective never returned.  Transient by definition —
      supervisors relaunch with ``--resume auto``.
    * ``PREEMPT_EXPIRED`` (74, EX_IOERR) — a preemption notice's grace
      window ran out before the final checkpoint committed (the
      ``preempt:at_step`` faultpoint's bounded-grace drill, and the shape
      of a real scheduler's hard kill): whatever the commit protocol made
      durable is what resume gets.  Transient — supervisors relaunch with
      ``--resume auto`` (possibly under a different ``--plan``: the
      manifest-recorded plan + topology make the checkpoint restorable on
      whatever hardware the scheduler grants next).

    External monitor (``tools/monitor.py``):

    * ``MONITOR_STALLED`` (1) — some host's heartbeat is stale/missing.
    * ``MONITOR_NO_HEARTBEATS`` (2) — no heartbeat files at all.
    * ``RESTART_BUDGET`` (3) — ``--restart-cmd`` budget exhausted (or
      nothing manifest-valid to restart from).  Terminal, like 70.
    """

    CLEAN = 0
    PREEMPTED = 0  # alias of CLEAN — see the docstring for why
    MONITOR_STALLED = 1
    MONITOR_NO_HEARTBEATS = 2
    RESTART_BUDGET = 3
    ROLLBACK_BUDGET = 70
    PREEMPT_EXPIRED = 74
    WEDGED = 75


class GracefulShutdown:
    """Context manager turning termination signals into a pollable stop flag.

    A second delivery of the same signal restores the previous handler and
    re-raises, so an impatient ``kill`` (or ctrl-C twice) still terminates
    immediately instead of waiting for the checkpoint.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._previous = {}
        self._requested = False

    # --- signal plumbing ---

    def _handler(self, signum, frame):
        if self._requested:  # second signal: escalate to the old behavior
            self._restore()
            signal.raise_signal(signum)
            return
        self._requested = True
        # note() is signal-safe here: Telemetry's lock is an RLock, so a
        # handler interrupting the main thread mid-event still emits
        telemetry.note(
            "run", "preempt_signal",
            f"received signal {signum}: will checkpoint and stop at the "
            "next step boundary (send again to force-quit)",
            prefix="[failure]", signum=int(signum))

    def _restore(self):
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous = {}

    def __enter__(self) -> "GracefulShutdown":
        for sig in self._signals:
            self._previous[sig] = signal.signal(sig, self._handler)
        return self

    def __exit__(self, *exc):
        self._restore()
        return False

    # --- polling API ---

    @property
    def requested(self) -> bool:
        """This process's local flag (no collective)."""
        return self._requested

    def should_stop(self, backend=None, step: Optional[int] = None,
                    check_every: int = 1) -> bool:
        """Collective stop decision, safe to act on with collective saves.

        Single-process: just the local flag.  Multi-process: every
        ``check_every`` steps all processes agree on OR(local flags) via the
        backend's ``average_all`` (flags are 0/1, so mean > 0 iff any set).
        Note the multi-process collective *blocks the host*; a loop that
        already averages a per-step metric should use
        :meth:`average_and_poll` instead, which rides the stop flag on that
        existing collective for free.  A ``check_every`` larger than 1 must
        be called symmetrically by every process — pass the global step so
        the modulo lines up.
        """
        if jax.process_count() <= 1 or backend is None:
            return self._requested
        if step is not None and check_every > 1 and step % check_every != 0:
            return False
        flag = np.float32(1.0 if self._requested else 0.0)
        return float(backend.average_all(flag)) > 0.0

    def average_and_poll(self, backend, value) -> tuple:
        """Average a per-step host metric *and* decide the collective stop
        in one collective: returns ``(mean_value, stop)``.

        The train loops already block once per step to average the loss
        across processes; gathering ``[loss, stop_flag]`` as a single
        2-vector makes the preemption check free instead of doubling the
        per-step host collectives.  Every process must call this
        symmetrically (same as the loss averaging it replaces).
        """
        if backend is None or jax.process_count() <= 1:
            return float(value), self._requested
        pair = np.asarray([np.float32(value),
                           np.float32(1.0 if self._requested else 0.0)])
        avg = backend.average_all(pair)
        return float(avg[0]), float(avg[1]) > 0.0


class Heartbeat:
    """Per-process progress file + optional in-process stall watchdog.

    ``run_id`` (explicit, else inherited from the active telemetry) and the
    telemetry stream's last-event sequence number ride every heartbeat
    write, so an external monitor can correlate a stalled host with its
    telemetry tail — not just *that* it stalled, but what it was doing
    (``tools/monitor.py --telemetry-dir``)."""

    def __init__(self, directory, beat_interval: float = 15.0,
                 stall_timeout: Optional[float] = None,
                 run_id: Optional[str] = None):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.path = self.dir / f"heartbeat-p{jax.process_index()}.json"
        self.run_id = run_id
        self.beat_interval = float(beat_interval)
        self._sweep_stale_temps()
        # None until the first beat: the stretch from construction to step 1
        # includes the XLA compile (minutes at real sizes), which must not
        # read as a stall
        self._last_beat = None
        self._last_write = None  # monotonic time of the last file write
        self._last_step = 0
        self._stop = threading.Event()
        self._thread = None
        self._stalled_since = None
        if stall_timeout:
            self._timeout = float(stall_timeout)
            self._thread = threading.Thread(
                target=self._watch, name="heartbeat-watchdog", daemon=True)
            self._thread.start()

    def beat(self, step: int, **extra) -> None:
        """Record a completed step.  The file write is rate-limited by
        *time* (``beat_interval`` seconds), not by step count — external
        monitors judge staleness by wall-clock age, so a slow-but-healthy
        run (minutes per step) must still look alive.  The first beat
        always writes so monitors see the file immediately."""
        now = time.monotonic()
        self._last_beat = now
        self._last_step = int(step)
        self._stalled_since = None
        if (self._last_write is not None
                and now - self._last_write < self.beat_interval):
            return
        self._last_write = now
        self._write({"step": int(step), "time": time.time(),
                     "process": jax.process_index(),
                     **self._correlation(), **self._memory(), **extra})

    @staticmethod
    def _memory() -> dict:
        """Compact memory snapshot riding every heartbeat (host RSS +
        summed device used/peak when the backend exposes counters) — the
        monitor reads a dying host's memory trajectory from the
        heartbeat trail alone, no telemetry stream required.  Guarded:
        a heartbeat must never die because a memory probe did."""
        try:
            from dalle_pytorch_tpu.obs import mem
            return mem.heartbeat_snapshot()
        except Exception:  # graftlint: disable=EXC001 (liveness signal outranks the memory garnish; heartbeat_snapshot itself guards the backend probe, this catches import-time breakage)
            return {}

    def _sweep_stale_temps(self) -> None:
        """A process killed inside ``_write`` (between mkstemp and the
        rename) leaks one ``.hb-*`` temp file; over many preemption cycles
        a long-lived heartbeat dir fills with them.  On startup, remove
        temps older than a few beat intervals — anything that old cannot
        belong to a write still in flight."""
        # graftlint: disable=OBS002 (cross-clock by design: the cutoff compares against file mtimes, which live on the wall clock)
        cutoff = time.time() - 3 * self.beat_interval
        for tmp in self.dir.glob(".hb-*"):
            try:
                if tmp.stat().st_mtime < cutoff:
                    tmp.unlink()
            except OSError:  # racing another process's write or sweep
                pass

    def _write(self, payload: dict) -> None:
        fd, tmp = tempfile.mkstemp(dir=self.dir, prefix=".hb-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)  # atomic on POSIX
        except BaseException:
            try:
                os.unlink(tmp)
            finally:
                raise

    def _watch(self) -> None:
        while not self._stop.wait(min(self._timeout / 4, 1.0)):
            if self._last_beat is None:  # still compiling step 1
                continue
            age = time.monotonic() - self._last_beat
            if age > self._timeout and self._stalled_since is None:
                self._stalled_since = time.monotonic()
                telemetry.note(
                    "run", "stall_warning",
                    f"possible stall: no training step for {age:.0f}s "
                    f"(timeout {self._timeout:.0f}s) — a hung collective "
                    "or device step?", prefix="[failure]",
                    age_s=age, step=self._last_step)

    def close(self, done: bool = False) -> None:
        """Stop the watchdog.  ``done=True`` stamps the heartbeat file with a
        done marker so external monitors can tell a *finished* run from a
        dead one (otherwise the aging heartbeat of a completed run reads as
        STALLED and an auto-restart wrapper would relaunch it forever).
        Interrupted/preempted runs close with ``done=False`` on purpose —
        there a restart is exactly what the babysitter should do."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if done:
            self._write({"step": self._last_step, "time": time.time(),
                         "process": jax.process_index(),
                         **self._correlation(), "done": True})

    def _correlation(self) -> dict:
        """run_id + telemetry last-seq + clock-beacon fields for every
        heartbeat write.  The clock payload (wall<->mono offset pair +
        boot nonce, obs/align.py's anchor material) rides here so a
        monitor can place this host on the fleet timebase even when the
        host died between telemetry rotations — and because the heartbeat
        file lands on the monitor's filesystem, its mtime doubles as a
        shared-clock rendezvous reference."""
        tel = telemetry.get()
        out = {"clock": telemetry.clock_beacon_payload()}
        run_id = self.run_id or (tel.run_id if tel is not None else None)
        if run_id is not None:
            out["run_id"] = run_id
        if tel is not None:
            out["telemetry_seq"] = tel.seq
        return out

    # --- external-monitor side ---

    @staticmethod
    def read(path) -> dict:
        return json.loads(Path(path).read_text())

    @staticmethod
    def is_stalled(path, timeout: float, now: Optional[float] = None) -> bool:
        """True if the heartbeat file is older than ``timeout`` seconds (or
        missing) — for an external babysitter scanning ``heartbeat-p*.json``
        to find dead/wedged hosts."""
        path = Path(path)
        if not path.exists():
            return True
        now = time.time() if now is None else now
        try:
            last = Heartbeat.read(path)["time"]
        except (json.JSONDecodeError, KeyError):  # mid-write torn read
            last = path.stat().st_mtime
        return (now - last) > timeout
