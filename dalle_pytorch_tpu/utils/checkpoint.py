"""Checkpoint save/load.

Format parity with the reference (`train_dalle.py:174-184`,
`train_vae.py:110-119`): a single file holding a dict with keys
``hparams`` / ``vae_params`` / ``weights`` (and, fixing the reference's gap
noted in SURVEY.md §5.3, optionally ``opt_state`` + ``step`` so training can
resume exactly).  Serialized with flax msgpack instead of torch pickles —
single-writer (process 0) semantics.
"""
from __future__ import annotations

import os
import tempfile
from pathlib import Path
from typing import Any

import jax
import numpy as np
from flax import serialization


def _to_numpy(tree):
    """Arrays -> numpy; tuples -> lists (msgpack has no tuple type — configs
    restore them via their `from_dict`, e.g. VAEConfig.normalization)."""
    if isinstance(tree, (list, tuple)):
        return [_to_numpy(v) for v in tree]
    if isinstance(tree, dict):
        return {k: _to_numpy(v) for k, v in tree.items()}
    if hasattr(tree, "shape"):
        return np.asarray(tree)
    return tree


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed to deserialize — truncated or corrupt."""


def save_checkpoint(path: str | Path, obj: dict) -> None:
    """Atomically write `obj` (a pytree of arrays + plain python) to `path`.
    The temp file is fsynced before the rename so a crash right after the
    publish cannot leave a renamed-but-empty file behind."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    data = serialization.msgpack_serialize(_to_numpy(obj))
    fd, tmp = tempfile.mkstemp(dir=str(path.parent), prefix=path.name, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_checkpoint(path: str | Path) -> Any:
    """Load either checkpoint format: a msgpack file, or (when `path` is a
    directory) an Orbax sharded checkpoint — so every CLI load site accepts
    both transparently.  A file that fails to deserialize (truncated by a
    kill mid-write, or corrupt) raises :class:`CheckpointCorruptError`
    naming the file and its size instead of a bare msgpack unpack error."""
    if is_sharded_checkpoint(path):
        return load_checkpoint_sharded(path)
    with open(path, "rb") as f:
        data = f.read()
    try:
        return serialization.msgpack_restore(data)
    except Exception as e:  # msgpack raises several unpack error classes
        raise CheckpointCorruptError(
            f"checkpoint {path} is corrupt or truncated ({len(data)} bytes): "
            f"{e}.  If this run keeps managed checkpoints (a --ckpt_dir with "
            "manifests), resume with --resume auto — "
            "CheckpointManager.latest_valid() skips corrupt checkpoints and "
            "falls back to the previous good one.") from e


def is_process_zero() -> bool:
    return jax.process_index() == 0


def _replicated_sharding():
    """A concrete fully-replicated sharding over every device — the
    placement shared by the sharded save's scalar lifting and the partial
    restore, so the two can never drift apart."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    return NamedSharding(Mesh(np.asarray(jax.devices()), ("_all",)),  # graftlint: disable=PLAN001 (checkpoint IO is plan-agnostic by design: restore must work under ANY plan, so it pins an explicit fully-replicated placement on a private mesh)
                         PartitionSpec())  # graftlint: disable=PLAN001 (the replicated spec of that plan-agnostic placement)


def save_checkpoint_sharded(path: str | Path, obj: dict) -> None:
    """Orbax-backed save for sharded/multi-host training: arrays are written
    per-shard by the hosts that own them (no gather to process 0, unlike the
    msgpack path, which `host_fetch`es everything).  `obj` may mix jax
    Arrays (possibly sharded), numpy, and plain python.  Layout: an Orbax
    PyTree checkpoint directory at ``path`` (use a ``.orbax`` suffix to keep
    it distinguishable from the single-file msgpack checkpoints).
    """
    try:
        import orbax.checkpoint as ocp
    except ImportError as e:
        raise SystemExit(
            "sharded checkpoints need orbax: pip install "
            "'dalle-pytorch-tpu[sharded]'") from e

    path = Path(path).resolve()
    if jax.process_count() > 1:
        # host-local jax.Arrays (the jit-init optax count, the injected lr
        # scalar from set_learning_rate) are unserializable multi-host;
        # their values are identical on every process by construction, so
        # lift them to replicated global arrays — after CHECKING that
        # construction-time assumption: lifting divergent local buffers
        # would silently persist an arbitrary process's value
        from jax.experimental import multihost_utils

        repl = _replicated_sharding()
        local = [np.asarray(leaf) for leaf in jax.tree.leaves(obj)
                 if (isinstance(leaf, jax.Array) and leaf.is_fully_addressable
                     and len(leaf.devices()) < jax.device_count())]
        if local:
            multihost_utils.assert_equal(
                local, "host-local checkpoint leaves diverge across "
                       "processes; refusing to save an arbitrary one")

        def globalize(leaf):
            if (isinstance(leaf, jax.Array)
                    and leaf.is_fully_addressable
                    and len(leaf.devices()) < jax.device_count()):
                return multihost_utils.host_local_array_to_global_array(
                    np.asarray(leaf), repl.mesh, repl.spec)
            return leaf

        obj = jax.tree.map(globalize, obj)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, args=ocp.args.PyTreeSave(obj), force=True)


def _checkpoint_meta_tree(ckptr, path):
    """Checkpoint metadata tree across orbax generations: older versions
    return it directly, newer ones wrap it as ``.item_metadata.tree``."""
    meta = ckptr.metadata(path)
    meta = getattr(meta, "item_metadata", meta)
    return getattr(meta, "tree", meta)


def _fill_skips_from_meta(item, meta, repl):
    """Replace ``...`` skip-leaves with replicated ShapeDtypeStruct targets
    read off the checkpoint metadata (structure-parallel walk)."""
    if item is ...:
        return jax.ShapeDtypeStruct(tuple(meta.shape), meta.dtype,
                                    sharding=repl)
    if isinstance(item, dict):
        return {k: _fill_skips_from_meta(v, meta[k], repl)
                for k, v in item.items()}
    if isinstance(item, list):
        return [_fill_skips_from_meta(v, m, repl)
                for v, m in zip(item, meta)]
    return item


def _reinsert_skips(template, restored):
    """Walk ``template`` and the restore output in parallel, putting the
    ``...`` sentinel back at every skipped position."""
    if template is ...:
        return ...
    if isinstance(template, dict):
        return {k: _reinsert_skips(v, restored[k])
                for k, v in template.items()}
    if isinstance(template, list):
        return [_reinsert_skips(v, r) for v, r in zip(template, restored)]
    return restored


def _rebuffer_cpu(tree):
    """Copy restored arrays into XLA-allocated buffers on the CPU backend.

    XLA:CPU (jax 0.4.37) segfaults outright when a *donating* executable —
    specifically one deserialized from the persistent compile cache —
    consumes buffers that orbax/tensorstore allocated rather than XLA
    (observed: sharded-resume params fed to the cached train step).  An
    eager ``jnp.copy`` reallocates through XLA and keeps each leaf's
    sharding; TPU restores keep the zero-copy path."""
    if jax.default_backend() != "cpu":
        return tree
    import jax.numpy as jnp

    return jax.tree.map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, tree)


def _restore_with_skips(ckptr, ocp, path, item):
    """Restore ``item``, where a ``...`` leaf means "skip reading this
    leaf".  orbax >= 0.9 understands the sentinel natively
    (``ocp.PLACEHOLDER`` is ``...``).  Older orbax has no placeholder
    concept, so skipped leaves are restored by value onto a replicated
    sharding (shape/dtype from the checkpoint metadata) and then dropped —
    same results, just without the lazy-read memory win; multi-host pods
    (where that win matters) run new enough orbax for the native path."""
    has_skips = any(
        leaf is ... for leaf in
        jax.tree.leaves(item, is_leaf=lambda l: l is ...))
    if not has_skips or hasattr(ocp, "PLACEHOLDER"):
        return _rebuffer_cpu(ckptr.restore(path, args=ocp.args.PyTreeRestore(
            item=item,
            restore_args=ocp.checkpoint_utils.construct_restore_args(item))))
    filled = _fill_skips_from_meta(item, _checkpoint_meta_tree(ckptr, path),
                                   _replicated_sharding())
    out = ckptr.restore(path, args=ocp.args.PyTreeRestore(
        item=filled,
        restore_args=ocp.checkpoint_utils.construct_restore_args(filled)))
    return _reinsert_skips(item, _rebuffer_cpu(out))


def load_checkpoint_sharded(path: str | Path, target=None):
    """Restore an Orbax checkpoint directory.  With `target` (a pytree of
    jax.ShapeDtypeStruct with shardings, or arrays), arrays restore directly
    onto the target shardings — each host reads only its shards.  The CLI
    resume path does exactly this via the two-phase ``load_sharded_small``
    flow (configs first, then arrays straight onto the new run's mesh), so
    sharded resumes never materialize the full tree in host memory and work
    across topology changes."""
    import orbax.checkpoint as ocp

    path = Path(path).resolve()
    with ocp.PyTreeCheckpointer() as ckptr:
        if target is None:
            return ckptr.restore(path)
        # target leaves may be: ShapeDtypeStruct w/ sharding (restore onto
        # it), a plain value (restored by value), or the ``...`` sentinel
        # (skip this leaf entirely — it comes back as ``...``)
        return _restore_with_skips(ckptr, ocp, path, target)


def is_sharded_checkpoint(path: str | Path) -> bool:
    """Orbax checkpoints are directories; msgpack checkpoints are files."""
    return Path(path).is_dir()


def load_sharded_small(path: str | Path):
    """Phase 1 of a two-phase elastic resume: restore ONLY the non-array
    leaves of an Orbax checkpoint (hparams, scheduler scalars, epoch, ...).
    Array leaves come back as the ``...`` (Ellipsis) placeholder sentinel.

    The caller uses the restored configs to rebuild the model and compute
    this run's shardings, replaces each placeholder with a matching
    ``jax.ShapeDtypeStruct`` carrying the new sharding, and passes the tree
    to ``load_checkpoint_sharded(path, target=...)`` — arrays then restore
    straight onto the new topology with each host reading only its shards,
    never materializing the full tree in host memory.
    """
    import orbax.checkpoint as ocp

    path = Path(path).resolve()
    # 0-d leaves that were saved as (replicated) jax Arrays — optax count,
    # the injected lr — must restore onto a concrete sharding; restoring
    # them "by value" leaves the deserializer without one and fails
    repl = _replicated_sharding()
    with ocp.PyTreeCheckpointer() as ckptr:
        meta = _checkpoint_meta_tree(ckptr, path)

        def to_item(node):
            if isinstance(node, dict):
                return {k: to_item(v) for k, v in node.items()}
            if isinstance(node, list):
                return [to_item(v) for v in node]
            # leaf metadata: >=1-d shapes are real arrays (skip); 0-d /
            # shapeless leaves (python scalars, strings, optax counts) are
            # cheap — restore their values.  Typed dummies, not None: a None
            # item leaf is an empty subtree to orbax and never gets restored
            shape = getattr(node, "shape", None)
            if shape:  # non-empty tuple
                return ...  # skip sentinel (ocp.PLACEHOLDER on new orbax)
            dtype = getattr(node, "dtype", None)
            if dtype is not None:
                if getattr(node, "sharding", None) is not None:
                    return jax.ShapeDtypeStruct((), dtype, sharding=repl)
                return np.zeros((), dtype)
            return ""  # string leaf

        item = to_item(meta)
        return _restore_with_skips(ckptr, ocp, path, item)


def migrate_head_kernels(tree, total_text: int):
    """In-place upgrade of legacy joint-vocab logits heads.

    Checkpoints written before the per-phase head split store
    ``to_logits_dense`` as ``{kernel: [dim, total], bias: [total]}``; the
    current layout is per-phase blocks (``text_kernel``/``image_kernel``,
    ``text_bias``/``image_bias`` — see models/dalle.py::PhaseLogits).  The
    split at ``total_text`` is an exact column partition of the old joint
    matmul, so migrated checkpoints are bit-identical.  Safe to call on
    current checkpoints (no-op).  Returns the tree.
    """
    if isinstance(tree, (list, tuple)):
        # serialized optimizer states nest param-shaped subtrees (the Adam
        # moments) inside chain lists — migrate those too
        for v in tree:
            migrate_head_kernels(v, total_text)
        return tree
    if not isinstance(tree, dict):
        return tree
    for key, val in tree.items():
        if key == "to_logits_dense" and isinstance(val, dict) \
                and "kernel" in val:
            kern = np.asarray(val.pop("kernel"))
            bias = np.asarray(val.pop("bias"))
            assert kern.shape[1] > total_text, (
                f"legacy head kernel width {kern.shape[1]} does not cover "
                f"total_text_tokens={total_text}")
            val["text_kernel"] = kern[:, :total_text]
            val["image_kernel"] = kern[:, total_text:]
            val["text_bias"] = bias[:total_text]
            val["image_bias"] = bias[total_text:]
        else:
            migrate_head_kernels(val, total_text)
    return tree


def migrate_qkv_kernels(tree, dim_head: int = 64):
    """In-place upgrade of legacy flat fused-QKV kernels.

    Checkpoints written before the DenseGeneral refactor store
    ``to_qkv/kernel`` as ``[dim, 3*heads*dim_head]``; the current layout is
    ``[dim, 3, heads, dim_head]`` (bit-compatible reshape).  Heads are
    inferred from the flat width.  Safe to call on current checkpoints
    (no-op).  Returns the tree.
    """
    if not isinstance(tree, dict):
        return tree
    for key, val in tree.items():
        if key == "to_qkv" and isinstance(val, dict):
            kern = val.get("kernel")
            if kern is not None and np.ndim(kern) == 2:
                kern = np.asarray(kern)
                width = kern.shape[1]
                assert width % (3 * dim_head) == 0, (
                    f"legacy to_qkv kernel width {width} not divisible by "
                    f"3*dim_head={3 * dim_head}")
                heads = width // (3 * dim_head)
                val["kernel"] = kern.reshape(kern.shape[0], 3, heads, dim_head)
        else:
            migrate_qkv_kernels(val, dim_head)
    return tree
